"""Long-context training bench: Llama-class model at 4k/8k sequence length.

The flash kernel's headline regime — the XLA einsum path materializes
[B, H, T, T] logits (4 GB per layer-pass at 8k) while flash streams blocks.
Prints one JSON line per (seq, impl) leg. One TPU job at a time.

    python scripts/bench_long_context.py [--seqs 4096,8192] [--layers 8]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", default="4096,8192")
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--xla_too", action="store_true",
                    help="also time the pure-XLA attention path")
    args = ap.parse_args()

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                            llama_flops_per_token)
    from deepspeed_tpu.parallel import groups

    print("devices:", jax.devices(), file=sys.stderr, flush=True)

    def run(seq, disable_pallas):
        cfg = LlamaConfig(
            vocab_size=32000, hidden_size=args.hidden,
            intermediate_size=args.hidden * 4 // 2 * 2,
            num_hidden_layers=args.layers, num_attention_heads=16,
            num_key_value_heads=4, max_position_embeddings=seq,
            scan_layers=True, remat=True)
        model = LlamaForCausalLM(cfg)
        batch = 1
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, size=(batch, seq)).astype(np.int32)
        data = {"input_ids": ids, "labels": ids}
        if disable_pallas:
            os.environ["DS_TPU_DISABLE_PALLAS"] = "1"
        else:
            os.environ.pop("DS_TPU_DISABLE_PALLAS", None)
        groups.reset()
        params = model.init(jax.random.PRNGKey(0), data)["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": batch,
                    "gradient_accumulation_steps": 1,
                    "bf16": {"enabled": True},
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                    "zero_optimization": {"stage": 1},
                    "activation_checkpointing": {"policy": "dots"}})

        def step():
            loss = engine(data)
            engine.backward(loss)
            engine.step()
            return loss

        jax.block_until_ready(step())
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = step()
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / args.steps
        toks = batch * seq / dt
        fpt = llama_flops_per_token(cfg, seq)
        kind = jax.devices()[0].device_kind
        from bench import peak_flops   # repo-root bench.py: one peak table
        peak = peak_flops(kind)
        print(json.dumps({
            "metric": f"llama_{args.hidden}h{args.layers}L_seq{seq}"
                      f"_{'xla' if disable_pallas else 'flash'}",
            "value": round(toks, 1), "unit": "tokens/s/chip",
            "vs_baseline": round(toks * fpt / peak / 0.45, 4),
            "extra": {"ms_per_step": round(dt * 1000, 1),
                      "mfu": round(toks * fpt / peak, 4)}}), flush=True)

    for seq in [int(s) for s in args.seqs.split(",")]:
        run(seq, disable_pallas=False)
        if args.xla_too:
            run(seq, disable_pallas=True)


if __name__ == "__main__":
    main()
