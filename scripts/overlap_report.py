"""Overlap & critical-path report: exposed-comm attribution from a device
trace, or chip-free from the analytic cost model.

Two modes, one payload shape:

**Trace mode** (stdlib-only — works on any machine with the trace files)::

    python scripts/overlap_report.py --trace /tmp/ds_tpu_trace
    python scripts/overlap_report.py --trace trace.json.gz --summary BENCH_x.json

ingests the trace-event JSON a ``jax.profiler`` capture (or our own
``telemetry.export_chrome_trace``) produced, reconstructs per-device op
timelines and attributes every collective's exposed seconds. ``--summary``
joins a bench payload's embedded telemetry ``comm`` table so collectives
the trace couldn't size carry bytes/wire bytes.

**Analytic mode** (chip-free, ``JAX_PLATFORMS=cpu`` + 8 forced host
devices — the repo's AOT-without-a-TPU pattern)::

    python scripts/overlap_report.py --analytic [--device-kind tpu_v5e]

traces (never executes) a small ZeRO-shaped step — all_gather the sharded
weights, matmul, reduce_scatter the grads, all_reduce the grad norm — so
the traced collectives land in comm telemetry with exact bytes and axes,
reads the compiled program's XLA cost analysis, and builds the schedule
XLA's synchronous collectives imply from ``autotuning/kernel_tuner.py``'s
roofline + link cost models: compute first, every collective serialized
after it, fully exposed. That worst-case exposure is the baseline the
future overlap-scheduling pass (ROADMAP item 2) ratchets against.

Prints the human table to stderr and ONE JSON payload line to stdout
(bench payload convention)::

    {"metric": "overlap_exposed_comm_s", "value": <s>, "unit": "s",
     "extra": {"overlap": <report>, "telemetry": <summary when enabled>}}

``scripts/perf_gate.py --dry-run`` shape-validates this payload and gates
``exposed_comm_s`` growth. See docs/OBSERVABILITY.md "Overlap".
"""

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def _load_comm_stats(summary_path):
    """The ``comm.ops`` table from a bench payload / summary JSON doc (the
    wire-byte join for trace mode). Accepts a raw summary, a bench payload
    with ``extra.telemetry``, or anything ``perf_gate.find_summary`` digs
    the summary out of."""
    with open(summary_path) as f:
        doc = json.load(f)
    for probe in (doc, doc.get("extra", {}).get("telemetry"),
                  doc.get("telemetry")):
        if isinstance(probe, dict) and isinstance(probe.get("comm"), dict):
            return probe["comm"].get("ops", {})
    return {}


def run_trace(args):
    from deepspeed_tpu.telemetry import overlap
    events = overlap.load_trace_events(args.trace)
    per_device = overlap.intervals_from_trace(events)
    if not per_device:
        print(f"no device duration events in {args.trace}", file=sys.stderr)
        return None
    comm_stats = _load_comm_stats(args.summary) if args.summary else None
    return overlap.overlap_report(per_device, mode="trace",
                                  comm_stats=comm_stats, top_k=args.top_k)


def run_analytic(args):
    # force a CPU host mesh BEFORE jax import — trace + AOT only, never run
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from deepspeed_tpu import telemetry
    from deepspeed_tpu.comm import comm
    from deepspeed_tpu.telemetry import overlap

    ndev = min(len(jax.devices()), 8)
    telemetry.configure(enabled=True, sample_sync=False)
    mesh = Mesh(np.array(jax.devices()[:ndev]), ("dp",))

    B, D, F = args.batch, args.hidden, args.ffn

    def zero_step(x, w_shard, g_full):
        # ZeRO shape: gather sharded weights, compute, scatter grads,
        # all-reduce the scalar grad norm — the collective mix a real
        # stage-3 micro step issues
        w = comm.all_gather(w_shard, axis_name="dp", axis=0)
        y = jnp.tanh(x @ w)
        g = comm.reduce_scatter(g_full, axis_name="dp", scatter_dim=0)
        gn = comm.all_reduce(jnp.sum(g * g) + jnp.sum(y) * 0.0,
                             axis_name="dp")
        return y, g, gn

    fn = jax.shard_map(zero_step, mesh=mesh,
                       in_specs=(P(), P("dp"), P()),
                       out_specs=(P(), P("dp"), P()), check_vma=False)
    x = jax.ShapeDtypeStruct((B, D), jnp.float32)
    w_shard = jax.ShapeDtypeStruct((D, F), jnp.float32)  # P("dp") shards dim 0
    g_full = jax.ShapeDtypeStruct((D, F), jnp.float32)

    lowered = jax.jit(fn).lower(x, w_shard, g_full)  # traced record_comm
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})

    comm_ops = []
    ops = telemetry.summary().get("comm", {}).get("ops", {})
    for op, per_axis in sorted(ops.items()):
        for axis, st in sorted(per_axis.items()):
            comm_ops.append({"op": op, "axis": axis, "bytes": st["bytes"],
                             "wire_bytes": st["wire_bytes"],
                             "count": st["count"]})
    report = overlap.analytic_report(
        dict(ca), comm_ops, device_kind=args.device_kind,
        axis_sizes={"dp": ndev}, top_k=args.top_k)

    if args.schedule:
        # scheduled analytic mode: run the overlap pass's two-resource
        # timeline over the SAME inventory; the serialized report's advice
        # seeds the planner when depth/buckets aren't pinned on the CLI
        from deepspeed_tpu.runtime.zero import overlap_schedule as osched
        specs = osched.fill_comm_seconds(comm_ops,
                                         device_kind=args.device_kind,
                                         axis_sizes={"dp": ndev})
        if args.prefetch_depth is None or args.grad_buckets is None:
            plan, _, _ = osched.best_plan(report["compute_s"], specs,
                                          hints=report.get("advice"),
                                          n_layers=args.layers)
            if args.prefetch_depth is not None:
                plan.prefetch_depth = args.prefetch_depth
            if args.grad_buckets is not None:
                plan.grad_buckets = args.grad_buckets
        else:
            plan = osched.OverlapPlan(prefetch_depth=args.prefetch_depth,
                                      grad_buckets=args.grad_buckets,
                                      n_layers=args.layers)
        report = osched.scheduled_report(dict(ca), comm_ops, plan,
                                         device_kind=args.device_kind,
                                         axis_sizes={"dp": ndev},
                                         top_k=args.top_k)
    telemetry.attach_overlap(report)
    return report


def emit_profile(report, args):
    """Fold the report's sized collectives into the persisted per-op profile
    store (telemetry/profile_store.py): per-call seconds = total_s / count,
    bucketed by per-call payload bytes. Returns a small provenance dict for
    the payload, or None when nothing was emitted."""
    from deepspeed_tpu.telemetry import profile_store

    entries = {}
    for c in report.get("collectives", []):
        count = max(int(c.get("count", 1) or 1), 1)
        total_s = float(c.get("total_s", 0.0) or 0.0)
        if total_s <= 0:
            continue
        per_call_s = total_s / count
        per_call_b = int(c.get("bytes", 0) or 0) // count
        key = profile_store.bucket_key(c["op"], per_call_b)
        prev = entries.get(key)
        if prev is not None and prev["count"] >= count:
            continue  # keep the better-sampled measurement per bucket
        entries[key] = profile_store.make_entry(
            per_call_s, per_call_b, args.profile_source, count=count,
            extra={"axis": c.get("axis")})
    if not entries:
        print("emit-profile: no sized collectives to record", file=sys.stderr)
        return None

    device = profile_store.default_device_kind()
    path = (args.emit_profile
            or os.environ.get("DS_TPU_PROFILE_STORE", "")
            or profile_store.store_path(device))
    mode = "--trace" if args.trace else "--analytic"
    doc = profile_store.merge_store(
        path, device, entries,
        generated_by=f"scripts/overlap_report.py {mode} --emit-profile")
    print(f"emit-profile: {len(entries)} entr"
          f"{'y' if len(entries) == 1 else 'ies'} -> {path} "
          f"(device {doc['device_kind']}, source {args.profile_source})",
          file=sys.stderr)
    return {"path": path, "device_kind": doc["device_kind"],
            "source": args.profile_source,
            "entries": len(doc["entries"]),
            "keys": sorted(entries)}


def main():
    ap = argparse.ArgumentParser(
        description="compute/comm overlap exposure report")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--trace",
                     help="trace-event .json/.json.gz file or jax.profiler "
                          "output directory")
    src.add_argument("--analytic", action="store_true",
                     help="chip-free analytic schedule (CPU, AOT only)")
    ap.add_argument("--summary",
                    help="bench payload / summary JSON to join comm wire "
                         "bytes (trace mode)")
    ap.add_argument("--device-kind", default="tpu_v5e",
                    help="cost-model chip for --analytic")
    ap.add_argument("--top-k", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--ffn", type=int, default=1024)
    ap.add_argument("--schedule", action="store_true",
                    help="analytic mode: score the overlap pass's scheduled "
                         "timeline (runtime/zero/overlap_schedule.py) "
                         "instead of the serialized worst case; the payload "
                         "carries the serialized baseline in "
                         "extra.overlap.schedule")
    ap.add_argument("--prefetch-depth", type=int, default=None,
                    help="pin the schedule's prefetch depth (default: "
                         "planner sweep seeded by the advisor hints)")
    ap.add_argument("--grad-buckets", type=int, default=None,
                    help="pin the schedule's grad bucket count (default: "
                         "planner sweep)")
    ap.add_argument("--layers", type=int, default=8,
                    help="layer count the scheduled timeline pipelines over")
    ap.add_argument("--advise", action="store_true",
                    help="print the top-K actionable prefetch hints with "
                         "their potential_saving_s")
    ap.add_argument("--emit-profile", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="merge the report's measured per-op seconds into "
                         "the profile store (telemetry/profile_store.py); "
                         "PATH overrides the default "
                         "onchip_results/profile_<device>.json "
                         "(DS_TPU_PROFILE_STORE / "
                         "DS_TPU_PROFILE_STORE_DEVICE honoured)")
    ap.add_argument("--profile-source", default="trace_cpu",
                    choices=["trace_cpu", "trace_tpu", "onchip", "manual"],
                    help="provenance tag for --emit-profile entries")
    args = ap.parse_args()

    if args.analytic:
        report = run_analytic(args)
    else:
        report = run_trace(args)
    if report is None:
        return 1

    from deepspeed_tpu.telemetry import overlap
    errs = overlap.validate_report(report)
    if errs:
        print("malformed report: " + "; ".join(errs), file=sys.stderr)
        return 1

    print(overlap.format_report(report, top_k=args.top_k), file=sys.stderr)
    if args.advise:
        hints = (report.get("advice") or [])[:args.top_k]
        print(f"advisor hints (top {len(hints)}):", file=sys.stderr)
        for h in hints:
            print(f"  {h['hint']}  "
                  f"potential_saving_s={h['potential_saving_s']}",
                  file=sys.stderr)
        if not hints:
            print("  (none — nothing exposed next to independent compute)",
                  file=sys.stderr)
    extra = {"overlap": report}
    if args.emit_profile is not None:
        emitted = emit_profile(report, args)
        if emitted is not None:
            extra["profile_store"] = emitted
    if args.analytic:
        from deepspeed_tpu import telemetry
        if telemetry.enabled():
            extra["telemetry"] = telemetry.summary()
    payload = {"metric": "overlap_exposed_comm_s",
               "value": report["exposed_comm_s"], "unit": "s",
               "extra": extra}
    print(json.dumps(payload))
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
