"""Automated perf gate: fail loudly on throughput/MFU/HBM/compile/serving
regression.

Compares a CANDIDATE measurement (a ``BENCH_*.json`` payload, a
``telemetry.summary()`` dict, or a ``BASELINE.json``-style doc) against a
BASELINE of any of the same shapes, with configurable relative thresholds:

    python scripts/perf_gate.py --baseline BASELINE.json \
        --candidate BENCH_r07.json \
        --max-tokens-drop 0.10 --max-mfu-drop 0.10 \
        --max-hbm-growth 0.10 --max-compile-growth 0.50

Serving-path metrics (``bench_serving.py --replay`` payloads or a summary's
``serving`` section) gate the latency direction: TTFT/TPOT p50+p99 and peak
KV-block occupancy regress when they GROW (``--max-ttft-growth``,
``--max-tpot-growth``, ``--max-kv-occupancy-growth``). Overlap reports
(``summary()["overlap"]`` / ``scripts/overlap_report.py`` payloads) gate
exposed-comm seconds the same way (``--max-exposed-growth``) and are
shape-validated on every input (finite, exposure <= comm total, fractions
in [0, 1]).

Only metrics present on BOTH sides are compared (an empty baseline —
``BASELINE.json`` before any published number — passes with a warning, so
the gate can be wired into CI before the first on-hardware run). Exit codes:

    0  pass (no compared metric regressed beyond its threshold)
    2  malformed input (unreadable file, schema violation, no JSON)
    3  regression (at least one metric beyond threshold)

``--dry-run`` validates inputs only — parses both docs, validates any
embedded telemetry summary against ``telemetry/summary.schema.json``, and
schema-checks the checked-in kernel tuning tables
(``deepspeed_tpu/autotuning/tables/``: valid per
``kernel_table.validate_table`` AND covering every ``BENCH_SHAPES`` bucket)
and drives the overlap analyzer jax-free over a fixed analytic schedule
(``check_overlap_analytic``), and re-derives the checked-in scheduled
overlap baseline (``onchip_results/overlap_analytic_baseline.json``)
jax-free, requiring the scheduled exposed seconds to reproduce and to sit
>= 30% below its serialized worst case (``check_overlap_schedule``), and
validates the checked-in shared-prefix replay baseline
(``onchip_results/serving_prefix_baseline.json``): prefix-mix payload shape
(hit rate in [0, 1], tokens saved <= prompt tokens, finite percentiles) plus
the acceptance ratchet — >= 40% prefill-token reduction, hit rate > 0.5,
cached TTFT p50 no worse than the cache-off leg (``check_prefix_baseline``)
— and validates the checked-in disaggregated fleet replay baseline
(``onchip_results/serving_fleet_baseline.json``): payload shape (finite
ordered percentiles for both legs, shed rate in [0, 1], every shipped KV
page bound) plus the fleet acceptance ratchet — saturation-rate multiplier
>= 2x the single replica, shed rate <= 0.1, at least one real handoff,
fleet TTFT p99 no worse than the saturated single replica
(``check_fleet_baseline``) — and validates the checked-in KV-fabric
baseline (``onchip_results/serving_kvfabric_baseline.json``): serialized
wire bytes per page <= 0.3x the fp32 device bytes they replace, the delta
leg shipping measurably fewer bytes than the no-delta leg, zero CRC
failures, every leg bit-exact against the monolithic reference, and a
two-process leg (decode in a separate OS process) that completed every
request (``check_kvfabric_baseline``) — and validates the checked-in
long-context KV
tiering baseline (``onchip_results/serving_longctx_baseline.json``):
payload shape (finite ordered percentiles, host occupancy in [0, 1], the
swap accounting identity ``swapped_out == swapped_in + swap_dropped +
resident_host_blocks``) plus the tiering acceptance ratchet — int8
capacity multiplier >= 2x at the fp leg's KV HBM budget, at least one
spill and one restore recorded, zero live swap-outs, a positive prefill
reduction across the spill/restore round trip (``check_longctx_baseline``;
stall growth between runs gates via ``--max-swap-stall-growth``) — and
validates the checked-in speculative-decode baseline
(``onchip_results/serving_speculate_baseline.json``): payload shape
(accept rate and verify-batch occupancy in [0, 1], the speculation counter
identity ``speculated == accepted + rejected``, a boolean parity flag)
plus the acceptance ratchet — tokens/s multiplier >= 1.5x plain decode on
the template-heavy greedy replay, greedy parity True (the bit-exactness
oracle), at least one token drafted and accepted
(``check_speculate_baseline``) — and
validates the checked-in elastic-reshard drill baseline
(``onchip_results/elastic_drill_baseline.json``): world sequence 8→4→8,
zero steps lost or double-applied, bitwise-equal restore-step losses, and
each reshard leg under the wall-clock ceiling
(``check_elastic_baseline``) — and traces the MoE hierarchical expert
all-to-all on 8 forced-host CPU devices requiring the quantized DCN leg's
wire bytes <= 0.5x fp32 with the ICI leg full precision
(``check_moe_wire``), and re-derives the checked-in MoE scheduled overlap
baseline (``onchip_results/moe_overlap_baseline.json``) jax-free,
requiring the chunked a2a/expert pipeline's exposed seconds to reproduce
and to sit >= 30% below its serialized worst case
(``check_moe_baseline``) — and validates every checked-in measured-cost
profile store (``onchip_results/profile_*.json``: schema via
``profile_store.validate_store`` plus a resolver round trip requiring the
``measured`` reason code, ``check_profile_store``) — and validates the
checked-in SLO replay baseline
(``onchip_results/serving_slo_baseline.json``): per-class attainment
arithmetic (``attained + violations == requests``), worst per-class
attainment >= 0.9, and >= 3 live time-series rings embedded
(``check_slo_baseline``; live runs gate via ``--min-slo-attainment``, and
every input doc's ``timeseries``/``slo_classes`` sections are
shape-validated) — then exits 0/2 without comparing. The tier-1 lane runs ``--dry-run`` against
the repo's own BASELINE.json so a malformed baseline, summary, or tuning
table fails fast on CPU (docs/OBSERVABILITY.md).
"""

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA_PATH = os.path.join(REPO_ROOT, "deepspeed_tpu", "telemetry",
                           "summary.schema.json")

#: metric -> (direction, threshold flag); "down" = lower candidate is a
#: regression, "up" = higher candidate is a regression
GATES = {
    "tokens_per_sec": ("down", "max_tokens_drop"),
    "mfu": ("down", "max_mfu_drop"),
    "goodput": ("down", "max_goodput_drop"),
    "peak_hbm_bytes": ("up", "max_hbm_growth"),
    "compile_seconds": ("up", "max_compile_growth"),
    # serving latency (bench_serving --replay / summary["serving"]): higher
    # is a regression
    "ttft_p50_s": ("up", "max_ttft_growth"),
    "ttft_p99_s": ("up", "max_ttft_growth"),
    "tpot_p50_s": ("up", "max_tpot_growth"),
    "tpot_p99_s": ("up", "max_tpot_growth"),
    "peak_kv_occupancy": ("up", "max_kv_occupancy_growth"),
    # overlap report (telemetry/overlap.py): exposed-comm seconds growing
    # means the schedule got worse at hiding collectives
    "exposed_comm_s": ("up", "max_exposed_growth"),
    # prefix-cache effectiveness (bench_serving --replay --prefix-mix):
    # the hit rate or the prefill-token reduction shrinking means prompt
    # reuse got worse
    "prefix_hit_rate": ("down", "max_prefix_hit_drop"),
    "prefill_reduction": ("down", "max_prefix_hit_drop"),
    # fleet replay (bench_serving --fleet --replay): the saturation-rate
    # multiplier over the monolithic single replica shrinking means the
    # disaggregation dividend regressed
    "rate_multiplier": ("down", "max_rate_multiplier_drop"),
    # long-context tiering (bench_serving --long-context): total seconds
    # stalled restoring spilled KV blocks from host DRAM growing means the
    # swap path got slower (or restores stopped overlapping decode)
    "swap_in_stall_s": ("up", "max_swap_stall_growth"),
    # chaos replay (bench_serving --chaos --diurnal): completed tokens per
    # live-replica-second UNDER FAULTS shrinking means recovery or the
    # autoscaler got more wasteful (re-prefill churn, idle over-provision)
    "goodput_tokens_per_replica_sec": ("down", "max_goodput_drop"),
}

#: extra/doc keys lifted verbatim into the metric dict when positive
SERVING_KEYS = ("ttft_p50_s", "ttft_p99_s", "tpot_p50_s", "tpot_p99_s",
                "peak_kv_occupancy")

#: prefix-mix payload keys (bench_serving --replay --prefix-mix); lifted and
#: validated only when present — plain replay payloads don't carry them
PREFIX_KEYS = ("prefix_hit_rate", "prefill_reduction")

#: fleet replay payload keys (bench_serving --fleet --replay); lifted only
#: when present (the rate multiplier rides the fleet payload's extra)
FLEET_KEYS = ("rate_multiplier",)

#: long-context tiering payload keys (bench_serving --long-context); lifted
#: only when present
LONGCTX_KEYS = ("swap_in_stall_s",)

#: chaos replay payload keys (bench_serving --chaos --diurnal); lifted only
#: when present
CHAOS_KEYS = ("goodput_tokens_per_replica_sec",)


def load_doc(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"perf_gate: cannot read {path}: {e}", file=sys.stderr)
        return None


def find_summary(doc):
    """Locate an embedded telemetry summary in any accepted doc shape."""
    if not isinstance(doc, dict):
        return None
    if "enabled" in doc and ("spans" in doc or doc.get("enabled") is False):
        return doc  # the doc IS a summary
    extra = doc.get("extra")
    if isinstance(extra, dict) and isinstance(extra.get("telemetry"), dict):
        return extra["telemetry"]
    if isinstance(doc.get("telemetry"), dict):
        return doc["telemetry"]
    return None


def extract_metrics(doc):
    """Comparable metrics from any accepted doc shape. Absent metrics are
    simply not compared."""
    m = {}
    if not isinstance(doc, dict):
        return m
    # bench payload: {"metric": "...tokens_per_sec...", "value": N, "extra": {}}
    # (overlap payloads carry exposed SECONDS as value — lower is better,
    # the opposite gate direction, so never lift them as throughput)
    if "value" in doc and "metric" in doc and \
            "overlap" not in str(doc.get("metric", "")):
        try:
            v = float(doc["value"])
            if v > 0:
                m["tokens_per_sec"] = v
        except (TypeError, ValueError):
            pass
    extra = doc.get("extra") if isinstance(doc.get("extra"), dict) else {}
    for src in (extra, doc):
        if "mfu" in src and "mfu" not in m:
            try:
                v = float(src["mfu"])
                if v > 0:
                    m["mfu"] = v
            except (TypeError, ValueError):
                pass
        if "peak_hbm_bytes" in src and "peak_hbm_bytes" not in m:
            try:
                v = int(src["peak_hbm_bytes"])
                if v > 0:
                    m["peak_hbm_bytes"] = v
            except (TypeError, ValueError):
                pass
        for key in SERVING_KEYS + PREFIX_KEYS + FLEET_KEYS + LONGCTX_KEYS \
                + CHAOS_KEYS:
            if key in src and key not in m:
                try:
                    v = float(src[key])
                    if v > 0:
                        m[key] = v
                except (TypeError, ValueError):
                    pass
    # BASELINE.json: {"published": {metric: value, ...}}
    pub = doc.get("published")
    if isinstance(pub, dict):
        for key, val in pub.items():
            try:
                val = float(val)
            except (TypeError, ValueError):
                continue
            for gate in GATES:
                if gate in key and gate not in m and val > 0:
                    m[gate] = val
    # telemetry summary (bare or embedded)
    s = find_summary(doc)
    if isinstance(s, dict) and s.get("enabled"):
        led = s.get("ledger", {})
        for key in ("mfu_rolling", "mfu"):
            if led.get(key) and "mfu" not in m:
                m["mfu"] = float(led[key])
                break
        if led.get("goodput") and "goodput" not in m:
            m["goodput"] = float(led["goodput"])
        mem = s.get("memory", {})
        if mem.get("peak_bytes") and "peak_hbm_bytes" not in m:
            m["peak_hbm_bytes"] = int(mem["peak_bytes"])
        progs = s.get("compile", {}).get("programs", {})
        total = sum(p.get("seconds", 0.0) for p in progs.values()
                    if isinstance(p, dict))
        if total > 0 and "compile_seconds" not in m:
            m["compile_seconds"] = total
        # serving stream: TTFT/TPOT percentiles + peak KV occupancy
        srv = s.get("serving", {})
        hists = srv.get("histograms", {}) if isinstance(srv, dict) else {}
        for hist_name, prefix in (("serving/ttft_s", "ttft"),
                                  ("serving/tpot_s", "tpot")):
            h = hists.get(hist_name)
            if isinstance(h, dict) and h.get("count"):
                for q in ("p50_s", "p99_s"):
                    key = f"{prefix}_{q}"
                    if key not in m and h.get(q, 0) > 0:
                        m[key] = float(h[q])
        g = srv.get("gauges", {}).get("serving/kv_occupancy") \
            if isinstance(srv, dict) else None
        if isinstance(g, dict) and g.get("peak", 0) > 0 and \
                "peak_kv_occupancy" not in m:
            m["peak_kv_occupancy"] = float(g["peak"])
    # overlap report: summary["overlap"] or a payload's extra["overlap"]
    for src in (find_summary(doc) or {}, extra, doc):
        ov = src.get("overlap") if isinstance(src, dict) else None
        if isinstance(ov, dict) and "exposed_comm_s" not in m:
            try:
                v = float(ov["exposed_comm_s"])
            except (KeyError, TypeError, ValueError):
                continue
            if v > 0:
                m["exposed_comm_s"] = v
    return m


def check_kernel_tables(tables_dir=None):
    """Validate every checked-in kernel tuning table (schema via
    ``kernel_table.validate_table``) and require the default-device table to
    cover all ``BENCH_SHAPES`` bucket keys. Returns (report, errors).

    ``kernel_table`` is loaded standalone (it is stdlib-only at module
    scope), so this check runs in the tier-1 dry-run lane without jax."""
    import importlib.util
    mod_path = os.path.join(REPO_ROOT, "deepspeed_tpu", "autotuning",
                            "kernel_table.py")
    spec = importlib.util.spec_from_file_location("_kernel_table", mod_path)
    kt = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(kt)

    tables_dir = tables_dir or kt.TABLES_DIR
    errors = []
    report = {"tables": {}, "bench_coverage": {}}
    try:
        names = sorted(n for n in os.listdir(tables_dir)
                       if n.endswith(".json"))
    except OSError as e:
        return report, [f"kernel tables dir unreadable: {e}"]
    if not names:
        errors.append(f"no kernel tuning tables under {tables_dir}")
    for name in names:
        path = os.path.join(tables_dir, name)
        doc = load_doc(path)
        if doc is None:
            errors.append(f"{name}: unreadable")
            continue
        errs = kt.validate_table(doc)
        report["tables"][name] = {"entries": len(doc.get("entries", {})),
                                  "errors": errs}
        errors.extend(f"{name}: {e}" for e in errs)
        if not errs:
            # bench-shape coverage: every shape the bench/AOT lanes run must
            # resolve as "tuned" on this device's table
            missing = []
            for kernel, shapes in kt.BENCH_SHAPES.items():
                for dims, dtype in shapes:
                    key = kt.bucket_key(kernel, dims, dtype)
                    if key not in doc["entries"]:
                        missing.append(key)
            report["bench_coverage"][name] = {
                "covered": not missing, "missing": missing}
            if missing:
                errors.append(f"{name}: bench shapes uncovered: {missing}")
    return report, errors


#: qgZ acceptance: wire bytes of the quantized DCN exchange relative to the
#: fp32 reduce-scatter path (ZeRO++: int8 + fp32 group scales ≈ 0.25)
QGZ_WIRE_MAX_RATIO = 0.3


def check_qgz_wire():
    """Trace (compile nothing, execute nothing) the qgZ hierarchical
    exchange on 8 forced-host CPU devices and require the DCN (``dpr``) leg's
    wire bytes <= ``QGZ_WIRE_MAX_RATIO`` x the logical fp32 bytes. The
    quantized collectives record ``wire_bytes`` comm telemetry at trace
    time, so ``jit(...).lower`` is enough — no TPU, no execution.

    Returns (report, errors); skipped without error when jax is missing or
    the host cannot present 8 devices (the dry-run lane must stay runnable
    on minimal CI hosts)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is baked into the image
        return {"skipped": f"jax unavailable: {e}"}, []
    if len(jax.devices()) < 8:
        return {"skipped": f"needs 8 devices, have {len(jax.devices())}"}, []
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        all_to_all_quant_reduce)

    telemetry.configure(enabled=True, sample_sync=False)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dpr", "dp"))
    grad = jax.ShapeDtypeStruct((8, 8192), jnp.float32)
    fn = jax.shard_map(
        lambda g: all_to_all_quant_reduce(g, intra_axis="dp",
                                          inter_axis="dpr"),
        mesh=mesh, in_specs=P(), out_specs=P(("dpr", "dp")),
        check_vma=False)
    jax.jit(fn).lower(grad)   # trace-time record_comm only

    ops = telemetry.summary().get("comm", {}).get("ops", {})
    report, errors = {}, []
    quant = ops.get("all_to_all_quant", {})
    if not quant:
        return report, ["qgz trace recorded no all_to_all_quant telemetry"]
    for axis, st in sorted(quant.items()):
        ratio = (st["wire_bytes"] / st["bytes"]) if st["bytes"] else 0.0
        report[axis] = {"bytes": st["bytes"],
                        "wire_bytes": st["wire_bytes"],
                        "ratio": round(ratio, 4)}
    dcn = report.get("dpr")
    if dcn is None:
        errors.append("qgz trace recorded no DCN (dpr) exchange")
    elif dcn["ratio"] > QGZ_WIRE_MAX_RATIO:
        errors.append(f"qgz DCN wire ratio {dcn['ratio']} > "
                      f"{QGZ_WIRE_MAX_RATIO}")
    return report, errors


#: MoE expert a2a acceptance: wire bytes of the quantized DCN dispatch/
#: combine leg relative to fp32 (int8 + fp32 group scales ≈ 0.26); the ICI
#: leg must stay full precision (payload-preserving token exchange)
MOE_WIRE_MAX_RATIO = 0.5


def check_moe_wire():
    """Trace (compile nothing, execute nothing) the hierarchical MoE expert
    all-to-all on 8 forced-host CPU devices and require the DCN (``dpr``)
    leg's wire bytes <= ``MOE_WIRE_MAX_RATIO`` x the logical fp32 bytes
    while the ICI (``ep``) leg stays full precision. Same trace-only idiom
    as :func:`check_qgz_wire` — the collectives record ``wire_bytes``
    telemetry at trace time under the "a2a_dispatch" op.

    Returns (report, errors); skipped without error when jax is missing or
    the host cannot present 8 devices."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is baked into the image
        return {"skipped": f"jax unavailable: {e}"}, []
    if len(jax.devices()) < 8:
        return {"skipped": f"needs 8 devices, have {len(jax.devices())}"}, []
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        moe_hierarchical_a2a)

    telemetry.configure(enabled=True, sample_sync=False)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dpr", "ep"))
    # [inter, intra, rows, d_model] per-peer token slabs
    tok = jax.ShapeDtypeStruct((4, 2, 16, 2048), jnp.float32)
    fn = jax.shard_map(
        lambda x: moe_hierarchical_a2a(x, intra_axis="ep", inter_axis="dpr",
                                       inter_bits=8),
        mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False)
    jax.jit(fn).lower(tok)   # trace-time record_comm only

    ops = telemetry.summary().get("comm", {}).get("ops", {})
    report, errors = {}, []
    a2a = ops.get("a2a_dispatch", {})
    if not a2a:
        return report, ["moe trace recorded no a2a_dispatch telemetry"]
    for axis, st in sorted(a2a.items()):
        ratio = (st["wire_bytes"] / st["bytes"]) if st["bytes"] else 0.0
        report[axis] = {"bytes": st["bytes"],
                        "wire_bytes": st["wire_bytes"],
                        "ratio": round(ratio, 4)}
    dcn = report.get("dpr")
    ici = report.get("ep")
    if dcn is None:
        errors.append("moe trace recorded no DCN (dpr) a2a leg")
    elif dcn["ratio"] > MOE_WIRE_MAX_RATIO:
        errors.append(f"moe DCN a2a wire ratio {dcn['ratio']} > "
                      f"{MOE_WIRE_MAX_RATIO}")
    if ici is None:
        errors.append("moe trace recorded no ICI (ep) a2a leg")
    elif ici["wire_bytes"] != ici["bytes"]:
        errors.append(
            f"moe ICI a2a leg is not full precision "
            f"(wire {ici['wire_bytes']} != logical {ici['bytes']}) — "
            "quantization belongs on the DCN leg only")
    return report, errors


def validate_summary(doc):
    """Schema-validate an embedded summary when jsonschema is available.
    Returns an error string or None."""
    s = find_summary(doc)
    if s is None:
        return None  # nothing embedded — nothing to validate
    try:
        import jsonschema
    except ImportError:
        return None
    try:
        with open(SCHEMA_PATH) as f:
            schema = json.load(f)
        jsonschema.validate(s, schema)
    except jsonschema.ValidationError as e:
        return f"summary schema violation: {e.message}"
    except (OSError, ValueError) as e:
        return f"cannot load schema {SCHEMA_PATH}: {e}"
    return None


def validate_serving_payload(doc):
    """Shape-check a bench_serving --replay payload: a SUCCESSFUL run (value
    > 0) must carry every serving metric, with finite ordered percentiles.
    Error payloads (value 0 + extra.error) pass untouched. Pure dict checks —
    runs in the tier-1 dry-run lane without jax or jsonschema. Returns an
    error string or None."""
    if not isinstance(doc, dict):
        return None
    if "serving_replay" not in str(doc.get("metric", "")):
        return None
    try:
        if float(doc.get("value", 0)) <= 0:
            return None
    except (TypeError, ValueError):
        return None
    extra = doc.get("extra")
    if not isinstance(extra, dict):
        return "serving replay payload has no extra dict"
    for key in SERVING_KEYS:
        v = extra.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return f"serving replay payload: extra[{key!r}] missing or " \
                   f"non-numeric (got {v!r})"
        if not (v == v and abs(v) != float("inf")):
            return f"serving replay payload: extra[{key!r}] not finite"
    for prefix in ("ttft", "tpot"):
        if extra[f"{prefix}_p50_s"] > extra[f"{prefix}_p99_s"]:
            return f"serving replay payload: {prefix} p50 > p99"
    if not 0.0 <= extra["peak_kv_occupancy"] <= 1.0:
        return "serving replay payload: peak_kv_occupancy outside [0, 1]"
    return _validate_prefix_fields(extra)


def _validate_prefix_fields(extra):
    """Shape-check the prefix-mix fields riding a replay payload's extra
    (present only for ``--prefix-mix`` runs): hit rate in [0, 1], saved and
    executed prefill tokens consistent with the prompt total, finite ordered
    nocache percentiles. Returns an error string or None."""
    if "prefix_hit_rate" not in extra:
        return None  # plain replay payload — nothing prefix to check
    def bad_num(v):
        return not isinstance(v, (int, float)) or isinstance(v, bool) or \
            not (v == v and abs(v) != float("inf"))
    for key in ("prefix_hit_rate", "prefill_tokens_saved",
                "executed_prefill_tokens", "executed_prefill_tokens_nocache",
                "prefill_reduction", "ttft_p50_nocache_s",
                "ttft_p99_nocache_s"):
        if bad_num(extra.get(key)):
            return f"prefix-mix payload: extra[{key!r}] missing or not finite"
    if not 0.0 <= extra["prefix_hit_rate"] <= 1.0:
        return "prefix-mix payload: prefix_hit_rate outside [0, 1]"
    prompt_total = extra.get("prompt_tokens_total")
    if isinstance(prompt_total, int) and prompt_total > 0:
        if extra["prefill_tokens_saved"] > prompt_total:
            return "prefix-mix payload: prefill_tokens_saved > prompt tokens"
        if extra["executed_prefill_tokens"] + extra["prefill_tokens_saved"] \
                > prompt_total:
            return "prefix-mix payload: executed + saved > prompt tokens"
    if not -1.0 <= extra["prefill_reduction"] <= 1.0:
        return "prefix-mix payload: prefill_reduction outside [-1, 1]"
    if extra["ttft_p50_nocache_s"] > extra["ttft_p99_nocache_s"]:
        return "prefix-mix payload: nocache ttft p50 > p99"
    return None


def validate_fleet_payload(doc):
    """Shape-check a bench_serving --fleet --replay payload: a SUCCESSFUL
    run (value > 0) must carry finite ordered percentiles for BOTH legs
    (fleet and the single-replica reference), a shed rate in [0, 1], a
    finite positive rate multiplier, and page conservation — every shipped
    KV page bound at a decode replica (a shipped-but-unbound page means the
    handoff protocol leaked). Pure dict checks — runs in the tier-1 dry-run
    lane without jax. Returns an error string or None."""
    if not isinstance(doc, dict):
        return None
    if "serving_fleet_replay" not in str(doc.get("metric", "")):
        return None
    try:
        if float(doc.get("value", 0)) <= 0:
            return None
    except (TypeError, ValueError):
        return None
    extra = doc.get("extra")
    if not isinstance(extra, dict):
        return "fleet replay payload has no extra dict"
    def bad_num(v):
        return not isinstance(v, (int, float)) or isinstance(v, bool) or \
            not (v == v and abs(v) != float("inf"))
    for key in ("ttft_p50_s", "ttft_p99_s", "tpot_p50_s", "tpot_p99_s",
                "single_ttft_p50_s", "single_ttft_p99_s", "rate_multiplier",
                "shed_rate", "requests_per_sec", "single_requests_per_sec",
                "handoffs", "pages_shipped", "pages_bound"):
        if bad_num(extra.get(key)):
            return f"fleet replay payload: extra[{key!r}] missing or " \
                   f"not finite (got {extra.get(key)!r})"
    for prefix in ("ttft", "tpot", "single_ttft"):
        if extra[f"{prefix}_p50_s"] > extra[f"{prefix}_p99_s"]:
            return f"fleet replay payload: {prefix} p50 > p99"
    if not 0.0 <= extra["shed_rate"] <= 1.0:
        return "fleet replay payload: shed_rate outside [0, 1]"
    if extra["rate_multiplier"] <= 0:
        return "fleet replay payload: rate_multiplier not positive"
    if extra["pages_shipped"] != extra["pages_bound"]:
        return (f"fleet replay payload: pages_shipped "
                f"{extra['pages_shipped']} != pages_bound "
                f"{extra['pages_bound']} — KV handoff leaked pages")
    if extra["handoffs"] < 0:
        return "fleet replay payload: negative handoff count"
    return None


def validate_kvfabric_payload(doc):
    """Shape-check a bench_serving --fleet --two-process payload: a
    SUCCESSFUL run (value > 0) must carry a wire-to-fp32 ratio in (0, 1),
    finite byte/page accounting for the no-delta and delta legs, a
    two_process sub-dict with its own fabric counters, and parity booleans
    for every leg. Pure dict checks — runs in the tier-1 dry-run lane
    without jax. Returns an error string or None."""
    if not isinstance(doc, dict):
        return None
    if "serving_kvfabric" not in str(doc.get("metric", "")):
        return None
    try:
        if float(doc.get("value", 0)) <= 0:
            return None
    except (TypeError, ValueError):
        return None
    extra = doc.get("extra")
    if not isinstance(extra, dict):
        return "kvfabric payload has no extra dict"

    def bad_num(v):
        return not isinstance(v, (int, float)) or isinstance(v, bool) or \
            not (v == v and abs(v) != float("inf"))
    for key in ("wire_fp32_ratio", "wire_page_bytes", "fp32_page_bytes",
                "nodelta_wire_bytes", "delta_wire_bytes", "wire_bytes_saved",
                "pages_shipped", "pages_delta_skipped", "crc_failures",
                "failed_handoffs", "handoffs"):
        if bad_num(extra.get(key)):
            return f"kvfabric payload: extra[{key!r}] missing or not " \
                   f"finite (got {extra.get(key)!r})"
    if not 0.0 < extra["wire_fp32_ratio"] < 1.0:
        return "kvfabric payload: wire_fp32_ratio outside (0, 1)"
    if extra["wire_page_bytes"] * extra["fp32_page_bytes"] <= 0:
        return "kvfabric payload: non-positive page byte costs"
    for key in ("parity_nodelta", "parity_delta"):
        if not isinstance(extra.get(key), bool):
            return f"kvfabric payload: extra[{key!r}] missing or not a bool"
    tp = extra.get("two_process")
    if not isinstance(tp, dict):
        return "kvfabric payload has no two_process leg"
    for key in ("handoffs", "transfers", "pages_shipped",
                "wire_bytes_shipped", "crc_naks", "fallbacks",
                "lost_requests"):
        if bad_num(tp.get(key)):
            return f"kvfabric payload: two_process[{key!r}] missing or " \
                   f"not finite (got {tp.get(key)!r})"
    if not isinstance(tp.get("parity"), bool):
        return "kvfabric payload: two_process['parity'] missing or " \
               "not a bool"
    return None


def validate_chaos_payload(doc):
    """Shape-check a bench_serving --chaos payload: a SUCCESSFUL run
    (value > 0) must carry finite recovery/elasticity accounting (losses,
    re-admissions, leaks, scale actions), ordered latency percentiles, a
    shed rate in [0, 1], non-negative per-class sheds, and the router's
    accounting identity — every submit admitted, rejected, or queued, with
    zero in-flight backlog after the drain (anything else means a terminal
    outcome failed to retire). Pure dict checks — runs in the tier-1
    dry-run lane without jax. Returns an error string or None."""
    if not isinstance(doc, dict):
        return None
    if "serving_chaos" not in str(doc.get("metric", "")):
        return None
    try:
        if float(doc.get("value", 0)) <= 0:
            return None
    except (TypeError, ValueError):
        return None
    extra = doc.get("extra")
    if not isinstance(extra, dict):
        return "chaos payload has no extra dict"
    def bad_num(v):
        return not isinstance(v, (int, float)) or isinstance(v, bool) or \
            not (v == v and abs(v) != float("inf"))
    for key in ("goodput_tokens_per_replica_sec", "wall_s",
                "replica_seconds", "replica_losses", "readmitted",
                "leaked_pages", "scale_ups", "scale_downs",
                "interactive_sheds", "shed_rate", "fault_trips",
                "requests_lost", "ttft_p50_s", "ttft_p99_s",
                "tpot_p50_s", "tpot_p99_s"):
        if bad_num(extra.get(key)):
            return f"chaos payload: extra[{key!r}] missing or not finite " \
                   f"(got {extra.get(key)!r})"
    for prefix in ("ttft", "tpot"):
        if extra[f"{prefix}_p50_s"] > extra[f"{prefix}_p99_s"]:
            return f"chaos payload: {prefix} p50 > p99"
    if not 0.0 <= extra["shed_rate"] <= 1.0:
        return "chaos payload: shed_rate outside [0, 1]"
    for key in ("replica_losses", "readmitted", "leaked_pages", "scale_ups",
                "scale_downs", "interactive_sheds", "requests_lost"):
        if extra[key] < 0:
            return f"chaos payload: negative {key}"
    if extra["replica_seconds"] < extra["wall_s"]:
        return ("chaos payload: replica_seconds below wall_s — the "
                "live-replica integral cannot undercount a 1-replica fleet")
    shed = extra.get("shed_by_class")
    if not isinstance(shed, dict) or \
            any(bad_num(v) or v < 0 for v in shed.values()):
        return "chaos payload: shed_by_class missing or malformed"
    acct = extra.get("accounting")
    if not isinstance(acct, dict) or \
            bad_num(acct.get("in_flight")) or bad_num(
                acct.get("backlog_total")):
        return "chaos payload: accounting section missing or malformed"
    if acct.get("identity_holds") is not True:
        return ("chaos payload: router accounting identity does not hold "
                "(admitted + rejected + queued != submitted)")
    if acct["in_flight"] != 0 or acct["backlog_total"] != 0:
        return ("chaos payload: drained run left phantom backlog "
                f"(in_flight={acct['in_flight']}, "
                f"backlog_total={acct['backlog_total']}) — some terminal "
                "outcome never retired from the router")
    return None


def validate_longctx_payload(doc):
    """Shape-check a bench_serving --long-context payload: a SUCCESSFUL run
    (value > 0) must carry finite ordered latency percentiles, a host-tier
    occupancy in [0, 1], non-negative stall seconds, and the swap
    accounting identity — every block swapped out is either swapped back
    in, explicitly dropped (host tier full), or still resident on host
    (``swapped_out == swapped_in + swap_dropped + resident_host_blocks``; a
    mismatch means the spill path leaked or resurrected blocks). Pure dict
    checks — runs in the tier-1 dry-run lane without jax. Returns an error
    string or None."""
    if not isinstance(doc, dict):
        return None
    if "serving_longctx" not in str(doc.get("metric", "")):
        return None
    try:
        if float(doc.get("value", 0)) <= 0:
            return None
    except (TypeError, ValueError):
        return None
    extra = doc.get("extra")
    if not isinstance(extra, dict):
        return "long-context payload has no extra dict"
    def bad_num(v):
        return not isinstance(v, (int, float)) or isinstance(v, bool) or \
            not (v == v and abs(v) != float("inf"))
    for key in ("ttft_p50_s", "ttft_p99_s", "tpot_p50_s", "tpot_p99_s",
                "swap_in_stall_s", "swap_out_stall_s", "host_kv_occupancy",
                "swapped_out", "swapped_in", "swap_dropped",
                "resident_host_blocks", "swap_outs_live",
                "capacity_multiplier", "concurrent_sequences_per_chip",
                "concurrent_sequences_per_chip_fp", "prefill_reduction"):
        if bad_num(extra.get(key)):
            return f"long-context payload: extra[{key!r}] missing or " \
                   f"not finite (got {extra.get(key)!r})"
    for prefix in ("ttft", "tpot"):
        if extra[f"{prefix}_p50_s"] > extra[f"{prefix}_p99_s"]:
            return f"long-context payload: {prefix} p50 > p99"
    if not 0.0 <= extra["host_kv_occupancy"] <= 1.0:
        return "long-context payload: host_kv_occupancy outside [0, 1]"
    for key in ("swap_in_stall_s", "swap_out_stall_s", "swapped_out",
                "swapped_in", "swap_dropped", "resident_host_blocks"):
        if extra[key] < 0:
            return f"long-context payload: extra[{key!r}] negative"
    if extra["swapped_out"] != extra["swapped_in"] + extra["swap_dropped"] \
            + extra["resident_host_blocks"]:
        return (f"long-context payload: swapped_out {extra['swapped_out']} "
                f"!= swapped_in {extra['swapped_in']} + dropped "
                f"{extra['swap_dropped']} + resident "
                f"{extra['resident_host_blocks']} — the host tier leaked "
                f"or resurrected KV blocks")
    if extra["capacity_multiplier"] <= 0:
        return "long-context payload: capacity_multiplier not positive"
    if not -1.0 <= extra["prefill_reduction"] <= 1.0:
        return "long-context payload: prefill_reduction outside [-1, 1]"
    return None


def validate_speculate_payload(doc):
    """Shape-check a bench_serving --speculate payload: a SUCCESSFUL run
    (value > 0) must carry a finite tokens/s multiplier consistent with the
    recorded walls, an accept rate and verify-batch occupancy in [0, 1],
    the speculation counter identity (``speculated == accepted +
    rejected``), a tokens-per-round >= 1, and a boolean greedy-parity flag.
    Pure dict checks — runs in the tier-1 dry-run lane without jax.
    Returns an error string or None."""
    if not isinstance(doc, dict):
        return None
    if "serving_speculate" not in str(doc.get("metric", "")):
        return None
    try:
        if float(doc.get("value", 0)) <= 0:
            return None
    except (TypeError, ValueError):
        return None
    extra = doc.get("extra")
    if not isinstance(extra, dict):
        return "speculate payload has no extra dict"
    def bad_num(v):
        return not isinstance(v, (int, float)) or isinstance(v, bool) or \
            not (v == v and abs(v) != float("inf"))
    for key in ("tokens_per_sec_multiplier", "accept_rate",
                "verify_batch_occupancy", "speculated_tokens",
                "accepted_tokens", "rejected_tokens", "tokens_per_round",
                "wall_s", "wall_plain_s"):
        if bad_num(extra.get(key)):
            return f"speculate payload: extra[{key!r}] missing or " \
                   f"not finite (got {extra.get(key)!r})"
    if not isinstance(extra.get("greedy_parity"), bool):
        return "speculate payload: greedy_parity missing or not a boolean"
    if not 0.0 <= extra["accept_rate"] <= 1.0:
        return "speculate payload: accept_rate outside [0, 1]"
    if not 0.0 <= extra["verify_batch_occupancy"] <= 1.0:
        return "speculate payload: verify_batch_occupancy outside [0, 1]"
    if extra["tokens_per_sec_multiplier"] <= 0:
        return "speculate payload: tokens_per_sec_multiplier not positive"
    for key in ("speculated_tokens", "accepted_tokens", "rejected_tokens"):
        if extra[key] < 0:
            return f"speculate payload: extra[{key!r}] negative"
    if extra["speculated_tokens"] != \
            extra["accepted_tokens"] + extra["rejected_tokens"]:
        return (f"speculate payload: speculated_tokens "
                f"{extra['speculated_tokens']} != accepted "
                f"{extra['accepted_tokens']} + rejected "
                f"{extra['rejected_tokens']} — the verify loop lost or "
                f"double-counted drafted tokens")
    if extra["tokens_per_round"] < 1.0:
        return "speculate payload: tokens_per_round below 1 — a decode " \
               "round always commits at least the plain-decode token"
    if extra["wall_s"] <= 0 or extra["wall_plain_s"] <= 0:
        return "speculate payload: non-positive wall seconds"
    return None


def _bad_num(v):
    return not isinstance(v, (int, float)) or isinstance(v, bool) or \
        not (v == v and abs(v) != float("inf"))


def validate_timeseries_payload(doc):
    """Shape-check the ``timeseries`` section of any embedded telemetry
    summary (``telemetry/timeseries.py`` ring rollups): positive window
    width, window counts >= 1, finite ordered min/mean/max, strictly
    increasing window indices, and live window counts never exceeding the
    lifetime total. Pure dict checks — runs in the tier-1 dry-run lane
    without jax or jsonschema. Returns an error string or None."""
    s = find_summary(doc)
    ts = s.get("timeseries") if isinstance(s, dict) else None
    if not isinstance(ts, dict):
        return None  # nothing embedded — nothing to validate
    for name, ring in ts.items():
        if not isinstance(ring, dict):
            return f"timeseries[{name!r}]: not a dict"
        if _bad_num(ring.get("window_s")) or ring["window_s"] <= 0:
            return f"timeseries[{name!r}]: window_s missing or not positive"
        if not isinstance(ring.get("num_windows"), int) or \
                ring["num_windows"] < 1:
            return f"timeseries[{name!r}]: num_windows missing or < 1"
        if not isinstance(ring.get("total_count"), int) or \
                ring["total_count"] < 0:
            return f"timeseries[{name!r}]: total_count missing or negative"
        wins = ring.get("windows")
        if not isinstance(wins, list):
            return f"timeseries[{name!r}]: windows missing or not a list"
        if len(wins) > ring["num_windows"]:
            return f"timeseries[{name!r}]: more live windows than the ring"
        prev_idx = None
        live = 0
        for w in wins:
            if not isinstance(w, dict):
                return f"timeseries[{name!r}]: window entry not a dict"
            if not isinstance(w.get("count"), int) or w["count"] < 1:
                return f"timeseries[{name!r}]: window count < 1 (sparse " \
                       f"rings never keep empty windows)"
            for k in ("sum", "min", "max", "mean"):
                if _bad_num(w.get(k)):
                    return f"timeseries[{name!r}]: window {k} not finite"
            if not w["min"] <= w["mean"] <= w["max"]:
                return f"timeseries[{name!r}]: window min/mean/max unordered"
            idx = w.get("index")
            if not isinstance(idx, int):
                return f"timeseries[{name!r}]: window index missing"
            if prev_idx is not None and idx <= prev_idx:
                return f"timeseries[{name!r}]: window indices not " \
                       f"strictly increasing"
            prev_idx = idx
            live += w["count"]
        if live > ring["total_count"]:
            return f"timeseries[{name!r}]: live window counts {live} exceed " \
                   f"lifetime total_count {ring['total_count']}"
    return None


def validate_slo_payload(doc):
    """Shape-check the per-SLO-class section riding a payload's extra
    (``extra["slo_classes"]``, bench_serving --replay / --fleet) and the
    summary's ``slo`` section: per-metric attainment arithmetic
    (``attained + violations == requests``), attainment in [0, 1] and
    consistent with the counters, ordered finite percentiles, and an
    ``extra["slo_min_attainment"]`` that matches the derived worst class.
    Pure dict checks — runs in the tier-1 dry-run lane without jax.
    Returns an error string or None."""
    if not isinstance(doc, dict):
        return None
    extra = doc.get("extra") if isinstance(doc.get("extra"), dict) else {}
    sections = []
    for src in (extra, find_summary(doc) or {}):
        for key in ("slo_classes", "slo"):
            sec = src.get(key) if isinstance(src, dict) else None
            if isinstance(sec, dict) and sec and \
                    not any(sec is s for s in sections):
                sections.append(sec)
    if not sections:
        return None
    worst = None
    for sec in sections:
        for cls, entry in sec.items():
            if not isinstance(entry, dict):
                return f"slo_classes[{cls!r}]: not a dict"
            metrics = entry.get("metrics")
            if not isinstance(metrics, dict) or not metrics:
                return f"slo_classes[{cls!r}]: no metrics recorded"
            for metric, st in metrics.items():
                if not isinstance(st, dict):
                    return f"slo_classes[{cls!r}][{metric!r}]: not a dict"
                for k in ("requests", "attained", "violations"):
                    if not isinstance(st.get(k), int) or st[k] < 0:
                        return f"slo_classes[{cls!r}][{metric!r}]: {k} " \
                               f"missing or negative"
                if st["attained"] + st["violations"] != st["requests"]:
                    return (f"slo_classes[{cls!r}][{metric!r}]: attained "
                            f"{st['attained']} + violations "
                            f"{st['violations']} != requests "
                            f"{st['requests']} — attainment counters leaked")
                att = st.get("attainment")
                if _bad_num(att) or not 0.0 <= att <= 1.0:
                    return f"slo_classes[{cls!r}][{metric!r}]: attainment " \
                           f"missing or outside [0, 1]"
                if st["requests"] and \
                        abs(att - st["attained"] / st["requests"]) > 1e-3:
                    return f"slo_classes[{cls!r}][{metric!r}]: attainment " \
                           f"{att} inconsistent with its own counters"
                if worst is None or att < worst:
                    worst = att
            pcts = entry.get("percentiles")
            if pcts is not None:
                if not isinstance(pcts, dict):
                    return f"slo_classes[{cls!r}]: percentiles not a dict"
                for metric, p in pcts.items():
                    for k in ("p50_s", "p95_s", "p99_s"):
                        if _bad_num(p.get(k)) if isinstance(p, dict) else True:
                            return f"slo_classes[{cls!r}][{metric!r}]: " \
                                   f"percentile {k} missing or not finite"
                    if not p["p50_s"] <= p["p95_s"] <= p["p99_s"]:
                        return f"slo_classes[{cls!r}][{metric!r}]: " \
                               f"percentiles unordered"
    floor = extra.get("slo_min_attainment")
    if floor is not None:
        if _bad_num(floor) or not 0.0 <= floor <= 1.0:
            return "slo_min_attainment missing or outside [0, 1]"
        if worst is not None and abs(floor - worst) > 1e-3:
            return (f"slo_min_attainment {floor} does not match the worst "
                    f"per-class attainment {worst} — the payload's headline "
                    f"drifted from its own class table")
    return None


def _slo_min_attainment(doc):
    """Worst per-class attainment carried by ``doc`` (the
    ``extra.slo_min_attainment`` headline, else derived from
    ``extra.slo_classes``); None when the doc has no SLO data."""
    if not isinstance(doc, dict):
        return None
    extra = doc.get("extra") if isinstance(doc.get("extra"), dict) else {}
    v = extra.get("slo_min_attainment")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    worst = None
    sec = extra.get("slo_classes")
    if isinstance(sec, dict):
        for entry in sec.values():
            for st in (entry.get("metrics") or {}).values():
                att = st.get("attainment") if isinstance(st, dict) else None
                if isinstance(att, (int, float)) and \
                        (worst is None or att < worst):
                    worst = float(att)
    return worst


def _load_overlap_module():
    """Load telemetry/overlap.py standalone (stdlib-only at module scope,
    same pattern as kernel_table) so overlap validation runs in the tier-1
    dry-run lane without importing the package or jax."""
    import importlib.util
    mod_path = os.path.join(REPO_ROOT, "deepspeed_tpu", "telemetry",
                            "overlap.py")
    spec = importlib.util.spec_from_file_location("_overlap", mod_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate_overlap_payload(doc):
    """Structurally validate any overlap report riding this doc — a bare
    ``summary()["overlap"]`` section, a payload's ``extra["overlap"]``
    (``scripts/overlap_report.py``), or a doc-level ``overlap`` key: every
    number finite, exposure <= comm total, fractions in [0, 1]. Pure dict
    checks via the standalone overlap module — no jax, no jsonschema.
    Returns an error string or None."""
    if not isinstance(doc, dict):
        return None
    extra = doc.get("extra") if isinstance(doc.get("extra"), dict) else {}
    reports = []
    for src in (find_summary(doc) or {}, extra, doc):
        ov = src.get("overlap") if isinstance(src, dict) else None
        if isinstance(ov, dict) and not any(ov is r for r in reports):
            reports.append(ov)
    if not reports:
        return None
    try:
        ov_mod = _load_overlap_module()
    except Exception as e:
        return f"cannot load overlap module: {e}"
    for rep in reports:
        errs = ov_mod.validate_report(rep)
        if errs:
            return "overlap report invalid: " + "; ".join(errs)
    return None


#: overlap-schedule acceptance: the checked-in scheduled baseline's exposed
#: seconds must sit at or below this fraction of its own serialized worst
#: case (>= 30% reduction — ROADMAP item 2's ratchet)
OVERLAP_SCHEDULE_MAX_RATIO = 0.7
OVERLAP_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                     "overlap_analytic_baseline.json")


def _load_overlap_schedule_module():
    """Load runtime/zero/overlap_schedule.py standalone (stdlib-only at
    module scope) with the standalone overlap module plugged into its
    ``_OVERLAP`` injection point — the scheduled-baseline re-derivation runs
    in the tier-1 dry-run lane without the package or jax."""
    import importlib.util
    mod_path = os.path.join(REPO_ROOT, "deepspeed_tpu", "runtime", "zero",
                            "overlap_schedule.py")
    spec = importlib.util.spec_from_file_location("_overlap_schedule",
                                                  mod_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._OVERLAP = _load_overlap_module()
    return mod


def check_overlap_schedule(baseline_path=None):
    """Re-derive the checked-in scheduled overlap baseline jax-free and hold
    it to the ratchet: rebuild the two-resource timeline from the recorded
    ``extra.overlap.schedule`` block (plan + compute_s + comm seconds),
    require the recomputed exposed seconds to match the recorded payload
    value, and require exposed <= ``OVERLAP_SCHEDULE_MAX_RATIO`` x the
    serialized worst case. Returns (report, errors) for the dry-run lane."""
    path = baseline_path or OVERLAP_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no scheduled baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable scheduled baseline {path}"]
    ov = doc.get("extra", {}).get("overlap") if isinstance(doc, dict) else None
    sched = ov.get("schedule") if isinstance(ov, dict) else None
    if not isinstance(sched, dict):
        return {}, ["scheduled baseline has no extra.overlap.schedule block"]
    try:
        osched = _load_overlap_schedule_module()
    except Exception as e:
        return {}, [f"cannot load overlap_schedule module: {e}"]
    errors = [f"schedule block: {e}"
              for e in osched.validate_schedule(sched)]
    if errors:
        return {}, errors
    plan = osched.OverlapPlan.from_dict(sched)
    recomputed = osched.plan_exposure(sched["compute_s"], sched["comm_ops"],
                                      plan)
    recorded = float(ov.get("exposed_comm_s", doc.get("value", -1.0)))
    serialized = float(sched["serialized_exposed_comm_s"])
    tol = max(1e-9, 1e-4 * max(serialized, recorded))
    if abs(recomputed - recorded) > tol:
        errors.append(
            f"recomputed exposed {recomputed:.3e}s does not match the "
            f"recorded baseline {recorded:.3e}s — the schedule block and "
            f"payload value drifted apart (regenerate with "
            f"scripts/overlap_report.py --analytic --schedule)")
    if serialized > 0 and recomputed > OVERLAP_SCHEDULE_MAX_RATIO * serialized:
        errors.append(
            f"scheduled exposed {recomputed:.3e}s > "
            f"{OVERLAP_SCHEDULE_MAX_RATIO} x serialized {serialized:.3e}s — "
            f"the overlap pass no longer hides >= "
            f"{1 - OVERLAP_SCHEDULE_MAX_RATIO:.0%} of the worst case")
    return {"exposed_comm_s": round(recomputed, 9),
            "serialized_exposed_comm_s": serialized,
            "reduction_fraction": round(
                (serialized - recomputed) / serialized, 6)
            if serialized > 0 else 0.0,
            "prefetch_depth": plan.prefetch_depth,
            "grad_buckets": plan.grad_buckets}, errors


MOE_OVERLAP_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                         "moe_overlap_baseline.json")


def check_moe_baseline(baseline_path=None):
    """Re-derive the checked-in MoE scheduled overlap baseline jax-free and
    hold it to the ratchet: rebuild the chunked dispatch/expert/combine
    timeline from the recorded ``extra.overlap.schedule`` block, require the
    recomputed exposed seconds to match the recorded value, and require
    exposed <= ``OVERLAP_SCHEDULE_MAX_RATIO`` x the serialized worst case —
    :func:`check_overlap_schedule`'s twin over
    ``moe_scheduled_intervals``/``moe_plan_exposure``. Returns
    (report, errors) for the dry-run lane."""
    path = baseline_path or MOE_OVERLAP_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no moe scheduled baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable moe scheduled baseline {path}"]
    ov = doc.get("extra", {}).get("overlap") if isinstance(doc, dict) else None
    sched = ov.get("schedule") if isinstance(ov, dict) else None
    if not isinstance(sched, dict):
        return {}, ["moe baseline has no extra.overlap.schedule block"]
    try:
        osched = _load_overlap_schedule_module()
    except Exception as e:
        return {}, [f"cannot load overlap_schedule module: {e}"]
    errors = [f"schedule block: {e}"
              for e in osched.validate_schedule(sched)]
    if errors:
        return {}, errors
    moe_classes = {"moe_dispatch", "moe_combine"}
    if not any(osched._op_class(s.get("op")) in moe_classes
               for s in sched["comm_ops"]):
        return {}, ["moe baseline schedule has no a2a_dispatch/a2a_combine "
                    "ops — not an MoE inventory"]
    plan = osched.OverlapPlan.from_dict(sched)
    recomputed = osched.moe_plan_exposure(sched["compute_s"],
                                          sched["comm_ops"], plan)
    recorded = float(ov.get("exposed_comm_s", doc.get("value", -1.0)))
    serialized = float(sched["serialized_exposed_comm_s"])
    tol = max(1e-9, 1e-4 * max(serialized, recorded))
    if abs(recomputed - recorded) > tol:
        errors.append(
            f"recomputed moe exposed {recomputed:.3e}s does not match the "
            f"recorded baseline {recorded:.3e}s — the schedule block and "
            f"payload value drifted apart (regenerate with "
            f"python bench.py --moe)")
    if serialized > 0 and recomputed > OVERLAP_SCHEDULE_MAX_RATIO * serialized:
        errors.append(
            f"moe scheduled exposed {recomputed:.3e}s > "
            f"{OVERLAP_SCHEDULE_MAX_RATIO} x serialized {serialized:.3e}s — "
            f"the chunked a2a pipeline no longer hides >= "
            f"{1 - OVERLAP_SCHEDULE_MAX_RATIO:.0%} of the worst case")
    return {"exposed_comm_s": round(recomputed, 9),
            "serialized_exposed_comm_s": serialized,
            "reduction_fraction": round(
                (serialized - recomputed) / serialized, 6)
            if serialized > 0 else 0.0,
            "a2a_chunks": plan.a2a_chunks}, errors


#: prefix-cache acceptance for the checked-in shared-prefix replay baseline:
#: the recorded run must have skipped >= 40% of prefill tokens with a hit
#: rate > 0.5 and a no-worse TTFT p50 than its own cache-off leg
PREFIX_MIN_REDUCTION = 0.40
PREFIX_MIN_HIT_RATE = 0.5
PREFIX_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                    "serving_prefix_baseline.json")


def check_prefix_baseline(baseline_path=None):
    """Validate the checked-in ``--prefix-mix`` replay baseline: payload
    shape (``validate_serving_payload`` incl. the prefix fields), internal
    consistency (executed + saved vs the recorded nocache leg), and the
    acceptance ratchet — prefill reduction >= ``PREFIX_MIN_REDUCTION``, hit
    rate > ``PREFIX_MIN_HIT_RATE``, cached TTFT p50 <= the nocache leg's.
    Pure dict checks over recorded values (wall-clock legs cannot be
    re-derived jax-free). Returns (report, errors) for the dry-run lane."""
    path = baseline_path or PREFIX_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no prefix baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable prefix baseline {path}"]
    err = validate_serving_payload(doc)
    if err:
        return {}, [f"prefix baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    if "prefix_hit_rate" not in extra:
        return {}, ["prefix baseline payload carries no prefix-mix fields "
                    "(regenerate with bench_serving --replay --prefix-mix)"]
    errors = []
    hit, red = extra["prefix_hit_rate"], extra["prefill_reduction"]
    executed = extra["executed_prefill_tokens"]
    nocache = extra["executed_prefill_tokens_nocache"]
    if nocache > 0:
        derived = (nocache - executed) / nocache
        if abs(derived - red) > 1e-3:
            errors.append(
                f"prefix baseline: recorded prefill_reduction {red} does not "
                f"match derived {derived:.6f} from executed token counts")
    if red < PREFIX_MIN_REDUCTION:
        errors.append(f"prefix baseline: prefill reduction {red} < "
                      f"{PREFIX_MIN_REDUCTION} — prompt reuse regressed")
    if hit <= PREFIX_MIN_HIT_RATE:
        errors.append(f"prefix baseline: prefix_hit_rate {hit} <= "
                      f"{PREFIX_MIN_HIT_RATE}")
    if extra["ttft_p50_s"] > extra["ttft_p50_nocache_s"]:
        errors.append(
            f"prefix baseline: cached TTFT p50 {extra['ttft_p50_s']}s worse "
            f"than the cache-off leg {extra['ttft_p50_nocache_s']}s")
    return {"prefix_hit_rate": hit, "prefill_reduction": red,
            "executed_prefill_tokens": executed,
            "executed_prefill_tokens_nocache": nocache,
            "ttft_p50_s": extra["ttft_p50_s"],
            "ttft_p50_nocache_s": extra["ttft_p50_nocache_s"]}, errors


#: fleet acceptance for the checked-in disaggregated replay baseline: the
#: recorded run must sustain >= 2x the single replica's saturation request
#: rate (the ISSUE's dividend) without shedding more than 10% of admits,
#: and must actually have exercised the KV handoff path
FLEET_MIN_RATE_MULTIPLIER = 2.0
FLEET_MAX_SHED_RATE = 0.1
FLEET_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                   "serving_fleet_baseline.json")


def check_fleet_baseline(baseline_path=None):
    """Validate the checked-in ``--fleet --replay`` baseline: payload shape
    (``validate_fleet_payload`` incl. page conservation), then the
    acceptance ratchet — rate multiplier >= ``FLEET_MIN_RATE_MULTIPLIER``,
    shed rate <= ``FLEET_MAX_SHED_RATE``, at least one real KV handoff, and
    fleet TTFT p99 no worse than the saturated single replica's (the whole
    point of admitting onto prefill-only replicas). Pure dict checks over
    recorded values (wall-clock legs cannot be re-derived jax-free).
    Returns (report, errors) for the dry-run lane."""
    path = baseline_path or FLEET_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no fleet baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable fleet baseline {path}"]
    err = validate_fleet_payload(doc)
    if err:
        return {}, [f"fleet baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    if "rate_multiplier" not in extra:
        return {}, ["fleet baseline payload carries no fleet fields "
                    "(regenerate with bench_serving --fleet --replay)"]
    errors = []
    mult = extra["rate_multiplier"]
    if mult < FLEET_MIN_RATE_MULTIPLIER:
        errors.append(
            f"fleet baseline: rate multiplier {mult} < "
            f"{FLEET_MIN_RATE_MULTIPLIER} — the disaggregated fleet no "
            f"longer sustains the required saturation-rate dividend")
    if extra["shed_rate"] > FLEET_MAX_SHED_RATE:
        errors.append(f"fleet baseline: shed_rate {extra['shed_rate']} > "
                      f"{FLEET_MAX_SHED_RATE}")
    if extra["handoffs"] <= 0:
        errors.append("fleet baseline: no KV handoffs recorded — the run "
                      "never exercised prefill->decode shipping")
    if extra["ttft_p99_s"] > extra["single_ttft_p99_s"]:
        errors.append(
            f"fleet baseline: fleet TTFT p99 {extra['ttft_p99_s']}s worse "
            f"than the saturated single replica "
            f"{extra['single_ttft_p99_s']}s")
    return {"rate_multiplier": mult, "shed_rate": extra["shed_rate"],
            "handoffs": extra["handoffs"],
            "pages_shipped": extra["pages_shipped"],
            "ttft_p99_s": extra["ttft_p99_s"],
            "single_ttft_p99_s": extra["single_ttft_p99_s"]}, errors


#: KV-fabric acceptance for the checked-in --fleet --two-process baseline:
#: a serialized int8 page (data row + fp32 scale) must cost at most this
#: fraction of the fp32 device bytes it replaces — (hd+4)/(4*hd), so the
#: 0.3 ceiling needs head_dim > 13 and holds 0.28125 at the bench's hd=32
KVFABRIC_MAX_WIRE_FP32_RATIO = 0.3
KVFABRIC_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                      "serving_kvfabric_baseline.json")


def check_kvfabric_baseline(baseline_path=None):
    """Validate the checked-in KV-fabric baseline: payload shape
    (``validate_kvfabric_payload``), then the acceptance ratchet — wire
    bytes per page <= ``KVFABRIC_MAX_WIRE_FP32_RATIO`` of fp32, the delta
    leg shipped measurably fewer bytes than the no-delta leg (with at
    least one page actually delta-skipped), zero CRC failures across the
    in-process legs, every leg bit-exact against the monolithic reference,
    and the two-process leg completed every request with zero losses.
    Pure dict checks over recorded values (the wall-clock legs cannot be
    re-derived jax-free). Returns (report, errors) for the dry-run
    lane."""
    path = baseline_path or KVFABRIC_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no kvfabric baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable kvfabric baseline {path}"]
    err = validate_kvfabric_payload(doc)
    if err:
        return {}, [f"kvfabric baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    if "wire_fp32_ratio" not in extra:
        return {}, ["kvfabric baseline payload carries no fabric fields "
                    "(regenerate with bench_serving --fleet --two-process)"]
    errors = []
    ratio = extra["wire_fp32_ratio"]
    if ratio > KVFABRIC_MAX_WIRE_FP32_RATIO:
        errors.append(
            f"kvfabric baseline: wire/fp32 byte ratio {ratio} > "
            f"{KVFABRIC_MAX_WIRE_FP32_RATIO} — the serialized page format "
            f"no longer pays for itself over shipping raw fp32")
    if extra["delta_wire_bytes"] >= extra["nodelta_wire_bytes"]:
        errors.append(
            f"kvfabric baseline: delta leg shipped "
            f"{extra['delta_wire_bytes']} bytes >= no-delta leg "
            f"{extra['nodelta_wire_bytes']} — delta-shipping saved nothing "
            f"on the prefix-mix trace")
    if extra["pages_delta_skipped"] <= 0 or extra["wire_bytes_saved"] <= 0:
        errors.append("kvfabric baseline: no pages delta-skipped — the "
                      "digest exchange never suppressed a transfer")
    if extra["crc_failures"] != 0:
        errors.append(f"kvfabric baseline: {extra['crc_failures']} CRC "
                      f"failure(s) on an uninjected run — the wire is "
                      f"corrupting pages")
    if extra["failed_handoffs"] != 0:
        errors.append(f"kvfabric baseline: {extra['failed_handoffs']} "
                      f"failed handoff(s)")
    if not (extra["parity_nodelta"] and extra["parity_delta"]):
        errors.append("kvfabric baseline: an in-process wire leg lost "
                      "greedy parity with the monolithic reference")
    tp = extra["two_process"]
    if tp["lost_requests"] != 0:
        errors.append(f"kvfabric baseline: two-process leg lost "
                      f"{tp['lost_requests']} request(s)")
    if not tp["parity"]:
        errors.append("kvfabric baseline: two-process leg lost greedy "
                      "parity — the serialized boundary is not bit-exact")
    if tp["handoffs"] <= 0:
        errors.append("kvfabric baseline: two-process leg recorded no "
                      "handoffs — the pipe transport never shipped a page")
    return {"wire_fp32_ratio": ratio,
            "nodelta_wire_bytes": extra["nodelta_wire_bytes"],
            "delta_wire_bytes": extra["delta_wire_bytes"],
            "wire_bytes_saved": extra["wire_bytes_saved"],
            "pages_delta_skipped": extra["pages_delta_skipped"],
            "crc_failures": extra["crc_failures"],
            "two_process_lost": tp["lost_requests"],
            "two_process_handoffs": tp["handoffs"]}, errors


#: chaos-replay acceptance for the checked-in baseline: the recorded run
#: must have ACTUALLY taken faults (a replica loss with live re-admissions),
#: recovered without losing a request or leaking a KV page, replaced the
#: lost capacity (scale-up), and kept the interactive class attained while
#: batch (or untagged) traffic absorbed every shed
CHAOS_MIN_INTERACTIVE_ATTAINMENT = 0.9
CHAOS_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                   "serving_chaos_baseline.json")


def check_chaos_baseline(baseline_path=None):
    """Validate the checked-in ``--chaos --diurnal`` baseline: payload shape
    (``validate_chaos_payload`` incl. the router accounting identity), then
    the acceptance ratchet — at least one injected replica loss with
    ``readmitted > 0``, zero requests lost, zero leaked KV pages, at least
    one autoscaler scale-up (the lost capacity was replaced), zero
    interactive sheds, interactive attainment >=
    ``CHAOS_MIN_INTERACTIVE_ATTAINMENT`` under faults, and a positive
    goodput per replica-second (the number the candidate-vs-baseline run
    ratchets via ``--max-goodput-drop``). Pure dict checks over recorded
    values. Returns (report, errors) for the dry-run lane."""
    path = baseline_path or CHAOS_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no chaos baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable chaos baseline {path}"]
    err = validate_chaos_payload(doc)
    if err:
        return {}, [f"chaos baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    if "replica_losses" not in extra:
        return {}, ["chaos baseline payload carries no chaos fields "
                    "(regenerate with bench_serving --chaos --diurnal)"]
    errors = []
    if extra["replica_losses"] < 1 or extra["fault_trips"] < 1:
        errors.append("chaos baseline: no replica loss recorded — the run "
                      "never exercised the recovery path")
    if extra["readmitted"] <= 0:
        errors.append("chaos baseline: replica lost but nothing re-admitted "
                      "— in-flight recovery never ran")
    if extra["requests_lost"] != 0:
        errors.append(f"chaos baseline: {extra['requests_lost']} admitted "
                      f"request(s) lost — recovery must complete every "
                      f"admitted stream")
    if extra["leaked_pages"] != 0:
        errors.append(f"chaos baseline: {extra['leaked_pages']} KV page(s) "
                      f"leaked after the drain")
    if extra["scale_ups"] < 1:
        errors.append("chaos baseline: autoscaler never scaled up — the "
                      "lost capacity was not replaced")
    if extra["interactive_sheds"] != 0:
        errors.append(f"chaos baseline: {extra['interactive_sheds']} "
                      f"interactive shed(s) — shedding must land on looser "
                      f"classes only")
    att = extra.get("interactive_attainment")
    if att is None:
        errors.append("chaos baseline: no interactive_attainment recorded")
    elif att < CHAOS_MIN_INTERACTIVE_ATTAINMENT:
        errors.append(f"chaos baseline: interactive attainment {att} < "
                      f"{CHAOS_MIN_INTERACTIVE_ATTAINMENT} under faults")
    goodput = extra["goodput_tokens_per_replica_sec"]
    if goodput <= 0:
        errors.append("chaos baseline: non-positive goodput per "
                      "replica-second")
    return {"goodput_tokens_per_replica_sec": goodput,
            "replica_losses": extra["replica_losses"],
            "readmitted": extra["readmitted"],
            "leaked_pages": extra["leaked_pages"],
            "scale_ups": extra["scale_ups"],
            "scale_downs": extra["scale_downs"],
            "interactive_sheds": extra["interactive_sheds"],
            "interactive_attainment": att}, errors


#: long-context tiering acceptance for the checked-in baseline: at the fp
#: leg's KV HBM budget the int8 pool must fit >= 2x the max-context
#: sequences, the recorded run must actually have spilled AND revived
#: prefix blocks through the host tier, and no live sequence may have paid
#: the preemption path while parked blocks could spill instead
LONGCTX_MIN_CAPACITY_MULTIPLIER = 2.0
LONGCTX_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                     "serving_longctx_baseline.json")


def check_longctx_baseline(baseline_path=None):
    """Validate the checked-in ``--long-context`` tiering baseline: payload
    shape (``validate_longctx_payload`` incl. the swap accounting
    identity), then the acceptance ratchet — int8 capacity multiplier >=
    ``LONGCTX_MIN_CAPACITY_MULTIPLIER`` at the equal HBM budget, at least
    one spill AND one restore recorded (the run exercised the tier), zero
    live swap-outs, and a positive prefill reduction across the
    spill/restore round trip. Pure dict checks over recorded values
    (wall-clock legs cannot be re-derived jax-free). Returns
    (report, errors) for the dry-run lane."""
    path = baseline_path or LONGCTX_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no long-context baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable long-context baseline {path}"]
    err = validate_longctx_payload(doc)
    if err:
        return {}, [f"longctx baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    if "swapped_out" not in extra:
        return {}, ["longctx baseline payload carries no tiering fields "
                    "(regenerate with bench_serving --long-context)"]
    errors = []
    mult = extra["capacity_multiplier"]
    if mult < LONGCTX_MIN_CAPACITY_MULTIPLIER:
        errors.append(
            f"longctx baseline: capacity multiplier {mult} < "
            f"{LONGCTX_MIN_CAPACITY_MULTIPLIER} — int8 KV pages no longer "
            f"fit 2x the sequences at the fp leg's HBM budget")
    if extra["swapped_out"] < 1:
        errors.append("longctx baseline: no KV blocks spilled — the run "
                      "never pressured the host tier")
    if extra["swapped_in"] < 1:
        errors.append("longctx baseline: no KV blocks restored — spilled "
                      "prefix chains never revived")
    if extra["swap_outs_live"] != 0:
        errors.append(
            f"longctx baseline: {extra['swap_outs_live']} live swap-outs — "
            f"a live sequence paid for pressure while parked blocks could "
            f"spill (pressure order broken)")
    if extra["prefill_reduction"] <= 0:
        errors.append("longctx baseline: prefill reduction not positive — "
                      "restored prefix chains saved no prefill work")
    return {"capacity_multiplier": mult,
            "concurrent_sequences_per_chip":
                extra["concurrent_sequences_per_chip"],
            "swapped_out": extra["swapped_out"],
            "swapped_in": extra["swapped_in"],
            "swap_in_stall_s": extra["swap_in_stall_s"],
            "prefill_reduction": extra["prefill_reduction"]}, errors


#: speculative-decode acceptance for the checked-in baseline: on the
#: prefix-heavy greedy replay the draft-then-verify leg must beat plain
#: decode by >= 1.5x wall-clock at bit-exact output (greedy parity), with
#: a sane accept rate and at least one drafted token — a drop below the
#: ratchet means drafting or verify-batching regressed
SPECULATE_MIN_MULTIPLIER = 1.5
SPECULATE_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                       "serving_speculate_baseline.json")


def check_speculate_baseline(baseline_path=None):
    """Validate the checked-in ``--speculate`` baseline: payload shape
    (``validate_speculate_payload`` incl. the speculation counter
    identity), then the acceptance ratchet — tokens/s multiplier >=
    ``SPECULATE_MIN_MULTIPLIER`` on the template-heavy greedy replay,
    greedy parity True (the speculate leg reproduced the plain stream
    token-for-token — the bit-exactness oracle), accept rate in (0, 1],
    and at least one token actually drafted. Pure dict checks over
    recorded values (wall-clock legs cannot be re-derived jax-free).
    Returns (report, errors) for the dry-run lane."""
    path = baseline_path or SPECULATE_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no speculate baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable speculate baseline {path}"]
    err = validate_speculate_payload(doc)
    if err:
        return {}, [f"speculate baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    if "tokens_per_sec_multiplier" not in extra:
        return {}, ["speculate baseline payload carries no speculation "
                    "fields (regenerate with bench_serving --speculate)"]
    errors = []
    mult = extra["tokens_per_sec_multiplier"]
    if mult < SPECULATE_MIN_MULTIPLIER:
        errors.append(
            f"speculate baseline: tokens/s multiplier {mult} < "
            f"{SPECULATE_MIN_MULTIPLIER} — draft-then-verify no longer "
            f"pays for its verify overhead on the prefix-heavy replay")
    if extra["greedy_parity"] is not True:
        errors.append(
            "speculate baseline: greedy parity broken — the speculate leg "
            "diverged from the plain greedy stream (accept/rollback is "
            "committing tokens plain decode would not have emitted)")
    if extra["speculated_tokens"] < 1:
        errors.append("speculate baseline: no tokens drafted — the run "
                      "never exercised the draft-then-verify path")
    if extra["accepted_tokens"] < 1:
        errors.append("speculate baseline: no drafted token accepted — "
                      "the drafter never matched the model's stream")
    return {"tokens_per_sec_multiplier": mult,
            "accept_rate": extra["accept_rate"],
            "verify_batch_occupancy": extra["verify_batch_occupancy"],
            "greedy_parity": extra["greedy_parity"],
            "speculated_tokens": extra["speculated_tokens"],
            "tokens_per_round": extra["tokens_per_round"]}, errors


#: elastic reshard drill acceptance for the checked-in baseline
#: (onchip_results/elastic_drill_baseline.json, regenerated with
#: ``scripts/fault_drill.py --emit-elastic-baseline``): the 8→4→8 CPU
#: drill must lose zero steps, double-apply none, restore bitwise at every
#: reshard, and keep each reshard leg under the wall-clock ceiling
ELASTIC_MAX_RESHARD_S = 30.0
ELASTIC_WORLD_SEQUENCE = [8, 4, 8]
ELASTIC_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                     "elastic_drill_baseline.json")


def check_elastic_baseline(baseline_path=None):
    """Validate the checked-in elastic-reshard drill baseline: the recorded
    run shrank 8→4 on a mid-step slice loss and re-expanded 4→8
    (``world_sequence``), lost zero steps and double-applied none across
    both reshards, restored the loss bitwise at every reshard step, kept
    the optimizer step count equal to the step budget, and each reshard
    leg's wall-seconds ratchets under :data:`ELASTIC_MAX_RESHARD_S`. Pure
    dict checks over recorded values (the drill itself needs jax + 8 CPU
    devices). Returns (report, errors) for the dry-run lane."""
    path = baseline_path or ELASTIC_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no elastic drill baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable elastic drill baseline {path}"]
    if not isinstance(doc, dict) or doc.get("drill") != "elastic-reshard-8-4-8":
        return {}, ["elastic baseline: not an elastic-reshard drill payload "
                    "(regenerate with fault_drill.py --emit-elastic-baseline)"]
    required = ("world_sequence", "steps_lost", "steps_double_applied",
                "restore_loss_bitwise_equal", "reshard_s", "steps",
                "final_optimizer_step")
    missing = [k for k in required if k not in doc]
    if missing:
        return {}, [f"elastic baseline: missing fields {missing}"]
    errors = []
    if list(doc["world_sequence"]) != ELASTIC_WORLD_SEQUENCE:
        errors.append(
            f"elastic baseline: world sequence {doc['world_sequence']} != "
            f"{ELASTIC_WORLD_SEQUENCE} — the drill did not shrink to the "
            f"surviving half and re-expand")
    if doc["steps_lost"] != 0:
        errors.append(f"elastic baseline: {doc['steps_lost']} steps lost — "
                      f"the reshard dropped part of the loss trajectory")
    if doc["steps_double_applied"] != 0:
        errors.append(
            f"elastic baseline: {doc['steps_double_applied']} steps "
            f"double-applied — the restore replayed a committed step")
    if not doc["restore_loss_bitwise_equal"]:
        errors.append("elastic baseline: restore-step loss not bitwise "
                      "equal to the full-world reference — the universal "
                      "reshard-restore altered state")
    if doc["final_optimizer_step"] != doc["steps"]:
        errors.append(
            f"elastic baseline: optimizer step count "
            f"{doc['final_optimizer_step']} != step budget {doc['steps']}")
    reshard_s = doc["reshard_s"]
    for leg in ("shrink", "expand"):
        if leg not in reshard_s:
            errors.append(f"elastic baseline: no {leg} reshard recorded")
        elif not 0 < reshard_s[leg] <= ELASTIC_MAX_RESHARD_S:
            errors.append(
                f"elastic baseline: {leg} reshard took {reshard_s[leg]}s "
                f"(ceiling {ELASTIC_MAX_RESHARD_S}s)")
    return {"world_sequence": list(doc["world_sequence"]),
            "steps_lost": doc["steps_lost"],
            "steps_double_applied": doc["steps_double_applied"],
            "restore_loss_bitwise_equal": doc["restore_loss_bitwise_equal"],
            "reshard_s": reshard_s}, errors


def check_overlap_analytic():
    """Drive the overlap analyzer end-to-end jax-free: build the analytic
    serialized schedule from a fixed collective inventory, attribute it,
    and require the report to validate AND model every collective as fully
    exposed (the synchronous-XLA worst case the scheduling pass ratchets
    from). Returns (report, errors) for the dry-run lane."""
    try:
        ov = _load_overlap_module()
    except Exception as e:
        return {}, [f"cannot load overlap module: {e}"]
    per_device = ov.analytic_intervals(1e-3, [
        {"op": "all_gather", "axis": "dp", "bytes": 1 << 20,
         "seconds": 2e-4, "count": 2},
        {"op": "reduce_scatter", "axis": "dp", "bytes": 1 << 20,
         "seconds": 3e-4},
        {"op": "all_reduce", "axis": "dp", "bytes": 4096, "seconds": 5e-5},
    ])
    report = ov.overlap_report(per_device, mode="analytic")
    errors = ov.validate_report(report)
    if not errors and abs(report["exposed_comm_s"]
                          - report["comm_s"]) > 1e-9:
        errors.append("analytic serialized schedule must be fully exposed "
                      f"(exposed {report['exposed_comm_s']} != comm "
                      f"{report['comm_s']})")
    if not errors and not report["critical_path"]["ops"]:
        errors.append("analytic report has an empty critical path")
    return {"exposed_comm_s": report.get("exposed_comm_s"),
            "comm_s": report.get("comm_s"),
            "collectives": len(report.get("collectives", [])),
            "critical_path_ops": len(
                report.get("critical_path", {}).get("ops", []))}, errors


def _load_profile_store_module():
    """Load telemetry/profile_store.py standalone (stdlib-only at module
    scope, the kernel_table idiom) so the measured per-op cost stores are
    validated in the tier-1 dry-run lane without the package or jax."""
    import importlib.util
    mod_path = os.path.join(REPO_ROOT, "deepspeed_tpu", "telemetry",
                            "profile_store.py")
    spec = importlib.util.spec_from_file_location("_profile_store", mod_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def check_profile_store(stores_dir=None):
    """Validate every checked-in measured-cost profile store
    (``onchip_results/profile_*.json``, schema via
    ``profile_store.validate_store``) and round-trip one entry per store
    through the resolver, requiring the ``measured`` reason code — a store
    whose own keys resolve as ``roofline_fallback`` would silently disable
    the measured-cost path in ``overlap_schedule``. Returns
    (report, errors); skipped without error when no store is checked in."""
    stores_dir = stores_dir or os.path.join(REPO_ROOT, "onchip_results")
    try:
        names = sorted(n for n in os.listdir(stores_dir)
                       if n.startswith("profile_") and n.endswith(".json"))
    except OSError:
        names = []
    if not names:
        return {"skipped": f"no profile stores under {stores_dir}"}, []
    try:
        ps = _load_profile_store_module()
    except Exception as e:
        return {}, [f"cannot load profile_store module: {e}"]
    report, errors = {"stores": {}}, []
    for name in names:
        path = os.path.join(stores_dir, name)
        doc = load_doc(path)
        if doc is None:
            errors.append(f"{name}: unreadable")
            continue
        errs = ps.validate_store(doc)
        entries = doc.get("entries", {}) if isinstance(doc, dict) else {}
        report["stores"][name] = {"entries": len(entries), "errors": errs}
        errors.extend(f"{name}: {e}" for e in errs)
        if errs or not entries:
            if not errs and not entries:
                errors.append(f"{name}: store has no entries")
            continue
        # resolver round trip on the store's own first key (the bucket is
        # already a power of two, so it maps back to itself)
        key = sorted(entries)[0]
        op, bucket, dtype = key.split("|")
        seconds, reason = ps.resolve(op, int(bucket[1:]), dtype=dtype,
                                     path=path)
        report["stores"][name]["resolved"] = {
            "key": key, "seconds": seconds, "reason": reason}
        if reason != "measured" or seconds is None:
            errors.append(
                f"{name}: key {key} resolved as {reason!r} — the store's "
                f"own entries must resolve with the 'measured' reason code")
    return report, errors


#: SLO replay acceptance for the checked-in baseline
#: (onchip_results/serving_slo_baseline.json, regenerated with
#: ``bench_serving --replay`` — the replay lane always tags requests with
#: the two built-in SLO classes): every class's recorded attainment must
#: clear the floor and the run must carry live time-series trajectories
SLO_MIN_ATTAINMENT = 0.9
SLO_MIN_SERIES = 3
SLO_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                 "serving_slo_baseline.json")


def check_slo_baseline(baseline_path=None):
    """Validate the checked-in SLO replay baseline: payload shape
    (``validate_serving_payload`` + ``validate_slo_payload`` incl. the
    attainment arithmetic), then the acceptance ratchet — both built-in SLO
    classes present with recorded requests, worst per-class attainment >=
    ``SLO_MIN_ATTAINMENT``, and an embedded summary carrying >=
    ``SLO_MIN_SERIES`` non-empty time-series rings (the trajectory plane
    must actually have recorded). Pure dict checks over recorded values.
    Returns (report, errors) for the dry-run lane."""
    path = baseline_path or SLO_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no slo baseline at {path}"}, []
    doc = load_doc(path)
    if doc is None:
        return {}, [f"unreadable slo baseline {path}"]
    err = validate_serving_payload(doc) or validate_slo_payload(doc) \
        or validate_timeseries_payload(doc)
    if err:
        return {}, [f"slo baseline: {err}"]
    extra = doc.get("extra", {}) if isinstance(doc, dict) else {}
    classes = extra.get("slo_classes")
    if not isinstance(classes, dict) or not classes:
        return {}, ["slo baseline payload carries no slo_classes section "
                    "(regenerate with bench_serving --replay)"]
    errors = []
    if len(classes) < 2:
        errors.append(f"slo baseline: only {len(classes)} SLO class(es) "
                      f"recorded — the replay lane tags two")
    for cls, entry in sorted(classes.items()):
        if not any(st.get("requests", 0) > 0
                   for st in (entry.get("metrics") or {}).values()):
            errors.append(f"slo baseline: class {cls!r} recorded no requests")
    worst = _slo_min_attainment(doc)
    if worst is None:
        errors.append("slo baseline: no attainment derivable")
    elif worst < SLO_MIN_ATTAINMENT:
        errors.append(
            f"slo baseline: worst per-class attainment {worst} < "
            f"{SLO_MIN_ATTAINMENT} — the serving path stopped meeting its "
            f"recorded SLO targets")
    s = find_summary(doc) or {}
    series = s.get("timeseries") if isinstance(s, dict) else None
    live = [n for n, ring in (series or {}).items()
            if isinstance(ring, dict) and ring.get("windows")]
    if len(live) < SLO_MIN_SERIES:
        errors.append(
            f"slo baseline: only {len(live)} non-empty time-series rings "
            f"embedded (need >= {SLO_MIN_SERIES}) — the trajectory plane "
            f"did not record")
    return {"classes": sorted(classes),
            "min_attainment": worst,
            "live_series": len(live)}, errors


#: graftlint ratchet: per-rule/per-file finding counts frozen by this doc
#: may only go down (see docs/ANALYSIS.md; regenerate with
#: scripts/graftlint.py --write-baseline)
LINT_BASELINE_PATH = os.path.join(REPO_ROOT, "onchip_results",
                                  "lint_baseline.json")


def _load_astlint_module():
    """Load analysis/astlint.py standalone (stdlib-only at module scope, the
    same idiom as ``_load_overlap_module``) so the tier-1 dry-run lane lints
    the tree without importing the package or jax."""
    import importlib.util
    mod_path = os.path.join(REPO_ROOT, "deepspeed_tpu", "analysis",
                            "astlint.py")
    spec = importlib.util.spec_from_file_location("_astlint", mod_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def check_lint_baseline(baseline_path=None, scan_root=None):
    """Run graftlint Layer A over the package and ratchet against the
    checked-in lint baseline. Returns (report, errors) for the dry-run
    lane — a new finding in any guarded (rule, file) is an error, exactly
    the exit-3 condition ``scripts/graftlint.py`` enforces standalone."""
    path = baseline_path or LINT_BASELINE_PATH
    if not os.path.exists(path):
        return {"skipped": f"no lint baseline at {path}"}, []
    try:
        lint = _load_astlint_module()
    except Exception as e:
        return {}, [f"cannot load astlint module: {e}"]
    baseline, err = lint.load_baseline(path)
    if err:
        return {}, [err]
    root = scan_root or os.path.join(REPO_ROOT, "deepspeed_tpu")
    findings = lint.lint_paths([root], relative_to=REPO_ROOT)
    verdict = lint.check_baseline(findings, baseline)
    return {"findings": len(findings), "counts": verdict["counts"],
            "improvements": verdict["improvements"]}, \
        verdict["regressions"]


#: checked-in exemplar postmortem bundle (telemetry/flightrec.py) — the
#: bundle schema and the analyzer's signature catalogue are pinned against
#: each other here; regenerate alongside any flightrec format bump
POSTMORTEM_EXEMPLAR_DIR = os.path.join(REPO_ROOT, "onchip_results",
                                       "postmortem_exemplar")


def _load_postmortem_module():
    """Load scripts/postmortem.py standalone (stdlib-only — the analyzer
    must run on hosts without jax, so the dry-run lane holds it to that)."""
    import importlib.util
    mod_path = os.path.join(REPO_ROOT, "scripts", "postmortem.py")
    spec = importlib.util.spec_from_file_location("_postmortem", mod_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def validate_postmortem_bundle(exemplar_dir=None):
    """Schema-validate the checked-in exemplar bundle with the analyzer's
    own ``validate_bundle`` (manifest spine, event keys, seq order,
    payload files). Returns (report, errors) for the dry-run lane."""
    d = exemplar_dir or POSTMORTEM_EXEMPLAR_DIR
    if not os.path.isdir(d):
        return {"skipped": f"no postmortem exemplar at {d}"}, []
    try:
        pm = _load_postmortem_module()
    except Exception as e:
        return {}, [f"cannot load postmortem module: {e}"]
    bundles = pm.find_bundles([d])
    if not bundles:
        return {}, [f"no postmortem-* bundle under {d}"]
    errors = []
    for b in bundles:
        errors.extend(f"{os.path.basename(b)}: {e}"
                      for e in pm.validate_bundle(b))
    return {"bundles": len(bundles)}, errors


def check_postmortem_classify(exemplar_dir=None):
    """Pin the exemplar's classification: the full analyzer pipeline
    (discover -> validate -> merge by run_id -> classify) must produce
    exactly one ``backend_unavailable`` incident — a signature-catalogue
    or timeline regression flips this. Returns (report, errors)."""
    d = exemplar_dir or POSTMORTEM_EXEMPLAR_DIR
    if not os.path.isdir(d):
        return {"skipped": f"no postmortem exemplar at {d}"}, []
    try:
        pm = _load_postmortem_module()
    except Exception as e:
        return {}, [f"cannot load postmortem module: {e}"]
    report, errors = pm.analyze([d])
    if report is None:
        return {}, errors
    incidents = [i["incident"] for i in report["incidents"]]
    if incidents != ["backend_unavailable"]:
        errors = list(errors) + [
            f"exemplar classified {incidents} != ['backend_unavailable'] — "
            f"the signature catalogue drifted from the bundle format"]
    events = sum(i["event_count"] for i in report["incidents"])
    if events < 3:
        errors = list(errors) + [
            f"exemplar incident carries {events} ring events (< 3) — the "
            f"flight-recorder timeline went missing from the bundle"]
    return {"incidents": incidents, "events": events}, errors


def compare(baseline, candidate, thresholds):
    """-> (verdicts, regressed). Only metrics on both sides are gated."""
    verdicts = []
    regressed = False
    for name, (direction, flag) in sorted(GATES.items()):
        if name not in baseline or name not in candidate:
            continue
        base, cand = baseline[name], candidate[name]
        thr = thresholds[flag]
        if base <= 0:
            continue
        delta = (cand - base) / base
        if direction == "down":
            bad = delta < -thr
        else:
            bad = delta > thr
        regressed |= bad
        verdicts.append({"metric": name, "baseline": base,
                         "candidate": cand, "delta": round(delta, 4),
                         "threshold": thr, "direction": direction,
                         "regressed": bad})
    return verdicts, regressed


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", required=True)
    ap.add_argument("--candidate", default="",
                    help="candidate doc; optional with --dry-run")
    ap.add_argument("--summary", default="",
                    help="optional standalone telemetry summary JSON merged "
                         "into the candidate metrics")
    ap.add_argument("--max-tokens-drop", type=float, default=0.10)
    ap.add_argument("--max-mfu-drop", type=float, default=0.10)
    ap.add_argument("--max-goodput-drop", type=float, default=0.10)
    ap.add_argument("--max-hbm-growth", type=float, default=0.10)
    ap.add_argument("--max-compile-growth", type=float, default=0.50)
    ap.add_argument("--max-ttft-growth", type=float, default=0.10)
    ap.add_argument("--max-tpot-growth", type=float, default=0.10)
    ap.add_argument("--max-kv-occupancy-growth", type=float, default=0.10)
    ap.add_argument("--max-exposed-growth", type=float, default=0.10,
                    help="allowed relative growth in exposed-comm seconds "
                         "(overlap report)")
    ap.add_argument("--max-prefix-hit-drop", type=float, default=0.10,
                    help="allowed relative drop in prefix-cache hit rate / "
                         "prefill reduction (--prefix-mix payloads)")
    ap.add_argument("--max-rate-multiplier-drop", type=float, default=0.10,
                    help="allowed relative drop in the fleet saturation-"
                         "rate multiplier (--fleet --replay payloads)")
    ap.add_argument("--max-swap-stall-growth", type=float, default=0.25,
                    help="allowed relative growth in host-tier swap-in "
                         "stall seconds (--long-context payloads)")
    ap.add_argument("--min-slo-attainment", type=float, default=None,
                    help="fail (exit 3) when the candidate's worst "
                         "per-SLO-class attainment (extra.slo_min_attainment "
                         "/ extra.slo_classes) is below this floor; exit 2 "
                         "when the candidate carries no SLO data")
    ap.add_argument("--dry-run", action="store_true",
                    help="validate inputs (parse + summary schema) only")
    args = ap.parse_args(argv)

    docs = {"baseline": load_doc(args.baseline)}
    if args.candidate:
        docs["candidate"] = load_doc(args.candidate)
    if args.summary:
        docs["summary"] = load_doc(args.summary)
    for label, doc in docs.items():
        if doc is None:
            return 2
        err = validate_summary(doc) or validate_serving_payload(doc) \
            or validate_fleet_payload(doc) or validate_chaos_payload(doc) \
            or validate_longctx_payload(doc) \
            or validate_speculate_payload(doc) \
            or validate_overlap_payload(doc) \
            or validate_timeseries_payload(doc) or validate_slo_payload(doc)
        if err:
            print(f"perf_gate: {label}: {err}", file=sys.stderr)
            return 2

    if args.dry_run:
        table_report, table_errors = check_kernel_tables()
        for err in table_errors:
            print(f"perf_gate: kernel_table: {err}", file=sys.stderr)
        qgz_report, qgz_errors = check_qgz_wire()
        for err in qgz_errors:
            print(f"perf_gate: qgz_wire: {err}", file=sys.stderr)
        moe_wire_report, moe_wire_errors = check_moe_wire()
        for err in moe_wire_errors:
            print(f"perf_gate: moe_wire: {err}", file=sys.stderr)
        overlap_report, overlap_errors = check_overlap_analytic()
        for err in overlap_errors:
            print(f"perf_gate: overlap: {err}", file=sys.stderr)
        sched_report, sched_errors = check_overlap_schedule()
        for err in sched_errors:
            print(f"perf_gate: overlap_schedule: {err}", file=sys.stderr)
        moe_base_report, moe_base_errors = check_moe_baseline()
        for err in moe_base_errors:
            print(f"perf_gate: moe_baseline: {err}", file=sys.stderr)
        prefix_report, prefix_errors = check_prefix_baseline()
        for err in prefix_errors:
            print(f"perf_gate: prefix_cache: {err}", file=sys.stderr)
        fleet_report, fleet_errors = check_fleet_baseline()
        for err in fleet_errors:
            print(f"perf_gate: fleet: {err}", file=sys.stderr)
        kvfabric_report, kvfabric_errors = check_kvfabric_baseline()
        for err in kvfabric_errors:
            print(f"perf_gate: kvfabric: {err}", file=sys.stderr)
        chaos_report, chaos_errors = check_chaos_baseline()
        for err in chaos_errors:
            print(f"perf_gate: chaos: {err}", file=sys.stderr)
        longctx_report, longctx_errors = check_longctx_baseline()
        for err in longctx_errors:
            print(f"perf_gate: longctx: {err}", file=sys.stderr)
        spec_report, spec_errors = check_speculate_baseline()
        for err in spec_errors:
            print(f"perf_gate: speculate: {err}", file=sys.stderr)
        elastic_report, elastic_errors = check_elastic_baseline()
        for err in elastic_errors:
            print(f"perf_gate: elastic: {err}", file=sys.stderr)
        lint_report, lint_errors = check_lint_baseline()
        for err in lint_errors:
            print(f"perf_gate: lint: {err}", file=sys.stderr)
        profile_report, profile_errors = check_profile_store()
        for err in profile_errors:
            print(f"perf_gate: profile_store: {err}", file=sys.stderr)
        slo_report, slo_errors = check_slo_baseline()
        for err in slo_errors:
            print(f"perf_gate: slo: {err}", file=sys.stderr)
        pm_report, pm_errors = validate_postmortem_bundle()
        for err in pm_errors:
            print(f"perf_gate: postmortem_bundle: {err}", file=sys.stderr)
        pm_cls_report, pm_cls_errors = check_postmortem_classify()
        for err in pm_cls_errors:
            print(f"perf_gate: postmortem_classify: {err}", file=sys.stderr)
        errors = table_errors + qgz_errors + moe_wire_errors \
            + overlap_errors + sched_errors + moe_base_errors \
            + prefix_errors + fleet_errors + kvfabric_errors \
            + chaos_errors \
            + longctx_errors + spec_errors + elastic_errors + lint_errors \
            + profile_errors + slo_errors + pm_errors + pm_cls_errors
        print(json.dumps({"dry_run": True,
                          "inputs_ok": not errors,
                          "kernel_table": table_report,
                          "qgz_wire": qgz_report,
                          "moe_wire": moe_wire_report,
                          "overlap": overlap_report,
                          "overlap_schedule": sched_report,
                          "moe_baseline": moe_base_report,
                          "prefix_cache": prefix_report,
                          "fleet": fleet_report,
                          "kvfabric": kvfabric_report,
                          "chaos": chaos_report,
                          "longctx": longctx_report,
                          "speculate": spec_report,
                          "elastic": elastic_report,
                          "lint": lint_report,
                          "profile_store": profile_report,
                          "slo": slo_report,
                          "postmortem_bundle": pm_report,
                          "postmortem_classify": pm_cls_report,
                          "metrics": {label: extract_metrics(doc)
                                      for label, doc in docs.items()}}))
        return 2 if errors else 0

    if "candidate" not in docs:
        print("perf_gate: --candidate is required without --dry-run",
              file=sys.stderr)
        return 2
    base_m = extract_metrics(docs["baseline"])
    cand_m = extract_metrics(docs["candidate"])
    if "summary" in docs:
        for k, v in extract_metrics(docs["summary"]).items():
            cand_m.setdefault(k, v)

    thresholds = {"max_swap_stall_growth": args.max_swap_stall_growth,
                  "max_tokens_drop": args.max_tokens_drop,
                  "max_mfu_drop": args.max_mfu_drop,
                  "max_goodput_drop": args.max_goodput_drop,
                  "max_hbm_growth": args.max_hbm_growth,
                  "max_compile_growth": args.max_compile_growth,
                  "max_ttft_growth": args.max_ttft_growth,
                  "max_tpot_growth": args.max_tpot_growth,
                  "max_kv_occupancy_growth": args.max_kv_occupancy_growth,
                  "max_exposed_growth": args.max_exposed_growth,
                  "max_prefix_hit_drop": args.max_prefix_hit_drop,
                  "max_rate_multiplier_drop": args.max_rate_multiplier_drop}
    verdicts, regressed = compare(base_m, cand_m, thresholds)
    if args.min_slo_attainment is not None:
        att = _slo_min_attainment(docs["candidate"])
        if att is None:
            print("perf_gate: --min-slo-attainment given but the candidate "
                  "carries no per-class SLO data", file=sys.stderr)
            return 2
        bad = att < args.min_slo_attainment
        regressed |= bad
        verdicts.append({"metric": "slo_min_attainment", "baseline":
                         args.min_slo_attainment, "candidate": att,
                         "delta": round(att - args.min_slo_attainment, 6),
                         "threshold": args.min_slo_attainment,
                         "direction": "down", "regressed": bad})
    result = {"compared": len(verdicts), "regressed": regressed,
              "verdicts": verdicts,
              "baseline_metrics": base_m, "candidate_metrics": cand_m}
    print(json.dumps(result, indent=2))
    if not verdicts:
        print("perf_gate: WARNING no overlapping metrics to compare "
              "(empty baseline?) — passing", file=sys.stderr)
        return 0
    if regressed:
        bad = [v["metric"] for v in verdicts if v["regressed"]]
        print(f"perf_gate: REGRESSION in {', '.join(bad)}", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
