#!/usr/bin/env python
"""Postmortem bundle analyzer — classify abnormal exits after the fact.

Loads one or many flight-recorder bundles (telemetry/flightrec.py), merges
multi-process/multi-host bundles by ``run_id`` (the trace_merge pattern),
reconstructs a causal event timeline from the ring contents, classifies
each incident against the known signature catalogue, and emits a human
verdict (stderr) plus ONE machine-readable JSON payload line (stdout) —
the same emit contract as bench.py.

Usage::

    python scripts/postmortem.py BUNDLE_OR_PARENT [more ...]
    python scripts/postmortem.py /runs/postmortems        # scans for
                                                          # postmortem-* dirs

Incident types (docs/OBSERVABILITY.md signature catalogue)::

    oom | stall | preemption | slice_loss | replica_loss | corrupt_ckpt
    | backend_unavailable | unknown

Exit codes: 0 = every bundle loaded and classified; 2 = no bundle found
or a bundle was malformed (missing/unparsable manifest or events).

Stdlib-only — runs on hosts without jax (the whole point: the process that
would have imported jax is dead).
"""

import argparse
import json
import os
import sys

BUNDLE_PREFIX = "postmortem-"
MANIFEST_NAME = "manifest.json"
EVENTS_NAME = "events.jsonl"
SUMMARY_NAME = "summary.json"
STATE_NAME = "state.json"

REPORT_SCHEMA = "postmortem_report.v1"

INCIDENT_TYPES = ("oom", "stall", "preemption", "slice_loss",
                  "replica_loss", "corrupt_ckpt", "backend_unavailable",
                  "unknown")

#: flush reasons that map straight to an incident type (the flusher knew
#: what was happening); event-signature matching covers the rest.
_REASON_MAP = {
    "oom": "oom",
    "stall": "stall",
    "watchdog_stall": "stall",
    "preemption": "preemption",
    "slice_loss": "slice_loss",
    "replica_loss": "replica_loss",
    "corrupt_ckpt": "corrupt_ckpt",
    "backend_unavailable": "backend_unavailable",
}

#: fault points whose presence in the ring implies an incident type even
#: when the flush reason is generic (unhandled_exception, injected_exit).
_FAULT_POINT_MAP = {
    "slice.lost": "slice_loss",
    "comm.partition": "slice_loss",
    "replica.lost": "replica_loss",
    "replica.stall": "replica_loss",
    "step.hang": "stall",
    "ckpt.write": "corrupt_ckpt",
    "ckpt.publish": "corrupt_ckpt",
}

_EXIT_CODE_MAP = {83: "preemption", 84: "slice_loss", 85: "stall"}


# ---------------------------------------------------------------------------
# loading + validation

def find_bundles(paths):
    """Expand each path to bundle dirs: a path that IS a bundle (has a
    manifest) counts as one; otherwise its ``postmortem-*`` children do."""
    out = []
    for p in paths:
        if os.path.isfile(os.path.join(p, MANIFEST_NAME)):
            out.append(p)
            continue
        if os.path.isdir(p):
            for name in sorted(os.listdir(p)):
                sub = os.path.join(p, name)
                if (name.startswith(BUNDLE_PREFIX) and ".tmp." not in name
                        and os.path.isfile(os.path.join(sub, MANIFEST_NAME))):
                    out.append(sub)
    return out


def load_bundle(path):
    """Load one bundle directory into a dict; raises on a malformed
    manifest/events (the crash-consistent publish makes partial bundles
    impossible, so malformed means tampered or truncated-in-transit)."""
    with open(os.path.join(path, MANIFEST_NAME)) as f:
        manifest = json.load(f)
    events = []
    ev_path = os.path.join(path, EVENTS_NAME)
    if os.path.exists(ev_path):
        with open(ev_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                events.append(json.loads(line))
    out = {"path": path, "manifest": manifest, "events": events,
           "summary": None, "state": None}
    for key, name in (("summary", SUMMARY_NAME), ("state", STATE_NAME)):
        p = os.path.join(path, name)
        if os.path.exists(p):
            try:
                with open(p) as f:
                    out[key] = json.load(f)
            except (OSError, ValueError):
                pass  # optional payloads: forensic extras, not the spine
    return out


_MANIFEST_REQUIRED = ("format_version", "kind", "reason", "host", "pid",
                      "run_id", "created_unix")
_EVENT_REQUIRED = ("seq", "ts", "kind", "name")


def validate_bundle(path):
    """Schema-validate one bundle dir; returns a list of error strings
    (empty = valid). Shared with perf_gate's ``validate_postmortem_bundle``
    dry-run check."""
    errors = []
    try:
        b = load_bundle(path)
    except (OSError, ValueError, KeyError) as e:
        return [f"unreadable bundle {path}: {type(e).__name__}: {e}"]
    man = b["manifest"]
    for key in _MANIFEST_REQUIRED:
        if key not in man:
            errors.append(f"manifest missing key {key!r}")
    if man.get("kind") != "postmortem_bundle":
        errors.append(f"manifest kind {man.get('kind')!r} != "
                      f"'postmortem_bundle'")
    if not isinstance(man.get("format_version"), int):
        errors.append("manifest format_version is not an int")
    for i, ev in enumerate(b["events"]):
        for key in _EVENT_REQUIRED:
            if key not in ev:
                errors.append(f"event #{i} missing key {key!r}")
                break
    seqs = [ev.get("seq") for ev in b["events"]]
    if seqs != sorted(seqs):
        errors.append("events are not in seq order")
    if not os.path.exists(os.path.join(path, SUMMARY_NAME)):
        errors.append(f"missing {SUMMARY_NAME}")
    if not os.path.exists(os.path.join(path, STATE_NAME)):
        errors.append(f"missing {STATE_NAME}")
    return errors


# ---------------------------------------------------------------------------
# classification

def _fault_points(events):
    """Injected/observed fault points in the ring: ``Fault/<point>``
    event names plus explicit ``fault_point`` manifest extras."""
    pts = []
    for ev in events:
        name = ev.get("name", "")
        if name.startswith("Fault/"):
            pts.append(name[len("Fault/"):])
    return pts


def classify_bundle(bundle):
    """Classify ONE bundle -> (incident_type, evidence list). Signature
    order is fixed: a direct flush reason wins, then fault-point and
    event-name signatures in catalogue order, then the exit code."""
    man = bundle["manifest"]
    events = bundle["events"]
    reason = str(man.get("reason", ""))
    evidence = []

    direct = _REASON_MAP.get(reason)
    if direct:
        return direct, [f"flush reason {reason!r}"]

    points = _fault_points(events)
    names = [ev.get("name", "") for ev in events]
    extra = man.get("extra") or {}
    if isinstance(extra, dict) and extra.get("fault_point"):
        points.append(str(extra["fault_point"]))

    # catalogue order mirrors INCIDENT_TYPES (docs/OBSERVABILITY.md)
    if "oom" in points:
        return "oom", ["Fault/oom event in ring"]
    for pt in points:
        mapped = _FAULT_POINT_MAP.get(pt)
        if mapped in ("slice_loss", "replica_loss"):
            return mapped, [f"fault point {pt!r} in ring"]
    if "slice_lost" in points:
        return "slice_loss", ["Fault/slice_lost event in ring"]
    if any(n == "replica/lost" or n == "replica/dead" for n in names):
        return "replica_loss", ["replica lifecycle death in ring"]
    if "ckpt_corrupt" in points:
        return "corrupt_ckpt", ["Fault/ckpt_corrupt event in ring"]
    if "backend_unavailable" in points:
        return "backend_unavailable", ["Fault/backend_unavailable in ring"]
    if "preemption" in points:
        return "preemption", ["Fault/preemption event in ring"]
    if "hang" in points:
        return "stall", ["Fault/hang (watchdog) event in ring"]
    for pt in points:
        mapped = _FAULT_POINT_MAP.get(pt)
        if mapped:
            return mapped, [f"fault point {pt!r} in ring"]

    code = man.get("exit_code")
    if code in _EXIT_CODE_MAP:
        return _EXIT_CODE_MAP[code], [f"exit code {code}"]
    evidence.append(f"flush reason {reason!r} matched no signature")
    return "unknown", evidence


def _merge_timeline(bundles):
    """Causal timeline across one incident's bundles: every ring event
    stamped with (host, pid), ordered by wall-clock ts then seq. Bundle
    timestamps are wall time (flightrec records time.time), so cross-host
    order is as causal as the hosts' clocks."""
    out = []
    for b in bundles:
        man = b["manifest"]
        who = f"{man.get('host', '?')}:{man.get('pid', '?')}"
        for ev in b["events"]:
            out.append({"ts": ev.get("ts", 0), "seq": ev.get("seq", 0),
                        "who": who, "kind": ev.get("kind"),
                        "name": ev.get("name"),
                        "detail": ev.get("detail")})
    out.sort(key=lambda e: (e["ts"], e["who"], e["seq"]))
    return out


def classify_incident(bundles):
    """Classify one run_id group. Per-bundle classifications are combined
    by specificity: any non-unknown type beats unknown; ties between
    different concrete types keep catalogue order (the earliest in
    INCIDENT_TYPES — the most root-cause-ish signature — names the
    incident, the rest ride as evidence)."""
    per = []
    for b in bundles:
        typ, ev = classify_bundle(b)
        per.append((typ, ev, b))
    concrete = [t for (t, _, _) in per if t != "unknown"]
    if concrete:
        incident = min(concrete, key=INCIDENT_TYPES.index)
    else:
        incident = "unknown"
    evidence = []
    for typ, ev, b in per:
        for e in ev:
            evidence.append(f"{os.path.basename(b['path'])}: {e}"
                            + (f" -> {typ}" if typ != incident else ""))
    timeline = _merge_timeline(bundles)
    return {
        "incident": incident,
        "run_id": bundles[0]["manifest"].get("run_id"),
        "bundles": [b["path"] for b in bundles],
        "hosts": sorted({b["manifest"].get("host") for b in bundles}),
        "pids": sorted({b["manifest"].get("pid") for b in bundles}),
        "exit_codes": sorted({b["manifest"].get("exit_code")
                              for b in bundles
                              if b["manifest"].get("exit_code") is not None}),
        "reasons": sorted({b["manifest"].get("reason") for b in bundles}),
        "evidence": evidence,
        "event_count": len(timeline),
        "first_ts": timeline[0]["ts"] if timeline else None,
        "last_ts": timeline[-1]["ts"] if timeline else None,
        "timeline_tail": timeline[-8:],
    }


def analyze(paths):
    """Full pipeline: discover -> validate -> group by run_id -> classify.
    Returns (report dict, error list)."""
    errors = []
    bundle_dirs = find_bundles(paths)
    if not bundle_dirs:
        return None, [f"no postmortem bundle found under {list(paths)}"]
    bundles = []
    for d in bundle_dirs:
        errs = validate_bundle(d)
        if errs:
            errors.extend(f"{d}: {e}" for e in errs)
            continue
        bundles.append(load_bundle(d))
    groups = {}
    for b in bundles:
        groups.setdefault(b["manifest"].get("run_id"), []).append(b)
    incidents = [classify_incident(bs)
                 for _, bs in sorted(groups.items(),
                                     key=lambda kv: str(kv[0]))]
    report = {"schema": REPORT_SCHEMA,
              "bundles": len(bundles),
              "malformed": len(bundle_dirs) - len(bundles),
              "incidents": incidents}
    return report, errors


# ---------------------------------------------------------------------------
# CLI

def _human_verdict(report, out=sys.stderr):
    for inc in report["incidents"]:
        hosts = ",".join(str(h) for h in inc["hosts"])
        codes = ",".join(str(c) for c in inc["exit_codes"]) or "-"
        print(f"incident run_id={inc['run_id']}: "
              f"{inc['incident'].upper()} "
              f"({len(inc['bundles'])} bundle(s), hosts [{hosts}], "
              f"exit [{codes}], {inc['event_count']} events)", file=out)
        for e in inc["evidence"]:
            print(f"  evidence: {e}", file=out)
        for ev in inc["timeline_tail"]:
            print(f"  {ev['ts']:.3f} {ev['who']} {ev['kind']}:{ev['name']}",
                  file=out)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="classify postmortem bundles into incident verdicts")
    ap.add_argument("paths", nargs="+",
                    help="bundle dirs and/or parents containing postmortem-*")
    ap.add_argument("--json-out", default="",
                    help="also write the JSON report to this file")
    args = ap.parse_args(argv)

    report, errors = analyze(args.paths)
    for e in errors:
        print(f"postmortem: {e}", file=sys.stderr)
    if report is None:
        return 2
    _human_verdict(report)
    line = json.dumps(report, sort_keys=True, default=str)
    print(line)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(line + "\n")
    return 2 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
