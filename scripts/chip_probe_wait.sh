#!/bin/bash
# Probe the TPU backend until it answers, then exit 0 (caller reacts).
# Logs every probe to onchip_results/watcher.log. Exits 1 at deadline.
# Usage: chip_probe_wait.sh [interval_seconds] [max_seconds]
INTERVAL=${1:-240}
MAXSEC=${2:-39600}
LOG=/root/repo/onchip_results/watcher.log
mkdir -p /root/repo/onchip_results
START=$(date +%s)
echo "probe-wait start $(date) interval=${INTERVAL}s max=${MAXSEC}s" >> "$LOG"
while :; do
  if timeout 90 python -c "import jax; d=jax.devices(); print(d)" >/dev/null 2>&1; then
    echo "CHIP BACK $(date)" >> "$LOG"
    exit 0
  fi
  echo "probe: still wedged $(date)" >> "$LOG"
  NOW=$(date +%s)
  if [ $((NOW - START)) -ge "$MAXSEC" ]; then
    echo "probe-wait deadline $(date)" >> "$LOG"
    exit 1
  fi
  sleep "$INTERVAL"
done
