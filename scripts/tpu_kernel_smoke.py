"""Real-hardware smoke test for every Pallas kernel in the tree.

Interpret-mode (CPU) tests validate numerics but NOT Mosaic lowering — block
shapes that violate the (8, 128) tiling rules only fail on a real TPU. This
script compiles and runs each kernel on the attached chip and checks numerics
against its pure-XLA twin. Run it after touching any kernel:

    python scripts/tpu_kernel_smoke.py

One TPU job at a time — the chip is exclusive.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

FAILED = []


def check(name, got, want, atol, rtol=1e-2):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    err = np.max(np.abs(got - want))
    ok = np.allclose(got, want, atol=atol, rtol=rtol)
    print(f"{'PASS' if ok else 'FAIL'} {name}: max err {err:.4g}", flush=True)
    if not ok:
        FAILED.append(name)


def smoke_flash():
    from deepspeed_tpu.ops.flash_attention import mha_reference
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha

    B, T, H, Dh = 2, 512, 4, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, T, H, Dh), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, T, H, Dh), jnp.bfloat16)
    out = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True))(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    check("flash_mha fwd", out, ref, atol=0.05)

    def loss(f):
        return lambda q, k, v: jnp.sum(
            f(q, k, v, causal=True).astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss(flash_mha), argnums=(0, 1, 2)))(q, k, v)
    gr = jax.grad(loss(mha_reference), argnums=(0, 1, 2))(q, k, v)
    for n, a, b in zip("qkv", g, gr):
        scale = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) or 1.0
        check(f"flash_mha d{n}", np.asarray(a) / scale, np.asarray(b) / scale,
              atol=0.05)

    # sliding window (in-kernel block skip + DMA-clamped index maps) — the
    # clamped index maps are traced scalar programs that must lower on Mosaic
    out_w = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True,
                                              window=128))(q, k, v)
    ref_w = mha_reference(q, k, v, causal=True, window=128)
    check("flash_mha window fwd", out_w, ref_w, atol=0.05)
    gw = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_mha(q, k, v, causal=True, window=128)
                                .astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    gwr = jax.grad(
        lambda q, k, v: jnp.sum(mha_reference(q, k, v, causal=True,
                                              window=128)
                                .astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for n, a, b in zip("qkv", gw, gwr):
        scale = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) or 1.0
        check(f"flash_mha window d{n}", np.asarray(a) / scale,
              np.asarray(b) / scale, atol=0.05)

    # packed-sequence segment ids (lane-/sublane-replicated tile layouts)
    rng = np.random.default_rng(0)
    cuts = np.sort(rng.choice(np.arange(1, T), size=3, replace=False))
    seg = jnp.asarray(np.searchsorted(cuts, np.arange(T), side="right")
                      [None, :].repeat(B, axis=0).astype(np.int32))
    out_s = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True,
                                              segment_ids=(seg, seg)))(q, k, v)
    ref_s = mha_reference(q, k, v, causal=True, segment_ids=(seg, seg))
    check("flash_mha segments fwd", out_s, ref_s, atol=0.05)


def smoke_paged():
    from deepspeed_tpu.inference.v2.model_implementations.llama import (
        _paged_attention_dense)
    from deepspeed_tpu.ops.pallas.paged_attention import paged_mha

    S, Q, H, KV, Dh, NB, bs, MB = 3, 2, 4, 2, 64, 10, 16, 4
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (S, Q, H, Dh), jnp.bfloat16)
    kp = jax.random.normal(ks[1], (NB, KV, bs, Dh), jnp.bfloat16)
    vp = jax.random.normal(ks[2], (NB, KV, bs, Dh), jnp.bfloat16)
    rng = np.random.default_rng(0)
    bt = jnp.asarray(rng.permutation((NB - 1) * MB)[: S * MB]
                     .reshape(S, MB) % (NB - 1), jnp.int32)
    seen = jnp.asarray(rng.integers(0, MB * bs - Q, size=S), jnp.int32)
    q_len = jnp.full((S,), Q, jnp.int32)
    out = jax.jit(paged_mha)(q, kp, vp, bt, seen, q_len)
    ref = _paged_attention_dense(q, kp, vp, bt, seen, bs)
    mask = np.arange(Q)[None, :] < np.asarray(q_len)[:, None]
    check("paged_mha decode", np.asarray(out)[mask], np.asarray(ref)[mask],
          atol=0.05)


def smoke_block_sparse():
    from deepspeed_tpu.ops.pallas.block_sparse_attention import sparse_mha
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        sparse_attention)

    B, H, S, D, block = 2, 4, 1024, 64, 128
    nq = S // block
    rng = np.random.default_rng(2)
    layout = (rng.random((H, nq, nq)) < 0.4)
    layout |= np.eye(nq, dtype=bool)[None]
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.bfloat16)
    out = sparse_mha(q, k, v, layout.astype(np.int32), block, causal=True)
    ref = sparse_attention(q, k, v, layout.astype(np.int32), block,
                           causal=True)
    check("sparse_mha fwd", out, ref, atol=0.05)


def smoke_grouped_gemm():
    from deepspeed_tpu.inference.v2.model_implementations.mixtral import (
        _moe_ffn)
    from deepspeed_tpu.ops.pallas.grouped_gemm import moe_ffn_gmm, topk_router

    ks = jax.random.split(jax.random.PRNGKey(4), 5)
    T, D, F, E, k = 40, 128, 256, 4, 2
    x = jax.random.normal(ks[0], (T, D), jnp.bfloat16)
    gate = jax.random.normal(ks[1], (D, E), jnp.float32) * 0.3
    w1 = jax.random.normal(ks[2], (E, D, F), jnp.bfloat16) * 0.05
    w2 = jax.random.normal(ks[3], (E, F, D), jnp.bfloat16) * 0.05
    w3 = jax.random.normal(ks[4], (E, D, F), jnp.bfloat16) * 0.05
    tv, ti = topk_router(x, gate, k)
    out = jax.jit(lambda *a: moe_ffn_gmm(*a, n_experts=E, dtype=jnp.bfloat16))(
        x, tv, ti, w1, w2, w3)
    ref = _moe_ffn(x, gate, w1, w2, w3, k=k, dtype=jnp.bfloat16,
                   force_einsum=True)
    check("moe_ffn_gmm", out, ref, atol=0.05)


def smoke_quantized_matmul():
    from deepspeed_tpu.inference.quantization.quantization import (
        QuantizedParameter)
    from deepspeed_tpu.ops.pallas.quantized_matmul import quantized_matmul

    ks = jax.random.split(jax.random.PRNGKey(5), 2)
    x = jax.random.normal(ks[0], (16, 512), jnp.bfloat16)
    w = np.asarray(jax.random.normal(ks[1], (512, 256), jnp.float32)) * 0.1
    qp = QuantizedParameter.from_array(w, num_bits=8, group_size=128)
    out = jax.jit(lambda a, q, s: quantized_matmul(a, q, s, 128))(
        x, qp.q, qp.scale)
    ref = x @ qp.dequantized(jnp.bfloat16)
    check("quantized_matmul", out, ref, atol=0.1)


SMOKES = {"flash": smoke_flash, "paged": smoke_paged,
          "block_sparse": smoke_block_sparse,
          "grouped_gemm": smoke_grouped_gemm,
          "quantized_matmul": smoke_quantized_matmul}


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", choices=sorted(SMOKES),
                    help="run a single kernel smoke in-process")
    ap.add_argument("--timeout", type=float, default=600,
                    help="per-kernel subprocess deadline (seconds) — first "
                         "Mosaic compiles over the axon tunnel can take "
                         "60-120s EACH, and a kernel smoke compiles several")
    args = ap.parse_args()

    if args.only:
        print("devices:", jax.devices(), flush=True)
        SMOKES[args.only]()
        sys.exit(1 if FAILED else 0)

    # parent mode: one subprocess per kernel so a hang (e.g. a Mosaic compile
    # that never returns) identifies the kernel and doesn't take out the
    # whole run; output is unbuffered into per-kernel logs
    import subprocess
    failed = []
    for name in SMOKES:
        log = f"/tmp/tpu_smoke_{name}.log"
        print(f"== {name} (log: {log})", flush=True)
        with open(log, "w") as lf:
            try:
                rc = subprocess.run(
                    [sys.executable, "-u", os.path.abspath(__file__),
                     "--only", name],
                    stdout=lf, stderr=subprocess.STDOUT,
                    timeout=args.timeout).returncode
            except subprocess.TimeoutExpired:
                rc = -1
                lf.write(f"\nTIMEOUT after {args.timeout}s\n")
        print(open(log).read(), end="", flush=True)
        if rc != 0:
            failed.append(name)
            print(f"== {name}: {'TIMEOUT/hang' if rc == -1 else 'FAILED'}",
                  flush=True)
            if rc == -1:
                # a killed TPU process can wedge the chip; don't pile on
                print("== stopping: chip may be held after the hang — "
                      "remaining kernels skipped", flush=True)
                break
    if failed:
        print("FAILED:", failed, flush=True)
        sys.exit(1)
    print("all kernels lower and match on TPU", flush=True)


if __name__ == "__main__":
    main()
