"""Microbench: Pallas flash attention vs XLA einsum attention on the TPU chip.

Run standalone (one TPU job at a time — the chip is exclusive):
    python scripts/bench_flash_attn.py
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.flash_attention import mha_reference
from deepspeed_tpu.ops.pallas.flash_attention import flash_mha


def bench(f, *args, n=20):
    jax.block_until_ready(f(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        r = f(*args)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / n * 1000


def main():
    print("devices:", jax.devices(), flush=True)
    B, T, H, Dh = 16, 1024, 12, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, T, H, Dh), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, T, H, Dh), jnp.bfloat16)

    f_flash = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True))
    f_ref = jax.jit(lambda q, k, v: mha_reference(q, k, v, causal=True))
    print("compiling flash fwd...", flush=True)
    o1 = jax.block_until_ready(f_flash(q, k, v))
    print("compiling ref fwd...", flush=True)
    o2 = jax.block_until_ready(f_ref(q, k, v))
    err = jnp.max(jnp.abs(o1.astype(jnp.float32) - o2.astype(jnp.float32)))
    print("fwd max err:", float(err), flush=True)

    def loss_f(q, k, v):
        return jnp.sum(flash_mha(q, k, v, causal=True).astype(jnp.float32) ** 2)

    def loss_r(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True).astype(jnp.float32) ** 2)

    g_flash = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))
    g_ref = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2)))
    print("compiling flash bwd...", flush=True)
    gf = jax.block_until_ready(g_flash(q, k, v))
    print("compiling ref bwd...", flush=True)
    gr = jax.block_until_ready(g_ref(q, k, v))
    for name, a, b in zip("qkv", gf, gr):
        e = jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32)))
        m = jnp.max(jnp.abs(b.astype(jnp.float32)))
        print(f"d{name} max abs err: {float(e):.4f} (max |ref| {float(m):.1f})", flush=True)

    print(f"fwd    flash {bench(f_flash, q, k, v):.2f}ms  ref {bench(f_ref, q, k, v):.2f}ms", flush=True)
    print(f"fwdbwd flash {bench(g_flash, q, k, v):.2f}ms  ref {bench(g_ref, q, k, v):.2f}ms", flush=True)

    # long-context leg: 4k sequence, GQA 4:1 — where flash matters most
    B2, T2, H2, KV2, Dh2 = 2, 4096, 16, 4, 128
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q2 = jax.random.normal(ks[0], (B2, T2, H2, Dh2), jnp.bfloat16)
    k2 = jax.random.normal(ks[1], (B2, T2, KV2, Dh2), jnp.bfloat16)
    v2 = jax.random.normal(ks[2], (B2, T2, KV2, Dh2), jnp.bfloat16)
    f2 = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True))
    r2 = jax.jit(lambda q, k, v: mha_reference(q, k, v, causal=True))
    print("compiling 4k...", flush=True)
    e2 = jnp.max(jnp.abs(jax.block_until_ready(f2(q2, k2, v2)).astype(jnp.float32)
                         - r2(q2, k2, v2).astype(jnp.float32)))
    print(f"4k GQA fwd max err: {float(e2)}", flush=True)
    print(f"4k GQA fwd flash {bench(f2, q2, k2, v2):.2f}ms  ref {bench(r2, q2, k2, v2):.2f}ms", flush=True)

    # sliding-window leg at 4k: DMA-elided block skip should scale ~T*W
    fw = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True, window=512))
    print("compiling 4k window...", flush=True)
    jax.block_until_ready(fw(q2, k2, v2))
    print(f"4k GQA window=512 fwd flash {bench(fw, q2, k2, v2):.2f}ms "
          f"(vs full-causal above)", flush=True)

    # packed-segments leg: 8 random documents per row
    import numpy as np
    rng = np.random.default_rng(0)
    cuts = np.sort(rng.choice(np.arange(1, T2), size=7, replace=False))
    seg = jnp.asarray(np.searchsorted(cuts, np.arange(T2), side="right")
                      [None, :].repeat(B2, axis=0).astype(np.int32))
    fs = jax.jit(lambda q, k, v: flash_mha(q, k, v, causal=True,
                                           segment_ids=(seg, seg)))
    print("compiling 4k segments...", flush=True)
    jax.block_until_ready(fs(q2, k2, v2))
    print(f"4k GQA packed-segments fwd flash {bench(fs, q2, k2, v2):.2f}ms",
          flush=True)


if __name__ == "__main__":
    main()
