"""End-to-end fault drill (docs/RESILIENCE.md) — kill/resume on CPU.

Four drills, each exercising a real process boundary (SIGKILL/SIGTERM on a
live training subprocess), pinning the acceptance behaviors the unit suite
(tests/test_resilience.py) checks in-process:

1. ``kill-async-save``  SIGKILL the trainer while an async checkpoint
   worker is inside the publish window (held open by a ``ckpt.publish``
   sleep fault). The live tag must remain loadable — the atomic
   tmp+rename publish means a crash at ANY instant leaves a complete tag.
2. ``bitflip``          flip one byte in the newest tag's array shard; the
   checksum manifest must catch it, quarantine the tag, and the load must
   transparently fall back to the prior tag (and repair ``latest``).
3. ``preemption``       real SIGTERM to a training process with the
   preemption handler enabled: it writes an emergency checkpoint at the
   next step boundary and exits 83 (clean preemption — budget-free for the
   elastic agent); a fresh engine then resumes from the emergency tag.
4. ``watchdog``         inject a ``step.hang`` stall into a process running
   the watchdog with ``abort`` on; the watchdog must dump stacks and
   hard-exit 85 within one heartbeat.
5. ``slice-loss``       elastic shrink under the agent: a 4-host gang loses
   its upper half mid-async-publish (SIGKILL), the survivors detect the
   slice loss, save an emergency universal checkpoint, and exit 84
   (reshardable slice loss); the elastic agent excludes the dead hosts and
   relaunches the 2 survivors budget-free, which resume from the exact
   checkpointed step — the loss trajectory continues.
6. ``replica-loss``     SERVING fleet chaos (subprocess on 8 forced CPU
   devices): a ``replica.lost`` fault kills a decode replica mid-stream;
   survivors must stay bit-exact, the dead replica's streams must re-admit
   and complete bit-exact against the fault-free run (seeded sampling
   included), and the fleet page census must show zero leaked KV pages.

``--emit-elastic-baseline PATH`` additionally runs the in-process 8→4→8
mesh reshard drill (resilience/elastic_reshard.py, 8 forced CPU devices)
and writes its payload — the checked-in
``onchip_results/elastic_drill_baseline.json`` that
``perf_gate.py --dry-run`` ratchets (``check_elastic_baseline``).

Usage:  python scripts/fault_drill.py [--drill NAME] [--keep]
Exit 0 iff every selected drill passes.
"""

import argparse
import importlib.util
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

EXIT_CLEAN_PREEMPTION = 83
EXIT_WATCHDOG_ABORT = 85

POSTMORTEM_ENV = "DS_TPU_POSTMORTEM_DIR"


def _postmortem_mod():
    """Load scripts/postmortem.py standalone (stdlib-only analyzer)."""
    spec = importlib.util.spec_from_file_location(
        "ds_tpu_postmortem", os.path.join(REPO, "scripts", "postmortem.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _assert_bundles(pm_dir, expect, desc):
    """Forensics leg of every drill: the kill/crash left EXACTLY the
    expected postmortem bundles, each schema-valid and classified by
    scripts/postmortem.py to the drill's incident type. ``expect`` maps
    incident type -> exact bundle count."""
    pm = _postmortem_mod()
    bundles = pm.find_bundles([pm_dir])
    got = {}
    for b in bundles:
        errs = pm.validate_bundle(b)
        assert not errs, f"{desc}: malformed bundle {b}: {errs}"
        typ, evidence = pm.classify_bundle(pm.load_bundle(b))
        got[typ] = got.get(typ, 0) + 1
    assert got == expect, (f"{desc}: bundle classification {got} != "
                           f"{expect} (bundles: {bundles})")
    return bundles

# one trainer template, parameterized by the resilience config and loop
# behavior — every drill runs this as a real subprocess
TRAINER = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import deepspeed_tpu
from tests.simple_model import SimpleModel, random_batches

out = sys.argv[1]
model = SimpleModel()
batch = random_batches(1, 8)[0]
params = model.init(jax.random.PRNGKey(0), batch)["params"]
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, config={config})
batches = random_batches(4, 8)
{body}
"""


def _write_trainer(workdir, config, body):
    p = os.path.join(workdir, "trainer.py")
    with open(p, "w") as f:
        f.write(TRAINER.format(repo=REPO, config=config,
                               body=textwrap.dedent(body)))
    return p


def _spawn(trainer, out, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    return subprocess.Popen([sys.executable, trainer, out], env=env)


def _wait_for(path, proc, timeout=180, desc="marker"):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if proc.poll() is not None:
            raise AssertionError(
                f"trainer exited {proc.returncode} before {desc}")
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError(f"timed out waiting for {desc}")
        time.sleep(0.05)


def _fresh_engine():
    import jax
    import deepspeed_tpu
    from tests.simple_model import SimpleModel, random_batches
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    return engine


BASE_CFG = {"train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}


# ---------------------------------------------------------------------------
# drills
# ---------------------------------------------------------------------------

def drill_kill_async_save(workdir):
    """SIGKILL mid-async-save: the publish window is held open by a sleep
    fault, the process dies inside it, and 'latest' must still load."""
    out = os.path.join(workdir, "ckpt")
    cfg = dict(BASE_CFG)
    # the async worker stalls 120s between finishing the tmp dir and the
    # atomic publish — the deterministic SIGKILL window. n2: the first
    # publish hit is the durable sync save, the second is the async worker
    cfg["resilience"] = {"faults": "ckpt.publish:n2!sleep120"}
    trainer = _write_trainer(workdir, cfg, """
        loss = engine(batches[0]); engine.backward(loss); engine.step()
        engine.save_checkpoint(out)                       # durable tag
        loss = engine(batches[1]); engine.backward(loss); engine.step()
        engine.save_checkpoint(out, async_save=True)      # stalls in publish
        import time
        time.sleep(1.0)  # let the worker reach the fault point
        open(os.path.join(out, "armed"), "w").close()
        time.sleep(600)  # parent SIGKILLs us here
    """)
    pm_dir = os.path.join(workdir, "pm")
    p = _spawn(trainer, out, extra_env={POSTMORTEM_ENV: pm_dir})
    try:
        _wait_for(os.path.join(out, "armed"), p, desc="publish-window marker")
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
    latest = os.path.join(out, "latest")
    assert os.path.exists(latest), "no 'latest' after SIGKILL"
    tag = open(latest).read().strip()
    assert tag == "global_step1", f"latest moved to unpublished tag: {tag}"
    engine = _fresh_engine()
    path, _ = engine.load_checkpoint(out)
    assert engine.global_steps == 1, engine.global_steps
    # forensics: the long publish stall flushed a "stall" bundle BEFORE the
    # SIGKILL landed — the black box survived the unflushable death
    _assert_bundles(pm_dir, {"stall": 1}, "kill-async-save")
    print(f"  latest={tag!r} loads, resumed at step {engine.global_steps}; "
          f"1 stall bundle left by the killed process")


def drill_bitflip(workdir):
    """Bit-flip in the newest tag: checksum catches it, loader quarantines
    and falls back to the prior tag, repairing 'latest'."""
    out = os.path.join(workdir, "ckpt")
    engine = _fresh_engine()
    from tests.simple_model import random_batches
    for i, b in enumerate(random_batches(2, 8)):
        loss = engine(b); engine.backward(loss); engine.step()
        engine.save_checkpoint(out)
    shard = os.path.join(out, "global_step2", "arrays.npz")
    raw = bytearray(open(shard, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(shard, "wb").write(bytes(raw))
    # this drill runs in-process: point the flight recorder at a scratch
    # destination so the quarantine path flushes a bundle here
    from deepspeed_tpu.telemetry import flightrec
    pm_dir = os.path.join(workdir, "pm")
    flightrec.reset()
    flightrec.configure(dir=pm_dir)
    try:
        path, _ = engine.load_checkpoint(out)
    finally:
        flightrec.reset()
    assert path.endswith("global_step1"), path
    assert os.path.isdir(os.path.join(out, "global_step2.corrupt"))
    assert open(os.path.join(out, "latest")).read().strip() == "global_step1"
    _assert_bundles(pm_dir, {"corrupt_ckpt": 1}, "bitflip")
    print("  bit-flip caught; fell back to global_step1; latest repaired; "
          "1 corrupt_ckpt bundle flushed at quarantine")


def drill_preemption(workdir):
    """Real SIGTERM → emergency checkpoint → exit 83 → resume."""
    out = os.path.join(workdir, "ckpt")
    cfg = dict(BASE_CFG)
    cfg["resilience"] = {"preemption": {
        "enabled": True, "save_dir": out, "tag": "emergency"}}
    trainer = _write_trainer(workdir, cfg, """
        i = 0
        while True:
            b = batches[i % 4]; i += 1
            loss = engine(b); engine.backward(loss); engine.step()
            open(os.path.join(out, "ready"), "w").close()
    """)
    os.makedirs(out, exist_ok=True)
    pm_dir = os.path.join(workdir, "pm")
    p = _spawn(trainer, out, extra_env={POSTMORTEM_ENV: pm_dir})
    try:
        _wait_for(os.path.join(out, "ready"), p, desc="first step")
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == EXIT_CLEAN_PREEMPTION, f"exit {rc}, want 83"
    assert open(os.path.join(out, "latest")).read().strip() == "emergency"
    engine = _fresh_engine()
    path, _ = engine.load_checkpoint(out)
    assert path.endswith("emergency")
    _assert_bundles(pm_dir, {"preemption": 1}, "preemption")
    print(f"  SIGTERM → exit {rc}; emergency tag resumed at step "
          f"{engine.global_steps}; 1 preemption bundle")


def drill_watchdog(workdir):
    """Injected step.hang + watchdog abort: the process must self-terminate
    with exit 85 (and dump stacks) instead of wedging forever."""
    out = os.path.join(workdir, "ckpt")
    dump = os.path.join(workdir, "hang_dump.txt")
    cfg = dict(BASE_CFG)
    cfg["resilience"] = {
        "faults": "step.hang:once@step2!sleep600",
        "watchdog": {"enabled": True, "min_interval_s": 1.0,
                     "poll_interval_s": 0.2, "hang_factor": 1e-3,
                     "abort": True, "dump_file": dump},
    }
    trainer = _write_trainer(workdir, cfg, """
        for b in batches:
            loss = engine(b); engine.backward(loss); engine.step()
    """)
    os.makedirs(out, exist_ok=True)
    pm_dir = os.path.join(workdir, "pm")
    p = _spawn(trainer, out, extra_env={POSTMORTEM_ENV: pm_dir})
    try:
        rc = p.wait(timeout=180)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == EXIT_WATCHDOG_ABORT, f"exit {rc}, want 85"
    assert os.path.exists(dump), "watchdog wrote no stack dump"
    report = open(dump).read()
    assert "no step progress" in report and "--- thread" in report
    # the injected long stall flushes first; the watchdog's own flush is
    # then skipped by the one-bundle-per-process guard → exactly one
    # artifact, classified stall
    _assert_bundles(pm_dir, {"stall": 1}, "watchdog")
    print(f"  hang flagged; aborted with exit {rc}; stack dump "
          f"({len(report)} bytes) written; 1 stall bundle")


# per-"host" worker for the slice-loss drill: rank/world come from the
# elastic agent's env contract; DS_ELASTIC_RESHARD_COUNT tells a worker
# which gang generation it belongs to
SLICE_WORKER = """
import json, os, signal, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import deepspeed_tpu
from deepspeed_tpu.checkpoint.universal import (latest_universal_tag,
                                                load_universal_checkpoint,
                                                save_universal_checkpoint)
from deepspeed_tpu.resilience import faults
from tests.simple_model import SimpleModel, random_batches

out = sys.argv[1]
rank = int(os.environ["RANK"])
world = int(os.environ["DS_ELASTIC_WORLD_SIZE"])
gen = int(os.environ.get("DS_ELASTIC_RESHARD_COUNT", "0"))
ckpt = os.path.join(out, f"rank{{rank}}")
cfg = {{"train_batch_size": 8,
        "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
        "resilience": {{"elastic": {{"enabled": True, "save_dir": ckpt}}}}}}
model = SimpleModel()
batches = random_batches(4, 8)
params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, config=cfg)

if gen == 0:
    losses = {{}}
    for i in range(2):  # steps 0, 1 commit with a durable tag each
        loss = engine(batches[i]); engine.backward(loss); engine.step()
        losses[i] = float(loss)
        save_universal_checkpoint(engine, ckpt, tag=f"ustep{{engine.global_steps}}")
    loss = engine(batches[2])  # step 2's forward — the step the slice kills
    losses[2] = float(loss)
    engine.backward(loss)
    with open(os.path.join(out, f"gen0_rank{{rank}}.json"), "w") as f:
        json.dump({{"losses": losses, "world": world}}, f)
    if rank >= world // 2:
        # the dying half: SIGKILL mid-async-publish (the publish window is
        # held open by a sleep fault) — exactly how a slice disappears
        faults.configure("ckpt.publish:once!sleep120")
        engine.save_checkpoint(os.path.join(out, f"async{{rank}}"),
                               async_save=True)
        time.sleep(0.5)  # let the worker thread reach the publish stall
        os.kill(os.getpid(), signal.SIGKILL)
    # the surviving half detects the loss mid-step: slice.lost fires before
    # the apply, the engine emergency-saves and exits 84
    time.sleep(1.0)  # let the upper half die first
    faults.configure("slice.lost:once")
    engine.step()
    sys.exit(97)  # unreachable: step() must SystemExit(84)

# gen 1: the survivors' gang at half world — resume and continue
tag = latest_universal_tag(ckpt)
assert tag == "ustep2", f"latest tag {{tag}} != ustep2"
load_universal_checkpoint(engine, os.path.join(ckpt, tag))
assert engine.global_steps == 2, engine.global_steps
with open(os.path.join(out, f"gen0_rank{{rank}}.json")) as f:
    gen0 = json.load(f)
losses = {{}}
for i in range(2, 4):  # replay step 2 (never applied), continue through 3
    loss = engine(batches[i]); engine.backward(loss); engine.step()
    losses[i] = float(loss)
with open(os.path.join(out, f"gen1_rank{{rank}}.json"), "w") as f:
    json.dump({{"losses": losses, "world": world, "resumed_at": 2,
               "gen0_loss2": gen0["losses"]["2"]}}, f)
sys.exit(0)
"""


def drill_slice_loss(workdir):
    """SIGKILL half the simulated hosts mid-async-publish; the elastic
    agent must classify the survivors' exit-84, exclude the dead hosts,
    relaunch at half world budget-free, and the relaunched gang must resume
    from the exact checkpointed step with the loss trajectory continuing."""
    import json
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    from deepspeed_tpu.utils.retry import BackoffPolicy
    out = os.path.join(workdir, "gang")
    os.makedirs(out, exist_ok=True)
    worker = os.path.join(workdir, "slice_worker.py")
    with open(worker, "w") as f:
        f.write(SLICE_WORKER.format(repo=REPO))
    agent = DSElasticAgent(worker, user_args=[out], hosts=["localhost"] * 4,
                           max_restarts=1,
                           backoff=BackoffPolicy(base=0.05, factor=1.0,
                                                 max_delay=0.05,
                                                 jitter="none"))
    # elastic-agent workers inherit os.environ: deliver the bundle
    # destination to every gang member through it
    pm_dir = os.path.join(workdir, "pm")
    os.environ[POSTMORTEM_ENV] = pm_dir
    try:
        rc = agent.run()
    finally:
        os.environ.pop(POSTMORTEM_ENV, None)
    assert rc == 0, f"agent exited {rc}"
    assert agent.world_history == [4, 2], agent.world_history
    assert agent.restart_counts["reshard"] == 1, dict(agent.restart_counts)
    assert agent.reshards == 1 and agent.restarts == 0, (
        agent.reshards, agent.restarts)
    for rank in (0, 1):
        with open(os.path.join(out, f"gen1_rank{rank}.json")) as f:
            g1 = json.load(f)
        assert g1["world"] == 2 and g1["resumed_at"] == 2
        # the replayed step-2 forward after restore matches the loss the
        # first gang computed before dying — the trajectory continued
        assert g1["losses"]["2"] == g1["gen0_loss2"], (
            g1["losses"]["2"], g1["gen0_loss2"])
    # forensics: the SIGKILLed half each flushed a stall bundle from the
    # held-open publish window; the surviving half each flushed a
    # slice_loss bundle on the exit-84 path. Gen-1 exits clean → no more.
    _assert_bundles(pm_dir, {"stall": 2, "slice_loss": 2}, "slice-loss")
    print(f"  4-host gang lost its upper half; agent relaunched 2 "
          f"survivors budget-free (reasons={agent.restart_reasons}); "
          f"resumed at step 2 with bitwise loss continuity")


# drill 6 worker: serving-fleet decode replica loss mid-stream, run as a
# real subprocess on 8 forced CPU host devices (the fleet needs one device
# per replica; the flag must land before jax first initializes). Runs the
# SAME seeded sampled trace fault-free then with ``replica.lost:n3@step3``
# (third hit at step 3 = decode0, with 2 prefill replicas ahead of it) and
# writes a JSON verdict for the parent. @REPO@ is substituted at write time.
REPLICA_LOSS_WORKER = '''
import json, os, sys
sys.path.insert(0, @REPO@)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
import numpy as np
import jax
from deepspeed_tpu.inference.v2.fleet import PrefillDecodeFleet
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.resilience import faults

out_path = sys.argv[1]
cfg = LlamaConfig.tiny(remat=False)
model = LlamaForCausalLM(cfg)
ids = np.random.default_rng(0).integers(
    0, cfg.vocab_size, (1, 8)).astype(np.int32)
params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
ENG = {"state_manager": {"max_ragged_sequence_count": 9,
                         "max_ragged_batch_size": 64,
                         "max_context": 96,
                         "num_kv_blocks": 96},
       "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}}
MAX_NEW = 6

def requests():
    rng = np.random.default_rng(5)
    out = {}
    for uid in range(6):
        prompt = rng.integers(0, cfg.vocab_size,
                              int(rng.integers(6, 60))).astype(np.int32)
        # seeded non-greedy sampling: recovery must preserve the
        # deterministic (seed, position) sampling contract, not just argmax
        out[uid] = (prompt, dict(max_new_tokens=MAX_NEW, seed=100 + uid,
                                 temperature=0.8, top_k=20, top_p=0.95))
    return out

def run(chaos):
    faults.reset()
    fleet = PrefillDecodeFleet(model, params, prefill_replicas=2,
                               decode_replicas=2, engine_config=ENG,
                               token_budget=48)
    for uid, (p, kw) in requests().items():
        fleet.submit(uid, p, **kw)
    if chaos:
        faults.configure(chaos)
    out = fleet.run_to_completion()
    faults.reset()
    return fleet, {u: [int(t) for t in v] for u, v in out.items()}

_, ref = run(None)
fleet, got = run("replica.lost:n3@step3")
readmitted_uids = sorted(fleet._readmit_prefix)
verdict = {
    "replica_losses": fleet.replica_losses,
    "readmitted": fleet.readmitted,
    "readmitted_uids": readmitted_uids,
    "bit_exact": all(got.get(u) == ref[u] for u in ref),
    "all_complete": sorted(got) == sorted(ref)
    and all(len(v) == MAX_NEW for v in got.values()),
    "leaked_pages": fleet.page_census()["leaked_pages"],
    "dead_replicas": fleet.lifecycle.counts()["dead"],
}
with open(out_path, "w") as f:
    json.dump(verdict, f)
'''


def drill_replica_loss(workdir):
    """Decode replica loss mid-stream on a live serving fleet: the failure
    path must re-admit the dead replica's streams and finish them BIT-EXACT
    against the fault-free run (seeded sampling included), leave survivors
    untouched, and leak zero KV pages."""
    import json
    worker = os.path.join(workdir, "replica_loss_worker.py")
    with open(worker, "w") as f:
        f.write(REPLICA_LOSS_WORKER.replace("@REPO@", repr(REPO)))
    verdict_path = os.path.join(workdir, "verdict.json")
    pm_dir = os.path.join(workdir, "pm")
    p = _spawn(worker, verdict_path, extra_env={POSTMORTEM_ENV: pm_dir})
    try:
        rc = p.wait(timeout=420)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == 0, f"worker exited {rc}"
    with open(verdict_path) as f:
        v = json.load(f)
    assert v["replica_losses"] == 1, v
    assert v["readmitted"] > 0, f"loss fired but nothing re-admitted: {v}"
    assert v["bit_exact"], f"recovery diverged from fault-free run: {v}"
    assert v["all_complete"], f"re-admitted streams incomplete: {v}"
    assert v["leaked_pages"] == 0, f"KV pages leaked: {v}"
    _assert_bundles(pm_dir, {"replica_loss": 1}, "replica-loss")
    print(f"  decode replica lost mid-stream; {v['readmitted']} request(s) "
          f"re-admitted (uids {v['readmitted_uids']}); all 6 streams "
          f"bit-exact vs fault-free; 0 pages leaked; 1 replica_loss bundle")


DRILLS = {
    "kill-async-save": drill_kill_async_save,
    "bitflip": drill_bitflip,
    "preemption": drill_preemption,
    "watchdog": drill_watchdog,
    "slice-loss": drill_slice_loss,
    "replica-loss": drill_replica_loss,
}


def emit_elastic_baseline(path):
    """Run the in-process 8→4→8 mesh reshard drill and write its payload —
    the baseline ``perf_gate.py check_elastic_baseline`` ratchets."""
    import json
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from deepspeed_tpu.resilience.elastic_reshard import run_elastic_drill
    workdir = tempfile.mkdtemp(prefix="elastic_baseline_")
    try:
        payload = run_elastic_drill(os.path.join(workdir, "uni"))
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"elastic baseline written to {path}: "
          f"worlds={payload['world_sequence']} "
          f"steps_lost={payload['steps_lost']} "
          f"bitwise={payload['restore_loss_bitwise_equal']}")
    ok = (payload["world_sequence"] == [8, 4, 8]
          and payload["steps_lost"] == 0
          and payload["restore_loss_bitwise_equal"])
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--drill", choices=sorted(DRILLS), default=None,
                    help="run one drill (default: all)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directories for inspection")
    ap.add_argument("--emit-elastic-baseline", metavar="PATH", default=None,
                    help="run the in-process 8→4→8 reshard drill and write "
                         "the perf_gate elastic baseline payload, then exit")
    args = ap.parse_args(argv)
    if args.emit_elastic_baseline:
        return emit_elastic_baseline(args.emit_elastic_baseline)
    names = [args.drill] if args.drill else list(DRILLS)
    failures = []
    for name in names:
        workdir = tempfile.mkdtemp(prefix=f"fault_drill_{name}_")
        print(f"drill {name} ({workdir})")
        t0 = time.monotonic()
        try:
            DRILLS[name](workdir)
            print(f"  PASS ({time.monotonic() - t0:.1f}s)")
        except Exception as e:
            failures.append(name)
            print(f"  FAIL: {type(e).__name__}: {e}")
        finally:
            if not args.keep:
                shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print(f"fault drill FAILED: {failures}")
        return 1
    print(f"fault drill: all {len(names)} drills passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
