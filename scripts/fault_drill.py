"""End-to-end fault drill (docs/RESILIENCE.md) — kill/resume on CPU.

Four drills, each exercising a real process boundary (SIGKILL/SIGTERM on a
live training subprocess), pinning the acceptance behaviors the unit suite
(tests/test_resilience.py) checks in-process:

1. ``kill-async-save``  SIGKILL the trainer while an async checkpoint
   worker is inside the publish window (held open by a ``ckpt.publish``
   sleep fault). The live tag must remain loadable — the atomic
   tmp+rename publish means a crash at ANY instant leaves a complete tag.
2. ``bitflip``          flip one byte in the newest tag's array shard; the
   checksum manifest must catch it, quarantine the tag, and the load must
   transparently fall back to the prior tag (and repair ``latest``).
3. ``preemption``       real SIGTERM to a training process with the
   preemption handler enabled: it writes an emergency checkpoint at the
   next step boundary and exits 83 (clean preemption — budget-free for the
   elastic agent); a fresh engine then resumes from the emergency tag.
4. ``watchdog``         inject a ``step.hang`` stall into a process running
   the watchdog with ``abort`` on; the watchdog must dump stacks and
   hard-exit 85 within one heartbeat.

Usage:  python scripts/fault_drill.py [--drill NAME] [--keep]
Exit 0 iff every selected drill passes.
"""

import argparse
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import textwrap
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

EXIT_CLEAN_PREEMPTION = 83
EXIT_WATCHDOG_ABORT = 85

# one trainer template, parameterized by the resilience config and loop
# behavior — every drill runs this as a real subprocess
TRAINER = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax
import deepspeed_tpu
from tests.simple_model import SimpleModel, random_batches

out = sys.argv[1]
model = SimpleModel()
batch = random_batches(1, 8)[0]
params = model.init(jax.random.PRNGKey(0), batch)["params"]
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, model_parameters=params, config={config})
batches = random_batches(4, 8)
{body}
"""


def _write_trainer(workdir, config, body):
    p = os.path.join(workdir, "trainer.py")
    with open(p, "w") as f:
        f.write(TRAINER.format(repo=REPO, config=config,
                               body=textwrap.dedent(body)))
    return p


def _spawn(trainer, out, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    return subprocess.Popen([sys.executable, trainer, out], env=env)


def _wait_for(path, proc, timeout=180, desc="marker"):
    deadline = time.monotonic() + timeout
    while not os.path.exists(path):
        if proc.poll() is not None:
            raise AssertionError(
                f"trainer exited {proc.returncode} before {desc}")
        if time.monotonic() > deadline:
            proc.kill()
            raise AssertionError(f"timed out waiting for {desc}")
        time.sleep(0.05)


def _fresh_engine():
    import jax
    import deepspeed_tpu
    from tests.simple_model import SimpleModel, random_batches
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    return engine


BASE_CFG = {"train_batch_size": 8,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}


# ---------------------------------------------------------------------------
# drills
# ---------------------------------------------------------------------------

def drill_kill_async_save(workdir):
    """SIGKILL mid-async-save: the publish window is held open by a sleep
    fault, the process dies inside it, and 'latest' must still load."""
    out = os.path.join(workdir, "ckpt")
    cfg = dict(BASE_CFG)
    # the async worker stalls 120s between finishing the tmp dir and the
    # atomic publish — the deterministic SIGKILL window. n2: the first
    # publish hit is the durable sync save, the second is the async worker
    cfg["resilience"] = {"faults": "ckpt.publish:n2!sleep120"}
    trainer = _write_trainer(workdir, cfg, """
        loss = engine(batches[0]); engine.backward(loss); engine.step()
        engine.save_checkpoint(out)                       # durable tag
        loss = engine(batches[1]); engine.backward(loss); engine.step()
        engine.save_checkpoint(out, async_save=True)      # stalls in publish
        import time
        time.sleep(1.0)  # let the worker reach the fault point
        open(os.path.join(out, "armed"), "w").close()
        time.sleep(600)  # parent SIGKILLs us here
    """)
    p = _spawn(trainer, out)
    try:
        _wait_for(os.path.join(out, "armed"), p, desc="publish-window marker")
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
    latest = os.path.join(out, "latest")
    assert os.path.exists(latest), "no 'latest' after SIGKILL"
    tag = open(latest).read().strip()
    assert tag == "global_step1", f"latest moved to unpublished tag: {tag}"
    engine = _fresh_engine()
    path, _ = engine.load_checkpoint(out)
    assert engine.global_steps == 1, engine.global_steps
    print(f"  latest={tag!r} loads, resumed at step {engine.global_steps}")


def drill_bitflip(workdir):
    """Bit-flip in the newest tag: checksum catches it, loader quarantines
    and falls back to the prior tag, repairing 'latest'."""
    out = os.path.join(workdir, "ckpt")
    engine = _fresh_engine()
    from tests.simple_model import random_batches
    for i, b in enumerate(random_batches(2, 8)):
        loss = engine(b); engine.backward(loss); engine.step()
        engine.save_checkpoint(out)
    shard = os.path.join(out, "global_step2", "arrays.npz")
    raw = bytearray(open(shard, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(shard, "wb").write(bytes(raw))
    path, _ = engine.load_checkpoint(out)
    assert path.endswith("global_step1"), path
    assert os.path.isdir(os.path.join(out, "global_step2.corrupt"))
    assert open(os.path.join(out, "latest")).read().strip() == "global_step1"
    print("  bit-flip caught; fell back to global_step1; latest repaired")


def drill_preemption(workdir):
    """Real SIGTERM → emergency checkpoint → exit 83 → resume."""
    out = os.path.join(workdir, "ckpt")
    cfg = dict(BASE_CFG)
    cfg["resilience"] = {"preemption": {
        "enabled": True, "save_dir": out, "tag": "emergency"}}
    trainer = _write_trainer(workdir, cfg, """
        i = 0
        while True:
            b = batches[i % 4]; i += 1
            loss = engine(b); engine.backward(loss); engine.step()
            open(os.path.join(out, "ready"), "w").close()
    """)
    os.makedirs(out, exist_ok=True)
    p = _spawn(trainer, out)
    try:
        _wait_for(os.path.join(out, "ready"), p, desc="first step")
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == EXIT_CLEAN_PREEMPTION, f"exit {rc}, want 83"
    assert open(os.path.join(out, "latest")).read().strip() == "emergency"
    engine = _fresh_engine()
    path, _ = engine.load_checkpoint(out)
    assert path.endswith("emergency")
    print(f"  SIGTERM → exit {rc}; emergency tag resumed at step "
          f"{engine.global_steps}")


def drill_watchdog(workdir):
    """Injected step.hang + watchdog abort: the process must self-terminate
    with exit 85 (and dump stacks) instead of wedging forever."""
    out = os.path.join(workdir, "ckpt")
    dump = os.path.join(workdir, "hang_dump.txt")
    cfg = dict(BASE_CFG)
    cfg["resilience"] = {
        "faults": "step.hang:once@step2!sleep600",
        "watchdog": {"enabled": True, "min_interval_s": 1.0,
                     "poll_interval_s": 0.2, "hang_factor": 1e-3,
                     "abort": True, "dump_file": dump},
    }
    trainer = _write_trainer(workdir, cfg, """
        for b in batches:
            loss = engine(b); engine.backward(loss); engine.step()
    """)
    os.makedirs(out, exist_ok=True)
    p = _spawn(trainer, out)
    try:
        rc = p.wait(timeout=180)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == EXIT_WATCHDOG_ABORT, f"exit {rc}, want 85"
    assert os.path.exists(dump), "watchdog wrote no stack dump"
    report = open(dump).read()
    assert "no step progress" in report and "--- thread" in report
    print(f"  hang flagged; aborted with exit {rc}; stack dump "
          f"({len(report)} bytes) written")


DRILLS = {
    "kill-async-save": drill_kill_async_save,
    "bitflip": drill_bitflip,
    "preemption": drill_preemption,
    "watchdog": drill_watchdog,
}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--drill", choices=sorted(DRILLS), default=None,
                    help="run one drill (default: all)")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directories for inspection")
    args = ap.parse_args(argv)
    names = [args.drill] if args.drill else list(DRILLS)
    failures = []
    for name in names:
        workdir = tempfile.mkdtemp(prefix=f"fault_drill_{name}_")
        print(f"drill {name} ({workdir})")
        t0 = time.monotonic()
        try:
            DRILLS[name](workdir)
            print(f"  PASS ({time.monotonic() - t0:.1f}s)")
        except Exception as e:
            failures.append(name)
            print(f"  FAIL: {type(e).__name__}: {e}")
        finally:
            if not args.keep:
                shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print(f"fault drill FAILED: {failures}")
        return 1
    print(f"fault drill: all {len(names)} drills passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
