"""Compile the bench ladder's configs for the REAL v5e target, chip-free
(VERDICT r4 #1 groundwork): per (batch, remat-policy), the XLA:TPU
compiler's own memory assignment decides feasibility — no more hand
activation-arithmetic (which had (32, save-all) fitting; the compiler says
26.2GB > 15.75GB HBM) — and its flops/bytes counts give the roofline that
bounds achievable MFU.

The programs are the bench's model fwd+bwd with the flash kernel active
(DS_TPU_ASSUME_TPU) under the ladder's activation policies. The engine's
fused step adds optimizer state (~14 bytes/param ≈ 1.8GB for GPT-2-small)
on top of the program's own allocation — column `fits+opt` accounts for it.

Feasibility is computed for the SINGLE-chip bench environment: one v5e,
ZeRO world 1, optimizer states unsharded (``--zero-world N`` divides the
state bytes for multi-chip what-ifs; program temp bytes stay per-chip
pessimistic since activations shard too).

Usage: python scripts/aot_ladder_calibration.py [--model gpt2|llama]
Writes onchip_results/ladder_calibration_{model}.json.
"""

import argparse
import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("DS_TPU_ASSUME_TPU", "1")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

HBM = 15.75e9          # v5e usable HBM (from the compiler's own OOM message)
PEAK = 197e12          # bf16 FLOP/s
BW = 819e9             # HBM bytes/s
OPT_BYTES_PER_PARAM = 14  # bf16 working + fp32 master + fp32 m,v


def _mesh():
    from jax.experimental import topologies
    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    return Mesh(np.array(topo.devices[:1]), ("d",))


def build(model_name, batch, policy):
    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
    checkpointing._CONFIG["policy"] = policy if policy != "nothing" else "dots"
    if model_name == "gpt2":
        from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2LMHeadModel,
                                               gpt2_flops_per_token)
        cfg = dataclasses.replace(GPT2Config.small(),
                                  remat=policy != "nothing")
        model = GPT2LMHeadModel(cfg)
        T = 1024
        fpt = gpt2_flops_per_token(cfg, T)
    else:
        from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                                llama_flops_per_token)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4096, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=2,
                          max_position_embeddings=2048,
                          remat=policy != "nothing")
        model = LlamaForCausalLM(cfg)
        T = 2048
        fpt = llama_flops_per_token(cfg, T)
    b = {"input_ids": jax.ShapeDtypeStruct((batch, T), jnp.int32),
         "labels": jax.ShapeDtypeStruct((batch, T), jnp.int32)}
    shapes = jax.eval_shape(lambda: model.init(
        jax.random.PRNGKey(0), {"input_ids": jnp.zeros((1, 8), jnp.int32)}))
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(shapes["params"]))
    fn = jax.value_and_grad(lambda p, bb: model.apply({"params": p}, bb))
    return fn, (shapes["params"], b), batch * T, fpt, n_params


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2", choices=("gpt2", "llama"))
    ap.add_argument("--configs", default="")
    ap.add_argument("--zero-world", type=int, default=1,
                    help="divide optimizer-state bytes by this (ZeRO shard "
                         "count) for multi-chip feasibility what-ifs")
    args = ap.parse_args()
    mesh = _mesh()
    s = NamedSharding(mesh, P())

    if args.configs:
        ladder = [(int(b), p) for b, p in
                  (c.split(":") for c in args.configs.split(","))]
    elif args.model == "gpt2":
        ladder = [(32, "nothing"), (64, "dots"), (32, "dots"), (16, "dots"),
                  (32, "everything")]
    else:
        ladder = [(16, "nothing"), (16, "dots"), (8, "dots"), (4, "dots"),
                  (8, "everything")]

    rows = []
    for batch, policy in ladder:
        t0 = time.perf_counter()
        try:
            fn, abstract, tokens, fpt, n_params = build(args.model, batch,
                                                        policy)
            c = jax.jit(fn, in_shardings=jax.tree.map(lambda _: s, abstract)) \
                .lower(*abstract).compile()
            ca, ma = c.cost_analysis(), c.memory_analysis()
            prog = (ma.temp_size_in_bytes + ma.argument_size_in_bytes +
                    ma.output_size_in_bytes - ma.alias_size_in_bytes)
            opt_extra = (n_params * OPT_BYTES_PER_PARAM // args.zero_world
                         - ma.argument_size_in_bytes)  # args hold the fp32
            # params this bare program takes; the engine replaces them with
            # bf16 working + (sharded) fp32 master/moments
            t_mem = ca["bytes accessed"] / BW
            t_flops = fpt * tokens / PEAK
            bound = max(t_mem, t_flops)
            rows.append({
                "batch": batch, "policy": policy, "ok": True,
                "compile_s": round(time.perf_counter() - t0, 1),
                "program_bytes": prog,
                "fits": prog < HBM,
                "fits_with_opt_states": prog + max(opt_extra, 0) < HBM,
                "xla_flops": ca["flops"],
                "bytes_accessed": ca["bytes accessed"],
                "t_mem_ms": round(t_mem * 1e3, 1),
                "t_flops_6nd_ms": round(t_flops * 1e3, 1),
                "mfu_ceiling": round(t_flops / bound, 3),
                "tokens": tokens})
            r = rows[-1]
            print(f"{args.model} b{batch} {policy:10s}: prog="
                  f"{prog/1e9:5.1f}GB fits={r['fits']} "
                  f"(+opt {r['fits_with_opt_states']})  "
                  f"t_mem={r['t_mem_ms']:6.1f}ms t_flops={r['t_flops_6nd_ms']:6.1f}ms "
                  f"mfu_ceiling={r['mfu_ceiling']:.2f}", flush=True)
        except Exception as e:
            msg = str(e)
            rows.append({"batch": batch, "policy": policy, "ok": False,
                         "compile_s": round(time.perf_counter() - t0, 1),
                         "error": f"{type(e).__name__}: {msg[:300]}"})
            oom = "RESOURCE_EXHAUSTED" in msg
            print(f"{args.model} b{batch} {policy:10s}: "
                  f"{'DOES NOT FIT (compiler OOM)' if oom else 'FAILED'} "
                  f"{msg[:120]}", flush=True)

    os.makedirs("onchip_results", exist_ok=True)
    path = f"onchip_results/ladder_calibration_{args.model}.json"
    with open(path, "w") as f:
        json.dump({"model": args.model, "hbm": HBM, "peak": PEAK, "bw": BW,
                   "rows": rows}, f, indent=1)
    print(json.dumps({"metric": f"ladder_feasible_{args.model}",
                      "value": sum(1 for r in rows if r.get("ok")),
                      "unit": f"configs (of {len(rows)})",
                      "vs_baseline": 1.0}))


if __name__ == "__main__":
    main()
