"""On-chip serving benchmarks: SplitFuse throughput, traffic replay, W8A16.

VERDICT r2 #9 plus the serving-observability stream (PR 6):

- ``serving_bench`` — fixed prompt/decode mix, peak tokens/s (the original
  throughput number).
- ``--replay`` — a seeded traffic-replay harness: heavy-tailed
  (lognormal) prompt/output-length mixes and Poisson or burst arrival
  schedules, submitted on a wall clock against the live scheduler. Emits the
  latency numbers a serving stack is actually judged on — p50/p99 TTFT,
  p50/p99 TPOT, tokens/s/chip, peak KV-block occupancy — sourced from the
  telemetry serving histograms/gauges, and gated by scripts/perf_gate.py.
- ``w8a16_check`` — fused W8A16 quantized matmul vs the fp reference.

Prints ONE JSON line per section plus stderr progress. ``DS_TPU_TELEMETRY=1``
additionally embeds the full telemetry summary in each payload's ``extra``
(same contract as bench.py; docs/OBSERVABILITY.md has the schema).

- ``--replay --prefix-mix`` — shared system-prompt pools: the same seeded
  trace runs with ``prefix_caching`` off then on, and the payload reports the
  prefill-token reduction, prefix hit rate, and TTFT comparison the prefix
  cache is judged on (gated by perf_gate's prefix checks).

- ``--speculate`` — draft-then-verify decode: the same seeded
  template-heavy greedy trace runs with speculation off then on (n-gram
  prompt-lookup drafting, verification through the ragged prefill kernel).
  Reports the wall-clock tokens/s multiplier, accept rate, verify-batch
  occupancy, and the greedy bit-exactness flag — gated by perf_gate's
  ``check_speculate_baseline`` (multiplier >= 1.5x, parity must hold).

- ``--long-context`` — KV capacity-tiering workload: seeded long prompts
  (32k–128k on TPU; scaled down on CPU) over a shared prefix, driven at an
  EQUAL KV HBM byte budget with fp then int8 KV pages, host-DRAM spill tier
  on. Reports concurrent max-context sequences per chip (the >= 2x int8
  capacity ratchet), swap-in stall seconds, the swap accounting identity,
  and the prefill reduction across a spill/restore round trip — gated by
  perf_gate's ``check_longctx_baseline`` and ``--max-swap-stall-growth``.

- ``--replay --fleet`` — serving-fleet replay: the same seeded trace runs
  twice — once against a single scheduler at its saturation rate, then
  against an ``SLORouter`` over a ``PrefillDecodeFleet`` (prefill/decode
  disaggregation with KV-page handoffs) at DOUBLE the offered rate. The
  payload reports the sustained-rate multiplier, both legs' TTFT/TPOT
  percentiles, the shed rate, and the page-handoff accounting
  (pages shipped == pages bound; bytes; latency), gated by perf_gate's
  fleet checks.

- ``--fleet --two-process`` — KV fabric microbench: a prefix-mix trace
  runs four legs — monolithic reference, in-process fleet on the
  serialized ``wire`` codec with delta-shipping OFF then ON (with
  ``FlowControl``), and a ``TwoProcessFleet`` leg where decode lives in a
  SEPARATE OS process and every KV page crosses a pipe as a framed,
  CRC32-checked wire message. The payload reports the int8-wire-to-fp32
  byte ratio, the delta-shipping savings, CRC failure counts, and greedy
  parity of every leg against the reference — gated by perf_gate's
  ``check_kvfabric_baseline``.

- ``--diurnal --chaos [SPEC]`` — elastic-fleet chaos replay: the SLO
  router + prefill/decode fleet + ``FleetAutoscaler`` drive a seeded
  diurnal trace with fault injection armed (a decode replica dies
  mid-stream, a handoff transfer drops, a replica stalls). Reports goodput
  per replica-second, re-admission/leak accounting, and per-class shedding
  — gated by perf_gate's ``check_chaos_baseline``.

Usage: python scripts/bench_serving.py [--replay] [--prefix-mix] [--fleet]
           [--speculate] [--long-context] [--longctx-max T]
           [--requests N] [--seed S] [--arrival poisson|burst] [--rate R]
           [--burst-size B] [--prompt T] [--new T]
           [--prefix-pools P] [--prefix-len L]
           [--fleet-prefill N] [--fleet-decode N] [--two-process]
           [--chaos [SPEC]] [--diurnal] [--diurnal-period T]
           [--diurnal-depth D]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # chip lease + probe/retry + emit


def _embed_telemetry(extra):
    """DS_TPU_TELEMETRY=1 -> fold the unified-telemetry summary into the
    payload (bench.py behavior)."""
    if os.environ.get("DS_TPU_TELEMETRY") != "1":
        return
    from deepspeed_tpu import telemetry
    extra["telemetry"] = telemetry.summary()


#: default two-class SLO mix for --replay / --fleet: an interactive class
#: with tight targets and a throughput-oriented batch class (docs/SERVING.md
#: "SLO classes"). Replay requests alternate classes deterministically so
#: the same seed yields the same per-class populations. Targets are
#: CPU-replay scale — 2x above the worst observed mid-run compile stall
#: (~1.6 s on the CPU grid) so a one-off stall does not violate, tight
#: enough that a real scheduling regression drags attainment under the
#: perf gate's 0.9 ratchet (onchip_results/serving_slo_baseline.json).
REPLAY_SLO_CLASSES = {
    "interactive": {"ttft_target_s": 4.0, "tpot_target_s": 3.0,
                    "attainment_target": 0.9},
    "batch": {"ttft_target_s": 30.0, "tpot_target_s": 10.0,
              "attainment_target": 0.9},
}


def _assign_slo_classes(n_req):
    """Deterministic per-request class assignment (alternating)."""
    names = sorted(REPLAY_SLO_CLASSES)  # ["batch", "interactive"]
    return [names[(i + 1) % len(names)] for i in range(n_req)]


def _slo_classes_extra(tm):
    """Per-class attainment + TTFT/TPOT percentiles for a bench payload
    (None when no SLO observations landed). perf_gate validates the shape
    and gates the minimum attainment."""
    from deepspeed_tpu import telemetry
    snap = telemetry.slo_snapshot()
    if not snap:
        return None
    out = {}
    for cls, entry in snap.items():
        e = dict(entry)
        pcts = {}
        for metric in ("ttft", "tpot"):
            p = tm.hist_percentiles(f"serving/{metric}_s/{cls}")
            if p is not None:
                pcts[metric] = {"p50_s": round(p[0], 6),
                                "p95_s": round(p[1], 6),
                                "p99_s": round(p[2], 6)}
        if pcts:
            e["percentiles"] = pcts
        out[cls] = e
    return out


def _min_attainment(slo):
    """Worst per-class/per-metric attainment in a ``slo_classes`` section
    (the number ``perf_gate --min-slo-attainment`` gates)."""
    vals = [m["attainment"] for e in (slo or {}).values()
            for m in e.get("metrics", {}).values()]
    return min(vals) if vals else None


def _build_stack(cfg, n_req, prompt_len, new_tokens, budget, on_tpu,
                 num_kv_blocks=None, prefix_caching=False, kv_dtype="fp",
                 host_kv_blocks=0, model_and_params=None, speculative=None,
                 slo_classes=None):
    import jax
    import numpy as np
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
    from deepspeed_tpu.models.llama import LlamaForCausalLM

    if model_and_params is None:
        model = LlamaForCausalLM(cfg)
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (1, 8)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0),
                            {"input_ids": ids})["params"]
    else:
        model, params = model_and_params

    block = 32 if on_tpu else 8
    max_ctx = prompt_len + new_tokens + block
    if num_kv_blocks is None:
        num_kv_blocks = max(64, (max_ctx // block + 2) * n_req)
    config = {
        "state_manager": {
            "max_ragged_sequence_count": max(4, n_req) + 1,  # +1 warmup
            "max_ragged_batch_size": budget,
            "max_context": max_ctx,
            "num_kv_blocks": num_kv_blocks,
            "kv_dtype": kv_dtype,
            "host_kv_blocks": host_kv_blocks},
        "kv_cache": {"block_size": block,
                     "cache_dtype": "bf16" if on_tpu else "fp32"},
        "prefix_caching": prefix_caching}
    if speculative is not None:
        config["speculative"] = speculative
    if slo_classes:
        config["slo_classes"] = dict(slo_classes)
    engine = InferenceEngineV2(model, params, config=config)
    return model, SplitFuseScheduler(engine, token_budget=budget)


def serving_bench(args, on_tpu):
    import numpy as np
    from deepspeed_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.prompt + args.new + 64,
                          remat=False)
        n_req, prompt_len, new_tokens = args.requests, args.prompt, args.new
        budget = 256
    else:
        cfg = LlamaConfig.tiny(remat=False)
        n_req, prompt_len, new_tokens, budget = 2, 24, 4, 16

    model, sched = _build_stack(cfg, n_req, prompt_len, new_tokens, budget,
                                on_tpu)
    rng = np.random.default_rng(0)
    prompts = {u: rng.integers(0, cfg.vocab_size, prompt_len).astype(np.int32)
               for u in range(n_req)}

    # warmup round (compile) with one request
    t0 = time.perf_counter()
    sched.submit(10_000, prompts[0], max_new_tokens=2)
    sched.run_to_completion()
    print(f"serving: warmup/compile {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    for u, p in prompts.items():
        sched.submit(u, p, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    got = sched.run_to_completion()
    dt = time.perf_counter() - t0
    # count ONLY the timed requests — run_to_completion also returns the
    # warmup uid, whose tokens were generated before the timer started
    decoded = sum(len(got[u]) for u in prompts)
    total = decoded + n_req * prompt_len
    extra = {"decode_tokens_per_sec": round(decoded / dt, 1),
             "requests": n_req, "prompt_len": prompt_len,
             "new_tokens": new_tokens, "token_budget": budget,
             "wall_s": round(dt, 2),
             "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}"}
    _embed_telemetry(extra)
    payload = {
        "metric": "splitfuse_serving_tokens_per_sec",
        "value": round(total / dt, 1),
        "unit": "tokens/s (prefill+decode)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def make_workload(n_req, seed, arrival="poisson", rate=4.0, burst_size=4,
                  prompt_scale=256, new_scale=64, max_prompt=2048,
                  max_new=512):
    """Seeded request trace: heavy-tailed lengths + an arrival schedule.

    Lengths are lognormal (the shape real prompt/completion mixes follow —
    most requests short, a fat tail of long ones). Arrivals are either
    ``poisson`` (exponential gaps at ``rate`` req/s — open-loop steady
    traffic) or ``burst`` (groups of ``burst_size`` land simultaneously,
    groups spaced to the same average rate — the queue-depth stress case).
    Same seed -> identical trace, so perf_gate compares like against like.
    """
    import numpy as np
    gen = np.random.default_rng(seed)
    prompt_lens = np.clip(
        gen.lognormal(np.log(prompt_scale), 0.7, n_req), 4, max_prompt
    ).astype(np.int64)
    out_lens = np.clip(
        gen.lognormal(np.log(new_scale), 0.6, n_req), 1, max_new
    ).astype(np.int64)
    if arrival == "poisson":
        arrivals = np.cumsum(gen.exponential(1.0 / rate, n_req))
    elif arrival == "burst":
        n_groups = -(-n_req // burst_size)
        group_t = np.arange(n_groups) * (burst_size / rate)
        arrivals = np.repeat(group_t, burst_size)[:n_req]
    else:
        raise ValueError(f"unknown arrival schedule {arrival!r}")
    arrivals -= arrivals[0]  # first request lands at t=0
    return prompt_lens, out_lens, arrivals


def _drive_replay(sched, prompts, out_lens, arrivals, slo_classes=None):
    """Open-loop wall-clock submission of a request trace against the live
    scheduler (uids = trace indices). ``slo_classes`` optionally maps each
    trace index to its SLO class name. Returns the wall seconds."""
    n_req = len(prompts)
    t_start = time.perf_counter()
    nxt = 0
    while nxt < n_req or sched.has_work:
        now = time.perf_counter() - t_start
        while nxt < n_req and arrivals[nxt] <= now:
            kw = {}
            if slo_classes is not None:
                kw["slo_class"] = slo_classes[nxt]
            sched.submit(nxt, prompts[nxt],
                         max_new_tokens=int(out_lens[nxt]), **kw)
            nxt += 1
        if sched.has_work:
            sched.step()
        elif nxt < n_req:
            # open-loop: idle until the next arrival is due
            time.sleep(min(float(arrivals[nxt]) - now, 0.05))
    return time.perf_counter() - t_start


def _precompile_batch_grid(sched, n_req, budget):
    """Compile every (sequence-bucket, token-bucket) batch shape the replay
    can reach, directly through ``put_sampled`` (the scheduler's only device
    path). ``RaggedBatchWrapper.build`` buckets S and Q to powers of two
    (min 4 / 8, capped at the config maxima), so the reachable grid is small
    and enumerable — compiling it up front makes the measured legs
    compile-free regardless of how arrival timing composes the batches.
    Sequences use throwaway uids and are flushed afterwards."""
    import numpy as np
    eng = sched._engine
    sm = eng._config.state_manager
    max_s = min(sm.max_ragged_sequence_count, n_req)
    s_vals, s = [], 4
    while s < max_s:
        s_vals.append(s)
        s *= 2
    s_vals.append(max_s)
    q_vals, q = [], 8
    while q < budget:
        q_vals.append(q)
        q *= 2
    q_vals.append(budget)
    for n in s_vals:
        for qb in q_vals:
            if qb < n:
                continue  # can't give every sequence a token
            # compose a batch totalling EXACTLY qb tokens so the wrapper
            # buckets it to (bucket(n), qb) — one chunk takes the slack,
            # the rest decode one token. Covers pure-decode rounds
            # (qb == min bucket) as well as chunked-prefill mixes; a shape
            # missed here cold-compiles inside the measured leg
            longest = qb - (n - 1)
            uids = list(range(90_000, 90_000 + n))
            toks = [np.zeros(longest, np.int32)] + \
                [np.zeros(1, np.int32)] * (n - 1)
            eng.put_sampled(uids, toks, temperatures=[0.0] * n,
                            top_ks=[0] * n, top_ps=[1.0] * n,
                            seeds=[0] * n, positions=[0] * n)
            for u in uids:
                eng.flush(u)


def prefix_mix_bench(args, on_tpu):
    """Shared-system-prompt replay: every request's prompt = one of
    ``--prefix-pools`` seeded pool prefixes + a private lognormal suffix.
    Runs the SAME trace twice — ``prefix_caching`` off, then on — so the
    payload carries a like-for-like prefill-token and TTFT comparison at an
    identical seed. Emits one ``serving_replay_tokens_per_sec_per_chip``
    payload (value = cached leg) whose extra adds the prefix-cache fields
    perf_gate validates (hit rate, tokens saved/executed, reduction,
    nocache TTFT)."""
    import jax
    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.prompt + args.new + 64,
                          remat=False)
        n_req, block = args.requests, 32
        prefix_len = args.prefix_len or 256
        suffix_scale, max_suffix = 32, 128
        new_scale, max_new = args.new, args.new * 2
        budget, rate = 256, args.rate
    else:
        cfg = LlamaConfig.tiny(remat=False)
        n_req, block = min(args.requests, 16), 8
        prefix_len = args.prefix_len or 40
        suffix_scale, max_suffix = 6, 16
        new_scale, max_new = 2, 4
        budget, rate = 48, max(args.rate, 200.0)
    prefix_len -= prefix_len % block  # block-aligned prefixes share fully
    n_pools = max(1, args.prefix_pools)

    suffix_lens, out_lens, arrivals = make_workload(
        n_req, args.seed, arrival=args.arrival, rate=rate,
        burst_size=args.burst_size, prompt_scale=suffix_scale,
        new_scale=new_scale, max_prompt=max_suffix, max_new=max_new)
    gen = np.random.default_rng(args.seed)
    pools = [gen.integers(0, cfg.vocab_size, prefix_len).astype(np.int32)
             for _ in range(n_pools)]
    assign = gen.integers(0, n_pools, n_req)
    prompts = [np.concatenate([
        pools[assign[i]],
        gen.integers(0, cfg.vocab_size, int(suffix_lens[i])).astype(np.int32)])
        for i in range(n_req)]
    prompt_total = int(sum(len(p) for p in prompts))

    legs = {}
    for label, caching in (("nocache", False), ("cached", True)):
        model, sched = _build_stack(cfg, n_req, prefix_len + max_suffix,
                                    int(max_new), budget, on_tpu,
                                    prefix_caching=caching)
        # warmup: compile the full reachable batch-shape grid before the
        # clock starts. The cached leg fuses more, shorter chunks per
        # forward and so composes different (seqs, tokens) buckets than the
        # nocache leg — a trace-shaped warmup chases a moving target, the
        # grid covers both legs by construction
        t0 = time.perf_counter()
        _precompile_batch_grid(sched, n_req, budget)
        print(f"prefix-mix[{label}]: warmup/compile "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        # the warmup batches must not pollute the comparison: zero the
        # prefill counters and drop their donated blocks + match stats so
        # the measured leg starts with a cold, empty cache
        sched.prefill_tokens_executed = 0
        sched.prefill_tokens_saved = 0
        cache = sched._engine._state.prefix_cache
        if cache is not None:
            cache.evict(cache.evictable_blocks)
            cache.hits = cache.misses = cache.tokens_saved = 0
            cache.insertions = cache.evictions = 0
        telemetry.reset()
        telemetry.configure(enabled=True, sample_sync=False,
                            chrome_trace_path=os.environ.get(
                                "DS_TPU_TELEMETRY_TRACE", ""))
        tm = telemetry.get_telemetry()
        wall = _drive_replay(sched, prompts, out_lens, arrivals)
        decoded = sum(len(r.generated) for u, r in sched._requests.items()
                      if u < 10_000)
        ttft = tm.hist_percentiles("serving/ttft_s", (0.5, 0.99)) or (0.0, 0.0)
        tpot = tm.hist_percentiles("serving/tpot_s", (0.5, 0.99)) or (0.0, 0.0)
        serving = telemetry.summary()["serving"]
        kv_gauge = serving["gauges"].get("serving/kv_occupancy", {})
        cached_gauge = serving["gauges"].get("serving/cached_blocks", {})
        legs[label] = {
            "wall": wall, "decoded": decoded,
            "executed": sched.prefill_tokens_executed,
            "saved": sched.prefill_tokens_saved,
            "ttft": ttft, "tpot": tpot,
            "kv_peak": float(kv_gauge.get("peak", 0.0)),
            "cached_blocks_peak": float(cached_gauge.get("peak", 0.0)),
            "hit_rate": cache.hit_rate if cache is not None else 0.0,
            "preemptions": int(serving["requests"].get("preempted", 0)),
        }
    c, nc = legs["cached"], legs["nocache"]
    reduction = (nc["executed"] - c["executed"]) / nc["executed"] \
        if nc["executed"] else 0.0
    total = c["decoded"] + prompt_total
    n_chips = jax.device_count()
    extra = {
        "ttft_p50_s": round(c["ttft"][0], 6),
        "ttft_p99_s": round(c["ttft"][1], 6),
        "tpot_p50_s": round(c["tpot"][0], 6),
        "tpot_p99_s": round(c["tpot"][1], 6),
        "tokens_per_sec": round(total / c["wall"], 1),
        "decode_tokens_per_sec": round(c["decoded"] / c["wall"], 1),
        "peak_kv_occupancy": round(c["kv_peak"], 6),
        "preemptions": c["preemptions"],
        "requests": n_req, "seed": args.seed, "arrival": args.arrival,
        "rate_req_per_s": rate,
        "prompt_tokens_total": prompt_total,
        "decode_tokens_total": int(c["decoded"]),
        "wall_s": round(c["wall"], 2), "chips": n_chips,
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}",
        # prefix-cache comparison (same trace, caching off vs on)
        "prefix_pools": n_pools, "prefix_len": prefix_len,
        "prefix_hit_rate": round(c["hit_rate"], 6),
        "prefill_tokens_saved": int(c["saved"]),
        "executed_prefill_tokens": int(c["executed"]),
        "executed_prefill_tokens_nocache": int(nc["executed"]),
        "prefill_reduction": round(reduction, 6),
        "ttft_p50_nocache_s": round(nc["ttft"][0], 6),
        "ttft_p99_nocache_s": round(nc["ttft"][1], 6),
        "wall_nocache_s": round(nc["wall"], 2),
        "cached_blocks_peak": int(c["cached_blocks_peak"]),
    }
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_replay_tokens_per_sec_per_chip",
        "value": round(total / c["wall"] / max(n_chips, 1), 1),
        "unit": "tokens/s/chip (prefill+decode)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def speculate_bench(args, on_tpu):
    """Draft-then-verify replay: the SAME seeded template-heavy greedy trace
    runs twice — speculation off, then on (n-gram self-speculation drafting
    through the ragged verify kernel) — and the payload reports the
    wall-clock tokens/s multiplier the second leg buys, the accept rate,
    verify-batch occupancy, and the greedy bit-exactness flag (speculate leg
    stream == plain leg stream, the correctness oracle). The workload is a
    tiled 4-token pattern: template-heavy in the way the prompt-lookup
    drafter exploits, and single-row so both legs pad to the same ragged
    token bucket and the comparison isolates round-count savings. Emits one
    ``serving_speculate_tokens_per_sec_multiplier`` payload gated by
    perf_gate's ``check_speculate_baseline`` (multiplier >= 1.5x)."""
    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=4096, remat=False)
        tile_reps, max_new, budget = 64, max(args.new, 96), 256
    else:
        # tiny() shape, but with room for the 40-token prompt + 96 new
        cfg = LlamaConfig(vocab_size=512, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=256, remat=False)
        tile_reps, max_new, budget = 10, 96, 32
    seed = args.seed or 31
    max_drafts = 7  # k_max buckets to 8 either way; wider drafts are free
    gen = np.random.default_rng(seed)
    prompt = np.tile(gen.integers(0, cfg.vocab_size, 4).astype(np.int32),
                     tile_reps)
    reps = 3  # sequential timed repetitions per leg; min wall wins

    legs = {}
    for label, spec in (
            ("plain", None),
            ("speculate", {"enabled": True,
                           "max_draft_tokens": max_drafts})):
        model, sched = _build_stack(cfg, reps, len(prompt), max_new, budget,
                                    on_tpu, speculative=spec)
        t0 = time.perf_counter()
        sched.submit(10_000, prompt, max_new_tokens=max_new)
        sched.run_to_completion()
        print(f"speculate[{label}]: warmup/compile "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        sched.speculated_tokens = 0
        sched.accepted_tokens = 0
        sched.rejected_tokens = 0
        telemetry.reset()
        telemetry.configure(enabled=True, sample_sync=False,
                            chrome_trace_path=os.environ.get(
                                "DS_TPU_TELEMETRY_TRACE", ""))
        walls = []
        for r in range(reps):
            t0 = time.perf_counter()
            sched.submit(r, prompt, max_new_tokens=max_new)
            sched.run_to_completion()
            walls.append(time.perf_counter() - t0)
        serving = telemetry.summary()["serving"]
        occ = serving["gauges"].get("serving/verify_batch_occupancy", {})
        ar = serving["gauges"].get("serving/accept_rate", {})
        legs[label] = {
            "wall": min(walls), "walls": walls,
            "stream": [int(t) for t in sched.results()[0]],
            "speculated": int(sched.speculated_tokens),
            "accepted": int(sched.accepted_tokens),
            "rejected": int(sched.rejected_tokens),
            "tokens_per_round": float(sched.tokens_per_round()),
            "verify_occ_peak": float(occ.get("peak", 0.0)),
            "accept_rate_gauge": float(ar.get("last", 0.0)),
        }
        print(f"speculate[{label}]: walls="
              f"{[round(w, 3) for w in walls]} "
              f"tokens_per_round={legs[label]['tokens_per_round']:.2f}",
              file=sys.stderr)
    pl, sp = legs["plain"], legs["speculate"]
    multiplier = pl["wall"] / sp["wall"] if sp["wall"] else 0.0
    accept_rate = sp["accepted"] / max(sp["speculated"], 1)
    parity = pl["stream"] == sp["stream"]
    decoded = len(sp["stream"]) * reps
    extra = {
        "tokens_per_sec_multiplier": round(multiplier, 4),
        "accept_rate": round(accept_rate, 6),
        "verify_batch_occupancy": round(sp["verify_occ_peak"], 6),
        "greedy_parity": bool(parity),
        "speculated_tokens": sp["speculated"],
        "accepted_tokens": sp["accepted"],
        "rejected_tokens": sp["rejected"],
        "tokens_per_round": round(sp["tokens_per_round"], 4),
        "decode_tokens_per_sec": round(decoded / sp["wall"], 1),
        "decode_tokens_per_sec_plain": round(decoded / pl["wall"], 1),
        "wall_s": round(sp["wall"], 4),
        "wall_plain_s": round(pl["wall"], 4),
        "walls_s": [round(w, 4) for w in sp["walls"]],
        "walls_plain_s": [round(w, 4) for w in pl["walls"]],
        "repetitions": reps, "seed": seed,
        "prompt_len": int(len(prompt)), "new_tokens": max_new,
        "max_draft_tokens": max_drafts, "token_budget": budget,
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}",
    }
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_speculate_tokens_per_sec_multiplier",
        "value": round(multiplier, 4),
        "unit": "x (plain wall / speculate wall, same greedy trace)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def long_context_bench(args, on_tpu):
    """Long-context KV capacity tiering: seeded long prompts over shared
    prefix pools, driven twice at an EQUAL KV HBM byte budget — fp pages,
    then int8 pages + per-row fp32 scales — both with prefix caching and
    the host-DRAM spill tier on. Each leg runs three deterministic waves:
    warm (park the shared prefixes), pressure (private long prompts force
    the parked blocks through the spill path), reuse (shared-prefix
    requests revive spilled chains from host DRAM). The payload reports
    the capacity ratchet (concurrent sequences per chip at the shared
    budget, fp vs int8) plus the host-tier numbers from the pressured fp
    leg: swap-in stall seconds, the swap accounting identity
    (swapped_out == swapped_in + swap_dropped + resident_host_blocks),
    host occupancy, and ``swap_outs_live == 0`` — no live sequence ever
    paid for pressure while parked blocks could. Gated by perf_gate's
    ``check_longctx_baseline`` / ``--max-swap-stall-growth``."""
    import jax
    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.longctx_max + 256,
                          remat=False)
        block = 32
        prefix_len = args.prefix_len or 32768       # 32k shared prefix
        suffix_scale, max_suffix = 16384, args.longctx_max - prefix_len
        new_tokens = args.new
        n_req, n_filler = args.requests, 2
        budget = 512
    else:
        # CPU leg: the same three-wave shape at toy scale (the prefix-mix
        # pattern) — tiny model, 64-token "long" prefixes, a pool tight
        # enough that wave 2 must spill wave 1's parked prefix blocks
        cfg = LlamaConfig.tiny(remat=False)
        block = 8
        prefix_len = args.prefix_len or 64
        suffix_scale, max_suffix = 12, 24
        new_tokens = 2
        n_req, n_filler = min(args.requests, 6), 2
        budget = 48
    prefix_len -= prefix_len % block  # block-aligned prefixes share fully
    max_ctx = prefix_len + max_suffix + new_tokens + block

    # equal HBM budget: size the fp pool to hold ~1.5 max-context sequences
    # (so wave-2 pressure exists), then give the int8 leg the SAME bytes
    num_layers = cfg.num_hidden_layers
    kv_heads = cfg.num_key_value_heads
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    fp_elt = 2.0 if on_tpu else 4.0                 # bf16 / fp32 pages
    q_elt = 1.0 + 4.0 / head_dim                    # int8 page + fp32 scale
    blk_tokens = 2 * num_layers * block * kv_heads * head_dim
    ctx_blocks = -(-max_ctx // block)
    fp_blocks = int(ctx_blocks * 1.5)
    budget_bytes = int(fp_blocks * blk_tokens * fp_elt)
    q_blocks = int(budget_bytes // (blk_tokens * q_elt))
    host_blocks = 4 * ctx_blocks

    seed_gen = np.random.default_rng(args.seed)
    pool_prefix = seed_gen.integers(
        0, cfg.vocab_size, prefix_len).astype(np.int32)
    suffix_lens = np.clip(seed_gen.lognormal(
        np.log(suffix_scale), 0.6, n_req), 4, max_suffix).astype(np.int64)
    reuse_prompts = [np.concatenate([
        pool_prefix,
        seed_gen.integers(0, cfg.vocab_size,
                          int(suffix_lens[i])).astype(np.int32)])
        for i in range(n_req)]
    filler_prompts = [seed_gen.integers(
        0, cfg.vocab_size,
        prefix_len + max_suffix).astype(np.int32) for _ in range(n_filler)]
    prompt_total = int(sum(len(p) for p in reuse_prompts)
                       + sum(len(p) for p in filler_prompts) + prefix_len + 4)

    legs = {}
    for label, kv_dtype, blocks in (("fp", "fp", fp_blocks),
                                    ("int8", "int8", q_blocks)):
        model, sched = _build_stack(
            cfg, n_req + n_filler + 1, prefix_len + max_suffix, new_tokens,
            budget, on_tpu, num_kv_blocks=blocks, prefix_caching=True,
            kv_dtype=kv_dtype, host_kv_blocks=host_blocks)
        engine = sched._engine
        t0 = time.perf_counter()
        _precompile_batch_grid(sched, n_req + n_filler + 1, budget)
        print(f"long-context[{label}]: warmup/compile "
              f"{time.perf_counter()-t0:.1f}s", file=sys.stderr)
        sched.prefill_tokens_executed = 0
        sched.prefill_tokens_saved = 0
        cache = engine._state.prefix_cache
        cache.evict(cache.evictable_blocks)
        cache.hits = cache.misses = cache.tokens_saved = 0
        cache.insertions = cache.evictions = 0
        telemetry.reset()
        telemetry.configure(enabled=True, sample_sync=False,
                            chrome_trace_path=os.environ.get(
                                "DS_TPU_TELEMETRY_TRACE", ""))
        t0 = time.perf_counter()
        # wave 1 — warm: park the shared prefix blocks
        sched.submit(10_000, np.concatenate(
            [pool_prefix,
             seed_gen.integers(0, cfg.vocab_size, 4).astype(np.int32)]),
            max_new_tokens=new_tokens)
        sched.run_to_completion()
        # wave 2 — pressure: private max-length prompts spill the parked
        # prefix chain into the host tier
        for i, p in enumerate(filler_prompts):
            sched.submit(20_000 + i, p, max_new_tokens=new_tokens)
            sched.run_to_completion()
        spilled_after_pressure = engine.kv_stats()["kv_spilled"]
        # wave 3 — reuse: shared-prefix requests revive the spilled chain
        for i, p in enumerate(reuse_prompts):
            sched.submit(i, p, max_new_tokens=new_tokens)
            sched.run_to_completion()
        wall = time.perf_counter() - t0
        if engine._state.kv_cache.swapper is not None:
            engine._state.kv_cache.swapper.drain()  # flush deferred landings

        stats = engine.kv_stats()
        srv = telemetry.summary()["serving"]
        hists = srv["histograms"]

        def hist_total(name):
            h = hists.get(name)
            return (h["count"] * h["mean_s"], h["p50_s"]) if h else (0.0, 0.0)

        swap_in_stall, swap_in_p50 = hist_total("serving/kv_swap_in_s")
        swap_out_stall, _ = hist_total("serving/kv_swap_out_s")
        tm = telemetry.get_telemetry()
        ttft = tm.hist_percentiles("serving/ttft_s", (0.5, 0.99)) or (0.0, 0.0)
        tpot = tm.hist_percentiles("serving/tpot_s", (0.5, 0.99)) or (0.0, 0.0)
        executed = sched.prefill_tokens_executed
        saved = sched.prefill_tokens_saved
        kv = engine._state.kv_cache
        pool_bytes = kv.k_pool.nbytes + kv.v_pool.nbytes
        if kv.quantized:
            pool_bytes += kv.k_scale.nbytes + kv.v_scale.nbytes
        legs[label] = {
            "blocks": blocks, "pool_bytes": int(pool_bytes), "wall": wall,
            "concurrent_seqs": blocks // ctx_blocks,
            "spilled": stats["kv_spilled"],
            "spilled_after_pressure": spilled_after_pressure,
            "restored": stats["kv_restored"],
            "dropped": stats["kv_dropped"],
            "resident_host": stats["host_kv_blocks"],
            "host_occupancy": stats["host_kv_occupancy"],
            "swap_outs_live": stats["swap_outs_live"],
            "swap_in_stall": swap_in_stall, "swap_in_p50": swap_in_p50,
            "swap_out_stall": swap_out_stall,
            "ttft": ttft, "tpot": tpot,
            "executed": executed, "saved": saved,
            "hit_rate": cache.hit_rate,
        }
    fp, q = legs["fp"], legs["int8"]
    n_chips = jax.device_count()
    reduction = fp["saved"] / (fp["saved"] + fp["executed"]) \
        if fp["saved"] + fp["executed"] else 0.0
    extra = {
        # capacity ratchet: same bytes, how many max-context sequences fit
        "concurrent_sequences_per_chip": round(
            q["concurrent_seqs"] / max(n_chips, 1), 4),
        "concurrent_sequences_per_chip_fp": round(
            fp["concurrent_seqs"] / max(n_chips, 1), 4),
        "capacity_multiplier": round(
            q["concurrent_seqs"] / fp["concurrent_seqs"], 4)
        if fp["concurrent_seqs"] else 0.0,
        "kv_hbm_budget_bytes": budget_bytes,
        "fp_blocks": fp["blocks"], "int8_blocks": q["blocks"],
        "fp_pool_bytes": fp["pool_bytes"], "int8_pool_bytes": q["pool_bytes"],
        "max_context_tokens": max_ctx, "blocks_per_sequence": ctx_blocks,
        # host-tier numbers from the pressured fp leg (equal budget -> it
        # must spill; the int8 leg's headroom is the capacity win)
        "swapped_out": fp["spilled"], "swapped_in": fp["restored"],
        "swap_dropped": fp["dropped"],
        "resident_host_blocks": fp["resident_host"],
        "host_kv_occupancy": round(fp["host_occupancy"], 6),
        "host_kv_capacity_blocks": host_blocks,
        "swap_outs_live": fp["swap_outs_live"],
        "swap_in_stall_s": round(fp["swap_in_stall"], 6),
        "swap_in_p50_s": round(fp["swap_in_p50"], 6),
        "swap_out_stall_s": round(fp["swap_out_stall"], 6),
        "spilled_after_pressure": fp["spilled_after_pressure"],
        # serving latency (fp leg headline; int8 leg for comparison)
        "ttft_p50_s": round(fp["ttft"][0], 6),
        "ttft_p99_s": round(fp["ttft"][1], 6),
        "tpot_p50_s": round(fp["tpot"][0], 6),
        "tpot_p99_s": round(fp["tpot"][1], 6),
        "ttft_p50_int8_s": round(q["ttft"][0], 6),
        "ttft_p99_int8_s": round(q["ttft"][1], 6),
        # prefix reuse across the spill/restore round trip
        "prefill_reduction": round(reduction, 6),
        "prefill_tokens_saved": int(fp["saved"]),
        "executed_prefill_tokens": int(fp["executed"]),
        "prefix_hit_rate": round(fp["hit_rate"], 6),
        "int8_swapped_out": q["spilled"], "int8_swapped_in": q["restored"],
        "requests": n_req, "fillers": n_filler, "seed": args.seed,
        "prefix_len": prefix_len, "prompt_tokens_total": prompt_total,
        "wall_s": round(fp["wall"] + q["wall"], 2), "chips": n_chips,
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}",
    }
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_longctx_concurrent_seqs_per_chip",
        "value": round(q["concurrent_seqs"] / max(n_chips, 1), 4),
        "unit": "max-context sequences/chip at the fp leg's KV HBM budget",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def fleet_replay_bench(args, on_tpu):
    """Serving-fleet replay: single scheduler at saturation rate R, then
    ``SLORouter`` + ``PrefillDecodeFleet`` at rate 2R over the same seeded
    trace (arrival gaps halved). The fleet leg must SUSTAIN the doubled
    rate: perf_gate's fleet baseline ratchet holds the completed-request
    rate multiplier >= 2x and the fleet's TTFT p99 near the single leg's,
    with bounded shedding and exact page-handoff accounting."""
    import jax
    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.v2.fleet import SLORouter, PrefillDecodeFleet
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    n_prefill, n_decode = args.fleet_prefill, args.fleet_decode
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.prompt + args.new + 64,
                          remat=False)
        n_req = args.requests
        prompt_scale, new_scale = args.prompt // 2, args.new
        max_prompt, max_new = args.prompt, args.new * 4
        budget, rate = 256, args.rate
    else:
        cfg = LlamaConfig.tiny(remat=False)
        n_req = min(args.requests, 32)
        # prompt-heavy with real decode tails: the monolithic leg must pay
        # the decode-interference tax (every live decode row occupies a
        # sequence slot in the shared forward — S-bucket padding plus one
        # budget token per round — throttling prefill), which is the
        # contention disaggregation removes
        prompt_scale, new_scale = 96, 4
        max_prompt, max_new = 256, 8
        # rate well past the single replica's service capacity: the
        # reference leg must be SATURATED for the multiplier to mean
        # anything (an underloaded single replica tracks the offered rate
        # and no fleet can look faster)
        budget, rate = 16, max(args.rate, 400.0)
    # the disaggregation dividend: a monolithic replica must chunk prefill
    # to the small TPOT-bounding budget (decode rows ride every forward),
    # but a prefill-only replica hosts no decodes, so it runs WHOLE-PROMPT
    # chunks (Splitwise/DistServe phase splitting — chunking exists solely
    # to protect decode latency); decode replicas keep the latency budget
    prefill_budget = max(budget * 4, max_prompt)
    if (n_prefill + n_decode) > len(jax.devices()):
        raise RuntimeError(
            f"fleet replay needs {n_prefill + n_decode} devices, have "
            f"{len(jax.devices())} (CPU runs force 8 host devices)")

    prompt_lens, out_lens, arrivals = make_workload(
        n_req, args.seed, arrival=args.arrival, rate=rate,
        burst_size=args.burst_size, prompt_scale=prompt_scale,
        new_scale=new_scale, max_prompt=max_prompt, max_new=max_new)
    gen = np.random.default_rng(args.seed)
    prompts = [gen.integers(0, cfg.vocab_size, int(n)).astype(np.int32)
               for n in prompt_lens]
    prompt_total = int(prompt_lens.sum())

    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    block = 32 if on_tpu else 8
    max_ctx = int(max_prompt) + int(max_new) + block
    eng_cfg = {
        "state_manager": {"max_ragged_sequence_count": max(4, n_req) + 1,
                          "max_ragged_batch_size": prefill_budget,
                          "max_context": max_ctx,
                          "num_kv_blocks":
                              max(64, (max_ctx // block + 2) * n_req)},
        "kv_cache": {"block_size": block,
                     "cache_dtype": "bf16" if on_tpu else "fp32"},
        "slo_classes": REPLAY_SLO_CLASSES}
    # prefill replicas cap the per-forward sequence count at the minimum
    # S bucket: forward cost scales with the PADDED sequence axis (sampling
    # rows, attention padding), and a prefill-only replica gains nothing
    # from packing many prompts into one chunk — submitted requests beyond
    # the cap wait in the scheduler and ride the next whole-prompt chunk
    prefill_cfg = {
        "state_manager": dict(eng_cfg["state_manager"],
                              max_ragged_sequence_count=4),
        "kv_cache": dict(eng_cfg["kv_cache"]),
        "slo_classes": REPLAY_SLO_CLASSES}
    slo_assign = _assign_slo_classes(n_req)

    def measure(backend, scheds, arr, label):
        """Warm the batch-shape grid on every replica, then drive the trace
        wall-clock with a clean telemetry stream. Returns the leg report."""
        t0 = time.perf_counter()
        for mesh, sched in scheds:
            with mesh:
                _precompile_batch_grid(sched, n_req, sched.budget)
        print(f"fleet[{label}]: warmup/compile {time.perf_counter()-t0:.1f}s",
              file=sys.stderr)
        telemetry.reset()
        telemetry.configure(enabled=True, sample_sync=False,
                            chrome_trace_path=os.environ.get(
                                "DS_TPU_TELEMETRY_TRACE", ""))
        tm = telemetry.get_telemetry()
        wall = _drive_replay(backend, prompts, out_lens, arr,
                             slo_classes=slo_assign)
        results = backend.results()
        decoded = int(sum(len(v) for v in results.values()))
        ttft = tm.hist_percentiles("serving/ttft_s", (0.5, 0.99)) or (0.0, 0.0)
        tpot = tm.hist_percentiles("serving/tpot_s", (0.5, 0.99)) or (0.0, 0.0)
        return {"wall": wall, "decoded": decoded,
                "completed": len(results),
                "ttft": ttft, "tpot": tpot,
                "slo": _slo_classes_extra(tm),
                "handoff_p50": (tm.hist_percentiles("fleet/handoff_s",
                                                    (0.5,)) or (0.0,))[0]}

    # leg 1 — single replica at its saturation rate (the reference the
    # multiplier is judged against); built through the same replica path so
    # both legs pin pools identically
    from deepspeed_tpu.inference.v2.replica_group import build_replica
    mesh1, sched1 = build_replica(model, params, [jax.devices()[0]],
                                  engine_config=eng_cfg, token_budget=budget)

    class _Single:
        has_work = property(lambda self: sched1.has_work)

        def submit(self, uid, prompt, **kw):
            with mesh1:
                sched1.submit(uid, prompt, **kw)

        def step(self):
            with mesh1:
                return sched1.step()

        def results(self):
            return sched1.results()

    single = measure(_Single(), [(mesh1, sched1)], arrivals, "single")

    # leg 2 — SLO router over a disaggregated fleet at DOUBLE the offered
    # rate (same trace, arrival gaps halved)
    fleet = PrefillDecodeFleet(
        model, params, prefill_replicas=n_prefill, decode_replicas=n_decode,
        engine_config=prefill_cfg, token_budget=prefill_budget,
        decode_engine_config=eng_cfg, decode_token_budget=budget)
    fleet.warm_transport()
    router = SLORouter(fleet, slo_ttft_s=max(4.0, single["ttft"][1] * 8),
                       queue_limit=n_req)
    fl = measure(router, fleet.prefill + fleet.decode, arrivals * 0.5,
                 "router+disagg")

    tstats = fleet.transport.stats()
    single_rps = single["completed"] / single["wall"]
    fleet_rps = fl["completed"] / fl["wall"]
    rate_multiplier = fleet_rps / single_rps if single_rps else 0.0
    total = fl["decoded"] + prompt_total
    n_chips = jax.device_count()
    extra = {
        # fleet leg (the payload's headline numbers)
        "ttft_p50_s": round(fl["ttft"][0], 6),
        "ttft_p99_s": round(fl["ttft"][1], 6),
        "tpot_p50_s": round(fl["tpot"][0], 6),
        "tpot_p99_s": round(fl["tpot"][1], 6),
        "tokens_per_sec": round(total / fl["wall"], 1),
        "requests_per_sec": round(fleet_rps, 3),
        "rate_multiplier": round(rate_multiplier, 4),
        "offered_rate_req_per_s": rate * 2,
        "shed_rate": round(router.shed_rate, 6),
        "admitted": router.admitted, "queued": router.queued,
        "rejected": router.rejected,
        "affinity_hits": router.affinity_hits,
        # handoff accounting (KVPageTransport + telemetry must agree)
        "handoffs": tstats["handoffs"],
        "handoff_transfers": tstats["transfers"],
        "pages_shipped": tstats["pages_shipped"],
        "pages_bound": tstats["pages_bound"],
        "handoff_bytes": tstats["bytes_shipped"],
        "handoff_total_s": round(tstats["total_s"], 6),
        "handoff_p50_s": round(fl["handoff_p50"], 6),
        "prefill_replicas": n_prefill, "decode_replicas": n_decode,
        "prefill_token_budget": prefill_budget,
        "decode_token_budget": budget,
        # single-replica reference leg
        "single_ttft_p50_s": round(single["ttft"][0], 6),
        "single_ttft_p99_s": round(single["ttft"][1], 6),
        "single_tpot_p50_s": round(single["tpot"][0], 6),
        "single_tpot_p99_s": round(single["tpot"][1], 6),
        "single_requests_per_sec": round(single_rps, 3),
        "single_rate_req_per_s": rate,
        "single_wall_s": round(single["wall"], 2),
        "requests": n_req, "seed": args.seed, "arrival": args.arrival,
        "prompt_tokens_total": prompt_total,
        "decode_tokens_total": fl["decoded"],
        "wall_s": round(fl["wall"], 2), "chips": n_chips,
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}",
    }
    if fl["slo"]:
        extra["slo_classes"] = fl["slo"]
        attain = _min_attainment(fl["slo"])
        if attain is not None:
            extra["slo_min_attainment"] = round(attain, 6)
    if single["slo"]:
        extra["single_slo_classes"] = single["slo"]
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_fleet_replay_tokens_per_sec_per_chip",
        "value": round(total / fl["wall"] / max(n_chips, 1), 1),
        "unit": "tokens/s/chip (prefill+decode)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def kvfabric_bench(args, on_tpu):
    """KV fabric microbench (``--fleet --two-process``): a prefix-mix trace
    (groups of requests sharing long prompt prefixes) runs four legs over
    int8 KV pools —

    1. monolithic single replica (the greedy parity reference),
    2. in-process fleet on the serialized ``wire`` codec, delta OFF
       (the no-delta wire-byte reference),
    3. same fleet with delta-shipping ON and ``FlowControl`` armed,
    4. ``TwoProcessFleet``: decode in a separate OS process, every page
       crossing a pipe as a framed, per-page-CRC32 wire message.

    Headline: serialized wire bytes per page over the fp32 device bytes
    they replace — the int8+scale wire row must stay under perf_gate's
    ``KVFABRIC_MAX_WIRE_FP32_RATIO``. The model pins head_dim=32 (2 heads
    on the tiny 64-wide trunk): the per-row overhead is hd+4 scale bytes
    over 4*hd fp32, and the ratchet needs hd > 13 to be satisfiable at
    all. Delta must ship measurably fewer bytes than leg 2, every leg must
    match leg 1 token-for-token (int8 pools quantize identically on both
    sides, so the wire is lossless end-to-end), and the two-process leg
    must complete every request."""
    import jax
    import numpy as np
    from deepspeed_tpu.inference.v2.fleet import (FlowControl,
                                                  PrefillDecodeFleet)
    from deepspeed_tpu.inference.v2.fleet.two_process import TwoProcessFleet
    from deepspeed_tpu.inference.v2.replica_group import build_replica
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=512, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=2,
                      num_key_value_heads=2, max_position_embeddings=128,
                      scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    eng_cfg = {"state_manager": {"max_ragged_sequence_count": 16,
                                 "max_ragged_batch_size": 64,
                                 "max_context": 96,
                                 "num_kv_blocks": 160,
                                 "kv_dtype": "int8"},
               "kv_cache": {"block_size": 8, "cache_dtype": "fp32"},
               "prefix_caching": True}
    max_new = 8

    # prefix-mix trace: pools of shared prefixes — the delta leg's savings
    # come from the decode pool already holding a group's prefix blocks
    # after its first member ships
    gen = np.random.default_rng(args.seed)
    n_pools = 4
    per_pool = 3
    prefixes = [gen.integers(1, cfg.vocab_size, 32).astype(np.int32)
                for _ in range(n_pools)]
    prompts = {}
    for g in range(n_pools):
        for i in range(per_pool):
            uid = g * per_pool + i
            suffix = gen.integers(1, cfg.vocab_size,
                                  4 + uid % 5).astype(np.int32)
            prompts[uid] = np.concatenate([prefixes[g], suffix])

    def drive(backend):
        for uid, p in prompts.items():
            backend.submit(uid, p, max_new_tokens=max_new,
                           temperature=0.0, seed=7)
        rounds = 0
        while backend.has_work:
            backend.step()
            rounds += 1
            if rounds > 4096:
                raise RuntimeError("kvfabric leg did not converge")
        return {u: np.asarray(v) for u, v in backend.results().items()}

    # leg 1 — monolithic reference
    mesh1, sched1 = build_replica(model, params, [jax.devices()[0]],
                                  engine_config=eng_cfg, token_budget=64)

    class _Single:
        has_work = property(lambda self: sched1.has_work)

        def submit(self, uid, prompt, **kw):
            with mesh1:
                sched1.submit(uid, prompt, **kw)

        def step(self):
            with mesh1:
                return sched1.step()

        def results(self):
            return sched1.results()

    ref = drive(_Single())

    def parity(out):
        return all(u in out and np.array_equal(ref[u], out[u])
                   for u in prompts)

    def fleet_leg(**kw):
        fleet = PrefillDecodeFleet(model, params, prefill_replicas=1,
                                   decode_replicas=1, engine_config=eng_cfg,
                                   token_budget=64, codec="wire", **kw)
        out = drive(fleet)
        return fleet, out

    # leg 2 — wire codec, delta OFF: the no-delta byte reference
    f_plain, out_plain = fleet_leg(delta_shipping=False)
    plain = f_plain.transport.stats()
    # fp32 equivalent of the SAME page traffic (pure shape math)
    kc = f_plain.prefill[0][1].engine._state.kv_cache
    n_layers, _, n_heads, bsz, hd = kc.k_pool.shape
    fp32_page = 2 * n_layers * n_heads * bsz * hd * 4
    wire_page = f_plain.transport.page_wire_cost(f_plain.prefill[0][1].engine)

    # leg 3 — delta-shipping ON + flow control
    flow = FlowControl(max_inflight_bytes=1 << 20)
    f_delta, out_delta = fleet_leg(delta_shipping=True, flow=flow)
    delta = f_delta.transport.stats()

    # leg 4 — two-process: decode across a real OS process boundary
    import dataclasses
    mc = dataclasses.asdict(cfg)
    tp = TwoProcessFleet(model, params, mc, engine_config=eng_cfg,
                         token_budget=64, delta_shipping=True)
    try:
        out_tp = drive(tp)
        tp_stats = tp.stats()
    finally:
        tp.close()
    tp_lost = [u for u in prompts if u not in out_tp or not len(out_tp[u])]
    tp_stats["lost_requests"] = len(tp_lost)

    ratio = wire_page / fp32_page
    extra = {
        "wire_fp32_ratio": round(ratio, 6),
        "wire_page_bytes": wire_page,
        "fp32_page_bytes": fp32_page,
        "head_dim": hd,
        "nodelta_wire_bytes": plain["wire_bytes_shipped"],
        "delta_wire_bytes": delta["wire_bytes_shipped"],
        "wire_bytes_saved": delta["wire_bytes_saved"],
        "pages_shipped": delta["pages_shipped"],
        "pages_delta_skipped": delta["pages_delta_skipped"],
        "crc_failures": plain["crc_failures"] + delta["crc_failures"],
        "failed_handoffs": plain["failed_handoffs"]
        + delta["failed_handoffs"],
        "handoffs": delta["handoffs"],
        "parity_nodelta": parity(out_plain),
        "parity_delta": parity(out_delta),
        "flow": flow.stats(),
        "two_process": dict(tp_stats, parity=parity(out_tp)),
        "requests": len(prompts), "prefix_pools": n_pools,
        "max_new_tokens": max_new, "seed": args.seed,
        "chips": jax.device_count(),
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}"
                 f"-hd{hd}-int8kv",
    }
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_kvfabric_wire_fp32_ratio",
        "value": round(ratio, 6),
        "unit": "serialized wire bytes / fp32 device bytes (lower=better)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


#: default chaos spec for --chaos with no argument. Step windows count
#: fleet rounds; fault hits within a round visit stepping replicas in
#: (prefill0, prefill1, decode0, ...) order, so with 2 prefill replicas the
#: third ``replica.lost`` hit at step 30 deterministically kills decode0
#: mid-trace. ``transport.drop:n2`` makes one handoff transfer fail (the
#: transport's retry absorbs it); ``replica.stall:once@step45`` wedges one
#: replica for a round (it skips WITHOUT heartbeating).
DEFAULT_CHAOS_SPEC = ("replica.lost:n3@step30-100000;"
                      "transport.drop:n2;"
                      "replica.stall:once@step45")


def _diurnal_arrivals(n_req, seed, base_rate, period_s, depth):
    """Non-homogeneous Poisson arrivals on a compressed diurnal cycle:
    instantaneous rate(t) = base_rate * (1 + depth*sin(2*pi*t/period_s)),
    realized by dividing seeded unit-exponential gaps by the local rate
    (inverse-intensity spacing). Same seed -> identical trace; peaks
    saturate the fleet, troughs idle it — the autoscaler's signal."""
    import numpy as np
    gen = np.random.default_rng(seed)
    gaps = gen.exponential(1.0, n_req)
    floor = max(base_rate * (1.0 - depth), 1e-3)
    t = 0.0
    out = np.empty(n_req)
    for i in range(n_req):
        r = base_rate * (1.0 + depth * np.sin(2.0 * np.pi * t / period_s))
        t += gaps[i] / max(r, floor)
        out[i] = t
    out -= out[0]
    return out


def chaos_replay_bench(args, on_tpu):
    """Elastic serving fleet under chaos (``--replay --chaos [--diurnal]``):
    ``SLORouter`` + ``PrefillDecodeFleet`` + ``FleetAutoscaler`` driven over
    a seeded (optionally diurnal) trace WITH fault injection armed for the
    whole measured leg — a decode replica dies mid-stream, a handoff
    transfer drops (retried), a replica stalls past a heartbeat. The fleet
    must route around the loss, re-admit the dead replica's in-flight
    requests bit-exactly, replace the lost capacity from the warm standby
    pool, and keep the interactive SLO class attained while ALL shedding
    lands on batch.

    Headline number: goodput per replica-second — completed requests'
    prompt+decode tokens divided by the integral of live replicas over the
    wall clock (re-prefill waste and over-provisioned idle replicas both
    drag it down). perf_gate's ``check_chaos_baseline`` ratchets it via
    onchip_results/serving_chaos_baseline.json."""
    import jax
    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.inference.v2.fleet import (FleetAutoscaler,
                                                  PrefillDecodeFleet,
                                                  RequestRejected, SLORouter)
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.resilience import faults

    n_prefill = args.fleet_prefill
    n_decode = max(args.fleet_decode, 2)  # the chaos kill needs a survivor
    standby = 1  # pre-built warm capacity the autoscaler revives
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.prompt + args.new + 64,
                          remat=False)
        n_req = args.requests
        prompt_scale, new_scale = args.prompt // 2, args.new
        max_prompt, max_new = args.prompt, args.new * 4
        budget, base_rate = 256, args.rate
        period_s = args.diurnal_period or 30.0
    else:
        cfg = LlamaConfig.tiny(remat=False)
        n_req = min(args.requests, 48)
        prompt_scale, new_scale = 64, 4
        max_prompt, max_new = 192, 8
        # peak rate (base * (1+depth)) must exceed the steady fleet's
        # service capacity so the diurnal crest queues and the trough
        # drains — the autoscaler's whole signal
        budget, base_rate = 16, max(args.rate, 20.0)
        period_s = args.diurnal_period or 1.2
    prefill_budget = max(budget * 4, max_prompt)
    need = n_prefill + n_decode + standby
    if need > len(jax.devices()):
        raise RuntimeError(
            f"chaos replay needs {need} devices, have "
            f"{len(jax.devices())} (CPU runs force 8 host devices)")
    spec = args.chaos if args.chaos else DEFAULT_CHAOS_SPEC

    prompt_lens, out_lens, arrivals = make_workload(
        n_req, args.seed, arrival=args.arrival, rate=base_rate,
        burst_size=args.burst_size, prompt_scale=prompt_scale,
        new_scale=new_scale, max_prompt=max_prompt, max_new=max_new)
    if args.diurnal:
        arrivals = _diurnal_arrivals(n_req, args.seed + 1, base_rate,
                                     period_s, args.diurnal_depth)
    gen = np.random.default_rng(args.seed)
    prompts = [gen.integers(0, cfg.vocab_size, int(n)).astype(np.int32)
               for n in prompt_lens]
    slo_assign = _assign_slo_classes(n_req)

    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    block = 32 if on_tpu else 8
    max_ctx = int(max_prompt) + int(max_new) + block
    eng_cfg = {
        "state_manager": {"max_ragged_sequence_count": max(4, n_req) + 1,
                          "max_ragged_batch_size": prefill_budget,
                          "max_context": max_ctx,
                          "num_kv_blocks":
                              max(64, (max_ctx // block + 2) * n_req)},
        "kv_cache": {"block_size": block,
                     "cache_dtype": "bf16" if on_tpu else "fp32"},
        "slo_classes": REPLAY_SLO_CLASSES}
    prefill_cfg = {
        "state_manager": dict(eng_cfg["state_manager"],
                              max_ragged_sequence_count=4),
        "kv_cache": dict(eng_cfg["kv_cache"]),
        "slo_classes": REPLAY_SLO_CLASSES}

    # build the fleet WITH the standby replica, warm every batch shape on
    # every engine (including the standby's), then retire the standby into
    # the warm pool — the autoscaler's mid-trace scale-up revives a fully
    # compiled engine, so elasticity costs a page-table reset, not a compile
    fleet = PrefillDecodeFleet(
        model, params, prefill_replicas=n_prefill,
        decode_replicas=n_decode + standby,
        engine_config=prefill_cfg, token_budget=prefill_budget,
        decode_engine_config=eng_cfg, decode_token_budget=budget)
    fleet.warm_transport()
    t0 = time.perf_counter()
    for mesh, sched in fleet.prefill + fleet.decode:
        with mesh:
            _precompile_batch_grid(sched, n_req, sched.budget)
    print(f"chaos: warmup/compile {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)
    fleet.scale_down_decode(n_decode + standby - 1)  # idle -> warm pool

    router = SLORouter(fleet, slo_ttft_s=max(
        4.0, REPLAY_SLO_CLASSES["interactive"]["ttft_target_s"]),
        queue_limit=n_req)
    scaler = FleetAutoscaler(fleet, router, min_decode=n_decode,
                             max_decode=n_decode + standby,
                             up_queue_depth=2, up_occupancy=0.85,
                             down_idle_rounds=30, cooldown_rounds=15)

    telemetry.reset()
    telemetry.configure(enabled=True, sample_sync=False,
                        chrome_trace_path=os.environ.get(
                            "DS_TPU_TELEMETRY_TRACE", ""))
    tm = telemetry.get_telemetry()

    def drive():
        t_start = time.perf_counter()
        last = t_start
        replica_seconds = 0.0
        nxt = 0
        rounds = 0
        outcomes = []
        while nxt < n_req or router.has_work:
            now = time.perf_counter() - t_start
            while nxt < n_req and arrivals[nxt] <= now:
                outcomes.append(router.submit(
                    nxt, prompts[nxt], max_new_tokens=int(out_lens[nxt]),
                    slo_class=slo_assign[nxt]))
                nxt += 1
            if router.has_work:
                router.step()
                scaler.observe()
                rounds += 1
                if rounds > 200_000:
                    raise RuntimeError("chaos replay did not converge")
            elif nxt < n_req:
                time.sleep(min(float(arrivals[nxt]) - now, 0.05))
            t = time.perf_counter()
            replica_seconds += fleet.live_replica_count() * (t - last)
            last = t
        return time.perf_counter() - t_start, replica_seconds, outcomes

    faults.reset()
    faults.configure(spec)
    try:
        wall, replica_seconds, outcomes = drive()
        fault_trips = faults.trip_count()
    finally:
        faults.reset()

    results = router.results()
    rejected_uids = {o.uid for o in outcomes
                     if isinstance(o, RequestRejected)}
    served = [i for i in range(n_req) if i not in rejected_uids]
    decoded = int(sum(len(results.get(i, ())) for i in served))
    served_prompt = int(sum(int(prompt_lens[i]) for i in served))
    completed = sum(1 for i in served if len(results.get(i, ())) > 0)
    goodput = (served_prompt + decoded) / replica_seconds \
        if replica_seconds else 0.0

    census = fleet.page_census()
    rep = router.report()
    tstats = fleet.transport.stats()
    slo = _slo_classes_extra(tm)
    ttft = tm.hist_percentiles("serving/ttft_s", (0.5, 0.99)) or (0.0, 0.0)
    tpot = tm.hist_percentiles("serving/tpot_s", (0.5, 0.99)) or (0.0, 0.0)
    shed_by_class = rep["shed_by_class"]
    extra = {
        "goodput_tokens_per_replica_sec": round(goodput, 1),
        "wall_s": round(wall, 2),
        "replica_seconds": round(replica_seconds, 2),
        "requests": n_req, "completed": completed,
        "requests_lost": len(served) - completed,
        "decode_tokens_total": decoded,
        "prompt_tokens_total": served_prompt,
        # chaos + recovery accounting
        "chaos_spec": spec, "fault_trips": fault_trips,
        "replica_losses": fleet.replica_losses,
        "readmitted": fleet.readmitted,
        "handoff_retries": tstats["retry_trips"],
        "handoff_fallbacks": fleet.handoff_fallbacks,
        "failed_handoffs": tstats["failed_handoffs"],
        "leaked_pages": census["leaked_pages"],
        # elasticity (autoscaler actions during the measured leg only)
        "scale_ups": scaler.scale_ups, "scale_downs": scaler.scale_downs,
        "live_decode_end": len(fleet.live_decode_indices()),
        "decode_replicas": n_decode, "standby_replicas": standby,
        "prefill_replicas": n_prefill,
        # SLO precedence: batch absorbs ALL shedding
        "shed_by_class": shed_by_class,
        "interactive_sheds": shed_by_class.get("interactive", 0),
        "shed_rate": round(router.shed_rate, 6),
        "admitted": router.admitted, "rejected": router.rejected,
        "accounting": rep["accounting"],
        "ttft_p50_s": round(ttft[0], 6), "ttft_p99_s": round(ttft[1], 6),
        "tpot_p50_s": round(tpot[0], 6), "tpot_p99_s": round(tpot[1], 6),
        "diurnal": bool(args.diurnal),
        "diurnal_period_s": period_s,
        "diurnal_depth": args.diurnal_depth,
        "base_rate_req_per_s": base_rate,
        "arrival": "diurnal" if args.diurnal else args.arrival,
        "seed": args.seed, "chips": jax.device_count(),
        "prefill_token_budget": prefill_budget,
        "decode_token_budget": budget,
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}",
    }
    if slo:
        extra["slo_classes"] = slo
        attain = _min_attainment(slo)
        if attain is not None:
            extra["slo_min_attainment"] = round(attain, 6)
        inter = _min_attainment({"interactive": slo["interactive"]}) \
            if "interactive" in slo else None
        if inter is not None:
            extra["interactive_attainment"] = round(inter, 6)
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_chaos_goodput_tokens_per_replica_sec",
        "value": round(goodput, 1),
        "unit": "tokens/replica-s (completed prompt+decode, under faults)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def replay_bench(args, on_tpu):
    """Wall-clock traffic replay; latency percentiles from the telemetry
    serving stream."""
    import jax
    import numpy as np
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.llama import LlamaConfig

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.prompt + args.new + 64,
                          remat=False)
        n_req = args.requests
        prompt_scale, new_scale = args.prompt // 2, args.new
        max_prompt, max_new = args.prompt, args.new * 4
        budget, rate = 256, args.rate
    else:
        cfg = LlamaConfig.tiny(remat=False)
        n_req = min(args.requests, 6)
        prompt_scale, new_scale = 16, 3
        max_prompt, max_new = 48, 8
        budget, rate = 16, max(args.rate, 20.0)

    prompt_lens, out_lens, arrivals = make_workload(
        n_req, args.seed, arrival=args.arrival, rate=rate,
        burst_size=args.burst_size, prompt_scale=prompt_scale,
        new_scale=new_scale, max_prompt=max_prompt, max_new=max_new)
    model, sched = _build_stack(cfg, n_req, int(max_prompt), int(max_new),
                                budget, on_tpu,
                                slo_classes=REPLAY_SLO_CLASSES)
    slo_assign = _assign_slo_classes(n_req)
    gen = np.random.default_rng(args.seed)
    prompts = [gen.integers(0, cfg.vocab_size, int(n)).astype(np.int32)
               for n in prompt_lens]

    # compile before the clock starts — replay measures serving latency,
    # not jit time
    t0 = time.perf_counter()
    sched.submit(10_000, prompts[0][:max(4, int(prompt_lens.min()))],
                 max_new_tokens=2)
    sched.run_to_completion()
    print(f"replay: warmup/compile {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    # the replay's latency numbers COME from the serving telemetry stream;
    # (re)start it clean after warmup so compile never pollutes TTFT — even
    # when DS_TPU_TELEMETRY=1 enabled it earlier
    telemetry.reset()
    telemetry.configure(enabled=True, sample_sync=False,
                        chrome_trace_path=os.environ.get(
                            "DS_TPU_TELEMETRY_TRACE", ""))
    tm = telemetry.get_telemetry()

    wall = _drive_replay(sched, prompts, out_lens, arrivals,
                         slo_classes=slo_assign)

    decoded = sum(len(r.generated) for u, r in sched._requests.items()
                  if u != 10_000)
    total = decoded + int(prompt_lens.sum())
    n_chips = jax.device_count()
    ttft = tm.hist_percentiles("serving/ttft_s", (0.5, 0.99)) or (0.0, 0.0)
    tpot = tm.hist_percentiles("serving/tpot_s", (0.5, 0.99)) or (0.0, 0.0)
    serving = telemetry.summary()["serving"]
    kv_gauge = serving["gauges"].get("serving/kv_occupancy", {})
    extra = {
        "ttft_p50_s": round(ttft[0], 6), "ttft_p99_s": round(ttft[1], 6),
        "tpot_p50_s": round(tpot[0], 6), "tpot_p99_s": round(tpot[1], 6),
        "tokens_per_sec": round(total / wall, 1),
        "decode_tokens_per_sec": round(decoded / wall, 1),
        "peak_kv_occupancy": round(float(kv_gauge.get("peak", 0.0)), 6),
        "preemptions": int(serving["requests"].get("preempted", 0)),
        "requests": n_req, "seed": args.seed, "arrival": args.arrival,
        "rate_req_per_s": rate,
        "prompt_tokens_total": int(prompt_lens.sum()),
        "decode_tokens_total": int(decoded),
        "wall_s": round(wall, 2), "chips": n_chips,
        "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}",
    }
    slo = _slo_classes_extra(tm)
    if slo:
        extra["slo_classes"] = slo
        attain = _min_attainment(slo)
        if attain is not None:
            extra["slo_min_attainment"] = round(attain, 6)
    _embed_telemetry(extra)
    payload = {
        "metric": "serving_replay_tokens_per_sec_per_chip",
        "value": round(total / wall / max(n_chips, 1), 1),
        "unit": "tokens/s/chip (prefill+decode)",
        "vs_baseline": None,
        "extra": extra,
    }
    bench.emit(payload)
    return payload


def w8a16_check(on_tpu):
    """Quantized-matmul hardware validation: W8A16 vs fp reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.inference.quantization.quantization import (
        QuantizedParameter)
    from deepspeed_tpu.ops.pallas.quantized_matmul import quantized_matmul

    rng = np.random.default_rng(0)
    results = []
    for (m, k, n) in ((256, 1024, 1024), (128, 2048, 512)):
        x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
        w = rng.normal(size=(k, n)).astype(np.float32) / np.sqrt(k)
        qp = QuantizedParameter.from_array(w, num_bits=8, group_size=128)
        t0 = time.perf_counter()
        out_q = jax.block_until_ready(
            quantized_matmul(x, qp.q, qp.scale, qp.group_size,
                             interpret=not on_tpu))
        dt_q = time.perf_counter() - t0
        # kernel exactness vs the XLA dequant reference (quantization error
        # itself is a separate, known quantity)
        ref = jax.block_until_ready(x @ qp.dequantized(jnp.float32))
        err = float(jnp.max(jnp.abs(out_q.astype(jnp.float32) - ref))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        results.append({"shape": [m, k, n], "rel_err": round(err, 4),
                        "first_call_s": round(dt_q, 3)})
    ok = all(r["rel_err"] < 0.05 for r in results)
    payload = {"metric": "w8a16_quantized_matmul_check",
               "value": 1.0 if ok else 0.0, "unit": "pass",
               "vs_baseline": None, "extra": {"cases": results}}
    bench.emit(payload)
    return payload


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--prompt", type=int, default=512)
    ap.add_argument("--new", type=int, default=64)
    ap.add_argument("--replay", action="store_true",
                    help="traffic-replay mode: seeded heavy-tailed lengths + "
                         "arrival schedule; emits TTFT/TPOT percentiles")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--arrival", choices=("poisson", "burst"),
                    default="poisson")
    ap.add_argument("--rate", type=float, default=4.0,
                    help="mean arrival rate, requests/s")
    ap.add_argument("--burst-size", type=int, default=4)
    ap.add_argument("--prefix-mix", action="store_true",
                    help="with --replay: shared system-prompt pools, run the "
                         "same trace with prefix_caching off then on and "
                         "report the prefill-token/TTFT comparison")
    ap.add_argument("--prefix-pools", type=int, default=4,
                    help="number of shared prefix pools (--prefix-mix)")
    ap.add_argument("--prefix-len", type=int, default=0,
                    help="shared prefix length in tokens; 0 = per-platform "
                         "default (--prefix-mix)")
    ap.add_argument("--speculate", action="store_true",
                    help="draft-then-verify leg: the same seeded greedy "
                         "trace with speculation off then on; reports the "
                         "tokens/s multiplier, accept rate, and the greedy "
                         "bit-exactness flag")
    ap.add_argument("--long-context", action="store_true",
                    help="long-context KV tiering workload: seeded long "
                         "prompts over a shared prefix, fp vs int8 KV at an "
                         "equal HBM budget with the host-DRAM spill tier on")
    ap.add_argument("--longctx-max", type=int, default=131072,
                    help="max prompt length for the TPU --long-context leg "
                         "(CPU runs scale down automatically)")
    ap.add_argument("--fleet", action="store_true",
                    help="with --replay: single-replica saturation leg, then "
                         "SLORouter over a prefill/decode fleet at 2x the "
                         "offered rate")
    ap.add_argument("--fleet-prefill", type=int, default=2,
                    help="prefill replicas in the fleet leg (--fleet)")
    ap.add_argument("--fleet-decode", type=int, default=1,
                    help="decode replicas in the fleet leg (--fleet); decode "
                         "throughput is bounded by live sequences per round, "
                         "not budget, so 1 is usually right until the KV "
                         "working set outgrows one pool")
    ap.add_argument("--two-process", action="store_true",
                    help="with --fleet: the KV fabric microbench — wire "
                         "codec byte ratios, delta-shipping savings, and a "
                         "leg where decode runs in a SEPARATE OS process "
                         "with every KV page crossing a pipe as a framed "
                         "CRC32-checked wire message")
    ap.add_argument("--chaos", nargs="?", const="", default=None,
                    metavar="SPEC",
                    help="elastic-fleet chaos replay: drive the SLO router + "
                         "prefill/decode fleet + autoscaler with fault "
                         "injection armed (replica loss, handoff drops, "
                         "stalls). SPEC is a resilience.faults grammar "
                         "string; bare --chaos uses the default kill-one-"
                         "decode-replica spec. Implies --replay")
    ap.add_argument("--diurnal", action="store_true",
                    help="replace the arrival schedule with a seeded "
                         "diurnal cycle (sinusoidal rate modulation) so the "
                         "autoscaler sees crests that queue and troughs "
                         "that idle")
    ap.add_argument("--diurnal-period", type=float, default=0.0,
                    help="diurnal cycle period in seconds; 0 = per-platform "
                         "default")
    ap.add_argument("--diurnal-depth", type=float, default=0.85,
                    help="diurnal modulation depth in [0,1): rate swings "
                         "between base*(1-depth) and base*(1+depth)")
    args = ap.parse_args()
    if args.chaos is not None:
        args.replay = True

    if args.fleet or args.chaos is not None:
        # the fleet leg needs one device per replica; CPU runs present them
        # via forced host devices (inert when a real TPU backend is used) —
        # must be set before jax first initializes
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = \
                (flags + " --xla_force_host_platform_device_count=8").strip()

    # DS_TPU_TELEMETRY=1: same contract as bench.py — enable the unified
    # telemetry stream up front; summaries land in each payload's extra
    if os.environ.get("DS_TPU_TELEMETRY") == "1":
        from deepspeed_tpu import telemetry
        telemetry.configure(enabled=True, sample_sync=False,
                            chrome_trace_path=os.environ.get(
                                "DS_TPU_TELEMETRY_TRACE", ""))

    metric = ("serving_kvfabric_wire_fp32_ratio"
              if args.fleet and args.two_process
              else "serving_speculate_tokens_per_sec_multiplier"
              if args.speculate
              else "serving_longctx_concurrent_seqs_per_chip"
              if args.long_context
              else "serving_chaos_goodput_tokens_per_replica_sec"
              if args.chaos is not None
              else "serving_fleet_replay_tokens_per_sec_per_chip"
              if args.replay and args.fleet
              else "serving_replay_tokens_per_sec_per_chip" if args.replay
              else "splitfuse_serving_tokens_per_sec")
    try:
        devs = bench.init_backend_with_retry(lease_name="bench_serving")
    except Exception as e:
        extra = {"error": f"{type(e).__name__}: {e}"[:300]}
        wedged = "UNAVAILABLE" in str(e) or "initialize backend" in str(e)
        if wedged:
            # same contract as bench.py's wedged-chip path: the fault goes
            # on the Fault/* stream AND leaves a postmortem bundle so the
            # next BENCH_r0x backend-unavailable round is diagnosable
            from deepspeed_tpu import telemetry
            if not telemetry.enabled():
                telemetry.configure(enabled=True, sample_sync=False)
            telemetry.count("Fault/backend_unavailable",
                            error=f"{type(e).__name__}: {e}"[:200])
            extra["fault"] = "backend_unavailable"
            extra["postmortem_bundle"] = telemetry.flush_postmortem(
                "backend_unavailable",
                detail=f"{type(e).__name__}: {e}"[:300],
                dir=os.environ.get("DS_TPU_POSTMORTEM_DIR")
                or os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, "postmortems"))
        bench.emit({"metric": metric, "value": 0.0,
                    "unit": "tokens/s", "vs_baseline": None,
                    "extra": extra})
        return
    on_tpu = devs[0].platform in ("tpu", "axon")
    if args.fleet and args.two_process:
        try:
            kvfabric_bench(args, on_tpu)
        except Exception as e:
            bench.emit({"metric": metric, "value": 0.0,
                        "unit": "ratio", "vs_baseline": None,
                        "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})
        return
    if args.speculate:
        try:
            speculate_bench(args, on_tpu)
        except Exception as e:
            bench.emit({"metric": metric, "value": 0.0,
                        "unit": "x", "vs_baseline": None,
                        "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})
        return
    if args.long_context:
        try:
            long_context_bench(args, on_tpu)
        except Exception as e:
            bench.emit({"metric": metric, "value": 0.0,
                        "unit": "sequences/chip", "vs_baseline": None,
                        "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})
        return
    if args.replay:
        try:
            if args.chaos is not None:
                chaos_replay_bench(args, on_tpu)
            elif args.fleet:
                fleet_replay_bench(args, on_tpu)
            elif args.prefix_mix:
                prefix_mix_bench(args, on_tpu)
            else:
                replay_bench(args, on_tpu)
        except Exception as e:
            bench.emit({"metric": metric, "value": 0.0,
                        "unit": "tokens/s/chip", "vs_baseline": None,
                        "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})
        return
    try:
        serving_bench(args, on_tpu)
    except Exception as e:
        bench.emit({"metric": metric, "value": 0.0,
                    "unit": "tokens/s", "vs_baseline": None,
                    "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})
    try:
        w8a16_check(on_tpu)
    except Exception as e:
        bench.emit({"metric": "w8a16_quantized_matmul_check", "value": 0.0,
                    "unit": "pass", "vs_baseline": None,
                    "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})


if __name__ == "__main__":
    main()
