"""On-chip serving throughput: SplitFuse continuous batching + W8A16 check.

VERDICT r2 #9: measure InferenceEngineV2 + SplitFuseScheduler tokens/s at a
fixed prompt/decode mix on real hardware, and validate the fused W8A16
quantized matmul (ops/pallas/quantized_matmul) against the fp path. Prints
ONE JSON line per section (serving, w8a16), plus a combined summary line.

Usage: python scripts/bench_serving.py [--requests N] [--prompt T] [--new T]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # probe/retry + emit


def serving_bench(args, on_tpu):
    import jax
    import numpy as np
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=args.prompt + args.new + 64,
                          remat=False)
        n_req, prompt_len, new_tokens = args.requests, args.prompt, args.new
        budget = 256
    else:
        cfg = LlamaConfig.tiny(remat=False)
        n_req, prompt_len, new_tokens, budget = 2, 24, 4, 16

    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]

    block = 32 if on_tpu else 8
    max_ctx = prompt_len + new_tokens + block
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {
            "max_ragged_sequence_count": max(4, n_req),
            "max_ragged_batch_size": budget,
            "max_context": max_ctx,
            "num_kv_blocks": max(64, (max_ctx // block + 2) * n_req)},
        "kv_cache": {"block_size": block,
                     "cache_dtype": "bf16" if on_tpu else "fp32"}})
    sched = SplitFuseScheduler(engine, token_budget=budget)
    prompts = {u: rng.integers(0, cfg.vocab_size, prompt_len).astype(np.int32)
               for u in range(n_req)}

    # warmup round (compile) with one request
    t0 = time.perf_counter()
    sched.submit(10_000, prompts[0], max_new_tokens=2)
    sched.run_to_completion()
    print(f"serving: warmup/compile {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    for u, p in prompts.items():
        sched.submit(u, p, max_new_tokens=new_tokens)
    t0 = time.perf_counter()
    got = sched.run_to_completion()
    dt = time.perf_counter() - t0
    # count ONLY the timed requests — run_to_completion also returns the
    # warmup uid, whose tokens were generated before the timer started
    decoded = sum(len(got[u]) for u in prompts)
    total = decoded + n_req * prompt_len
    payload = {
        "metric": "splitfuse_serving_tokens_per_sec",
        "value": round(total / dt, 1),
        "unit": "tokens/s (prefill+decode)",
        "vs_baseline": None,
        "extra": {"decode_tokens_per_sec": round(decoded / dt, 1),
                  "requests": n_req, "prompt_len": prompt_len,
                  "new_tokens": new_tokens, "token_budget": budget,
                  "wall_s": round(dt, 2),
                  "model": f"llama-{cfg.hidden_size}x{cfg.num_hidden_layers}"},
    }
    bench.emit(payload)
    return payload


def w8a16_check(on_tpu):
    """Quantized-matmul hardware validation: W8A16 vs fp reference."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from deepspeed_tpu.inference.quantization.quantization import (
        QuantizedParameter)
    from deepspeed_tpu.ops.pallas.quantized_matmul import quantized_matmul

    rng = np.random.default_rng(0)
    results = []
    for (m, k, n) in ((256, 1024, 1024), (128, 2048, 512)):
        x = jnp.asarray(rng.normal(size=(m, k)), jnp.float32)
        w = rng.normal(size=(k, n)).astype(np.float32) / np.sqrt(k)
        qp = QuantizedParameter.from_array(w, num_bits=8, group_size=128)
        t0 = time.perf_counter()
        out_q = jax.block_until_ready(
            quantized_matmul(x, qp.q, qp.scale, qp.group_size,
                             interpret=not on_tpu))
        dt_q = time.perf_counter() - t0
        # kernel exactness vs the XLA dequant reference (quantization error
        # itself is a separate, known quantity)
        ref = jax.block_until_ready(x @ qp.dequantized(jnp.float32))
        err = float(jnp.max(jnp.abs(out_q.astype(jnp.float32) - ref))
                    / (jnp.max(jnp.abs(ref)) + 1e-9))
        results.append({"shape": [m, k, n], "rel_err": round(err, 4),
                        "first_call_s": round(dt_q, 3)})
    ok = all(r["rel_err"] < 0.05 for r in results)
    payload = {"metric": "w8a16_quantized_matmul_check",
               "value": 1.0 if ok else 0.0, "unit": "pass",
               "vs_baseline": None, "extra": {"cases": results}}
    bench.emit(payload)
    return payload


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--prompt", type=int, default=512)
    ap.add_argument("--new", type=int, default=64)
    args = ap.parse_args()
    try:
        devs = bench.init_backend_with_retry()
    except Exception as e:
        bench.emit({"metric": "splitfuse_serving_tokens_per_sec", "value": 0.0,
                    "unit": "tokens/s", "vs_baseline": None,
                    "extra": {"error": f"{type(e).__name__}: {e}"[:300]}})
        return
    on_tpu = devs[0].platform in ("tpu", "axon")
    try:
        serving_bench(args, on_tpu)
    except Exception as e:
        bench.emit({"metric": "splitfuse_serving_tokens_per_sec", "value": 0.0,
                    "unit": "tokens/s", "vs_baseline": None,
                    "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})
    try:
        w8a16_check(on_tpu)
    except Exception as e:
        bench.emit({"metric": "w8a16_quantized_matmul_check", "value": 0.0,
                    "unit": "pass", "vs_baseline": None,
                    "extra": {"error": f"{type(e).__name__}: {e}"[:400]}})


if __name__ == "__main__":
    main()
