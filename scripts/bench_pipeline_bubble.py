"""Measure the realized pipeline bubble of ``collective_pipeline`` vs the
ideal schedule model M*V/ticks (VERDICT r4 #5).

Method: fixed S=4 stages, a compute-heavy block, sweep the microbatch count
M and time the jitted forward after warmup. The schedule model says
T(M) = c * ticks(M) + d (c = per-tick cost, d = fixed dispatch overhead);
c is fit from the two largest M. Realized overhead at a given M is
measured_T / (c * ticks) - 1 — the cost the implementation adds on top of
the inherent fill/drain bubble. Run on the CPU mesh (schedule properties
are hardware-independent) or a real TPU slice.

Usage:
    python scripts/bench_pipeline_bubble.py [--stages 4] [--dim 256]
        [--ms 4,8,16,32] [--virtual 1,2] [--iters 5]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--tpu" not in sys.argv:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")
import jax  # noqa: E402

if "--tpu" not in sys.argv:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from deepspeed_tpu.runtime.pipe.engine import (  # noqa: E402
    collective_pipeline, ideal_bubble_fraction, pipeline_ticks)


def _block(p, x, extra):
    return jnp.tanh(x @ p["w"] + p["b"])


def bench(S, V, M, dim, iters, mesh, L):
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(0, 0.1, (L, dim, dim)), jnp.float32),
        "b": jnp.asarray(rng.normal(0, 0.1, (L, dim)), jnp.float32)}
    x = jnp.asarray(rng.normal(size=(M, 8, dim)), jnp.float32)

    fn = jax.jit(lambda p, x: collective_pipeline(
        _block, p, x, mesh, num_stages=S, remat=False, num_layers=L,
        virtual_stages=V))
    fn(params, x).block_until_ready()   # compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn(params, x).block_until_ready()
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--ms", default="4,8,16,32")
    ap.add_argument("--virtual", default="1,2")
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--tpu", action="store_true",
                    help="run on the attached accelerator instead of the "
                         "8-device CPU mesh")
    args = ap.parse_args()

    S = args.stages
    ms = [int(m) for m in args.ms.split(",")]
    vs = [int(v) for v in args.virtual.split(",")]
    ndev = len(jax.devices())
    assert ndev >= S, f"need >= {S} devices, have {ndev}"
    mesh = Mesh(np.array(jax.devices()[:S]), ("pp",))
    import math
    lcm = 1
    for v in vs:
        lcm = lcm * v // math.gcd(lcm, v)
    L = S * lcm * 2         # divisible by S*V for every V in the sweep

    report = {"stages": S, "dim": args.dim, "layers": L, "sweeps": {}}
    for V in vs:
        rows = []
        for M in ms:
            t = bench(S, V, M, args.dim, args.iters, mesh, L)
            rows.append({"M": M, "ticks": pipeline_ticks(M, S, V),
                         "time_s": t,
                         "ideal_bubble": ideal_bubble_fraction(M, S, V)})
        # per-tick cost from the two largest M (amortizes fixed overhead);
        # fall back to a single-point fit (c includes the fixed dispatch
        # cost d, overstating per-tick) when the sweep can't give a slope
        if len(rows) >= 2 and rows[-1]["ticks"] != rows[-2]["ticks"]:
            (m1, m2) = rows[-2], rows[-1]
            c = (m2["time_s"] - m1["time_s"]) / (m2["ticks"] - m1["ticks"])
        else:
            c = rows[-1]["time_s"] / rows[-1]["ticks"]
            print("warning: single-point fit (need >=2 distinct tick counts "
                  "for a slope); overhead numbers include fixed dispatch cost",
                  file=sys.stderr)
        for r in rows:
            model = c * r["ticks"]
            r["overhead_vs_model"] = r["time_s"] / model - 1.0 if model > 0 else None
            # realized efficiency: useful work (M*V chunk ticks) over
            # measured wall-clock expressed in tick units
            r["realized_efficiency"] = (r["M"] * V * c) / r["time_s"]
            r["ideal_efficiency"] = 1.0 - r["ideal_bubble"]
        report["sweeps"][f"V{V}"] = {"per_tick_cost_s": c, "rows": rows}
        for r in rows:
            ov = (f"{r['overhead_vs_model']*100:+.1f}%"
                  if r["overhead_vs_model"] is not None else "n/a (c<=0)")
            print(f"S={S} V={V} M={r['M']:3d}: {r['time_s']*1e3:8.2f} ms  "
                  f"ticks={r['ticks']:3d}  ideal_eff={r['ideal_efficiency']:.3f}  "
                  f"realized_eff={r['realized_efficiency']:.3f}  "
                  f"overhead={ov}", flush=True)

    # the VERDICT gate: overhead at M=2S under the classic schedule
    gate = next((r for r in report["sweeps"].get("V1", {}).get("rows", [])
                 if r["M"] == 2 * S), None)
    if gate and gate["overhead_vs_model"] is not None:
        print(f"\noverhead at M=2S (V=1): {gate['overhead_vs_model']*100:+.1f}% "
              f"(gate: 15% -> interleaved schedule justified)")
        if len(vs) > 1:
            g2 = next((r for r in report["sweeps"][f"V{vs[1]}"]["rows"]
                       if r["M"] == 2 * S), None)
            if g2:
                speed = gate["time_s"] / g2["time_s"]
                print(f"interleaved V={vs[1]} at M=2S: {speed:.2f}x the V=1 "
                      f"wall-clock (ideal {(1-gate['ideal_bubble'])/(1-g2['ideal_bubble']):.2f}x"
                      f" from bubble alone, at V× rotation comm)")
    print(json.dumps(report))


if __name__ == "__main__":
    main()
