#!/bin/bash
# Full on-chip evidence sequence, strictly serial (ONE TPU job at a time).
# Results land in onchip_results/ so the driver's end-of-round snapshot
# keeps them. Safe to re-run; each leg overwrites its own files.
#
# Wedge-proof (round-3 postmortem): a leg that times out or a probe that
# fails ABORTS the remaining legs and kills every child this script spawned.
# Round 3 died by stacking bench/llama/longctx onto a chip already wedged by
# the smoke leg's hung kernel — each new leg became a "holder" blocking the
# next, including the driver's own bench run.
OUT=/root/repo/onchip_results
LOG=$OUT/sequence.log
mkdir -p "$OUT"
cd /root/repo
# one run id for the whole sequence: legs are recognisable as "this run" by
# bench.py recovery, and never reaped as stale by their own sequence-mates
export DS_TPU_HARNESS_RUN_ID="seq-$$-$(date +%s)"
# persistent compilation cache: cold Mosaic/XLA compiles over the axon tunnel
# run 60-120s PER PROGRAM; the cache makes every re-run (and the driver's own
# bench) start warm
export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/root/repo/.jax_cache}
echo "sequence start $(date) run_id=$DS_TPU_HARNESS_RUN_ID" >> "$LOG"

# every leg runs as its own setsid process GROUP so that grandchildren
# orphaned by `timeout`'s kill (the usual wedge: a libtpu worker reparented
# to init) still die with the group — pgrep -P walks only LIVE direct
# children and misses exactly those
LEG_PGIDS=""

kill_children() {
  local pg
  for pg in $LEG_PGIDS; do
    kill -TERM -- "-$pg" 2>/dev/null
  done
  sleep 5
  for pg in $LEG_PGIDS; do
    kill -KILL -- "-$pg" 2>/dev/null
  done
}

abort() {
  echo "ABORT: $1 $(date)" >> "$LOG"
  kill_children
  echo "sequence aborted $(date)" >> "$LOG"
  exit 1
}

probe() {
  # cheap backend liveness check between legs; rc!=0 = chip held/wedged.
  # --kill-after: a probe wedged in libtpu can survive SIGTERM and become
  # the next chip holder itself
  timeout --kill-after=30 120 python - <<'EOF'
from deepspeed_tpu.utils.backend_probe import probe_backend
import sys
kind, detail = probe_backend(timeout_s=90)
print(f"probe: {kind} {detail}", flush=True)
sys.exit(0 if kind == "ok" else 1)
EOF
}

run_leg() {
  local name=$1 timeout_s=$2; shift 2
  echo "leg $name start $(date)" >> "$LOG"
  setsid timeout --kill-after=30 "$timeout_s" "$@" \
    > "$OUT/$name.json" 2> "$OUT/$name.err" &
  local pid=$!
  LEG_PGIDS="$LEG_PGIDS $pid"
  wait "$pid"
  local rc=$?
  echo "leg $name rc=$rc $(date)" >> "$LOG"
  if [ "$rc" -ne 124 ] && [ "$rc" -ne 137 ]; then
    # leg exited on its own: drop its pgid so a later kill_children can't
    # signal a recycled pid's process group
    LEG_PGIDS=$(printf '%s' "$LEG_PGIDS" | sed "s/ $pid\b//")
  fi
  if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    # leg timed out -> its client may have wedged the chip; do NOT stack
    # more work on it. Reap the whole process group (incl. orphaned
    # grandchildren), verify with a probe, abort the sequence if held.
    kill_children
    if ! probe >> "$LOG" 2>&1; then
      abort "leg $name timed out and chip probe failed"
    fi
    echo "leg $name timed out but chip recovered; continuing" >> "$LOG"
  fi
  return $rc
}

# leg 0 (CHIP-FREE): validate every Pallas kernel + flagship step against
# the real Mosaic/XLA:TPU compiler via the local v5e topology, and prewarm
# the persistent compile cache. Lowering failures surface HERE, with the
# chip untouched, instead of mid-smoke while holding it (the r2-r4 wedge
# class). Runs before the probe on purpose — it needs no accelerator.
echo "leg aot_prewarm start $(date)" >> "$LOG"
# same wedge-proofing as run_leg (setsid group + --kill-after) — a compile
# hung in native threads must not survive into the chip legs — but a leg-0
# timeout does NOT abort the sequence: this leg never touches the chip
setsid timeout --kill-after=30 3000 python scripts/aot_tpu_check.py --full \
  > "$OUT/aot_prewarm.json" 2> "$OUT/aot_prewarm.err" &
AOT_PID=$!
LEG_PGIDS="$LEG_PGIDS $AOT_PID"
wait "$AOT_PID"
AOT_RC=$?
LEG_PGIDS=$(printf '%s' "$LEG_PGIDS" | sed "s/ $AOT_PID\b//")
echo "leg aot_prewarm rc=$AOT_RC $(date)" >> "$LOG"
# verdict from THIS run's output (the persistent aot_check.json could be a
# stale artifact if the run died before writing it)
if [ "$AOT_RC" -eq 0 ] && grep -q '"failed": \[\]' "$OUT/aot_prewarm.json"; then
  echo "aot prewarm clean: all programs lower for the TPU target" >> "$LOG"
else
  echo "aot prewarm rc=$AOT_RC or failures; smoke will exercise fallbacks" >> "$LOG"
fi

if ! probe >> "$LOG" 2>&1; then
  abort "initial chip probe failed"
fi

# >=900s per kernel: the flash smoke compiles fwd AND both bwd kernels; round-2
# postmortem measured 60-120s per cold Mosaic compile over the tunnel, and the
# round-4 run proved 420s is NOT enough (fwd passed, bwd compile hit the axe)
run_leg smoke 5400 python scripts/tpu_kernel_smoke.py --timeout 900
if grep -q "FAIL\|TIMEOUT/hang" "$OUT/smoke.json" 2>/dev/null; then
  # a hung kernel smoke means the Pallas path wedges THIS platform: gate it
  # off for the remaining legs instead of re-wedging the chip leg by leg
  if grep -q "TIMEOUT/hang" "$OUT/smoke.json"; then
    echo "smoke hang detected: exporting DS_TPU_DISABLE_PALLAS=1 for remaining legs" >> "$LOG"
    export DS_TPU_DISABLE_PALLAS=1
    probe >> "$LOG" 2>&1 || abort "chip did not recover after smoke hang"
  else
    echo "smoke numeric FAIL; continuing (kernels compile+run, numbers logged)" >> "$LOG"
  fi
fi
run_leg bench 1800 python bench.py
run_leg llama 2400 python scripts/bench_llama.py
run_leg longctx 2400 python scripts/bench_long_context.py --seqs 4096,8192 --layers 8
run_leg serving 1800 python scripts/bench_serving.py
echo "sequence done $(date)" >> "$LOG"
