#!/bin/bash
# Full on-chip evidence sequence, strictly serial (ONE TPU job at a time).
# Results land in onchip_results/ so the driver's end-of-round snapshot
# keeps them. Safe to re-run; each leg overwrites its own files.
OUT=/root/repo/onchip_results
LOG=$OUT/sequence.log
mkdir -p "$OUT"
cd /root/repo
echo "sequence start $(date)" >> "$LOG"

run_leg() {
  local name=$1 timeout_s=$2; shift 2
  echo "leg $name start $(date)" >> "$LOG"
  timeout "$timeout_s" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  echo "leg $name rc=$? $(date)" >> "$LOG"
}

run_leg smoke 3600 python scripts/tpu_kernel_smoke.py --timeout 600
if grep -q "FAIL\|TIMEOUT/hang" "$OUT/smoke.json" 2>/dev/null; then
  echo "smoke not clean; continuing with bench anyway (driver wants a number)" >> "$LOG"
fi
run_leg bench 1800 python bench.py
run_leg llama 2400 python scripts/bench_llama.py
run_leg longctx 2400 python scripts/bench_long_context.py --seqs 4096,8192 --layers 8
run_leg serving 1800 python scripts/bench_serving.py
echo "sequence done $(date)" >> "$LOG"
