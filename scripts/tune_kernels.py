"""Regenerate the persistent kernel tuning tables (docs/AUTOTUNING.md).

Chip-free (default — no TPU needed; compiles every candidate for the target
topology and ranks by the XLA cost-analysis roofline proxy):

    python scripts/tune_kernels.py --mode chip-free --topology v5e:2x2

On-chip (requires a live TPU; timed sweep, ground truth):

    python scripts/tune_kernels.py --mode on-chip

Both write the table to ``deepspeed_tpu/autotuning/tables/<device>.json``
(the file every dispatch reads — commit it) and the full per-candidate
ranking to ``onchip_results/kernel_tuning_<device>.json`` (the evidence —
commit that too, so a table change is always attributable to a sweep).
"""

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO_ROOT, ".jax_cache"))
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")  # chip-free host: libtpu
# must not probe the GCP metadata server (30 HTTP retries per var)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("chip-free", "on-chip"),
                    default="chip-free")
    ap.add_argument("--topology", default="v5e:2x2",
                    help="AOT compile target for chip-free mode")
    ap.add_argument("--kernels", default="",
                    help="comma list (default: all five)")
    ap.add_argument("--iters", type=int, default=10,
                    help="timed iterations per candidate (on-chip)")
    ap.add_argument("--out", default="",
                    help="table path (default: the device's checked-in "
                         "tables/<device>.json)")
    ap.add_argument("--results-dir", default="onchip_results")
    args = ap.parse_args(argv)

    if args.mode == "chip-free":
        # host platform is CPU; compiles target the real TPU topology. Must
        # happen before the backend initializes (same as aot_tpu_check).
        import jax
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_compilation_cache_dir",
                          os.environ["JAX_COMPILATION_CACHE_DIR"])
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from deepspeed_tpu.autotuning import kernel_table, kernel_tuner

    kernels = [k for k in args.kernels.split(",") if k] or None
    entries, report = kernel_tuner.tune(mode=args.mode, kernels=kernels,
                                        topology_name=args.topology,
                                        iters=args.iters)
    device = report["device_kind"]

    os.makedirs(args.results_dir, exist_ok=True)
    ranking_path = os.path.join(args.results_dir,
                                f"kernel_tuning_{device}.json")
    with open(ranking_path, "w") as f:
        json.dump(report, f, indent=1)
    print(f"ranking -> {ranking_path} "
          f"({sum(len(s['candidates']) for s in report['sweeps'])} "
          f"candidates across {len(report['sweeps'])} sweeps)")

    if not entries:
        print("no feasible candidates — table NOT written", file=sys.stderr)
        return 1

    out = args.out or kernel_table.table_path(device)
    generated_by = (f"scripts/tune_kernels.py --mode {args.mode}"
                    + (f" --topology {args.topology}"
                       if args.mode == "chip-free" else ""))
    kernel_table.save_table(out, device, entries, generated_by)
    print(f"table -> {out} ({len(entries)} entries)")
    missing = [k for k in (kernels or kernel_table.KERNEL_KNOBS)
               if not any(key.startswith(f"{k}|") for key in entries)]
    if missing:
        print(f"WARNING: no feasible entry for {missing}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
