"""Multi-host trace merge + straggler report (docs/OBSERVABILITY.md).

Folds N per-host telemetry JSONL files (every record is stamped with
``(host, pid, run_id)`` by ``telemetry/core.py``) into ONE Chrome-trace file
with a separate track per host, and computes a straggler report: per-step
cross-host skew measured at matching collective timestamps.

Each host's records map to the merged trace as:

- span records (``kind: "span"``)       -> ``X`` duration events
- ``comm/*`` records                    -> ``X`` events (cat ``comm``)
- ``memory/*`` records                  -> a per-host ``hbm_bytes_in_use``
                                           counter track (``C`` events)
- ``mfu`` / ``goodput`` gauges          -> per-host counter tracks
- request-flow records (``kind: "flow"``) -> Chrome flow events (``s``/
                                           ``t``/``f``); the flow ``id`` is
                                           the record's uid-derived value,
                                           NOT remapped per host, so one
                                           request's admit -> prefill ->
                                           handoff -> decode -> finish chain
                                           binds across host tracks
- SLO observations (``kind: "slo"``)    -> folded into the straggler
                                           report's per-class attainment by
                                           host (``slo_attainment_by_host``)
- everything else                       -> instant events (``i``)

Hosts have independent perf_counter epochs, so absolute timestamps are not
comparable across files. The merge aligns hosts on their FIRST SHARED
collective: for every host the ts of the first occurrence of the earliest
``comm/*`` (op, axis) key all hosts share becomes t=0. Skew is then the
spread of matched k-th occurrences of each collective key across hosts —
a persistently-late host is a straggler (data loader, thermal throttle,
failing chip).

Each host's comm records are additionally run through the overlap
analyzer (``telemetry/overlap.py``, loaded standalone — no jax): exposed
segments (comm not covered by that host's fwd/bwd/step spans) land on a
per-host ``exposure`` lane (tid 1) in the merged trace, and the straggler
report ranks hosts by exposed-comm seconds (``exposure_by_host`` /
``most_exposed_host``) so cross-host skew and exposure read off one
report.

Postmortem bundles (``--bundles``, telemetry/flightrec.py) fold in as a
per-host ``flightrec`` lane (tid 2): every ring event a dead process left
behind becomes an instant event on its host's track, so the last beats,
faults and flush of a crashed host read in the same timeline as the
survivors' spans. Bundle timestamps are wall-clock (not perf_counter), so
the flightrec lanes are zero-based on the earliest ring event across all
bundles — causal order holds across bundles, not against the span lanes.

Usage:
    python scripts/trace_merge.py host0.jsonl host1.jsonl ... \
        --out merged_trace.json --report straggler_report.json
    python scripts/trace_merge.py --bundles /runs/postmortems \
        --out merged_trace.json          # bundles alone: a dead fleet

Exit 0 on success, 2 on unreadable/empty input.
"""

import argparse
import importlib.util
import json
import os
import sys
from collections import defaultdict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _overlap_module():
    """telemetry/overlap.py loaded standalone (stdlib-only at module scope,
    the kernel_table pattern) — trace_merge stays repo-import-free."""
    spec = importlib.util.spec_from_file_location(
        "_overlap", os.path.join(REPO_ROOT, "deepspeed_tpu", "telemetry",
                                 "overlap.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def host_exposures(per_host):
    """Per-host exposed-comm attribution from the JSONL records: spans
    (fwd/bwd/step/eval) are the compute union, ``comm/*`` records the
    collectives. Timestamps stay in each host's own epoch — callers
    subtract the alignment offset. Returns
    ``{host: {"exposed_comm_s", "comm_s", "exposed_fraction",
    "intervals": [comm interval + exposed segments]}}``."""
    ov = _overlap_module()
    out = {}
    for host, records in per_host.items():
        att = ov.attribute(ov.intervals_from_jsonl_records(records,
                                                           host=host))
        tot = att["totals"]
        out[host] = {
            "exposed_comm_s": round(tot["exposed_comm_s"], 6),
            "comm_s": round(tot["comm_s"], 6),
            "exposed_fraction": round(
                min(tot["exposed_comm_s"] / tot["comm_s"], 1.0)
                if tot["comm_s"] > 0 else 0.0, 6),
            "intervals": att["comm_intervals"],
        }
    return out


def _postmortem_module():
    """scripts/postmortem.py loaded standalone (stdlib-only, same idiom as
    the overlap analyzer) — bundle discovery/parsing stays in one place."""
    spec = importlib.util.spec_from_file_location(
        "_postmortem", os.path.join(REPO_ROOT, "scripts", "postmortem.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_bundle_lanes(bundle_paths):
    """Discover + load postmortem bundles -> ``{host_label: [bundle]}``
    keyed by the SAME ``host:pid`` label scheme the JSONL loader uses, so
    a crashed process's flightrec lane lands on its own telemetry track
    when both artifacts survive."""
    pm = _postmortem_module()
    lanes = {}
    for d in pm.find_bundles(bundle_paths):
        try:
            b = pm.load_bundle(d)
        except (OSError, ValueError) as e:
            print(f"trace_merge: skipping malformed bundle {d}: {e}",
                  file=sys.stderr)
            continue
        man = b["manifest"]
        label = f"{man.get('host', '?')}:{man.get('pid', '?')}"
        lanes.setdefault(label, []).append(b)
    return lanes


def flightrec_lane_events(lanes, host_pids):
    """Chrome events for the per-host ``flightrec`` lane (tid 2). Hosts
    already holding a track keep their chrome pid; bundle-only hosts (the
    process died before telemetry exported anything) get fresh pids. Ring
    timestamps are wall-clock, zero-based on the earliest event across ALL
    bundles so cross-process causal order is preserved."""
    all_ts = [ev.get("ts", 0.0)
              for bundles in lanes.values()
              for b in bundles for ev in b["events"]]
    base = min(all_ts) if all_ts else 0.0
    events = []
    next_pid = max(host_pids.values(), default=0) + 1
    for label in sorted(lanes):
        pid = host_pids.get(label)
        if pid is None:
            pid = next_pid
            next_pid += 1
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "args": {"name": label}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 2, "args": {"name": "flightrec"}})
        for b in lanes[label]:
            for ev in b["events"]:
                events.append({
                    "pid": pid, "tid": 2,
                    "name": ev.get("name", "?"), "ph": "i", "s": "t",
                    "cat": "flightrec",
                    "ts": round((ev.get("ts", 0.0) - base) * 1e6, 3),
                    "args": {"kind": ev.get("kind"), "seq": ev.get("seq"),
                             "detail": ev.get("detail")}})
            man = b["manifest"]
            events.append({
                "pid": pid, "tid": 2,
                "name": f"postmortem:{man.get('reason', '?')}", "ph": "i",
                "s": "p", "cat": "flightrec",
                "ts": round((man.get("created_unix", base) - base) * 1e6, 3),
                "args": {"exit_code": man.get("exit_code"),
                         "detail": man.get("detail"),
                         "dropped": man.get("event_dropped"),
                         "bundle": os.path.basename(b["path"])}})
    return events


def load_host_records(path):
    """Parse one JSONL file -> (host_label, [records]). Malformed lines are
    skipped (a crashed run can truncate its last line)."""
    records = []
    host = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict) or "ts" not in rec:
                continue
            records.append(rec)
            if host is None and rec.get("host"):
                host = f"{rec['host']}:{rec.get('pid', '?')}"
    if host is None:
        host = os.path.basename(path)
    return host, records


def comm_key(rec):
    # "comm/all_reduce" + axis -> alignment key
    return (rec["name"], (rec.get("tags") or {}).get("axis", "?"))


def align_offsets(per_host):
    """Per-host ts offset so matching collectives line up: the first
    occurrence of the earliest collective key PRESENT ON ALL HOSTS defines
    each host's t=0. Hosts with no shared collective keep offset = min ts
    (best effort)."""
    first_comm = {}   # host -> {key: first ts}
    for host, records in per_host.items():
        firsts = {}
        for rec in records:
            if rec["name"].startswith("comm/"):
                k = comm_key(rec)
                if k not in firsts:
                    firsts[k] = rec["ts"]
        first_comm[host] = firsts
    shared = None
    for firsts in first_comm.values():
        keys = set(firsts)
        shared = keys if shared is None else (shared & keys)
    offsets = {}
    anchor = None
    if shared:
        # earliest shared key by mean first-ts (deterministic order)
        anchor = min(sorted(shared),
                     key=lambda k: sum(f[k] for f in first_comm.values())
                     / len(first_comm))
    for host, records in per_host.items():
        if anchor is not None:
            offsets[host] = first_comm[host][anchor]
        else:
            offsets[host] = min((r["ts"] for r in records), default=0.0)
    return offsets, anchor


def slo_attainment_by_host(per_host):
    """Per-class SLO attainment rebuilt from each host's raw ``kind: "slo"``
    observation records (one line per ``slo_observe``). Returns
    ``{host: {slo_class: {metric: {requests, attained, violations,
    attainment}}}}`` — empty dict when no host recorded SLO classes. A
    fleet whose global attainment clears the bar can still hide one host
    violating persistently; this is the per-host split that surfaces it."""
    out = {}
    for host, records in per_host.items():
        per_cls = {}
        for rec in records:
            if rec.get("kind") != "slo":
                continue
            tags = rec.get("tags") or {}
            cls = tags.get("slo_class")
            metric = tags.get("metric")
            if not cls or not metric:
                continue
            n = int(tags.get("n", 1))
            st = per_cls.setdefault(cls, {}).setdefault(
                metric, {"requests": 0, "attained": 0, "violations": 0})
            st["requests"] += n
            st["attained" if tags.get("attained") else "violations"] += n
        for per in per_cls.values():
            for st in per.values():
                st["attainment"] = round(
                    st["attained"] / st["requests"], 6) \
                    if st["requests"] else 1.0
        if per_cls:
            out[host] = per_cls
    return out


def straggler_report(per_host, offsets, exposures=None):
    """Match the k-th occurrence of each collective key across hosts; skew
    of one matched set = max - min aligned timestamp. A host that is
    consistently the max is the straggler."""
    occ = defaultdict(lambda: defaultdict(list))  # key -> host -> [aligned ts]
    for host, records in per_host.items():
        off = offsets[host]
        for rec in records:
            if rec["name"].startswith("comm/"):
                step = (rec.get("tags") or {}).get("step")
                occ[comm_key(rec)][host].append(
                    (step, round(rec["ts"] - off, 6)))
    matches = []
    worst = defaultdict(int)
    hosts = sorted(per_host)
    for key, per in sorted(occ.items()):
        if set(per) != set(hosts) or len(hosts) < 2:
            continue
        n = min(len(v) for v in per.values())
        for k in range(n):
            sample = {h: per[h][k] for h in hosts}
            # prefer explicit step tags for the match label when present
            steps = {s for s, _ in sample.values() if s is not None}
            label = steps.pop() if len(steps) == 1 else k
            ts = {h: t for h, (_, t) in sample.items()}
            late = max(ts, key=ts.get)
            skew = round(max(ts.values()) - min(ts.values()), 6)
            worst[late] += 1
            matches.append({"collective": list(key), "occurrence": k,
                            "step": label, "skew_s": skew,
                            "latest_host": late, "aligned_ts": ts})
    skews = [m["skew_s"] for m in matches]
    report = {
        "hosts": hosts,
        "matched_collectives": len(matches),
        "max_skew_s": max(skews) if skews else 0.0,
        "mean_skew_s": round(sum(skews) / len(skews), 6) if skews else 0.0,
        "late_counts": dict(sorted(worst.items())),
        "straggler": max(worst, key=worst.get) if worst else None,
        "matches": matches,
    }
    if exposures:
        ranked = sorted(exposures.items(),
                        key=lambda kv: (-kv[1]["exposed_comm_s"], kv[0]))
        report["exposure_by_host"] = {
            h: {k: v for k, v in e.items() if k != "intervals"}
            for h, e in ranked}
        report["most_exposed_host"] = \
            ranked[0][0] if ranked and ranked[0][1]["exposed_comm_s"] > 0 \
            else None
    slo = slo_attainment_by_host(per_host)
    if slo:
        report["slo_attainment_by_host"] = slo
        # the host with the worst single-class attainment — the SLO analog
        # of most_exposed_host
        worst_h, worst_a = None, None
        for h, per_cls in sorted(slo.items()):
            for per in per_cls.values():
                for st in per.values():
                    if worst_a is None or st["attainment"] < worst_a:
                        worst_h, worst_a = h, st["attainment"]
        report["worst_slo_host"] = worst_h
    return report


def merged_trace_events(per_host, offsets, exposures=None):
    """Chrome events with one synthetic pid per host (per-host tracks).
    Exposed-comm segments land on a dedicated ``exposure`` lane (tid 1) so
    the uncovered slices of each collective are visible next to the spans
    that failed to hide them."""
    events = []
    for chrome_pid, host in enumerate(sorted(per_host), start=1):
        events.append({"name": "process_name", "ph": "M", "pid": chrome_pid,
                       "args": {"name": host}})
        events.append({"name": "thread_name", "ph": "M", "pid": chrome_pid,
                       "tid": 1, "args": {"name": "exposure"}})
        off = offsets[host]
        for iv in (exposures or {}).get(host, {}).get("intervals", []):
            for seg_start, seg_end in iv["exposed_segments"]:
                events.append({
                    "pid": chrome_pid, "tid": 1,
                    "name": f"exposed:{iv['op']}", "ph": "X",
                    "cat": "exposure",
                    "ts": round((seg_start - off) * 1e6, 3),
                    "dur": round((seg_end - seg_start) * 1e6, 3),
                    "args": {"axis": iv["axis"], "bytes": iv["bytes"],
                             "exposed_s": round(iv["exposed_s"], 6)}})
        for rec in per_host[host]:
            ts_us = round((rec["ts"] - off) * 1e6, 3)
            name, kind = rec["name"], rec.get("kind")
            tags = rec.get("tags") or {}
            base = {"pid": chrome_pid, "tid": 0}
            if kind == "span":
                # span records emit at END; value = duration in seconds
                dur = float(rec.get("value", 0.0))
                events.append({**base, "name": name, "ph": "X", "cat": "span",
                               "ts": round(ts_us - dur * 1e6, 3),
                               "dur": round(dur * 1e6, 3), "args": tags})
            elif name.startswith("comm/"):
                dur = float(tags.get("seconds", 0.0))
                events.append({**base, "name": name, "ph": "X", "cat": "comm",
                               "ts": round(ts_us - dur * 1e6, 3),
                               "dur": round(dur * 1e6, 3),
                               "args": {**tags, "bytes": rec.get("value")}})
            elif name.startswith("memory/"):
                events.append({**base, "name": "hbm_bytes_in_use", "ph": "C",
                               "cat": "memory", "ts": ts_us,
                               "args": {"bytes_in_use": rec.get("value", 0)}})
            elif name in ("mfu", "goodput"):
                events.append({**base, "name": name, "ph": "C", "cat": "ledger",
                               "ts": ts_us,
                               "args": {name: rec.get("value", 0.0)}})
            elif kind == "flow":
                # flow id stays the record's uid-derived value so one
                # request's chain binds across the per-host pid remap
                ph = tags.get("flow_phase", "t")
                ev = {**base, "name": "reqflow", "ph": ph, "cat": "serving",
                      "id": int(rec.get("value", 0)), "ts": ts_us,
                      "args": {**tags,
                               "point": name.rsplit("/", 1)[-1]}}
                if ph == "f":
                    ev["bp"] = "e"
                events.append(ev)
            else:
                events.append({**base, "name": name, "ph": "i", "s": "t",
                               "ts": ts_us,
                               "args": {**tags, "value": rec.get("value")}})
    return events


def merge(paths, out_path=None, report_path=None, bundles=None):
    per_host = {}
    for path in paths:
        host, records = load_host_records(path)
        if not records:
            print(f"trace_merge: {path}: no parseable records",
                  file=sys.stderr)
            return None, None
        if host in per_host:  # two files from the same host:pid — append
            per_host[host].extend(records)
        else:
            per_host[host] = records
    offsets, anchor = align_offsets(per_host)
    exposures = host_exposures(per_host)
    events = merged_trace_events(per_host, offsets, exposures=exposures)
    report = straggler_report(per_host, offsets, exposures=exposures)
    report["alignment_anchor"] = list(anchor) if anchor else None
    if bundles:
        lanes = load_bundle_lanes(bundles)
        if not lanes:
            print(f"trace_merge: no postmortem bundle under {bundles}",
                  file=sys.stderr)
            return None, None
        host_pids = {h: pid for pid, h in
                     enumerate(sorted(per_host), start=1)}
        events.extend(flightrec_lane_events(lanes, host_pids))
        report["flightrec"] = {
            "bundles": sum(len(bs) for bs in lanes.values()),
            "hosts": sorted(lanes),
            "reasons": sorted({b["manifest"].get("reason")
                               for bs in lanes.values() for b in bs}),
        }
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "otherData": {"producer": "deepspeed_tpu.scripts.trace_merge",
                         "hosts": sorted(set(per_host)
                                         | set(report.get("flightrec", {})
                                               .get("hosts", [])))}}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f)
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=2)
    return doc, report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="*",
                    help="per-host telemetry JSONL files (optional when "
                         "--bundles is given)")
    ap.add_argument("--out", default="merged_trace.json",
                    help="merged Chrome-trace output path")
    ap.add_argument("--report", default="",
                    help="straggler-report JSON output path ('' = stdout only)")
    ap.add_argument("--bundles", nargs="+", default=None, metavar="PATH",
                    help="postmortem bundle dirs (or parents holding "
                         "postmortem-*) folded in as per-host flightrec "
                         "lanes")
    args = ap.parse_args(argv)
    if not args.jsonl and not args.bundles:
        ap.error("need at least one JSONL file or --bundles")
    doc, report = merge(args.jsonl, out_path=args.out,
                        report_path=args.report or None,
                        bundles=args.bundles)
    if doc is None:
        return 2
    brief = {k: v for k, v in report.items() if k != "matches"}
    print(json.dumps(brief, indent=2))
    print(f"trace_merge: {len(doc['traceEvents'])} events from "
          f"{len(doc['otherData']['hosts'])} host(s) -> {args.out}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
