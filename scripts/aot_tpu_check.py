"""Chip-free real-Mosaic compile validation + compile-cache prewarm
(VERDICT r4 #2/#3).

``jax.experimental.topologies.get_topology_desc("v5e:2x2")`` exposes the
REAL XLA:TPU + Mosaic compiler for "TPU v5 lite" locally — no chip, no axon
tunnel. This script compiles every Pallas kernel at the on-chip smoke's
exact shapes (``scripts/tpu_kernel_smoke.py``) plus the flagship train
steps, which:

1. catches the whole lowering-failure class interpret-mode tests miss —
   round 2's (8,128)-tiling violations only surfaced on silicon; now they
   surface here, with the chip untouched;
2. measures true compile times per program, calibrating the on-chip smoke's
   per-kernel timeout (round 4's wedge was an axe set below flash-bwd's
   real compile time);
3. exercises the persistent-cache key path against JAX_COMPILATION_CACHE_DIR
   (default: the repo's .jax_cache, the same directory ``onchip_sequence.sh``
   exports). CAVEAT, pinned by tests/test_compile_cache_key.py: on the
   current jax/jaxlib the compile-only topology client computes correct,
   process-stable cache keys but CANNOT serialize executables
   (``serialize_executable`` rejects ``CompileOnlyPyClient``), so no cache
   entries are actually written — the prewarm is key-validation only, and
   on-chip runs still pay the cold compile. The keys also fold in the cache
   dir path itself, so prewarm and live run must export the same
   JAX_COMPILATION_CACHE_DIR.

Usage:
    python scripts/aot_tpu_check.py [--full]
    # default lane: every Pallas kernel + the multichip (tp2xdp2 train,
    # sp2 Ulysses, ep2 grouped-GEMM MoE, tp2 serving) sharded legs
    # --full adds the flagship train steps and bench legs
Output: one JSON line + onchip_results/aot_check.json
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))

os.environ.setdefault("DS_TPU_ASSUME_TPU", "1")  # traced programs must take
# the TPU fast paths (flash kernel etc.) even though the HOST platform is CPU
# — the compile target is the real v5e

os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")  # chip-free host: libtpu
# must not probe the GCP instance-metadata server for topology env vars (30
# HTTP retries per variable -> multi-minute hang before the first compile)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # host platform; compiles target TPU
jax.config.update("jax_compilation_cache_dir",
                  os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402


def _topology():
    from jax.experimental import topologies
    return topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")


def kernel_programs():
    """(name, build() -> (fn, abstract_args)) at the smoke's exact shapes."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha

    B, T, H, Dh = 2, 512, 4, 64
    qkv = tuple(jax.ShapeDtypeStruct((B, T, H, Dh), jnp.bfloat16)
                for _ in range(3))

    def flash_fwd():
        return (lambda q, k, v: flash_mha(q, k, v, causal=True)), qkv

    def flash_bwd():
        def loss(q, k, v):
            return jnp.sum(flash_mha(q, k, v, causal=True)
                           .astype(jnp.float32) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2)), qkv

    def flash_window_fwd():
        return (lambda q, k, v: flash_mha(q, k, v, causal=True,
                                          window=128)), qkv

    def flash_window_bwd():
        def loss(q, k, v):
            return jnp.sum(flash_mha(q, k, v, causal=True, window=128)
                           .astype(jnp.float32) ** 2)
        return jax.grad(loss, argnums=(0, 1, 2)), qkv

    def flash_segments_fwd():
        seg = jax.ShapeDtypeStruct((B, T), jnp.int32)
        return (lambda q, k, v, s: flash_mha(q, k, v, causal=True,
                                             segment_ids=(s, s))), qkv + (seg,)

    def paged():
        from deepspeed_tpu.ops.pallas.paged_attention import paged_mha
        S, Q, H, KV, Dh, NB, bs, MB = 3, 2, 4, 2, 64, 10, 16, 4
        args = (jax.ShapeDtypeStruct((S, Q, H, Dh), jnp.bfloat16),
                jax.ShapeDtypeStruct((NB, KV, bs, Dh), jnp.bfloat16),
                jax.ShapeDtypeStruct((NB, KV, bs, Dh), jnp.bfloat16),
                jax.ShapeDtypeStruct((S, MB), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.int32))
        return paged_mha, args

    def block_sparse():
        from deepspeed_tpu.ops.pallas.block_sparse_attention import sparse_mha
        B, H, S, D, block = 2, 4, 1024, 64, 128
        nq = S // block
        rng = np.random.default_rng(2)
        layout = ((rng.random((H, nq, nq)) < 0.4)
                  | np.eye(nq, dtype=bool)[None]).astype(np.int32)
        args = tuple(jax.ShapeDtypeStruct((B, H, S, D), jnp.bfloat16)
                     for _ in range(3))
        return (lambda q, k, v: sparse_mha(q, k, v, layout, block,
                                           causal=True)), args

    def grouped_gemm():
        from deepspeed_tpu.ops.pallas.grouped_gemm import moe_ffn_gmm
        T, D, F, E, k = 40, 128, 256, 4, 2
        args = (jax.ShapeDtypeStruct((T, D), jnp.bfloat16),
                jax.ShapeDtypeStruct((T, k), jnp.float32),
                jax.ShapeDtypeStruct((T, k), jnp.int32),
                jax.ShapeDtypeStruct((E, D, F), jnp.bfloat16),
                jax.ShapeDtypeStruct((E, F, D), jnp.bfloat16),
                jax.ShapeDtypeStruct((E, D, F), jnp.bfloat16))
        return (lambda x, tv, ti, w1, w2, w3: moe_ffn_gmm(
            x, tv, ti, w1, w2, w3, n_experts=E, dtype=jnp.bfloat16)), args

    def quantized():
        from deepspeed_tpu.ops.pallas.quantized_matmul import quantized_matmul
        # scale layout is [K, N//G] (QuantizedParameter.from_array)
        args = (jax.ShapeDtypeStruct((16, 512), jnp.bfloat16),
                jax.ShapeDtypeStruct((512, 256), jnp.int8),
                jax.ShapeDtypeStruct((512, 256 // 128), jnp.float32))
        return (lambda x, q, s: quantized_matmul(x, q, s, 128)), args

    def block_quant():
        from deepspeed_tpu.ops.pallas.quant_collective import block_quantize
        args = (jax.ShapeDtypeStruct((64, 2048), jnp.float32),)
        return (lambda x: block_quantize(x, num_bits=4, group_size=2048)), args

    def block_deq_reduce():
        from deepspeed_tpu.ops.pallas.quant_collective import (
            block_dequantize_reduce)
        args = (jax.ShapeDtypeStruct((4, 64 * 1024), jnp.uint8),
                jax.ShapeDtypeStruct((4, 64), jnp.float32))
        return (lambda q, s: block_dequantize_reduce(
            q, s, num_bits=4, group_size=2048)), args

    return [("flash_fwd", flash_fwd), ("flash_bwd", flash_bwd),
            ("flash_window_fwd", flash_window_fwd),
            ("flash_window_bwd", flash_window_bwd),
            ("flash_segments_fwd", flash_segments_fwd),
            ("paged_mha", paged), ("block_sparse", block_sparse),
            ("grouped_gemm", grouped_gemm), ("quantized_matmul", quantized),
            ("block_quantize", block_quant),
            ("block_dequantize_reduce", block_deq_reduce)]


def train_programs():
    """Flagship fwd+bwd steps at the bench's exact on-chip shapes (program
    bodies only — optimizer fusion differs per engine config, but the model
    fwd+bwd dominates compile time and covers every kernel in context)."""

    def gpt2_step():
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        cfg = GPT2Config.small()
        model = GPT2LMHeadModel(cfg)
        B, T = 32, 1024
        batch = {"input_ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((B, T), jnp.int32)}
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               {"input_ids": jnp.zeros((1, 8), jnp.int32)}))

        def loss_fn(params, b):
            # the models return the LM loss when the batch carries labels
            return model.apply({"params": params}, b)

        return jax.value_and_grad(loss_fn), (shapes["params"], batch)

    def llama_step():
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1536,
                          intermediate_size=4096, num_hidden_layers=16,
                          num_attention_heads=12, num_key_value_heads=2,
                          max_position_embeddings=2048)
        model = LlamaForCausalLM(cfg)
        B, T = 8, 2048
        batch = {"input_ids": jax.ShapeDtypeStruct((B, T), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((B, T), jnp.int32)}
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               {"input_ids": jnp.zeros((1, 8), jnp.int32)}))

        def loss_fn(params, b):
            return model.apply({"params": params}, b)

        return jax.value_and_grad(loss_fn), (shapes["params"], batch)

    return [("gpt2_small_fwd_bwd_b32", gpt2_step),
            ("llama_0p5b_fwd_bwd_b8", llama_step)]


def bench_leg_programs():
    """The longctx and serving bench legs' exact programs — compile-validated
    chip-free so legs 4-5 of onchip_sequence.sh never discover a lowering
    problem while holding the chip."""

    def longctx_step(seq):
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=2048 * 4 // 2 * 2,
                          num_hidden_layers=8, num_attention_heads=16,
                          num_key_value_heads=4, max_position_embeddings=seq,
                          scan_layers=True, remat=True)
        model = LlamaForCausalLM(cfg)
        batch = {"input_ids": jax.ShapeDtypeStruct((1, seq), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((1, seq), jnp.int32)}
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               {"input_ids": jnp.zeros((1, 8), jnp.int32)}))

        def loss_fn(p, b):
            return model.apply({"params": p}, b)

        return jax.value_and_grad(loss_fn), (shapes["params"], batch)

    def serving_forward():
        # bench_serving on-TPU shapes: 8 requests, prompt 512 + 64 new,
        # budget 256 tokens, block 32
        import ml_dtypes
        from deepspeed_tpu.models.llama import LlamaConfig
        from deepspeed_tpu.inference.v2.model_implementations.llama import (
            ragged_forward)
        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=512 + 64 + 64, remat=False)
        from deepspeed_tpu.models.llama import LlamaForCausalLM
        model = LlamaForCausalLM(cfg)
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               {"input_ids": jnp.zeros((1, 8), jnp.int32)}))
        S, budget, block = 8, 256, 32
        max_ctx = 512 + 64 + 32
        MB = -(-max_ctx // block)
        NB = max(64, (max_ctx // block + 2) * 8) + 1   # + trash block
        L, KV, Dh = cfg.num_hidden_layers, 4, 64
        bf16 = jnp.bfloat16
        args = (shapes["params"],
                jax.ShapeDtypeStruct((L, NB, KV, block, Dh), bf16),
                jax.ShapeDtypeStruct((L, NB, KV, block, Dh), bf16),
                jax.ShapeDtypeStruct((S, budget // S), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S, MB), jnp.int32))
        return (lambda p, kp, vp, t, ql, sn, bt: ragged_forward(
            cfg, p, kp, vp, t, ql, sn, bt)), args

    def device_sampler():
        from deepspeed_tpu.inference.v2.sampling import sample_rows
        S, V = 8, 32000
        args = (jax.ShapeDtypeStruct((S, V), jnp.float32),
                jax.ShapeDtypeStruct((S,), jnp.float32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.float32),
                jax.ShapeDtypeStruct((S,), jnp.int32),
                jax.ShapeDtypeStruct((S,), jnp.int32))
        return (lambda l, t, k, p, sd, ps: sample_rows(l, t, k, p, sd, ps)), \
            args

    return [("longctx_4k_fwd_bwd", lambda: longctx_step(4096)),
            ("longctx_8k_fwd_bwd", lambda: longctx_step(8192)),
            ("serving_ragged_forward", serving_forward),
            ("serving_device_sampler", device_sampler)]


def multichip_programs(topo):
    """Sharded programs compiled for the REAL 2x2 v5e topology: validate that
    the Pallas kernels + GSPMD partitioning + ICI collectives (param
    all-gathers, grad reduce-scatters, Ulysses all-to-alls) all lower for
    actual TPU hardware — one level beyond the CPU-mesh dryrun (same
    semantics, emulated collectives) in ``__graft_entry__.dryrun_multichip``.

    GSPMD cannot auto-partition Mosaic kernels, so every leg here depends on
    the SPMD kernel dispatch layer (``ops/registry.sharded_kernel_call`` over
    ``parallel/topology.use_kernel_mesh``) wrapping the kernel invocations in
    shard_map. These legs run in the DEFAULT lane: they are the cheap,
    load-bearing proof that the multi-chip flagship compiles at all."""
    from deepspeed_tpu.parallel.topology import use_kernel_mesh

    def llama_tp2_dp2():
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024,
                          intermediate_size=2816, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=4,
                          max_position_embeddings=1024)
        model = LlamaForCausalLM(cfg)
        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dp", "tp"))
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               {"input_ids": jnp.zeros((1, 8), jnp.int32)}))
        params = shapes["params"]
        tp_specs = model.param_specs(params)

        def shard_param(spec, leaf):
            # tp spec + ZeRO-style dp shard on the first free axis when the
            # leaf is large enough (mirrors the stage-3 partitioner's rule)
            spec = spec if spec is not None else P()
            entries = list(spec) + [None] * (len(leaf.shape) - len(spec))
            if leaf.ndim >= 1 and leaf.shape[0] % 2 == 0 and \
                    entries[0] is None:
                entries[0] = "dp"
            return NamedSharding(mesh, P(*entries))

        in_shardings = (
            jax.tree.map(shard_param, tp_specs, params,
                         is_leaf=lambda x: x is None or isinstance(x, P)),
            {"input_ids": NamedSharding(mesh, P("dp")),
             "labels": NamedSharding(mesh, P("dp"))})
        batch = {"input_ids": jax.ShapeDtypeStruct((8, 1024), jnp.int32),
                 "labels": jax.ShapeDtypeStruct((8, 1024), jnp.int32)}

        def loss_fn(p, b):
            # the active kernel mesh (read at trace time) makes flash_mha
            # dispatch through shard_map over (dp, tp)
            with use_kernel_mesh(mesh):
                return model.apply({"params": p}, b)

        fn = jax.value_and_grad(loss_fn)
        return fn, (params, batch), in_shardings

    def flash_ulysses_sp2():
        # Ulysses: seq-sharded q/k/v, all-to-all to head-sharded inside an
        # explicit shard_map, flash kernel on the full local sequence. The
        # active kernel mesh is deliberately set too: inside the shard_map
        # both axes are already manual, so the dispatcher must detect that
        # and NOT double-wrap.
        from deepspeed_tpu.ops.pallas.flash_attention import flash_mha
        from deepspeed_tpu.sequence.layer import DistributedAttention
        from deepspeed_tpu.utils import jax_compat

        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dp", "sp"))
        B, T, H, Dh = 2, 1024, 8, 64
        attn = DistributedAttention(
            lambda q, k, v: flash_mha(q, k, v, causal=True), "sp")
        sharded = jax_compat.shard_map(
            lambda q, k, v: attn(q, k, v), mesh=mesh,
            in_specs=(P("dp", "sp"),) * 3, out_specs=P("dp", "sp"),
            check_vma=False)

        def loss(q, k, v):
            with use_kernel_mesh(mesh):
                return jnp.sum(sharded(q, k, v).astype(jnp.float32) ** 2)

        sh = NamedSharding(mesh, P("dp", "sp"))
        abstract = tuple(jax.ShapeDtypeStruct((B, T, H, Dh), jnp.bfloat16)
                         for _ in range(3))
        return jax.grad(loss, argnums=(0, 1, 2)), abstract, (sh, sh, sh)

    def moe_gmm_ep2():
        from deepspeed_tpu.ops.pallas.grouped_gemm import moe_ffn_gmm

        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dp", "ep"))
        T, D, F, E, k = 64, 256, 512, 4, 2

        def fn(x, tv, ti, w1, w2, w3):
            # tokens shard over dp x ep (the expert world is carved out of
            # DP); the dispatcher shard_maps the scatter->gmm->gather chain
            with use_kernel_mesh(mesh):
                return moe_ffn_gmm(x, tv, ti, w1, w2, w3, n_experts=E,
                                   dtype=jnp.bfloat16)

        abstract = (jax.ShapeDtypeStruct((T, D), jnp.bfloat16),
                    jax.ShapeDtypeStruct((T, k), jnp.float32),
                    jax.ShapeDtypeStruct((T, k), jnp.int32),
                    jax.ShapeDtypeStruct((E, D, F), jnp.bfloat16),
                    jax.ShapeDtypeStruct((E, F, D), jnp.bfloat16),
                    jax.ShapeDtypeStruct((E, D, F), jnp.bfloat16))
        tok = NamedSharding(mesh, P(("dp", "ep")))
        rep = NamedSharding(mesh, P())
        return fn, abstract, (tok, tok, tok, rep, rep, rep)

    def moe_gmm_ep2_dropless():
        # dropless expert parallelism: routed rows sort by owning peer,
        # ride the explicit dispatch all-to-all into the per-row grouped
        # GEMM, and come back through the combine a2a — no capacity dim
        # anywhere, so the whole chain must lower with ragged group sizes
        from deepspeed_tpu.moe import sharded_moe
        from deepspeed_tpu.utils import jax_compat

        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dp", "ep"))
        T, D, F, E, k = 64, 256, 512, 4, 2

        def body(xl, gl, el, w1l, w2l, w3l):
            return sharded_moe._moe_gmm_ep_shard(
                xl, gl, el, w1l, w2l, w3l, n_experts=E, ep_axis="ep",
                bits=None, dtype=jnp.bfloat16, interpret=False)

        tok = P(("dp", "ep"))
        fn = jax_compat.shard_map(
            body, mesh=mesh,
            in_specs=(tok, tok, tok, P("ep"), P("ep"), P("ep")),
            out_specs=tok, check_vma=False)
        abstract = (jax.ShapeDtypeStruct((T, D), jnp.bfloat16),
                    jax.ShapeDtypeStruct((T, k), jnp.float32),
                    jax.ShapeDtypeStruct((T, k), jnp.int32),
                    jax.ShapeDtypeStruct((E, D, F), jnp.bfloat16),
                    jax.ShapeDtypeStruct((E, F, D), jnp.bfloat16),
                    jax.ShapeDtypeStruct((E, D, F), jnp.bfloat16))
        toksh = NamedSharding(mesh, tok)
        epsh = NamedSharding(mesh, P("ep"))
        return fn, abstract, (toksh, toksh, toksh, epsh, epsh, epsh)

    def moe_quant_a2a_ep2():
        # hierarchy-split expert a2a: full-precision exchange over the ICI
        # 'ep' ring, int8 + per-group scales over the DCN 'dpr' hop — the
        # block quant/dequant Pallas kernels must lower inside the
        # manual-axes shard_map, like qgz_hpz_grad_exchange
        from deepspeed_tpu.runtime.comm.coalesced_collectives import (
            moe_hierarchical_a2a)
        from deepspeed_tpu.utils import jax_compat

        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dpr", "ep"))

        def body(x):
            y = moe_hierarchical_a2a(x, intra_axis="ep", inter_axis="dpr",
                                     inter_bits=8)
            return jnp.sum(y.astype(jnp.float32))

        fn = jax_compat.shard_map(body, mesh=mesh, in_specs=(P(),),
                                  out_specs=P(), check_vma=False)
        abstract = (jax.ShapeDtypeStruct((2, 2, 16, 2048), jnp.float32),)
        return fn, abstract, (NamedSharding(mesh, P()),)

    def serving_ragged_tp2():
        # FastGen TP serving: the bench_serving ragged decode step under
        # tp=2 x dp=2 — paged_mha (inside lax.scan over layers) must
        # shard_map over sequences (dp) and KV heads (tp)
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from deepspeed_tpu.inference.v2.model_implementations.llama import (
            ragged_forward)

        cfg = LlamaConfig(vocab_size=32000, hidden_size=768,
                          intermediate_size=2048, num_hidden_layers=12,
                          num_attention_heads=12, num_key_value_heads=4,
                          max_position_embeddings=512 + 64 + 64, remat=False)
        model = LlamaForCausalLM(cfg)
        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dp", "tp"))
        shapes = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               {"input_ids": jnp.zeros((1, 8), jnp.int32)}))
        params = shapes["params"]
        tp_specs = model.param_specs(params)

        def shard_param(spec, leaf):
            spec = spec if spec is not None else P()
            entries = list(spec) + [None] * (len(leaf.shape) - len(spec))
            return NamedSharding(mesh, P(*entries))

        S, budget, block = 8, 256, 32
        max_ctx = 512 + 64 + 32
        MB = -(-max_ctx // block)
        NB = max(64, (max_ctx // block + 2) * 8) + 1
        L, KV, Dh = cfg.num_hidden_layers, 4, 64
        bf16 = jnp.bfloat16
        abstract = (params,
                    jax.ShapeDtypeStruct((L, NB, KV, block, Dh), bf16),
                    jax.ShapeDtypeStruct((L, NB, KV, block, Dh), bf16),
                    jax.ShapeDtypeStruct((S, budget // S), jnp.int32),
                    jax.ShapeDtypeStruct((S,), jnp.int32),
                    jax.ShapeDtypeStruct((S,), jnp.int32),
                    jax.ShapeDtypeStruct((S, MB), jnp.int32))
        pool = NamedSharding(mesh, P(None, None, "tp"))
        seq = NamedSharding(mesh, P("dp"))
        in_shardings = (
            jax.tree.map(shard_param, tp_specs, params,
                         is_leaf=lambda x: x is None or isinstance(x, P)),
            pool, pool, seq, seq, seq, seq)

        def fn(p, kp, vp, t, ql, sn, bt):
            with use_kernel_mesh(mesh):
                return ragged_forward(cfg, p, kp, vp, t, ql, sn, bt)

        return fn, abstract, in_shardings

    def qgz_hpz_exchange():
        # ZeRO++ composed leg: hpZ secondary param all-gather rides ICI (dp)
        # full precision while the qgZ gradient exchange quantizes int4 over
        # dp and int8 over DCN (dpr) — the Pallas quant kernels must lower
        # inside the manual-axes shard_map for the real topology
        from deepspeed_tpu.runtime.comm.coalesced_collectives import (
            all_to_all_quant_reduce)
        from deepspeed_tpu.utils import jax_compat

        mesh = Mesh(np.array(topo.devices).reshape(2, 2), ("dpr", "dp"))

        def body(g, w):
            wg = jax.lax.all_gather(w, "dp", axis=0, tiled=True)  # hpZ fp leg
            shard = all_to_all_quant_reduce(g, intra_axis="dp",
                                            inter_axis="dpr")
            return shard, jnp.sum(wg.astype(jnp.float32))

        fn = jax_compat.shard_map(body, mesh=mesh,
                                  in_specs=(P(), P("dp")),
                                  out_specs=(P(("dpr", "dp")), P()),
                                  check_vma=False)
        abstract = (jax.ShapeDtypeStruct((16, 4096), jnp.float32),
                    jax.ShapeDtypeStruct((256, 128), jnp.bfloat16))
        in_shardings = (NamedSharding(mesh, P()),
                        NamedSharding(mesh, P("dp")))
        return fn, abstract, in_shardings

    return [("qgz_hpz_grad_exchange", qgz_hpz_exchange),
            ("llama_tp2xdp2_zero_fwd_bwd", llama_tp2_dp2),
            ("flash_ulysses_sp2_fwd_bwd", flash_ulysses_sp2),
            ("moe_gmm_ep2_fwd", moe_gmm_ep2),
            ("moe_gmm_ep2_dropless", moe_gmm_ep2_dropless),
            ("moe_quant_a2a_ep2", moe_quant_a2a_ep2),
            ("serving_ragged_tp2", serving_ragged_tp2)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true",
                    help="also compile the flagship train steps and the "
                         "longctx/serving bench legs")
    ap.add_argument("--only", default="", help="comma list of program names")
    args = ap.parse_args()

    topo = _topology()
    mesh = Mesh(np.array(topo.devices[:1]), ("d",))
    shard = NamedSharding(mesh, P())
    target = topo.devices[0].device_kind

    # multichip legs are default-lane: they are the cheap proof that the
    # Pallas kernels partition at all (the historical red leg), and CI pins
    # them green (tests/test_aot_tpu_lowering.py)
    programs = kernel_programs() + multichip_programs(topo)
    if args.full:
        programs += train_programs() + bench_leg_programs()
    if args.only:
        keep = set(args.only.split(","))
        programs = [p for p in programs if p[0] in keep]

    # telemetry layer 4 (docs/OBSERVABILITY.md): per-program compile seconds
    # + persistent-cache hit/miss. The compile-only topology client cannot
    # serialize executables, so hit/miss is detected structurally — by
    # diffing the cache dir's file set around each compile (a miss writes a
    # new cache entry, a hit does not).
    from deepspeed_tpu import telemetry
    telemetry.configure(enabled=True, sample_sync=False)
    cache_dir = os.environ["JAX_COMPILATION_CACHE_DIR"]

    def _cache_files():
        try:
            return {os.path.join(r, f) for r, _, fs in os.walk(cache_dir)
                    for f in fs}
        except OSError:
            return set()

    results, failed = [], []
    for name, build in programs:
        cache_before = _cache_files()
        t0 = time.perf_counter()
        try:
            built = build()
            if len(built) == 3:       # multichip: explicit shardings
                fn, abstract, in_shardings = built
            else:
                fn, abstract = built
                in_shardings = jax.tree.map(lambda _: shard, abstract)
            jitted = jax.jit(fn, in_shardings=in_shardings,
                             out_shardings=None)
            compiled = jitted.lower(*abstract).compile()
            dt = time.perf_counter() - t0
            mem = compiled.memory_analysis()
            cache = ("miss" if _cache_files() - cache_before else
                     ("hit" if cache_before else "unknown"))
            mem_bytes = {
                "argument_bytes": mem.argument_size_in_bytes,
                "output_bytes": mem.output_size_in_bytes,
                "temp_bytes": mem.temp_size_in_bytes,
                "code_bytes": mem.generated_code_size_in_bytes,
            }
            telemetry.record_compile(name, dt, topology="v5e:2x2",
                                     cache=cache, memory=mem_bytes)
            results.append({"name": name, "ok": True,
                            "compile_s": round(dt, 2),
                            "cache": cache,
                            **mem_bytes})
            print(f"PASS {name}: compiled for {target} in {dt:.1f}s "
                  f"(code {mem.generated_code_size_in_bytes//1024}KB)",
                  flush=True)
        except Exception as e:
            dt = time.perf_counter() - t0
            failed.append(name)
            results.append({"name": name, "ok": False,
                            "compile_s": round(dt, 2),
                            "error": f"{type(e).__name__}: {str(e)[:500]}"})
            print(f"FAIL {name} after {dt:.1f}s: {type(e).__name__}: "
                  f"{str(e)[:300]}", flush=True)
            traceback.print_exc(limit=3)
        finally:
            # engine-building legs install a global groups topology; drop it
            # so the SPMD kernel dispatcher never wraps a LATER single-device
            # program in a stale multi-device shard_map. clear_caches too:
            # the kernel mesh binds at TRACE time, and inner-jit traces
            # (e.g. the jitted ragged_forward, shared between the tp2 leg
            # and the single-device bench leg) are cached by shapes only —
            # a cached trace would smuggle the previous leg's mesh across
            from deepspeed_tpu.parallel import groups
            groups.reset()
            jax.clear_caches()

    out = {"target": target, "cache_dir": os.environ["JAX_COMPILATION_CACHE_DIR"],
           "full": bool(args.full), "only": args.only or None,
           "results": results, "FAILED": failed,
           "telemetry": telemetry.summary()}
    os.makedirs("onchip_results", exist_ok=True)
    # a filtered debug run must never clobber the canonical artifact the
    # sequence/judge read — partial reports go to their own file
    fname = ("onchip_results/aot_check.json" if args.full and not args.only
             else "onchip_results/aot_check_partial.json")
    with open(fname, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"metric": "aot_mosaic_compile_pass",
                      "value": len(results) - len(failed),
                      "unit": f"programs (of {len(results)})",
                      "vs_baseline": 1.0 if not failed else 0.0,
                      "extra": {"failed": failed, "target": target}}))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
