"""Capture a jax.profiler trace of the headline training step on the chip.

Writes a perfetto/tensorboard trace to ``/tmp/ds_tpu_trace`` and prints the
top compiled-program cost split (from XLA's own cost analysis) so the next
optimization lever is visible without a trace viewer. One TPU job at a time.

    python scripts/profile_step.py [--batch 32] [--remat dots] [--steps 5]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--remat", default="dots")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default="/tmp/ds_tpu_trace")
    args = ap.parse_args()

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel import groups

    print("devices:", jax.devices(), flush=True)
    seq = 1024
    cfg = GPT2Config.small()
    cfg = type(cfg)(**{**cfg.__dict__, "n_positions": max(cfg.n_positions, seq),
                       "scan_layers": True, "remat": True})
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(args.batch, seq)).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    groups.reset()
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": args.batch,
                "gradient_accumulation_steps": 1,
                "bf16": {"enabled": True},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 1},
                "gradient_clipping": 1.0,
                "activation_checkpointing": {"policy": args.remat}})

    def step():
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        return loss

    print("compiling...", flush=True)
    jax.block_until_ready(step())

    # cost analysis of the compiled micro-step: flops vs bytes accessed tells
    # whether the step is MXU- or HBM-bound before opening any trace
    try:
        lowered = engine._micro_step_fn.lower(engine.state, batch)
        ca = lowered.compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = ca.get("flops", 0.0)
        bytes_ = ca.get("bytes accessed", 0.0)
        print(f"micro-step cost analysis: {flops/1e12:.2f} TFLOP, "
              f"{bytes_/1e9:.2f} GB accessed, "
              f"arithmetic intensity {flops/max(bytes_,1):.0f} flop/byte",
              flush=True)
    except Exception as e:
        print(f"cost analysis unavailable: {type(e).__name__}: {e}", flush=True)

    t0 = time.perf_counter()
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            loss = step()
        jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    toks = args.batch * seq / dt
    print(f"{dt*1000:.1f} ms/step, {toks:.0f} tokens/s "
          f"(batch {args.batch}, remat {args.remat})", flush=True)
    print(f"trace written to {args.out}", flush=True)


if __name__ == "__main__":
    main()
