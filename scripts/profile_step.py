"""Capture a jax.profiler trace of the headline training step on the chip.

Writes a perfetto/tensorboard trace to ``/tmp/ds_tpu_trace`` and prints the
top compiled-program cost split (from XLA's own cost analysis) so the next
optimization lever is visible without a trace viewer. Takes the shared chip
lease (``utils/chip_lease``) like bench.py — one TPU job at a time.

``DS_TPU_TELEMETRY=1`` enables the unified telemetry pipeline and emits one
JSON payload line to stdout (bench payload convention) with the summary —
including the overlap report attributed from the captured trace
(``telemetry/overlap.py``) — embedded in ``extra.telemetry``.

    python scripts/profile_step.py [--batch 32] [--remat dots] [--steps 5]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _attach_trace_overlap(trace_dir):
    """Best-effort: attribute exposure from the trace just captured and
    attach it to telemetry. Profiler output layout varies by jax version —
    never let report plumbing kill the profile run."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.telemetry import overlap
    try:
        events = overlap.load_trace_events(trace_dir)
        per_device = overlap.intervals_from_trace(events)
        if not per_device:
            return None
        report = overlap.overlap_report(
            per_device, mode="trace",
            comm_stats=telemetry.get_telemetry().comm_stats)
        return telemetry.attach_overlap(report)
    except Exception as e:
        print(f"overlap attribution unavailable: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--remat", default="dots")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--out", default="/tmp/ds_tpu_trace")
    args = ap.parse_args()

    # one TPU job at a time: same per-host flock bench.py serializes on
    # (no-op None on CPU-pinned runs; auto-released at process exit)
    from deepspeed_tpu.utils import chip_lease
    chip_lease.process_lease(name="profile_step")

    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel import groups

    telemetry_on = os.environ.get("DS_TPU_TELEMETRY") == "1"
    if telemetry_on:
        telemetry.configure(enabled=True, sample_sync=False,
                            chrome_trace_path=os.environ.get(
                                "DS_TPU_TELEMETRY_TRACE", ""))

    print("devices:", jax.devices(), flush=True)
    seq = 1024
    cfg = GPT2Config.small()
    cfg = type(cfg)(**{**cfg.__dict__, "n_positions": max(cfg.n_positions, seq),
                       "scan_layers": True, "remat": True})
    model = GPT2LMHeadModel(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(args.batch, seq)).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    groups.reset()
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": args.batch,
                "gradient_accumulation_steps": 1,
                "bf16": {"enabled": True},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 1},
                "gradient_clipping": 1.0,
                "activation_checkpointing": {"policy": args.remat}})

    def step():
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        return loss

    print("compiling...", flush=True)
    jax.block_until_ready(step())

    # cost analysis of the compiled micro-step: flops vs bytes accessed tells
    # whether the step is MXU- or HBM-bound before opening any trace
    try:
        lowered = engine._micro_step_fn.lower(engine.state, batch)
        ca = lowered.compile().cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops = ca.get("flops", 0.0)
        bytes_ = ca.get("bytes accessed", 0.0)
        print(f"micro-step cost analysis: {flops/1e12:.2f} TFLOP, "
              f"{bytes_/1e9:.2f} GB accessed, "
              f"arithmetic intensity {flops/max(bytes_,1):.0f} flop/byte",
              flush=True)
    except Exception as e:
        print(f"cost analysis unavailable: {type(e).__name__}: {e}", flush=True)

    t0 = time.perf_counter()
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            loss = step()
        jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / args.steps
    toks = args.batch * seq / dt
    print(f"{dt*1000:.1f} ms/step, {toks:.0f} tokens/s "
          f"(batch {args.batch}, remat {args.remat})", flush=True)
    print(f"trace written to {args.out}", flush=True)

    if telemetry_on:
        _attach_trace_overlap(args.out)
        payload = {"metric": "profile_step_ms", "value": round(dt * 1e3, 3),
                   "unit": "ms",
                   "extra": {"tokens_per_s": round(toks, 1),
                             "batch": args.batch, "remat": args.remat,
                             "trace_dir": args.out,
                             "telemetry": telemetry.summary()}}
        print(json.dumps(payload), flush=True)


if __name__ == "__main__":
    main()
