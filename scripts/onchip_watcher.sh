#!/bin/bash
# Autonomous recovery watcher: wait for the chip, then run the full on-chip
# sequence ONCE. Deadline-bounded so it never outlives the round. A lockfile
# keeps it from colliding with an interactive session that took over.
DEADLINE_S=${1:-25200}   # default 7h from launch
LOCK=/tmp/ds_tpu_onchip.lock
OUT=/root/repo/onchip_results
LOG=$OUT/watcher.log
mkdir -p "$OUT"
cd /root/repo
START=$(date +%s)
echo "onchip_watcher start $(date) deadline=${DEADLINE_S}s" >> "$LOG"
while [ $(( $(date +%s) - START )) -lt "$DEADLINE_S" ]; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "CHIP BACK $(date)" >> "$LOG"
    if ! mkdir "$LOCK" 2>/dev/null; then
      echo "another session holds $LOCK; exiting" >> "$LOG"
      exit 0
    fi
    trap 'rmdir "$LOCK" 2>/dev/null' EXIT
    bash scripts/onchip_sequence.sh
    exit 0
  fi
  echo "probe: still wedged $(date)" >> "$LOG"
  sleep 300
done
echo "onchip_watcher deadline reached $(date)" >> "$LOG"
