"""Host CPU-Adam microbench: AVX-512 native step vs numpy fallback
(reference ``tests/perf/adam_test.py`` analog). Host-only — no accelerator.

    python scripts/bench_cpu_adam.py [--n 50000000] [--iters 5]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def bench(update_fn, params, grads, iters):
    update_fn(params, grads)      # warm the code path / page in state
    t0 = time.perf_counter()
    for _ in range(iters):
        update_fn(params, grads)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=50_000_000,
                    help="elements in the flat shard (50M fp32 = 200MB)")
    ap.add_argument("--iters", type=int, default=5)
    args = ap.parse_args()

    from deepspeed_tpu.ops import cpu_adam as ca
    from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam

    rng = np.random.default_rng(0)
    params = rng.normal(size=args.n).astype(np.float32)
    grads = rng.normal(size=args.n).astype(np.float32)
    out16 = np.zeros(args.n, dtype=np.uint16)

    native = ca._native() is not None
    opt = DeepSpeedCPUAdam(lr=1e-4)

    def step(p, g):
        opt.begin_step()
        opt.update("k", p, g)

    def step_bf16(p, g):
        opt.begin_step()
        opt.update("k", p, g, out_bf16=out16)

    results = {}
    if native:
        results["native"] = bench(step, params, grads, args.iters)
        results["native+bf16copy"] = bench(step_bf16, params, grads,
                                           args.iters)
    # force the numpy path by hiding the native lib
    saved = ca._native
    ca._native = lambda: None
    try:
        opt_np = DeepSpeedCPUAdam(lr=1e-4)

        def step_np(p, g):
            opt_np.begin_step()
            opt_np.update("k", p, g)

        results["numpy"] = bench(step_np, params, grads, args.iters)
    finally:
        ca._native = saved

    gb = args.n * 4 * 4 / 1e9   # p+g+m+v read (+p/m/v write ~ same order)
    for name, dt in results.items():
        print(f"{name:>16}: {dt*1000:8.1f} ms/step  "
              f"{args.n/dt/1e9:6.2f} Gelem/s  (~{gb/dt:5.1f} GB/s read)")
    if native and "numpy" in results:
        print(f"speedup native vs numpy: "
              f"{results['numpy']/results['native']:.2f}x")


if __name__ == "__main__":
    main()
