// Async file I/O for NVMe offload (ZeRO-Infinity tier).
//
// TPU-native analog of the reference's libaio-based module
// (csrc/aio/py_lib/py_ds_aio.cpp, deepspeed_aio_thread.cpp): a C ABI exposing
// the same aio_handle semantics — pread/pwrite ops split across a worker
// thread pool in block_size chunks, submitted asynchronously and drained with
// wait(). On a TPU-VM host the win comes from overlapping O_DIRECT-class
// block I/O with XLA device execution (dispatch is async), so plain
// pread/pwrite on a thread pool with deep queues is the right primitive;
// queue_depth/single_submit knobs are accepted for config parity.

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct AioOp {
    // one scheduled chunk of a user-submitted read/write
    bool is_read;
    int fd;
    char* buf;
    int64_t nbytes;
    int64_t offset;
    std::atomic<int64_t>* remaining;  // chunks left in parent op
    std::atomic<int64_t>* error;      // sticky errno for parent op
};

struct ParentOp {
    std::atomic<int64_t> remaining{0};
    std::atomic<int64_t> error{0};
    int fd = -1;
};

class AioHandle {
  public:
    AioHandle(int64_t block_size, int64_t queue_depth, bool single_submit,
              bool overlap_events, int num_threads)
        : block_size_(block_size > 0 ? block_size : (1 << 20)),
          queue_depth_(queue_depth > 0 ? queue_depth : 8),
          single_submit_(single_submit),
          overlap_events_(overlap_events),
          stop_(false),
          inflight_(0),
          completed_(0) {
        int n = num_threads > 0 ? num_threads : 1;
        for (int i = 0; i < n; ++i)
            threads_.emplace_back([this] { worker(); });
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& t : threads_) t.join();
        for (auto* p : parents_) delete p;
    }

    int64_t block_size() const { return block_size_; }
    int64_t queue_depth() const { return queue_depth_; }
    bool single_submit() const { return single_submit_; }
    bool overlap_events() const { return overlap_events_; }
    int thread_count() const { return (int)threads_.size(); }

    // schedule one logical read/write, split into block_size chunks
    int64_t submit(bool is_read, char* buf, int64_t nbytes, const char* filename) {
        int fd;
        if (is_read) {
            fd = ::open(filename, O_RDONLY);
        } else {
            fd = ::open(filename, O_WRONLY | O_CREAT, 0644);
        }
        if (fd < 0) return -1;
        if (is_read) {
            struct stat st;
            if (::fstat(fd, &st) == 0 && st.st_size < nbytes) {
                ::close(fd);
                return -2;  // short file
            }
        }
        auto* parent = new ParentOp();
        parent->fd = fd;
        int64_t nchunks = (nbytes + block_size_ - 1) / block_size_;
        if (nchunks == 0) nchunks = 1;
        parent->remaining.store(nchunks);
        {
            std::lock_guard<std::mutex> lk(mu_);
            parents_.push_back(parent);
            inflight_ += 1;
            for (int64_t c = 0; c < nchunks; ++c) {
                int64_t off = c * block_size_;
                int64_t len = std::min(block_size_, nbytes - off);
                if (len < 0) len = 0;
                queue_.push_back(AioOp{is_read, fd, buf + off, len, off,
                                       &parent->remaining, &parent->error});
            }
        }
        cv_.notify_all();
        return 0;
    }

    // block until all submitted ops finish; returns ops completed since last wait
    int64_t wait() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return inflight_ == 0; });
        int64_t n = completed_;
        completed_ = 0;
        int64_t err = 0;
        for (auto* p : parents_) {
            if (p->error.load() != 0) err = p->error.load();
            delete p;
        }
        parents_.clear();
        return err != 0 ? -err : n;
    }

  private:
    void worker() {
        for (;;) {
            AioOp op;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
                if (stop_ && queue_.empty()) return;
                op = queue_.front();
                queue_.pop_front();
            }
            int64_t left = op.nbytes;
            char* p = op.buf;
            int64_t off = op.offset;
            while (left > 0) {
                ssize_t n = op.is_read ? ::pread(op.fd, p, left, off)
                                       : ::pwrite(op.fd, p, left, off);
                if (n <= 0) {
                    op.error->store(errno ? errno : EIO);
                    break;
                }
                left -= n;
                p += n;
                off += n;
            }
            if (op.remaining->fetch_sub(1) == 1) {
                // last chunk of this logical op
                ::close(op.fd);
                std::lock_guard<std::mutex> lk(mu_);
                inflight_ -= 1;
                completed_ += 1;
                if (inflight_ == 0) done_cv_.notify_all();
            }
        }
    }

    int64_t block_size_, queue_depth_;
    bool single_submit_, overlap_events_;
    std::mutex mu_;
    std::condition_variable cv_, done_cv_;
    std::deque<AioOp> queue_;
    std::vector<std::thread> threads_;
    std::vector<ParentOp*> parents_;
    bool stop_;
    int64_t inflight_;
    int64_t completed_;
};

}  // namespace

extern "C" {

void* aio_handle_new(int64_t block_size, int64_t queue_depth, int single_submit,
                     int overlap_events, int num_threads) {
    return new AioHandle(block_size, queue_depth, single_submit != 0,
                         overlap_events != 0, num_threads);
}

void aio_handle_free(void* h) { delete static_cast<AioHandle*>(h); }

int64_t aio_get_block_size(void* h) { return static_cast<AioHandle*>(h)->block_size(); }
int64_t aio_get_queue_depth(void* h) { return static_cast<AioHandle*>(h)->queue_depth(); }
int aio_get_single_submit(void* h) { return static_cast<AioHandle*>(h)->single_submit(); }
int aio_get_overlap_events(void* h) { return static_cast<AioHandle*>(h)->overlap_events(); }
int aio_get_thread_count(void* h) { return static_cast<AioHandle*>(h)->thread_count(); }

// async: schedule and return immediately; drain with aio_wait
int64_t aio_async_pread(void* h, char* buf, int64_t nbytes, const char* filename) {
    return static_cast<AioHandle*>(h)->submit(true, buf, nbytes, filename);
}

int64_t aio_async_pwrite(void* h, char* buf, int64_t nbytes, const char* filename) {
    return static_cast<AioHandle*>(h)->submit(false, buf, nbytes, filename);
}

int64_t aio_wait(void* h) { return static_cast<AioHandle*>(h)->wait(); }

// sync: schedule + drain
int64_t aio_sync_pread(void* h, char* buf, int64_t nbytes, const char* filename) {
    auto* handle = static_cast<AioHandle*>(h);
    int64_t rc = handle->submit(true, buf, nbytes, filename);
    if (rc != 0) return rc;
    return handle->wait() >= 0 ? 0 : -1;
}

int64_t aio_sync_pwrite(void* h, char* buf, int64_t nbytes, const char* filename) {
    auto* handle = static_cast<AioHandle*>(h);
    int64_t rc = handle->submit(false, buf, nbytes, filename);
    if (rc != 0) return rc;
    return handle->wait() >= 0 ? 0 : -1;
}

}  // extern "C"
