// Host-side optimizer kernels for ZeRO-Offload.
//
// TPU-native analog of the reference's AVX-vectorized CPU optimizers
// (csrc/adam/cpu_adam_impl.cpp, csrc/adagrad/cpu_adagrad.cpp,
// csrc/lion/cpu_lion_impl.cpp): the fp32 master weights and moments live in
// host DRAM, gradients arrive from the device, and the update runs on the
// TPU-VM host CPU. Vectorization is left to the compiler (-O3 -march=native
// auto-vectorizes these simple elementwise loops as well as the reference's
// hand-written AVX intrinsics) with OpenMP across cores.
//
// The *_copy_bf16 variants additionally produce the bf16 working copy in the
// same pass (the reference's param_copy fused half-precision write-back),
// saving one full sweep over the master weights before device upload.

#include <cmath>
#include <cstdint>
#include <cstring>

namespace {

inline uint16_t float_to_bf16(float f) {
    // round-to-nearest-even, matching XLA's convert semantics
    uint32_t bits;
    std::memcpy(&bits, &f, sizeof(bits));
    if ((bits & 0x7FFFFFFFu) > 0x7F800000u) {
        // NaN: keep it a NaN (the rounding bias could carry into the exponent
        // and launder a NaN into a finite value)
        return (uint16_t)((bits >> 16) | 0x0040u);
    }
    uint32_t rounding_bias = 0x7FFF + ((bits >> 16) & 1);
    return (uint16_t)((bits + rounding_bias) >> 16);
}

}  // namespace

extern "C" {

// Fused Adam/AdamW step over a flat fp32 shard.
//   adamw_mode: decoupled weight decay (AdamW); else L2-into-grad Adam.
//   bias_correction: apply 1/(1-beta^t) correction (reference ds_adam default).
// Matches optax.adamw: u = m_hat / (sqrt(v_hat) + eps) + wd*p; p -= lr*u.
void ds_adam_step(int64_t step, float lr, float beta1, float beta2, float eps,
                  float weight_decay, int bias_correction, int adamw_mode,
                  float* params, const float* grads, float* exp_avg,
                  float* exp_avg_sq, int64_t n) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (weight_decay > 0.0f && !adamw_mode) g += weight_decay * p;
        float m = exp_avg[i] = beta1 * exp_avg[i] + one_minus_b1 * g;
        float v = exp_avg_sq[i] = beta2 * exp_avg_sq[i] + one_minus_b2 * g * g;
        float update = (m / bc1) / (std::sqrt(v / bc2) + eps);
        if (weight_decay > 0.0f && adamw_mode) update += weight_decay * p;
        params[i] = p - lr * update;
    }
}

void ds_adam_step_copy_bf16(int64_t step, float lr, float beta1, float beta2,
                            float eps, float weight_decay, int bias_correction,
                            int adamw_mode, float* params, const float* grads,
                            float* exp_avg, float* exp_avg_sq, uint16_t* out_bf16,
                            int64_t n) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float p = params[i];
        if (weight_decay > 0.0f && !adamw_mode) g += weight_decay * p;
        float m = exp_avg[i] = beta1 * exp_avg[i] + one_minus_b1 * g;
        float v = exp_avg_sq[i] = beta2 * exp_avg_sq[i] + one_minus_b2 * g * g;
        float update = (m / bc1) / (std::sqrt(v / bc2) + eps);
        if (weight_decay > 0.0f && adamw_mode) update += weight_decay * p;
        p = p - lr * update;
        params[i] = p;
        out_bf16[i] = float_to_bf16(p);
    }
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp): v += g^2; p -= lr*g/(sqrt(v)+eps)
void ds_adagrad_step(float lr, float eps, float weight_decay, float* params,
                     const float* grads, float* exp_avg_sq, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        if (weight_decay > 0.0f) g += weight_decay * params[i];
        float v = exp_avg_sq[i] = exp_avg_sq[i] + g * g;
        params[i] -= lr * g / (std::sqrt(v) + eps);
    }
}

// Lion (reference csrc/lion/cpu_lion_impl.cpp):
//   u = sign(beta1*m + (1-beta1)*g); p -= lr*(u + wd*p); m = beta2*m + (1-beta2)*g
void ds_lion_step(float lr, float beta1, float beta2, float weight_decay,
                  float* params, const float* grads, float* exp_avg, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float m = exp_avg[i];
        float c = beta1 * m + (1.0f - beta1) * g;
        float u = (c > 0.0f) ? 1.0f : (c < 0.0f ? -1.0f : 0.0f);
        if (weight_decay > 0.0f) u += weight_decay * params[i];
        params[i] -= lr * u;
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
}

// fp32 -> bf16 bulk convert (device upload staging)
void ds_copy_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) dst[i] = float_to_bf16(src[i]);
}

}  // extern "C"
