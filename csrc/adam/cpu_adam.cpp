// Host-side optimizer kernels for ZeRO-Offload.
//
// TPU-native analog of the reference's AVX-vectorized CPU optimizers
// (csrc/adam/cpu_adam_impl.cpp + csrc/includes/simd.h,
// csrc/adagrad/cpu_adagrad.cpp, csrc/lion/cpu_lion_impl.cpp): the fp32 master
// weights and moments live in host DRAM, gradients arrive from the device,
// and the update runs on the TPU-VM host CPU. The Adam hot loop has an
// explicit AVX-512 path (16 floats/iteration incl. the fused bf16 write-back)
// with a scalar tail/fallback; Adagrad/Lion are simple enough that -O3
// -march=native auto-vectorizes them. OpenMP spreads across cores when the
// host has them.
//
// The *_copy_bf16 variants additionally produce the bf16 working copy in the
// same pass (the reference's param_copy fused half-precision write-back),
// saving one full sweep over the master weights before device upload.

#include <cmath>
#include <cstdint>
#include <cstring>

#ifdef __AVX512F__
#include <immintrin.h>
#endif

namespace {

inline uint16_t float_to_bf16(float f) {
    // round-to-nearest-even, matching XLA's convert semantics
    uint32_t bits;
    std::memcpy(&bits, &f, sizeof(bits));
    if ((bits & 0x7FFFFFFFu) > 0x7F800000u) {
        // NaN: keep it a NaN (the rounding bias could carry into the exponent
        // and launder a NaN into a finite value)
        return (uint16_t)((bits >> 16) | 0x0040u);
    }
    uint32_t rounding_bias = 0x7FFF + ((bits >> 16) & 1);
    return (uint16_t)((bits + rounding_bias) >> 16);
}

// One scalar Adam element — shared by the tail paths and the scalar build.
inline float adam_elem(float g, float p, float* m_io, float* v_io, float beta1,
                       float beta2, float one_minus_b1, float one_minus_b2,
                       float inv_bc1, float inv_bc2, float eps, float wd_l2,
                       float wd_w, float lr) {
    g += wd_l2 * p;
    float m = *m_io = beta1 * (*m_io) + one_minus_b1 * g;
    float v = *v_io = beta2 * (*v_io) + one_minus_b2 * g * g;
    float update = (m * inv_bc1) / (std::sqrt(v * inv_bc2) + eps);
    update += wd_w * p;
    return p - lr * update;
}

#ifdef __AVX512F__
// round-to-nearest-even fp32 -> bf16 for 16 lanes, NaN-safe
inline __m256i bf16_pack16(__m512 x) {
    const __m512i bits = _mm512_castps_si512(x);
    const __m512i lsb = _mm512_and_si512(_mm512_srli_epi32(bits, 16),
                                         _mm512_set1_epi32(1));
    const __m512i bias = _mm512_add_epi32(lsb, _mm512_set1_epi32(0x7FFF));
    __m512i rounded = _mm512_srli_epi32(_mm512_add_epi32(bits, bias), 16);
    // NaN lanes: truncate + set a mantissa bit instead of rounding
    const __mmask16 is_nan = _mm512_cmp_ps_mask(x, x, _CMP_UNORD_Q);
    const __m512i nan16 = _mm512_or_si512(_mm512_srli_epi32(bits, 16),
                                          _mm512_set1_epi32(0x0040));
    rounded = _mm512_mask_mov_epi32(rounded, is_nan, nan16);
    return _mm512_cvtepi32_epi16(rounded);
}

// Core AVX-512 Adam step; writes bf16 working copy when out_bf16 != nullptr.
inline void adam_avx512(float beta1, float beta2, float one_minus_b1,
                        float one_minus_b2, float inv_bc1, float inv_bc2,
                        float eps, float wd_l2, float wd_w, float lr,
                        float* params, const float* grads, float* exp_avg,
                        float* exp_avg_sq, uint16_t* out_bf16, int64_t n) {
    const __m512 vb1 = _mm512_set1_ps(beta1), vb2 = _mm512_set1_ps(beta2);
    const __m512 vomb1 = _mm512_set1_ps(one_minus_b1);
    const __m512 vomb2 = _mm512_set1_ps(one_minus_b2);
    const __m512 vibc1 = _mm512_set1_ps(inv_bc1), vibc2 = _mm512_set1_ps(inv_bc2);
    const __m512 veps = _mm512_set1_ps(eps);
    const __m512 vwdl2 = _mm512_set1_ps(wd_l2), vwdw = _mm512_set1_ps(wd_w);
    const __m512 vlr = _mm512_set1_ps(lr);
    int64_t i = 0;
#pragma omp parallel for schedule(static)
    for (i = 0; i <= n - 16; i += 16) {
        __m512 g = _mm512_loadu_ps(grads + i);
        __m512 p = _mm512_loadu_ps(params + i);
        g = _mm512_fmadd_ps(vwdl2, p, g);
        __m512 m = _mm512_loadu_ps(exp_avg + i);
        m = _mm512_fmadd_ps(vb1, m, _mm512_mul_ps(vomb1, g));
        __m512 v = _mm512_loadu_ps(exp_avg_sq + i);
        v = _mm512_fmadd_ps(vb2, v, _mm512_mul_ps(vomb2, _mm512_mul_ps(g, g)));
        _mm512_storeu_ps(exp_avg + i, m);
        _mm512_storeu_ps(exp_avg_sq + i, v);
        // sqrt and divide via rsqrt14/rcp14 + one Newton-Raphson step each:
        // ~fp32 accuracy at a fraction of vsqrtps/vdivps latency
        const __m512 vh = _mm512_mul_ps(v, vibc2);
        __m512 y = _mm512_rsqrt14_ps(vh);
        y = _mm512_mul_ps(y, _mm512_fnmadd_ps(
                _mm512_mul_ps(_mm512_set1_ps(0.5f), vh), _mm512_mul_ps(y, y),
                _mm512_set1_ps(1.5f)));
        __m512 s = _mm512_mul_ps(vh, y);  // sqrt(vh); 0 -> rsqrt=inf -> nan
        s = _mm512_mask_mov_ps(s, _mm512_cmp_ps_mask(vh, _mm512_setzero_ps(),
                                                     _CMP_EQ_OQ),
                               _mm512_setzero_ps());
        const __m512 denom = _mm512_add_ps(s, veps);
        __m512 r = _mm512_rcp14_ps(denom);
        r = _mm512_mul_ps(r, _mm512_fnmadd_ps(denom, r, _mm512_set1_ps(2.0f)));
        __m512 upd = _mm512_mul_ps(_mm512_mul_ps(m, vibc1), r);
        upd = _mm512_fmadd_ps(vwdw, p, upd);
        p = _mm512_fnmadd_ps(vlr, upd, p);
        _mm512_storeu_ps(params + i, p);
        if (out_bf16) _mm256_storeu_si256((__m256i*)(out_bf16 + i), bf16_pack16(p));
    }
    for (i = n - (n % 16); i < n; ++i) {  // scalar tail
        params[i] = adam_elem(grads[i], params[i], exp_avg + i, exp_avg_sq + i,
                              beta1, beta2, one_minus_b1, one_minus_b2,
                              inv_bc1, inv_bc2, eps, wd_l2, wd_w, lr);
        if (out_bf16) out_bf16[i] = float_to_bf16(params[i]);
    }
}
#endif  // __AVX512F__

}  // namespace

extern "C" {

// Fused Adam/AdamW step over a flat fp32 shard.
//   adamw_mode: decoupled weight decay (AdamW); else L2-into-grad Adam.
//   bias_correction: apply 1/(1-beta^t) correction (reference ds_adam default).
// Matches optax.adamw: u = m_hat / (sqrt(v_hat) + eps) + wd*p; p -= lr*u.
void ds_adam_step(int64_t step, float lr, float beta1, float beta2, float eps,
                  float weight_decay, int bias_correction, int adamw_mode,
                  float* params, const float* grads, float* exp_avg,
                  float* exp_avg_sq, int64_t n) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;
    const float wd_l2 = adamw_mode ? 0.0f : weight_decay;
    const float wd_w = adamw_mode ? weight_decay : 0.0f;
#ifdef __AVX512F__
    adam_avx512(beta1, beta2, one_minus_b1, one_minus_b2, 1.0f / bc1, 1.0f / bc2,
                eps, wd_l2, wd_w, lr, params, grads, exp_avg, exp_avg_sq,
                nullptr, n);
#else
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        params[i] = adam_elem(grads[i], params[i], exp_avg + i, exp_avg_sq + i,
                              beta1, beta2, one_minus_b1, one_minus_b2,
                              1.0f / bc1, 1.0f / bc2, eps, wd_l2, wd_w, lr);
    }
#endif
}

// Deliberately unvectorized build of the same math — the microbench baseline
// for the SIMD speedup claim (not used by the framework). Still
// OpenMP-parallel so the scalar-vs-SIMD comparison isolates vectorization,
// not thread count.
__attribute__((optimize("no-tree-vectorize")))
void ds_adam_step_scalar(int64_t step, float lr, float beta1, float beta2,
                         float eps, float weight_decay, int bias_correction,
                         int adamw_mode, float* params, const float* grads,
                         float* exp_avg, float* exp_avg_sq, int64_t n) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;
    const float wd_l2 = adamw_mode ? 0.0f : weight_decay;
    const float wd_w = adamw_mode ? weight_decay : 0.0f;
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        params[i] = adam_elem(grads[i], params[i], exp_avg + i, exp_avg_sq + i,
                              beta1, beta2, one_minus_b1, one_minus_b2,
                              1.0f / bc1, 1.0f / bc2, eps, wd_l2, wd_w, lr);
    }
}

void ds_adam_step_copy_bf16(int64_t step, float lr, float beta1, float beta2,
                            float eps, float weight_decay, int bias_correction,
                            int adamw_mode, float* params, const float* grads,
                            float* exp_avg, float* exp_avg_sq, uint16_t* out_bf16,
                            int64_t n) {
    const float bc1 = bias_correction ? 1.0f - std::pow(beta1, (float)step) : 1.0f;
    const float bc2 = bias_correction ? 1.0f - std::pow(beta2, (float)step) : 1.0f;
    const float one_minus_b1 = 1.0f - beta1;
    const float one_minus_b2 = 1.0f - beta2;
    const float wd_l2 = adamw_mode ? 0.0f : weight_decay;
    const float wd_w = adamw_mode ? weight_decay : 0.0f;
#ifdef __AVX512F__
    adam_avx512(beta1, beta2, one_minus_b1, one_minus_b2, 1.0f / bc1, 1.0f / bc2,
                eps, wd_l2, wd_w, lr, params, grads, exp_avg, exp_avg_sq,
                out_bf16, n);
#else
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float p = adam_elem(grads[i], params[i], exp_avg + i, exp_avg_sq + i,
                            beta1, beta2, one_minus_b1, one_minus_b2,
                            1.0f / bc1, 1.0f / bc2, eps, wd_l2, wd_w, lr);
        params[i] = p;
        out_bf16[i] = float_to_bf16(p);
    }
#endif
}

// Adagrad (reference csrc/adagrad/cpu_adagrad.cpp capability) with optax
// scale_by_rss math — v += g^2; p -= lr * g / sqrt(v + eps) — so the host
// tier matches the device-resident optax.adagrad leaves exactly (the caller
// seeds v with optax's initial_accumulator_value).
void ds_adagrad_step(float lr, float eps, float weight_decay, float* params,
                     const float* grads, float* exp_avg_sq, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        if (weight_decay > 0.0f) g += weight_decay * params[i];
        float v = exp_avg_sq[i] = exp_avg_sq[i] + g * g;
        params[i] -= lr * g / std::sqrt(v + eps);
    }
}

// Lion (reference csrc/lion/cpu_lion_impl.cpp):
//   u = sign(beta1*m + (1-beta1)*g); p -= lr*(u + wd*p); m = beta2*m + (1-beta2)*g
void ds_lion_step(float lr, float beta1, float beta2, float weight_decay,
                  float* params, const float* grads, float* exp_avg, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float g = grads[i];
        float m = exp_avg[i];
        float c = beta1 * m + (1.0f - beta1) * g;
        float u = (c > 0.0f) ? 1.0f : (c < 0.0f ? -1.0f : 0.0f);
        if (weight_decay > 0.0f) u += weight_decay * params[i];
        params[i] -= lr * u;
        exp_avg[i] = beta2 * m + (1.0f - beta2) * g;
    }
}

// fp32 -> bf16 bulk convert (device upload staging)
void ds_copy_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) dst[i] = float_to_bf16(src[i]);
}

}  // extern "C"

extern "C" {
// Compile-time SIMD capability probe for the Python-side bench/skip logic.
int ds_built_with_avx512(void) {
#ifdef __AVX512F__
    return 1;
#else
    return 0;
#endif
}
}  // extern "C"
