"""1-bit optimizer family + compressed allreduce tests.

Mirrors the reference's onebit coverage (``tests/unit/runtime/half_precision/
onebit/test_onebit.py``, ``tests/onebit/``): compression correctness (error
feedback makes compression unbiased over steps), warmup == exact Adam, and
convergence through the engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu.ops.onebit import onebit_adam, onebit_lamb, zero_one_adam
from deepspeed_tpu.runtime.comm.compressed import (compressed_allreduce,
                                                   init_error_buffers)


def _quadratic_losses(tx, steps=300, dim=32, seed=0):
    key = jax.random.PRNGKey(seed)
    target = jax.random.normal(key, (dim,))
    params = {"w": jnp.zeros(dim)}
    state = tx.init(params)

    @jax.jit
    def step(params, state):
        def loss_fn(p):
            return jnp.sum((p["w"] - target) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(params)
        upd, state2 = tx.update(g, state, params)
        return optax.apply_updates(params, upd), state2, loss

    losses = []
    for _ in range(steps):
        params, state, loss = step(params, state)
        losses.append(float(loss))
    return losses


@pytest.mark.parametrize("factory,final_tol", [
    (lambda: onebit_adam(learning_rate=0.05, freeze_step=50), 1e-2),
    (lambda: zero_one_adam(learning_rate=0.05, var_freeze_step=50), 1e-2),
    # sign-compressed LAMB plateaus/oscillates near the optimum without LR
    # decay; require deep progress into the compression stage + no blow-up
    (lambda: onebit_lamb(learning_rate=0.05, freeze_step=50), 0.5),
])
def test_onebit_converges_past_freeze(factory, final_tol):
    losses = _quadratic_losses(factory())
    # must keep converging well into the compression stage
    assert min(losses) < 2e-2 * losses[0]
    assert min(losses[60:]) < losses[60]
    assert losses[-1] < final_tol * losses[0]


def test_onebit_adam_warmup_matches_adam():
    """Before freeze_step the trajectory is exact Adam (reference warmup)."""
    l_1bit = _quadratic_losses(onebit_adam(learning_rate=0.05, freeze_step=10**6),
                               steps=50)
    l_adam = _quadratic_losses(optax.adam(0.05), steps=50)
    np.testing.assert_allclose(l_1bit, l_adam, rtol=1e-4)


def test_compressed_allreduce_matches_mean_over_steps(eight_devices):
    """Error feedback ⇒ the *accumulated* compressed mean tracks the exact
    accumulated mean (the property 1-bit Adam depends on)."""
    world = 8
    mesh = Mesh(np.asarray(eight_devices), ("dp",))
    n = 1000
    w_err, s_err = init_error_buffers(n, world)
    # per-device distinct state: leading world dim, sharded over dp
    w_errs = jnp.zeros((world,) + w_err.shape)
    s_errs = jnp.zeros((world,) + s_err.shape)

    @jax.jit
    def run(xs, w_errs, s_errs):
        def f(x, we, se):
            out, we2, se2 = compressed_allreduce(x[0], we[0], se[0], axis_name="dp")
            return out[None], we2[None], se2[None]
        return shard_map(f, mesh=mesh,
                         in_specs=(P("dp"), P("dp"), P("dp")),
                         out_specs=(P("dp"), P("dp"), P("dp")))(xs, w_errs, s_errs)

    rng = np.random.default_rng(0)
    acc_exact = np.zeros(n)
    acc_comp = np.zeros(n)
    for _ in range(30):
        xs = jnp.asarray(rng.normal(size=(world, n)).astype(np.float32))
        outs, w_errs, s_errs = run(xs, w_errs, s_errs)
        outs = np.asarray(outs)
        # every rank sees the same reduced tensor
        np.testing.assert_allclose(outs[0], outs[-1], rtol=1e-5, atol=1e-5)
        acc_exact += np.asarray(xs).mean(axis=0)
        acc_comp += outs[0]
    denom = np.linalg.norm(acc_exact)
    assert np.linalg.norm(acc_comp - acc_exact) / denom < 0.35
    # without error feedback the single-shot error is large; with it the
    # accumulated estimate must be much closer than one uncorrected shot
    one_shot = jnp.asarray(rng.normal(size=(world, n)).astype(np.float32))
    w0 = jnp.zeros_like(w_errs)
    s0 = jnp.zeros_like(s_errs)
    raw, _, _ = run(one_shot, w0, s0)
    raw_rel = np.linalg.norm(np.asarray(raw)[0] - np.asarray(one_shot).mean(0)) \
        / np.linalg.norm(np.asarray(one_shot).mean(0))
    acc_rel = np.linalg.norm(acc_comp - acc_exact) / denom
    assert acc_rel < raw_rel


def test_engine_accepts_onebit_names():
    import deepspeed_tpu
    from tests.simple_model import SimpleModel, random_batches

    for name in ("OneBitAdam", "ZeroOneAdam", "OneBitLamb"):
        model = SimpleModel(hidden_dim=16)
        batch = random_batches(1, 8)[0]
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 8,
                    "optimizer": {"type": name,
                                  "params": {"lr": 1e-3, "freeze_step": 2}}})
        l0 = float(engine(batch))
        engine.backward(l0)
        engine.step()
        for _ in range(5):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
        assert float(loss) < l0
