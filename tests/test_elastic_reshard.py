"""Elastic multi-slice reshard (resilience/elastic_reshard.py): the 8→4→8
CPU drill — kill half the slice set mid-step, continue on the survivors
from the checkpointed step with the loss trajectory intact, re-expand to
the original partition layout — plus the topology/checkpoint helpers the
reshard path is built from."""

import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.checkpoint.universal import (latest_universal_tag,
                                                read_universal_meta,
                                                save_universal_checkpoint,
                                                topology_remap,
                                                _opt_step_count)
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.resilience.elastic_reshard import (
    ElasticReshardController, SliceLostError, build_topology_for,
    run_elastic, run_elastic_drill, slice_devices, surviving_devices)
from tests.simple_model import SimpleModel, random_batches

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean():
    faults.reset()
    groups.reset()
    yield
    faults.reset()
    groups.reset()


# --------------------------------------------------------------- helpers

def test_slice_devices_partitioning():
    devs = list(range(8))
    slices = slice_devices(devs, 2)
    assert slices == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert surviving_devices(devs, [1], 2) == [0, 1, 2, 3]
    assert surviving_devices(devs, [0], 4) == [2, 3, 4, 5, 6, 7]
    with pytest.raises(ValueError):
        slice_devices(devs, 3)  # 8 devices don't split into 3 slices
    with pytest.raises(SliceLostError):
        surviving_devices(devs, [0, 1], 2)  # every slice gone


def test_build_topology_preserves_model_axes():
    """Shrink is dp-only: tp survives the reshard, and a survivor count
    that can't carry the model-parallel layout fails loud."""
    devs = jax.devices()
    like = MeshTopology(tp=2, devices=devs)
    topo = build_topology_for(devs[:4], like=like)
    assert (topo.tp_size, topo.dp_size) == (2, 2)
    like3 = MeshTopology(tp=8, devices=devs)
    with pytest.raises(SliceLostError, match="model-parallel"):
        build_topology_for(devs[:4], like=like3)


def test_build_topology_clamps_hpz_shard_size():
    """The hpZ shard group is re-derived for the survivors: it clamps to a
    divisor of the new dp world, collapsing to plain ZeRO when the
    survivors fit a single shard group."""
    devs = jax.devices()
    like = MeshTopology(devices=devs, zero_shard_size=4,
                        zero_hierarchy="hpz")
    assert (like.dp_size, like.dpr_size) == (4, 2)
    shrunk = build_topology_for(devs[:4], like=like)
    # 4 survivors == one shard group: the hierarchy collapses
    assert shrunk.zero_hierarchy is None and shrunk.dp_size == 4
    regrown = build_topology_for(devs, like=like)
    assert (regrown.zero_hierarchy, regrown.dp_size, regrown.dpr_size) == \
        ("hpz", 4, 2)


def test_topology_remap_accounting(tmp_path):
    model = SimpleModel()
    b = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), b)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    save_universal_checkpoint(engine, str(tmp_path), tag="ustep0")
    meta = read_universal_meta(str(tmp_path / "ustep0"))
    assert meta["topology"]["world_size"] == 8
    groups.reset()
    remap = topology_remap(meta, MeshTopology(devices=jax.devices()[:4]))
    assert remap["resharded"] and (remap["from_world"], remap["to_world"]) \
        == (8, 4)
    assert remap["axis_deltas"]["dp"] == (8, 4)


def test_latest_universal_tag_pointer_and_fallback(tmp_path):
    root = tmp_path / "uni"
    assert latest_universal_tag(str(root)) is None
    model = SimpleModel()
    b = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), b)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}})
    save_universal_checkpoint(engine, str(root), tag="ustep0")
    loss = engine(b); engine.backward(loss); engine.step()
    save_universal_checkpoint(engine, str(root), tag="ustep1")
    assert latest_universal_tag(str(root)) == "ustep1"
    # pointer gone -> fallback scans complete tag dirs, newest first
    os.remove(str(root / "latest_universal"))
    assert latest_universal_tag(str(root)) == "ustep1"
    # a torn tag (missing meta) is never a candidate
    os.remove(str(root / "ustep1" / "universal_meta.json"))
    assert latest_universal_tag(str(root)) == "ustep0"


# ------------------------------------------------------------- e2e drill

@pytest.fixture(scope="module")
def drill_payload(tmp_path_factory):
    """One full 8→4→8 drill shared by the acceptance assertions below
    (the drill trains 3 runs; split the checks, not the work)."""
    d = tmp_path_factory.mktemp("elastic_drill")
    return run_elastic_drill(str(d / "uni"))


def test_drill_continues_on_survivors_bitwise(drill_payload):
    """(a) after the mid-step slice loss, training continues on the
    4-device survivor mesh from the checkpointed step, the replayed
    restore-step loss is bitwise identical to the full-world reference,
    and the trajectory stays continuous."""
    p = drill_payload
    assert p["world_sequence"][:2] == [8, 4]
    assert p["steps_lost"] == 0
    assert p["restore_loss_bitwise_equal"] is True
    assert p["restore_steps"] == [p["fail_at_step"], p["expand_at"]]
    # every step of the trajectory within float32 reduction-order noise
    assert p["trajectory_max_rel_err"] < 1e-5
    # losses recorded for every step — nothing skipped across two reshards
    assert sorted(int(k) for k in p["losses"]) == list(range(p["steps"]))


def test_drill_reexpands_to_original_layout(drill_payload):
    """(b) re-expansion restores the original 8-way partition layout."""
    p = drill_payload
    assert p["world_sequence"] == [8, 4, 8]
    assert p["reshard_count"] == 2
    assert set(p["reshard_s"]) == {"shrink", "expand"}
    assert all(s > 0 for s in p["reshard_s"].values())


def test_drill_no_step_double_applied(drill_payload):
    """(c) the optimizer step count is strictly monotonic — the killed
    step was never half-applied, and no committed step replayed."""
    p = drill_payload
    assert p["steps_double_applied"] == 0
    assert p["final_optimizer_step"] == p["steps"]


# -------------------------------------------------------- controller API

def _build_engine_factory(config):
    model = SimpleModel(hidden_dim=32)
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]

    def build(topo):
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=dict(config),
            mesh=topo)
        return engine
    return build


def test_controller_comm_partition_triggers_shrink(tmp_path):
    """comm.partition (a DCN partition) is a slice-loss signal too: the
    controller reshards instead of crashing."""
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 1}}
    ctl = ElasticReshardController(_build_engine_factory(cfg),
                                   str(tmp_path / "uni"))
    ctl.start()
    batches = random_batches(3, 8)
    assert ctl.train_step(batches[0]) is not None
    faults.configure("comm.partition:once")
    # route one host-level collective through the comm shim inside the
    # step — the site comm.partition instruments (CPU engines trace their
    # collectives, so the drill supplies the host-path call)
    real_step = ctl.engine.step

    def step_with_host_collective():
        from deepspeed_tpu.comm import comm
        comm.all_reduce(np.ones(4, dtype=np.float32))
        return real_step()

    ctl.engine.step = step_with_host_collective
    result = run_elastic(ctl, batches)
    assert ctl.world_history[0] == 8 and 4 in ctl.world_history
    assert ctl.reshard_events[0]["kind"] == "shrink"
    # step 0 ran before run_elastic; steps 1-2 (incl. the replay) inside
    assert sorted(result["losses"]) == [1, 2]


def test_controller_replays_exact_step_after_shrink(tmp_path):
    """The restore rewinds global_steps to the last durable tag, so the
    batch whose step never applied is replayed — once."""
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}}
    ctl = ElasticReshardController(_build_engine_factory(cfg),
                                   str(tmp_path / "uni"))
    ctl.start()
    batches = random_batches(4, 8)
    faults.configure("slice.lost:once@step1")
    result = run_elastic(ctl, batches)
    assert result["opt_steps"] == [1, 2, 3, 4]  # strictly monotonic
    assert _opt_step_count(ctl.engine.state.opt_state) == 4
    ev = ctl.reshard_events[0]
    assert ev["kind"] == "shrink" and ev["step"] == 1 and ev["tag"] == "ustep1"
