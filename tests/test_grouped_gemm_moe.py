"""Ragged grouped-GEMM MoE FFN (megablox) vs the GShard einsum oracle
(reference ``tests/unit/inference/v2/kernels/cutlass_ops`` +
``ragged_ops/moe_*`` analogs). Interpret mode on CPU; real-TPU lowering is
covered by scripts/tpu_kernel_smoke.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.model_implementations.mixtral import _moe_ffn
from deepspeed_tpu.ops.pallas.grouped_gemm import (is_supported, moe_ffn_gmm,
                                                   topk_router)


def make_case(T=16, D=128, F=256, E=4, k=2, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    x = jax.random.normal(ks[0], (T, D), jnp.float32)
    gate = jax.random.normal(ks[1], (D, E), jnp.float32) * 0.3
    w1 = jax.random.normal(ks[2], (E, D, F), jnp.float32) * 0.05
    w2 = jax.random.normal(ks[3], (E, F, D), jnp.float32) * 0.05
    w3 = jax.random.normal(ks[4], (E, D, F), jnp.float32) * 0.05
    return x, gate, w1, w2, w3, k


@pytest.mark.parametrize("T", [16, 40])
def test_matches_einsum_oracle(T):
    x, gate, w1, w2, w3, k = make_case(T=T)
    tv, ti = topk_router(x, gate, k)
    got = moe_ffn_gmm(x, tv, ti, w1, w2, w3, n_experts=gate.shape[1],
                      dtype=jnp.float32, interpret=True)
    want = _moe_ffn(x, gate, w1, w2, w3, k=k, dtype=jnp.float32,
                    force_einsum=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_skewed_routing():
    """Heavily skewed routing (one expert takes nearly all tokens): ragged
    groups handle it with no capacity overflow, matching the lossless
    einsum oracle."""
    x, gate, w1, w2, w3, k = make_case(T=24, seed=3)
    x = jnp.abs(x)                  # positive tokens: the col-0 bump then
    gate = gate.at[:, 0].add(5.0)   # routes every token to expert 0
    logits = (x @ gate).astype(jnp.float32)
    top_idx = jnp.argmax(logits, axis=-1)
    assert int((top_idx == 0).sum()) >= 22  # fixture sanity: real skew
    tv, ti = topk_router(x, gate, 1)
    got = moe_ffn_gmm(x, tv, ti, w1, w2, w3, n_experts=gate.shape[1],
                      dtype=jnp.float32, interpret=True)
    want = _moe_ffn(x, gate, w1, w2, w3, k=1, dtype=jnp.float32,
                    force_einsum=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-3, atol=2e-3)


def test_is_supported_gate():
    assert is_supported(128, 256)
    assert not is_supported(96, 256)
    assert not is_supported(128, 200)
