"""Flight recorder + postmortem bundle tests (telemetry/flightrec.py,
scripts/postmortem.py).

Pins the black-box contract: a randomized ring property test against a
naive keep-last-N reference, the O(1)/one-clock-read/zero-allocation
recording guarantees, Fault/Recovery mirroring while telemetry is
DISABLED, crash-consistent bundle publish (schema, atomicity, the
one-bundle-per-process guard), the classifier signature catalogue, the
faults long-sleep flush and the watchdog flush, and the analyzer CLI
end to end.
"""

import importlib.util
import json
import os
import random
import tracemalloc

import pytest

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry import flightrec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pm():
    """scripts/postmortem.py, loaded standalone (it is not a package
    module on purpose: it must run on hosts without jax)."""
    spec = importlib.util.spec_from_file_location(
        "pm_under_test", os.path.join(REPO, "scripts", "postmortem.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean():
    """Fresh, unconfigured recorder and DISABLED telemetry per test."""
    flightrec.reset()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)
    yield
    flightrec.reset()
    telemetry.close()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

class _NaiveRecorder:
    """The obvious O(n) reference: append everything, slice the tail."""

    def __init__(self, capacity):
        self.capacity = capacity
        self.all = []

    def record(self, kind, name, detail, ts):
        self.all.append(
            {"seq": len(self.all), "ts": ts, "kind": kind, "name": name,
             "detail": detail})

    def events(self):
        return self.all[-self.capacity:]


@pytest.mark.parametrize("capacity", [1, 3, 7, 64])
def test_ring_matches_naive_reference(capacity):
    """Randomized equivalence: for any append sequence the ring holds
    exactly the newest ``capacity`` events in seq order, and the lifetime
    counters (total, per-kind, dropped) survive eviction."""
    rng = random.Random(1000 + capacity)
    ring = flightrec.FlightRecorder(capacity)
    naive = _NaiveRecorder(capacity)
    kinds = ("fault", "recovery", "watchdog", "memory", "slo")
    for i in range(rng.randrange(2 * capacity, 6 * capacity + 10)):
        kind = rng.choice(kinds)
        detail = {"i": i} if rng.random() < 0.5 else None
        seq = ring.record(kind, f"{kind}/e{i}", detail=detail, ts=float(i))
        naive.record(kind, f"{kind}/e{i}", detail, float(i))
        assert seq == i
        assert ring.events() == naive.events()
        assert ring.total_count == len(naive.all)
        assert ring.dropped == max(len(naive.all) - capacity, 0)
    want_counts = {}
    for ev in naive.all:
        want_counts[ev["kind"]] = want_counts.get(ev["kind"], 0) + 1
    assert ring.counts_by_kind == want_counts
    snap = ring.snapshot()
    assert snap["capacity"] == capacity
    assert snap["total_count"] == ring.total_count
    assert snap["dropped"] == ring.dropped
    assert snap["events"] == naive.events()


def test_record_overhead_one_clock_read_zero_growth(monkeypatch):
    """The always-on guarantee: exactly one wall-clock read per event
    (zero when the caller stamps ``ts``), and once the ring is full,
    recording allocates nothing inside flightrec (in-place eviction)."""
    reads = [0]

    def _clock():
        reads[0] += 1
        return 123.0

    monkeypatch.setattr(flightrec, "_now_wall", _clock)
    ring = flightrec.FlightRecorder(32)
    for i in range(50):
        ring.record("fault", "Fault/x")
    assert reads[0] == 50
    ring.record("fault", "Fault/x", ts=1.0)
    assert reads[0] == 50, "caller-stamped events must not read the clock"

    # allocation growth must be bounded by CAPACITY (the live slot
    # contents), never by event count: 5x the events, same footprint
    def _grown(n):
        tracemalloc.start()
        snap0 = tracemalloc.take_snapshot()
        for _ in range(n):
            ring.record("fault", "Fault/x", ts=1.0)
        snap1 = tracemalloc.take_snapshot()
        tracemalloc.stop()
        filt = [tracemalloc.Filter(True, flightrec.__file__)]
        return sum(st.size_diff for st in
                   snap1.filter_traces(filt).compare_to(
                       snap0.filter_traces(filt), "lineno")
                   if st.size_diff > 0)

    for _ in range(64):  # warm: every slot materialized, eviction engaged
        ring.record("fault", "Fault/x", ts=1.0)
    g1 = _grown(2000)
    g2 = _grown(10000)
    assert g1 <= 64 * ring.capacity, f"footprint not capacity-bounded: {g1}B"
    assert g2 <= g1 + 256, \
        f"record() allocation scales with event count: {g1}B -> {g2}B"


def test_fault_events_mirrored_while_telemetry_disabled():
    """The whole point of the black box: Fault/* and Recovery/* land in
    the ring even when telemetry is off, and telemetry itself stays a
    strict no-op (summary still reports disabled)."""
    assert not telemetry.enabled()
    base = flightrec.get_recorder().total_count
    telemetry.record("Fault/slice.lost", 1, kind="counter", hit=1)
    telemetry.record("Recovery/readmit", 1, kind="counter")
    telemetry.record("loss", 1.0)  # ordinary metric: NOT ring-worthy
    evs = flightrec.get_recorder().events()
    tail = [e for e in evs if e["seq"] >= base]
    assert [(e["kind"], e["name"]) for e in tail] == [
        ("fault", "Fault/slice.lost"), ("recovery", "Recovery/readmit")]
    assert tail[0]["detail"] == {"hit": 1}
    assert telemetry.summary() == {"enabled": False}


# ---------------------------------------------------------------------------
# bundle publish
# ---------------------------------------------------------------------------

def test_flush_without_destination_is_noop(tmp_path):
    flightrec.record("fault", "Fault/x")
    assert flightrec.flush_bundle("stall") is None
    assert flightrec.last_bundle() is None


def test_bundle_schema_atomicity_and_classification(tmp_path):
    pm = _pm()
    flightrec.configure(dir=str(tmp_path))
    flightrec.record("fault", "Fault/slice.lost", {"hit": 1})
    flightrec.record("recovery", "Recovery/emergency_save")
    path = flightrec.flush_bundle("slice_loss", detail="drill", exit_code=84,
                                  extra={"fault_point": "slice.lost"})
    assert path and os.path.isdir(path)
    assert os.path.basename(path).startswith(flightrec.BUNDLE_PREFIX)
    # atomic publish: no tmp sibling survives, all five payloads present
    assert not [n for n in os.listdir(tmp_path) if ".tmp." in n]
    for name in (flightrec.MANIFEST_NAME, flightrec.EVENTS_NAME,
                 flightrec.SUMMARY_NAME, flightrec.STATE_NAME,
                 flightrec.STACKS_NAME):
        assert os.path.isfile(os.path.join(path, name)), name
    assert pm.validate_bundle(path) == []

    b = pm.load_bundle(path)
    man = b["manifest"]
    assert man["reason"] == "slice_loss" and man["exit_code"] == 84
    assert man["pid"] == os.getpid()
    assert man["extra"]["fault_point"] == "slice.lost"
    assert man["counts_by_kind"]["fault"] >= 1
    names = [e["name"] for e in b["events"]]
    assert "Fault/slice.lost" in names
    assert "postmortem/flush" in names, "the flush itself rides in the ring"
    assert b["summary"] == {"enabled": False}
    assert "env" in b["state"] and "faults" in b["state"]
    typ, evidence = pm.classify_bundle(b)
    assert typ == "slice_loss", (typ, evidence)


def test_one_bundle_per_process_guard_and_force(tmp_path):
    flightrec.configure(dir=str(tmp_path))
    first = flightrec.flush_bundle("stall")
    again = flightrec.flush_bundle("watchdog_stall")
    assert again == first, "second abnormal path must reuse the artifact"
    assert flightrec.last_bundle() == first
    names = [e["name"] for e in flightrec.get_recorder().events()]
    assert "postmortem/skipped" in names
    forced = flightrec.flush_bundle("oom", force=True)
    assert forced and forced != first
    assert len([n for n in os.listdir(tmp_path)
                if n.startswith(flightrec.BUNDLE_PREFIX)]) == 2


def test_failing_collector_is_captured_not_fatal(tmp_path):
    pm = _pm()
    flightrec.configure(dir=str(tmp_path))

    def _bad():
        raise RuntimeError("census exploded")

    flightrec.register_collector("fleet/bad", _bad)
    flightrec.register_collector("fleet/good", lambda: {"pages": 7})
    path = flightrec.flush_bundle("replica_loss")
    state = pm.load_bundle(path)["state"]
    assert state["collectors"]["fleet/good"] == {"pages": 7}
    assert state["collectors"]["fleet/bad"]["error"].startswith(
        "RuntimeError")
    assert pm.validate_bundle(path) == []


# ---------------------------------------------------------------------------
# classifier signature catalogue
# ---------------------------------------------------------------------------

def _bundle(reason="unhandled_exception", events=(), exit_code=None,
            run_id="r", extra=None):
    return {"path": f"/x/postmortem-0-0-{reason}",
            "manifest": {"format_version": 1, "kind": "postmortem_bundle",
                         "reason": reason, "host": "h", "pid": 1,
                         "run_id": run_id, "created_unix": 0.0,
                         "exit_code": exit_code, "extra": extra or {}},
            "events": [{"seq": i, "ts": float(i), "kind": "fault", "name": n}
                       for i, n in enumerate(events)],
            "summary": None, "state": None}


def test_classifier_direct_reasons():
    pm = _pm()
    for reason, want in [("oom", "oom"), ("stall", "stall"),
                         ("watchdog_stall", "stall"),
                         ("preemption", "preemption"),
                         ("slice_loss", "slice_loss"),
                         ("replica_loss", "replica_loss"),
                         ("corrupt_ckpt", "corrupt_ckpt"),
                         ("backend_unavailable", "backend_unavailable")]:
        typ, _ = pm.classify_bundle(_bundle(reason=reason))
        assert typ == want, (reason, typ)


def test_classifier_event_signatures_and_exit_codes():
    pm = _pm()
    cases = [
        (_bundle(events=["Fault/slice.lost"]), "slice_loss"),
        (_bundle(events=["Fault/replica.lost"]), "replica_loss"),
        (_bundle(events=["Fault/step.hang"]), "stall"),
        (_bundle(events=["Fault/ckpt.write"]), "corrupt_ckpt"),
        (_bundle(events=["Fault/oom"]), "oom"),
        (_bundle(extra={"fault_point": "comm.partition"}), "slice_loss"),
        (_bundle(exit_code=83), "preemption"),
        (_bundle(exit_code=84), "slice_loss"),
        (_bundle(exit_code=85), "stall"),
        (_bundle(), "unknown"),
    ]
    for b, want in cases:
        typ, evidence = pm.classify_bundle(b)
        assert typ == want, (b["manifest"], typ, evidence)


def test_incident_merge_by_run_id_and_tiebreak():
    """Bundles sharing a run_id are one incident; ties between concrete
    types resolve to the earliest catalogue entry (most root-cause-ish),
    and the merged timeline is wall-clock ordered across processes."""
    pm = _pm()
    a = _bundle(reason="stall", run_id="gang1")
    b = _bundle(reason="slice_loss", run_id="gang1", exit_code=84)
    inc = pm.classify_incident([b, a])
    assert inc["incident"] == "stall"  # stall precedes slice_loss
    assert inc["run_id"] == "gang1"
    assert sorted(inc["reasons"]) == ["slice_loss", "stall"]
    assert inc["exit_codes"] == [84]


# ---------------------------------------------------------------------------
# producers: faults long-sleep flush + watchdog flush
# ---------------------------------------------------------------------------

def test_faults_long_sleep_flushes_before_stalling(tmp_path, monkeypatch):
    """A sleep-action fault at or above STALL_FLUSH_MIN_SLEEP_S is a
    wedge: the bundle must hit disk BEFORE the sleep starts, so a SIGKILL
    landing inside the window still leaves the artifact. Short chaos
    sleeps must NOT flush."""
    pm = _pm()
    from deepspeed_tpu.resilience import faults
    slept = []
    monkeypatch.setattr(faults.time, "sleep", lambda s: slept.append(s))
    flightrec.configure(dir=str(tmp_path))
    try:
        faults.configure("step.hang:once!sleep60")
        faults.maybe_fail("step.hang")
        assert slept == [60.0]
        bundles = pm.find_bundles([str(tmp_path)])
        assert len(bundles) == 1
        typ, _ = pm.classify_bundle(pm.load_bundle(bundles[0]))
        assert typ == "stall"
        # below the wedge threshold: chaos latency, no artifact
        flightrec.reset()
        short_dir = tmp_path / "short"
        flightrec.configure(dir=str(short_dir))
        faults.configure("step.hang:once!sleep2")
        faults.maybe_fail("step.hang")
        assert slept[-1] == 2.0
        assert pm.find_bundles([str(short_dir)]) == []
        assert flightrec.last_bundle() is None
    finally:
        faults.reset()


def test_watchdog_fire_flushes_stall_bundle(tmp_path):
    """The watchdog's non-abort fire path leaves a classifiable bundle
    (abort=True takes the identical path before os._exit — exercised as
    a real subprocess by scripts/fault_drill.py --drill watchdog)."""
    pm = _pm()
    from deepspeed_tpu.resilience.watchdog import StepWatchdog
    flightrec.configure(dir=str(tmp_path))
    wd = StepWatchdog(abort=False, min_interval_s=1.0)
    wd.beat(step_seconds=0.5)
    report = wd._fire(12.0, 1.0)
    assert "no step progress" in report
    bundles = pm.find_bundles([str(tmp_path)])
    assert len(bundles) == 1
    b = pm.load_bundle(bundles[0])
    assert b["manifest"]["reason"] == "watchdog_stall"
    assert b["manifest"]["exit_code"] is None, "abort=False carries no code"
    names = [e["name"] for e in b["events"]]
    assert "watchdog/beat" in names, "heartbeats ride in the black box"
    assert "Fault/hang" in names
    typ, _ = pm.classify_bundle(b)
    assert typ == "stall"


# ---------------------------------------------------------------------------
# analyzer CLI
# ---------------------------------------------------------------------------

def test_postmortem_cli_end_to_end(tmp_path, capsys):
    pm = _pm()
    flightrec.configure(dir=str(tmp_path / "pm"))
    flightrec.record("fault", "Fault/preemption", {"signal": 15})
    assert flightrec.flush_bundle("preemption", exit_code=83)
    json_out = tmp_path / "report.json"
    rc = pm.main([str(tmp_path / "pm"), "--json-out", str(json_out)])
    assert rc == 0
    report = json.loads(json_out.read_text())
    assert report["schema"] == pm.REPORT_SCHEMA
    assert report["bundles"] == 1 and report["malformed"] == 0
    (inc,) = report["incidents"]
    assert inc["incident"] == "preemption"
    assert inc["exit_codes"] == [83]
    out = capsys.readouterr()
    assert out.out.strip().splitlines()[-1] == json.dumps(
        report, sort_keys=True, default=str), "stdout is ONE json line"
    assert "PREEMPTION" in out.err


def test_trace_merge_folds_bundles_into_flightrec_lanes(tmp_path,
                                                        monkeypatch):
    """--bundles: a dead process's ring lands on its OWN host track (same
    host:pid label as its telemetry JSONL) as a tid-2 ``flightrec`` lane;
    bundle-only hosts get fresh tracks; lane timestamps zero-base on the
    earliest ring event so cross-process order survives the merge."""
    spec = importlib.util.spec_from_file_location(
        "tm_under_test", os.path.join(REPO, "scripts", "trace_merge.py"))
    tm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tm)

    pm_dir = tmp_path / "pm"
    monkeypatch.setattr(flightrec, "_now_wall", lambda: 102.0)
    monkeypatch.setattr(flightrec, "_identity", lambda: ("host-a", 1, "r1"))
    flightrec.configure(dir=str(pm_dir))
    flightrec.record("fault", "Fault/step.hang", ts=100.0)
    assert flightrec.flush_bundle("stall", exit_code=85)
    flightrec.reset()
    monkeypatch.setattr(flightrec, "_identity", lambda: ("host-b", 2, "r1"))
    flightrec.configure(dir=str(pm_dir))
    flightrec.record("watchdog", "watchdog/beat", ts=101.0)
    assert flightrec.flush_bundle("slice_loss", exit_code=84)

    jl = tmp_path / "a.jsonl"
    jl.write_text(json.dumps(
        {"kind": "span", "name": "fwd", "ts": 2.0, "value": 1.0,
         "host": "host-a", "pid": 1, "run_id": "r1"}) + "\n")
    doc, report = tm.merge([str(jl)], bundles=[str(pm_dir)])
    assert report["flightrec"] == {
        "bundles": 2, "hosts": ["host-a:1", "host-b:2"],
        "reasons": ["slice_loss", "stall"]}
    assert doc["otherData"]["hosts"] == ["host-a:1", "host-b:2"]

    evs = doc["traceEvents"]
    lane = [e for e in evs if e.get("cat") == "flightrec"]
    assert lane and all(e["tid"] == 2 for e in lane)
    span_pid = next(e["pid"] for e in evs if e.get("cat") == "span")
    a_lane = [e for e in lane if e["pid"] == span_pid]
    assert any(e["name"] == "Fault/step.hang" for e in a_lane), \
        "the dead host's ring must ride its existing telemetry track"
    b_lane = [e for e in lane if e["pid"] != span_pid]
    assert any(e["name"] == "watchdog/beat" for e in b_lane)
    # zero-based on the earliest ring event (100.0): host-a fault at 0us,
    # host-b beat at 1s, flush markers stamped from manifest created_unix
    assert min(e["ts"] for e in a_lane) == 0.0
    assert any(e["ts"] == pytest.approx(1e6) for e in b_lane)
    markers = sorted(e["name"] for e in lane
                     if e["name"].startswith("postmortem:"))
    assert markers == ["postmortem:slice_loss", "postmortem:stall"]


def test_postmortem_cli_rejects_empty_and_malformed(tmp_path, capsys):
    pm = _pm()
    assert pm.main([str(tmp_path)]) == 2  # nothing to classify
    bad = tmp_path / "postmortem-1-1-x"
    bad.mkdir()
    (bad / "manifest.json").write_text("{not json")
    assert pm.main([str(tmp_path)]) == 2
    capsys.readouterr()
