"""Hierarchical ZeRO (hpZ / MiCS) must EMIT hierarchical collectives.

`tests/test_zeropp.py` proves loss parity and shard placement; this file
proves the compiled programs carry the communication pattern the hierarchy
promises (reference semantics: ``deepspeed/runtime/zero/mics.py`` shard-group
comm + ``partition_parameters.py`` ds_secondary_tensor):

- hpZ (stage 3, zero_hpz_partition_size=2 on an 8-device world → dpr=4 × dp=2):
  every parameter all-gather in the fwd/bwd step must be restricted to the
  ICI-local shard group (replica_groups=[4,2]<=[8] — four consecutive pairs),
  never the full world.
- MiCS (stage 2, mics_shard_size=2): gradients still reduce over the FULL
  data-parallel world ([1,8] all-reduce — the math is unchanged), while every
  master/optimizer-state collective in the apply step stays inside the shard
  group ([4,2]).
- Flat stage 3 (the control): its param all-gathers DO span the world
  ([1,8]) — proving this parser would catch XLA silently widening the
  hierarchical groups.

Technique (as in test_spmd_resharding.py): run the step in a subprocess with
--xla_dump_to and parse replica_groups from the optimized HLO. XLA's iota
notation: [G,S]<=[8] = G groups of S consecutive devices.
"""

import glob
import os
import re
import subprocess
import sys

import pytest

_SCRIPT = r"""
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
    " --xla_force_host_platform_device_count=8 --xla_dump_to=%(dump)s"
    " --xla_dump_hlo_module_re=.*(micro_step|apply_step|fused_step).*")
sys.path.insert(0, %(repo)r)
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np, deepspeed_tpu
from tests.simple_model import SimpleModel, random_batches
model = SimpleModel(hidden_dim=64)
batches = random_batches(2, batch_size=8, seed=1)
params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
    config={"train_batch_size": 8,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": %(zero)s})
for b in batches:
    loss = engine(b); engine.backward(loss); engine.step()
print("STEP_OK", float(jax.device_get(loss)))
"""

_GROUPS_RE = re.compile(
    r"%(?P<op>all-gather|all-reduce|reduce-scatter)[.\d]*\s*=.*?"
    r"replica_groups=(?P<groups>\[[\d,]+\]<=\[[\d,()T]+\])")


def _run_and_parse(tmp_path, zero_config, tag):
    dump = str(tmp_path / tag)
    os.makedirs(dump, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    script = _SCRIPT % {"dump": dump, "repo": repo, "zero": repr(zero_config)}
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=900,
                          cwd=repo)
    assert "STEP_OK" in proc.stdout, (
        f"step failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    found = {}   # phase -> list[(op, groups_str)]
    for path in glob.glob(f"{dump}/*after_optimizations.txt"):
        m = re.search(r"jit_(\w+)\.", os.path.basename(path))
        phase = m.group(1) if m else "unknown"
        with open(path) as f:
            for line in f:
                g = _GROUPS_RE.search(line)
                if g:
                    found.setdefault(phase, []).append(
                        (g.group("op"), g.group("groups")))
    assert found, f"no collectives parsed from {dump} — dump flags changed?"
    return found


@pytest.mark.slow
def test_flat_stage3_gathers_span_world(tmp_path):
    """Control: the parser must SEE full-world gathers in flat ZeRO-3 —
    otherwise the hierarchical assertions below could pass vacuously."""
    found = _run_and_parse(tmp_path, {
        "stage": 3, "stage3_param_persistence_threshold": 0}, "flat")
    micro = [g for op, g in found.get("micro_step", []) if op == "all-gather"]
    assert micro, f"no param all-gathers in flat stage-3 micro step: {found}"
    assert all(g.startswith("[1,8]") for g in micro), micro


@pytest.mark.slow
def test_hpz_param_gathers_confined_to_shard_group(tmp_path):
    """hpZ secondary partition: every fwd/bwd parameter all-gather rides the
    ICI-local group ([4,2] = consecutive pairs), none spans the world. Fails
    if XLA silently widens the groups."""
    found = _run_and_parse(tmp_path, {
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_hpz_partition_size": 2}, "hpz")
    micro = [g for op, g in found.get("micro_step", []) if op == "all-gather"]
    assert len(micro) >= 3, f"expected >=3 param gathers, got {found}"
    assert all(g == "[4,2]<=[8]" for g in micro), (
        f"hpZ param all-gather escaped the shard group: {micro}")
    # gradient reduction still spans the full data-parallel world
    reduces = [g for op, g in found.get("micro_step", [])
               if op == "all-reduce"]
    assert any(g.startswith("[1,8]") for g in reduces), reduces


@pytest.mark.slow
def test_mics_apply_confined_grads_full_world(tmp_path):
    """MiCS: the update math is full-DP (grad all-reduce [1,8]) but
    master/optimizer state never leaves the shard group in the apply step."""
    found = _run_and_parse(tmp_path, {
        "stage": 2, "mics_shard_size": 2}, "mics")
    reduces = [g for op, g in found.get("micro_step", [])
               if op == "all-reduce"]
    assert any(g.startswith("[1,8]") for g in reduces), (
        f"MiCS must keep full-world gradient reduction: {found}")
    apply_groups = [g for op, g in found.get("apply_step", [])]
    assert apply_groups, f"no apply-step collectives: {found}"
    assert all(g == "[4,2]<=[8]" for g in apply_groups), (
        f"MiCS master/optimizer collective escaped the shard group: "
        f"{apply_groups}")
