"""Compression + data-efficiency tests (reference ``tests/unit/compression``,
curriculum/data-sampling units)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.compression import apply_compression, init_compression, redundancy_clean
from deepspeed_tpu.compression.compress import layer_reduction
from deepspeed_tpu.runtime.data_pipeline import (CurriculumDataSampler,
                                                 CurriculumScheduler, DataAnalyzer,
                                                 RandomLTDScheduler,
                                                 random_ltd_gather,
                                                 random_ltd_scatter)
from deepspeed_tpu.runtime.data_pipeline.data_sampler import apply_seqlen_curriculum
from tests.simple_model import SimpleModel, random_batches

_BASE = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
}


def _engine(extra, steps=0, hidden=32):
    model = SimpleModel(hidden_dim=hidden)
    batches = random_batches(max(steps, 1), batch_size=8)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    cfg = dict(_BASE, **extra)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=cfg)
    for b in batches[:steps]:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    return engine, batches


# ---------------------------------------------------------------- compression

def test_weight_quant_qat_trains():
    comp = {"compression_training": {"weight_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0},
        "different_groups": {"wq1": {"params": {"target_bits": 8},
                                     "modules": ["kernel"]}}}}}
    engine, batches = _engine(comp)
    state = apply_compression(engine)
    assert state.plans, "kernels should be planned for quantization"
    losses = []
    for b in batches * 6:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], "QAT training must still converge"


def test_activation_quantization_enabled_raises():
    """activation_quantization is unimplemented: enabling it must be a loud
    ValueError at init, never a silent no-op (the old behavior skipped the
    technique while the user believed it was training quantization-aware)."""
    from deepspeed_tpu.compression.compress import init_compression
    comp = {"compression_training": {"activation_quantization": {
        "shared_parameters": {"enabled": True, "quantization_type": "symmetric",
                              "activation_bits": 8},
        "different_groups": {"aq1": {"params": {"bits": 8},
                                     "modules": ["kernel"]}}}}}
    params = {"layer": {"kernel": jnp.zeros((8, 8)), "bias": jnp.zeros((8,))}}
    with pytest.raises(ValueError, match="activation_quantization"):
        init_compression(params, comp)


def test_sparse_pruning_masks_apply():
    comp = {"compression_training": {"sparse_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0,
                              "method": "l1"},
        "different_groups": {"sp1": {"params": {"dense_ratio": 0.5},
                                     "modules": ["kernel"]}}}}}
    engine, batches = _engine(comp)
    state = apply_compression(engine)
    rep = state.sparsity_report(engine.get_model_parameters())
    kernels = {k: v for k, v in rep.items() if "kernel" in k}
    assert kernels
    for k, sparsity in kernels.items():
        assert 0.4 <= sparsity <= 0.6, f"{k}: {sparsity}"
    # training with masks: pruned entries stay (effectively) dead in forward
    loss0 = engine(batches[0])
    engine.backward(loss0)
    engine.step()


def test_row_and_head_pruning_structured():
    rng = np.random.default_rng(0)
    params = {"attn": {"kernel": jnp.asarray(rng.normal(size=(16, 32)),
                                             dtype=jnp.float32)}}
    cfg = {"compression_training": {
        "row_pruning": {"shared_parameters": {"enabled": True, "schedule_offset": 0},
                        "different_groups": {"r": {"params": {"dense_ratio": 0.5},
                                                   "modules": ["attn"]}}}}}
    state = init_compression(params, cfg)
    out = redundancy_clean(params, state)
    w = np.asarray(out["attn"]["kernel"])
    zero_rows = (np.abs(w).sum(axis=0) == 0).sum()
    assert zero_rows == 16  # half of 32 output rows zeroed

    cfg_h = {"compression_training": {
        "head_pruning": {"shared_parameters": {"enabled": True, "schedule_offset": 0},
                         "different_groups": {"h": {"params": {"dense_ratio": 0.5,
                                                               "num_heads": 4},
                                                    "modules": ["attn"]}}}}}
    state_h = init_compression(params, cfg_h)
    out_h = redundancy_clean(params, state_h)
    w_h = np.asarray(out_h["attn"]["kernel"])
    head_alive = [np.abs(w_h[:, h * 8:(h + 1) * 8]).sum() > 0 for h in range(4)]
    assert sum(head_alive) == 2


def test_schedule_offset_delays_compression():
    w = jnp.arange(1, 65, dtype=jnp.float32).reshape(8, 8)
    params = {"m": {"kernel": w}}
    cfg = {"compression_training": {"sparse_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 100},
        "different_groups": {"g": {"params": {"dense_ratio": 0.5},
                                   "modules": ["*"]}}}}}
    state = init_compression(params, cfg)
    before = state.transform(params, step=jnp.int32(0))
    np.testing.assert_allclose(np.asarray(before["m"]["kernel"]), np.asarray(w))
    after = state.transform(params, step=jnp.int32(100))
    assert (np.asarray(after["m"]["kernel"]) == 0).sum() == 32


def test_layer_reduction():
    stacked = {"w": jnp.arange(6 * 4).reshape(6, 4).astype(jnp.float32)}
    kept = layer_reduction(stacked, [0, 2, 4])
    assert kept["w"].shape == (3, 4)
    np.testing.assert_array_equal(np.asarray(kept["w"][1]),
                                  np.asarray(stacked["w"][2]))


# ---------------------------------------------------------------- curriculum

def test_curriculum_schedules():
    lin = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                               "schedule_type": "fixed_linear",
                               "schedule_config": {"total_curriculum_step": 100,
                                                   "difficulty_step": 8}})
    assert lin.get_difficulty(0) == 8
    assert lin.get_difficulty(100) == 64
    mid = lin.get_difficulty(50)
    assert 8 < mid < 64 and mid % 8 == 0

    root = CurriculumScheduler({"min_difficulty": 8, "max_difficulty": 64,
                                "schedule_type": "fixed_root",
                                "schedule_config": {"total_curriculum_step": 100,
                                                    "difficulty_step": 8,
                                                    "root_degree": 2}})
    assert root.get_difficulty(25) >= lin.get_difficulty(25)

    disc = CurriculumScheduler({"schedule_type": "fixed_discrete",
                                "schedule_config": {"difficulty": [8, 16, 32],
                                                    "max_step": [10, 20, 30]}})
    assert disc.get_difficulty(5) == 8
    assert disc.get_difficulty(15) == 16
    assert disc.get_difficulty(99) == 32


def test_seqlen_curriculum_truncation():
    batch = {"input_ids": np.ones((4, 64), np.int32),
             "labels": np.ones((4, 64), np.int32)}
    out = apply_seqlen_curriculum(batch, 16)
    assert out["input_ids"].shape == (4, 16)


def test_engine_seqlen_curriculum():
    cfg = dict(_BASE)
    cfg["curriculum_learning"] = {
        "enabled": True, "curriculum_type": "seqlen", "min_difficulty": 4,
        "max_difficulty": 8, "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 4}}
    from tests.simple_model import tiny_gpt2_batches
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    model = GPT2LMHeadModel(GPT2Config.tiny())
    batches = tiny_gpt2_batches(1, batch_size=8, seq_len=8,
                                vocab=GPT2Config.tiny().vocab_size)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    from deepspeed_tpu.parallel import groups
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=cfg)
    loss = engine(batches[0])  # step 0: seqlen truncated to 4 — must not crash
    engine.backward(loss)
    engine.step()
    assert engine.curriculum_scheduler.current_difficulty == 4


# ---------------------------------------------------------------- sampler

def test_data_analyzer_and_sampler(tmp_path):
    data = {"x": np.random.default_rng(0).normal(size=(100, 8)).astype(np.float32)}
    analyzer = DataAnalyzer(data, {"norm": lambda s: float(np.abs(s["x"]).sum())},
                            save_path=str(tmp_path))
    res = analyzer.run_map_reduce()
    vals = res["norm"]["values"]
    order = res["norm"]["index_sorted_by_metric"]
    assert (np.diff(vals[order]) >= 0).all()
    loaded = DataAnalyzer.load(str(tmp_path), "norm")
    np.testing.assert_array_equal(loaded["values"], vals)

    sampler = CurriculumDataSampler(
        vals, batch_size=8,
        curriculum_config={"min_difficulty": 10, "max_difficulty": 100,
                           "schedule_type": "fixed_linear",
                           "schedule_config": {"total_curriculum_step": 10,
                                               "difficulty_step": 1}},
        difficulty_type="percentile")
    easy_batch = sampler.next_batch_indices()
    easy_pool = set(order[:10])
    assert set(easy_batch).issubset(easy_pool)
    sampler.set_step(100)  # fully open
    late_batch = sampler.next_batch_indices()
    assert len(late_batch) == 8


# ---------------------------------------------------------------- random-LTD

def test_random_ltd_gather_scatter():
    x = jnp.arange(2 * 8 * 4).reshape(2, 8, 4).astype(jnp.float32)
    sel, idx = random_ltd_gather(x, keep=3, rng=jax.random.PRNGKey(0))
    assert sel.shape == (2, 3, 4)
    assert (np.diff(np.asarray(idx), axis=1) > 0).all()  # sorted, unique
    # selected rows match their source positions
    for b in range(2):
        for j in range(3):
            np.testing.assert_array_equal(np.asarray(sel[b, j]),
                                          np.asarray(x[b, idx[b, j]]))
    back = random_ltd_scatter(x, sel * 2, idx)
    for b in range(2):
        for j in range(3):
            np.testing.assert_array_equal(np.asarray(back[b, idx[b, j]]),
                                          np.asarray(x[b, idx[b, j]] * 2))


def test_random_ltd_scheduler():
    s = RandomLTDScheduler({"schedule_config": {"min_value": 16, "max_value": 64,
                                                "step_size": 16,
                                                "total_layer_budget": 100}})
    assert s.get_value(0) == 16
    assert s.get_value(100) == 64
    assert s.get_value(50) in (32, 48)


# ---------------------------------------------------------------------------
# distributed data analyzer (VERDICT r2 #10)
# ---------------------------------------------------------------------------

def _build_corpus(prefix, n=37, seed=0):
    from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
        MMapIndexedDatasetBuilder)
    rng = np.random.default_rng(seed)
    b = MMapIndexedDatasetBuilder(prefix, dtype=np.int32)
    for _ in range(n):
        b.add_item(rng.integers(0, 100, size=rng.integers(3, 40)))
    b.finalize()


_WORKER_SCRIPT = """
import sys
sys.path.insert(0, {repo!r})
from deepspeed_tpu.runtime.data_pipeline.data_sampler import DistributedDataAnalyzer
from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import MMapIndexedDataset
ds = MMapIndexedDataset({prefix!r})
metrics = {{"seqlen": lambda s: float(len(s)), "toksum": lambda s: float(s.sum())}}
DistributedDataAnalyzer(ds, metrics, {save!r},
                        num_workers={nw}, worker_id={wid}).run_map()
print("WORKER_DONE", {wid})
"""


def test_distributed_analyzer_matches_single_process(tmp_path):
    """Two real worker PROCESSES map disjoint shards; reduce merges via
    MMapIndexedDatasetBuilder.merge_file; index maps must equal the
    single-process DataAnalyzer byte for byte."""
    import os
    import subprocess
    import sys
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
        DataAnalyzer, DistributedDataAnalyzer)
    from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
        MMapIndexedDataset)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prefix = str(tmp_path / "corpus")
    _build_corpus(prefix)
    save = str(tmp_path / "analysis")

    procs = [subprocess.run(
        [sys.executable, "-c", _WORKER_SCRIPT.format(
            repo=repo, prefix=prefix, save=save, nw=2, wid=w)],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}) for w in range(2)]
    for p in procs:
        assert p.returncode == 0, p.stdout + p.stderr
        assert "WORKER_DONE" in p.stdout

    merged = DistributedDataAnalyzer.run_reduce(save, ["seqlen", "toksum"],
                                                num_workers=2)

    ds = MMapIndexedDataset(prefix)
    single = DataAnalyzer(ds, {"seqlen": lambda s: float(len(s)),
                               "toksum": lambda s: float(s.sum())}).run_map_reduce()
    for m in ("seqlen", "toksum"):
        np.testing.assert_array_equal(merged[m]["values"], single[m]["values"])
        np.testing.assert_array_equal(merged[m]["index_sorted_by_metric"],
                                      single[m]["index_sorted_by_metric"])
    # persisted maps load through the same API the curriculum sampler uses
    loaded = DataAnalyzer.load(save, "seqlen")
    np.testing.assert_array_equal(loaded["values"], single["seqlen"]["values"])


def test_distributed_analyzer_uneven_shards(tmp_path):
    """num_workers that does not divide the corpus still reduces exactly."""
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import (
        DataAnalyzer, DistributedDataAnalyzer)
    from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
        MMapIndexedDataset)
    prefix = str(tmp_path / "corpus")
    _build_corpus(prefix, n=10, seed=3)
    save = str(tmp_path / "analysis")
    ds = MMapIndexedDataset(prefix)
    metrics = {"seqlen": lambda s: float(len(s))}
    for w in range(3):  # in-process workers: shard math is what's under test
        DistributedDataAnalyzer(ds, metrics, save, num_workers=3,
                                worker_id=w).run_map()
    merged = DistributedDataAnalyzer.run_reduce(save, ["seqlen"], 3)
    single = DataAnalyzer(ds, metrics).run_map_reduce()
    np.testing.assert_array_equal(merged["seqlen"]["values"],
                                  single["seqlen"]["values"])
