"""Prefix-cached paged KV (copy-on-write block sharing).

Pins the four allocator states (free / live / cached / host) and their
invariants — device side ``free + live + cached == num_blocks`` always, and
``free + live + cached + host == total`` with the host-DRAM spill tier — the
chain-digest prefix cache (strict-prefix matching, park/revive/evict
lifecycle, insert dedup, children-first LRU order, LRU-ordered spill to
host), the O(free) incremental allocator stats against a sorted-scan
reference, a randomized property test over
allocate/share/deref/flush/evict/spill/restore (including
no-resurrection-of-consumed-spill-handles), and — at the engine level —
physical block sharing plus bit-exact generation parity cache-on vs
cache-off (greedy and seeded sampling, including preemption interleavings)
on the 8-device CPU mesh. Eviction of idle cached blocks must run BEFORE
the scheduler host-swaps any live victim.
"""

import numpy as np
import pytest

import jax

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.v2.ragged.prefix_cache import PrefixCache
from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def served():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def make_engine(cfg, model, params, prefix_caching=False, num_kv_blocks=64,
                max_tokens=16, max_context=128, kv_dtype="fp",
                host_kv_blocks=0):
    return InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": max_tokens,
                          "max_context": max_context,
                          "num_kv_blocks": num_kv_blocks,
                          "kv_dtype": kv_dtype,
                          "host_kv_blocks": host_kv_blocks},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"},
        "prefix_caching": prefix_caching})


# ---------------------------------------------------------------------------
# allocator refcounts
# ---------------------------------------------------------------------------

def test_allocator_refcount_lifecycle_and_double_free():
    a = BlockedAllocator(8)
    b1, b2 = a.allocate(2)
    assert a.counts() == {"free": 6, "live": 2, "cached": 0, "host": 0, "nvme": 0,
                          "total": 8}
    a.ref([b1])
    assert a.refcount(b1) == 2
    a.free([b1])  # shared: one holder left, stays live
    assert a.refcount(b1) == 1
    assert a.counts()["live"] == 2
    a.free([b1])
    assert a.counts() == {"free": 7, "live": 1, "cached": 0, "host": 0, "nvme": 0,
                          "total": 8}
    with pytest.raises(ValueError, match="double free"):
        a.free([b1])
    with pytest.raises(ValueError, match="non-live"):
        a.ref([b1])
    with pytest.raises(ValueError, match="only 7 free"):
        a.allocate(8)
    a.free([b2])
    assert a.counts()["free"] == 8


def test_allocator_deref_returns_zeroed_without_disposing():
    """``deref`` is the disposal-decision primitive: blocks hitting
    refcount 0 are reported but NOT returned to the free list (``free``
    layers the park-or-release choice on top)."""
    a = BlockedAllocator(4)
    blocks = a.allocate(2)
    zeroed = a.deref([blocks[0]])
    assert zeroed == [blocks[0]]
    assert a.refcount(blocks[0]) == 0
    assert a.free_blocks == 2  # limbo: zeroed but not yet released
    with pytest.raises(ValueError, match="double free"):
        a.deref([blocks[0]])
    with pytest.raises(ValueError, match="out of range"):
        a.deref([99])


def test_allocator_revive_and_release_guards():
    a = BlockedAllocator(4)
    b = a.allocate(1)[0]
    with pytest.raises(ValueError, match="non-parked"):
        a.revive(b)  # live, not parked
    free_id = a._free[0]
    with pytest.raises(ValueError, match="non-parked"):
        a.release([free_id])  # free, not parked


# ---------------------------------------------------------------------------
# O(free) stats vs sorted-scan reference
# ---------------------------------------------------------------------------

def _reference_stats(free_ids, total):
    """Sorted-scan free-run structure (the pre-refactor behavior)."""
    ids = sorted(free_ids)
    runs, largest, i = 0, 0, 0
    while i < len(ids):
        j = i
        while j + 1 < len(ids) and ids[j + 1] == ids[j] + 1:
            j += 1
        runs += 1
        largest = max(largest, j - i + 1)
        i = j + 1
    frag = 1.0 - largest / len(ids) if ids else 0.0
    return {"free": len(ids), "total": total, "free_runs": runs,
            "largest_free_run": largest, "fragmentation": frag}


def test_allocator_stats_behavior_identical_to_sorted_reference():
    rng = np.random.default_rng(0)
    total = 32
    a = BlockedAllocator(total)
    held = []
    for _ in range(300):
        if held and (not a.free_blocks or rng.random() < 0.5):
            a.free([held.pop(int(rng.integers(len(held))))])
        else:
            n = int(rng.integers(1, min(4, a.free_blocks) + 1))
            held.extend(a.allocate(n))
        assert a.stats() == _reference_stats(a._free_set, total)
    # the cached result is a copy: mutating it doesn't poison later reads
    s = a.stats()
    s["free"] = -1
    assert a.stats()["free"] == a.free_blocks


# ---------------------------------------------------------------------------
# prefix cache unit behavior
# ---------------------------------------------------------------------------

def test_prefix_cache_strict_prefix_match_and_lifecycle():
    a = BlockedAllocator(16)
    c = PrefixCache(a, block_size=4)
    tokens = np.arange(12, dtype=np.int32)
    blocks = a.allocate(3)
    d0, _ = c.insert(b"", tokens[:4], blocks[0])
    c.insert(d0, tokens[4:8], blocks[1])
    # strict prefix: an 8-token prompt may only match 1 full block — the
    # final token must run a forward to produce first-token logits
    got, _ = c.lookup_chain(tokens[:8])
    assert got == [blocks[0]]
    got, digs = c.lookup_chain(tokens[:9])
    assert got == [blocks[0], blocks[1]]
    # divergent second block breaks the chain after block 0
    other = np.concatenate([tokens[:4], tokens[4:8] + 1, [0]])
    got, _ = c.lookup_chain(other)
    assert got == [blocks[0]]

    # flush-style donation: children free first, cached blocks park
    a.free([blocks[2]])  # uncommitted tail: straight to the free list
    a.free([blocks[1]])
    a.free([blocks[0]])
    assert a.counts() == {"free": 14, "live": 0, "cached": 2, "host": 0, "nvme": 0,
                          "total": 16}
    assert c.evictable_blocks == 2

    # a hit revives parked blocks
    got, digs = c.lookup_chain(tokens[:9])
    c.acquire_chain(got, digs)
    assert a.counts()["live"] == 2 and a.counts()["cached"] == 0
    assert c.hits == 1 and c.tokens_saved == 8

    # park again (children-first), then LRU-evict: the leaf goes first so
    # no reachable ancestor is orphaned
    a.free([blocks[1]])
    a.free([blocks[0]])
    assert c.evict(1) == 1
    got, _ = c.lookup_chain(tokens[:9])
    assert got == [blocks[0]]  # parent chain still matchable

    # allocator-driven eviction under pool pressure: 15 free + 1 parked
    out = a.allocate(16)
    assert len(out) == 16 and c.evictions == 2
    assert a.counts() == {"free": 0, "live": 16, "cached": 0, "host": 0, "nvme": 0,
                          "total": 16}
    with pytest.raises(ValueError, match="only 0 free"):
        a.allocate(1)


def test_prefix_cache_insert_dedup_returns_canonical():
    a = BlockedAllocator(8)
    c = PrefixCache(a, block_size=4)
    toks = np.arange(4, dtype=np.int32)
    b_first = a.allocate(1)[0]
    b_dup = a.allocate(1)[0]
    d, canon = c.insert(b"", toks, b_first)
    assert canon == b_first
    d2, canon2 = c.insert(b"", toks, b_dup)
    assert d2 == d and canon2 == b_first
    assert a.refcount(b_first) == 2  # dedup took a reference for the caller
    a.free([b_dup])  # caller drops its private copy
    assert a.counts() == {"free": 7, "live": 1, "cached": 0, "host": 0, "nvme": 0,
                          "total": 8}


# ---------------------------------------------------------------------------
# randomized property test
# ---------------------------------------------------------------------------

class _StubSpiller:
    """Page-mover stand-in for allocator/cache property tests: records the
    spill/restore traffic and hands back verifiable payloads."""

    def __init__(self):
        self.spill_calls = 0
        self.restore_calls = 0

    def spill_block(self, block):
        self.spill_calls += 1
        return ("pages", block)

    def restore_block(self, payload, block):
        assert payload[0] == "pages"
        self.restore_calls += 1


class _StubNVMeStore:
    """NVMe store stand-in (``runtime/swap_tensor/nvme_kv_store.py``
    surface: write/read/drop) — records live keys so the property test can
    assert the store's census matches the allocator's nvme tier exactly."""

    def __init__(self):
        self._next = 0
        self.payloads = {}
        self.writes = 0

    @property
    def live(self):
        return set(self.payloads)

    def write(self, arrays):
        key = self._next
        self._next += 1
        self.payloads[key] = arrays
        self.writes += 1
        return key

    def read(self, key):
        return self.payloads[key]

    def drop(self, key):
        del self.payloads[key]


def test_random_share_flush_evict_spill_preserve_invariants():
    """Random allocate/share/flush/evict/spill/restore PLUS speculative
    advance/rollback through the PrefixCache over a host-capable allocator,
    checking after every op: device side ``free + live + cached ==
    num_blocks`` (hard), the census ``free + live + cached + host ==
    total``, the swap accounting identity ``spilled == restored + dropped +
    resident``, the free list holds no duplicates and only refcount-0
    blocks, refcounts never negative, draft-tail blocks stay private
    (refcount exactly 1, never cached), rollback never frees a block
    another chain holds, and the cache's evictable/host counts equal the
    allocator's."""
    rng = np.random.default_rng(42)
    total, bs, host_cap, nvme_cap = 24, 4, 6, 4
    a = BlockedAllocator(total, host_capacity=host_cap)
    c = PrefixCache(a, bs)
    sp = _StubSpiller()
    c.bind_spiller(sp)
    store = _StubNVMeStore()
    a.bind_nvme(store, nvme_cap)
    live = {}   # uid -> committed chain blocks (shareable through the cache)
    tails = {}  # uid -> private speculative tail blocks (refcount-1 only)
    streams = []
    next_uid, next_tok = 0, 0
    advances = rollbacks = 0

    def fresh(n):
        nonlocal next_tok
        out = np.arange(next_tok, next_tok + n, dtype=np.int32)
        next_tok += n
        return out

    def check():
        cnt = a.counts()
        assert cnt["free"] + cnt["live"] + cnt["cached"] == total
        assert cnt["free"] + cnt["live"] + cnt["cached"] + cnt["host"] \
            + cnt["nvme"] == cnt["total"] == total + cnt["host"] \
            + cnt["nvme"]
        assert cnt["host"] <= host_cap and cnt["nvme"] <= nvme_cap
        assert min(cnt.values()) >= 0
        hs = a.host_swap_stats()
        # the fifth-state identity: a spilled record is consumed, dropped,
        # or still parked in ONE of the two off-device tiers
        assert hs["spilled"] == hs["restored"] + hs["dropped"] \
            + hs["resident"] + hs["nvme_resident"]
        assert hs["spilled"] == sp.spill_calls
        assert hs["restored"] == sp.restore_calls == c.restores
        # the stub store's live keys ARE the allocator's nvme census (every
        # restore/drop of a demoted record must drop its store key)
        assert hs["nvme_resident"] == len(store.live) == cnt["nvme"]
        assert store.writes == hs["nvme_demotions"]
        free_list = list(a._free)
        assert len(free_list) == len(set(free_list)), "free-list duplicate"
        assert all(a.refcount(b) == 0 for b in free_list)
        assert all(a.refcount(b) >= 0 for b in range(total))
        assert c.evictable_blocks == cnt["cached"]
        # the prefix cache sees one off-device tier; demotion host -> nvme
        # is invisible to it (the spill handle stays valid)
        assert c.host_cached_blocks == cnt["host"] + cnt["nvme"]
        assert a.stats()["free"] == cnt["free"]
        spec_tail = [b for t in tails.values() for b in t]
        assert len(spec_tail) == len(set(spec_tail))
        assert all(a.refcount(b) == 1 for b in spec_tail), \
            "draft-tail blocks are private to their row — never shared"

    for _ in range(400):
        op = rng.random()
        if op < 0.4:
            # new sequence of k full blocks, possibly reusing a prior stream
            k = int(rng.integers(1, 4))
            if streams and rng.random() < 0.6:
                base = streams[int(rng.integers(len(streams)))]
                reuse = min(len(base) // bs, int(rng.integers(0, k + 1))) * bs
                toks = np.concatenate([base[:reuse], fresh(k * bs - reuse)]) \
                    if reuse < k * bs else base[:k * bs].copy()
            else:
                toks = fresh(k * bs)
            streams.append(toks)
            matched, digests = c.lookup_chain(np.append(toks, np.int32(0)))
            # acquire first: host-resident links restore (consuming free
            # blocks) and the chain may truncate when the pool is tight
            blocks = list(c.acquire_chain(matched, digests)) if matched \
                else []
            digests = list(digests[:len(blocks)])
            need = k - len(blocks)
            if a.free_blocks + c.evictable_blocks < need:
                if blocks:
                    a.free(list(reversed(blocks)))
                check()
                continue
            for b in (a.allocate(need) if need else []):
                i = len(blocks)
                parent = digests[-1] if digests else b""
                d, canon = c.insert(parent, toks[i * bs:(i + 1) * bs], b)
                if canon != b:
                    a.free([b])  # dedup: adopt the canonical shared block
                blocks.append(canon)
                digests.append(d)
            live[next_uid] = blocks
            next_uid += 1
        elif op < 0.55 and live:
            # speculative advance: a verify chunk's KV grows the chain with
            # PRIVATE draft blocks — ordinary refcount-1 tenants of the same
            # pool, never inserted into the chain-digest cache (their
            # contents are unverified)
            uid = list(live)[int(rng.integers(len(live)))]
            n = int(rng.integers(1, 3))
            if a.free_blocks + c.evictable_blocks >= n:
                tails.setdefault(uid, []).extend(a.allocate(n))
                advances += 1
        elif op < 0.65 and any(tails.values()):
            # rejected drafts: roll the cursor back over a suffix of the
            # private tail; the committed (possibly shared) chain blocks
            # keep their refcounts untouched
            holders = [u for u, t in tails.items() if t]
            uid = holders[int(rng.integers(len(holders)))]
            t = tails[uid]
            k = int(rng.integers(1, len(t) + 1))
            before = [a.refcount(b) for b in live[uid]]
            victims = t[len(t) - k:]
            del t[len(t) - k:]
            a.free(list(reversed(victims)))
            assert [a.refcount(b) for b in live[uid]] == before, \
                "rollback must never free a block another chain holds"
            rollbacks += 1
        elif op < 0.85 and live:
            uid = list(live)[int(rng.integers(len(live)))]
            tail = tails.pop(uid, [])
            # tail frees first (it extends the chain), then children park
            a.free(list(reversed(live.pop(uid) + tail)))
        else:
            # pressure: parked LRU blocks spill to host while it has room,
            # then evict outright
            c.evict(int(rng.integers(1, 4)))
        check()

    assert sp.spill_calls > 0, "400 steps must exercise the spill tier"
    assert sp.restore_calls > 0, "reused streams must restore host blocks"
    assert a.host_swap_stats()["nvme_demotions"] > 0, \
        "400 steps must push the host tier over capacity into NVMe"
    assert advances > 10 and rollbacks > 10, \
        "400 steps must exercise speculative advance AND rollback"
    for uid in list(live):
        tail = tails.pop(uid, [])
        a.free(list(reversed(live.pop(uid) + tail)))
        check()
    c.evict(c.evictable_blocks)
    cnt = a.counts()
    assert cnt["free"] == total and cnt["live"] == 0 and cnt["cached"] == 0
    assert cnt["host"] + cnt["nvme"] == c.host_cached_blocks
    check()


def test_host_tier_spill_restore_guards_and_no_resurrection():
    """Spill handles are single-shot: restore consumes, a second restore (or
    restore-after-drop) raises — swapped-out refs cannot resurrect. Spill is
    legal only from the parked state, and a full host tier refuses."""
    a = BlockedAllocator(8, host_capacity=1)
    c = PrefixCache(a, block_size=4)
    b1, b2 = a.allocate(2)
    with pytest.raises(ValueError, match="non-parked"):
        a.spill(b1, "pages")  # live, not parked
    d1, _ = c.insert(b"", np.arange(4, dtype=np.int32), b1)
    c.insert(d1, np.arange(4, 8, dtype=np.int32), b2)
    a.free([b2])  # park both (children first)
    a.free([b1])
    ref = a.spill(b1, "pages-b1")
    assert a.counts()["host"] == 1 and a.counts()["free"] == 7
    with pytest.raises(ValueError, match="host tier full"):
        a.spill(b2, "pages-b2")  # parked, but capacity is 1
    assert a.restore(ref) == "pages-b1"
    with pytest.raises(ValueError, match="non-host record"):
        a.restore(ref)  # consumed: no resurrection
    with pytest.raises(ValueError, match="non-host record"):
        a.drop_host(ref)
    hs = a.host_swap_stats()
    assert hs == {"spilled": 1, "restored": 1, "dropped": 0, "resident": 0,
                  "capacity": 1, "nvme_resident": 0, "nvme_capacity": 0,
                  "nvme_demotions": 0}


def test_prefix_cache_spills_lru_first_and_restores_on_match():
    """Eviction pressure demotes the LEAST recently parked block to host
    first; a later chain match transparently restores it into a fresh
    device block with the contents the spiller preserved."""
    a = BlockedAllocator(8, host_capacity=4)
    c = PrefixCache(a, block_size=4)
    sp = _StubSpiller()
    c.bind_spiller(sp)
    toks = np.arange(8, dtype=np.int32)
    b0, b1 = a.allocate(2)
    d0, _ = c.insert(b"", toks[:4], b0)
    c.insert(d0, toks[4:8], b1)
    a.free([b1])
    a.free([b0])  # park order: b1 (LRU) then b0
    assert c.evict(1) == 1
    assert sp.spill_calls == 1 and a.host_blocks == 1
    # the leaf b1 parked FIRST, so it spilled first (children-first flush
    # order makes leaves LRU) — its digest is still matchable
    got, digs = c.lookup_chain(toks.tolist() + [0])
    assert got[0] == b0 and got[1] is None, \
        "host-resident link must appear as None in a pure lookup"
    resolved = c.acquire_chain(got, digs)
    assert len(resolved) == 2 and resolved[1] is not None
    assert sp.restore_calls == 1 and a.host_blocks == 0
    assert c.restores == 1
    cnt = a.counts()
    assert cnt["live"] == 2 and cnt["host"] == 0


def test_acquire_chain_pins_links_before_reentrant_restore_eviction():
    """``_restore`` allocates, and allocation pressure re-enters ``evict``:
    with zero free blocks the eviction victim must be an UNRELATED parked
    block, never a not-yet-acquired device link of the chain being acquired
    — the stale-id path would ref a freed (or worse, reallocated) block and
    silently attach another prompt's KV pages to the sequence."""
    a = BlockedAllocator(3, host_capacity=4)
    c = PrefixCache(a, block_size=4)
    sp = _StubSpiller()
    c.bind_spiller(sp)
    toks = np.arange(8, dtype=np.int32)
    b0, b1, u = a.allocate(3)
    d0, _ = c.insert(b"", toks[:4], b0)
    d1, _ = c.insert(d0, toks[4:8], b1)
    c.insert(b"", np.arange(100, 104, dtype=np.int32), u)  # unrelated chain
    a.free([b0])  # park order: b0 is LRU-first, then b1, then u
    a.free([b1])
    a.free([u])
    assert c.evict(1) == 1 and a.host_blocks == 1  # d0 -> host
    x = a.allocate(1)[0]  # soak the freed id: zero free blocks remain
    got, digs = c.lookup_chain(np.append(toks, np.int32(0)))
    assert got[0] is None and got[1] == b1
    resolved = c.acquire_chain(got, digs)
    # the restore's allocate had to evict something — b1 (next in LRU
    # order, but pinned by the in-flight acquisition) was immune, so the
    # unrelated u spilled instead and the chain resolved intact
    assert len(resolved) == 2 and resolved[1] == b1
    assert resolved[0] not in (b1, x)
    assert c._map[d0] == resolved[0] and c._map[d1] == b1
    assert sp.spill_calls == 2 and sp.restore_calls == 1
    assert a.refcount(b1) == 1 and a.refcount(resolved[0]) == 1
    assert a.refcount(x) == 1
    assert c.hits == 1 and c.misses == 0
    assert a.counts() == {"free": 0, "live": 3, "cached": 0, "host": 1, "nvme": 0,
                          "total": 4}


def test_acquire_chain_failed_restore_unpins_and_counts_miss():
    """When no link resolves (the chain's first link is host-resident and
    the pool can't host the restore even after eviction) the acquisition is
    a MISS — ``hit_rate`` must not credit it — and device links pinned
    ahead of the failed restore re-park, still matchable."""
    a = BlockedAllocator(2, host_capacity=4)
    c = PrefixCache(a, block_size=4)
    sp = _StubSpiller()
    c.bind_spiller(sp)
    toks = np.arange(8, dtype=np.int32)
    b0, b1 = a.allocate(2)
    d0, _ = c.insert(b"", toks[:4], b0)
    c.insert(d0, toks[4:8], b1)
    a.free([b0])  # park parent first: d0 spills before d1
    a.free([b1])
    assert c.evict(1) == 1 and a.host_blocks == 1  # d0 -> host
    x = a.allocate(1)[0]  # zero free: a restore cannot find device room
    got, digs = c.lookup_chain(np.append(toks, np.int32(0)))
    assert got == [None, b1]
    assert c.acquire_chain(got, digs) == []
    assert c.hits == 0 and c.misses == 1 and c.hit_rate == 0.0
    # d0's host record survived the failed restore (no half-consumed
    # handle), b1 re-parked, and the unrelated live block was untouched
    assert c.host_cached_blocks == 1 and sp.restore_calls == 0
    assert c.evictable_blocks == 1 and a.refcount(x) == 1
    assert a.counts() == {"free": 0, "live": 1, "cached": 1, "host": 1, "nvme": 0,
                          "total": 3}


def test_full_host_tier_falls_back_to_plain_eviction():
    """When the host tier has no room the cache must evict outright (never
    silently drop a spill) so the accounting identity stays exact."""
    a = BlockedAllocator(8, host_capacity=1)
    c = PrefixCache(a, block_size=4)
    sp = _StubSpiller()
    c.bind_spiller(sp)
    parent = b""
    blocks = a.allocate(3)
    for i, b in enumerate(blocks):
        toks = np.arange(i * 4, (i + 1) * 4, dtype=np.int32)
        parent, _ = c.insert(parent, toks, b)
    a.free(list(reversed(blocks)))
    assert c.evict(3) == 3
    assert sp.spill_calls == 1          # host capacity 1
    assert c.evictions == 2             # remainder evicted, not dropped
    hs = a.host_swap_stats()
    assert hs["spilled"] == 1 and hs["dropped"] == 0
    assert a.counts()["free"] == 8


# ---------------------------------------------------------------------------
# engine-level sharing
# ---------------------------------------------------------------------------

def test_engine_shares_physical_blocks_across_requests(served):
    cfg, model, params = served
    engine = make_engine(cfg, model, params, prefix_caching=True)
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(10)
    prefix = rng.integers(0, cfg.vocab_size, 24).astype(np.int32)
    sched.submit(0, prefix, max_new_tokens=4)
    sched.run_to_completion()
    cache = engine._state.prefix_cache
    assert cache.cached_blocks >= 2
    assert engine._state.kv_cache.allocator.cached_blocks >= 2

    prompt2 = np.concatenate(
        [prefix[:16], rng.integers(0, cfg.vocab_size, 8).astype(np.int32)])
    expect, _ = cache.lookup_chain(prompt2)
    assert len(expect) == 2
    sched.submit(1, prompt2, max_new_tokens=4)
    sched.step()
    seq = engine._state.get_sequence(1)
    assert list(seq.kv_blocks[:2]) == list(expect), \
        "matched blocks must be the SAME physical ids, not copies"
    assert sched.prefill_tokens_saved == 16
    assert cache.hits == 1
    sched.run_to_completion()


def _run_mode(cfg, model, params, waves, caching, num_kv_blocks=64,
              budget=16):
    """Drive the same staggered workload with prefix caching on or off;
    waves of submits interleave with scheduler steps so later requests
    arrive mid-generation of earlier ones."""
    engine = make_engine(cfg, model, params, prefix_caching=caching,
                         num_kv_blocks=num_kv_blocks, max_tokens=budget)
    sched = SplitFuseScheduler(engine, token_budget=budget)
    for wave in waves:
        for uid, prompt, kw in wave:
            sched.submit(uid, prompt, **kw)
        for _ in range(2):
            if sched.has_work:
                sched.step()
    got = sched.run_to_completion()
    return {u: got[u].tolist() for u in got}, engine


def _shared_prefix_waves(cfg, seed, kw_fn):
    """Three waves over two prefix pools: wave 2/3 reuse wave-1 prefixes."""
    rng = np.random.default_rng(seed)
    pool_a = rng.integers(0, cfg.vocab_size, 24).astype(np.int32)
    pool_b = rng.integers(0, cfg.vocab_size, 16).astype(np.int32)

    def mk(pool, n_suffix):
        return np.concatenate(
            [pool, rng.integers(0, cfg.vocab_size, n_suffix).astype(np.int32)])

    return [
        [(0, mk(pool_a, 5), kw_fn(0)), (1, mk(pool_b, 3), kw_fn(1))],
        [(2, mk(pool_a, 9), kw_fn(2))],
        [(3, mk(pool_b, 7), kw_fn(3)), (4, mk(pool_a, 2), kw_fn(4))],
    ]


def test_generation_parity_cache_on_off_greedy(served, eight_devices):
    """Bit-exact token parity, caching on vs off, greedy decode over
    staggered shared-prefix waves on the 8-device CPU mesh."""
    cfg, model, params = served
    waves = _shared_prefix_waves(cfg, 20, lambda u: {"max_new_tokens": 4})
    off, _ = _run_mode(cfg, model, params, waves, caching=False)
    on, engine = _run_mode(cfg, model, params, waves, caching=True)
    assert on == off
    cache = engine._state.prefix_cache
    assert cache.hits >= 2, "workload must actually exercise sharing"
    assert cache.tokens_saved > 0


def test_generation_parity_cache_on_off_sampled(served, eight_devices):
    """Same parity under seeded per-request sampling: the device sampler
    keys on (seed, position), so skipped prefill must not shift streams."""
    cfg, model, params = served

    def kw(uid):
        return {"max_new_tokens": 4, "temperature": 0.7, "top_k": 8,
                "seed": 1000 + uid * 13}

    waves = _shared_prefix_waves(cfg, 21, kw)
    off, _ = _run_mode(cfg, model, params, waves, caching=False)
    on, engine = _run_mode(cfg, model, params, waves, caching=True)
    assert on == off
    assert engine._state.prefix_cache.hits >= 2


def test_generation_parity_with_preemption_interleaving(served,
                                                        eight_devices):
    """A 12-block pool over two 44-token shared-prefix requests forces the
    cache-off leg through host-swap preemption; outputs must still match
    the cache-on leg token for token."""
    cfg, model, params = served
    rng = np.random.default_rng(22)
    prefix = rng.integers(0, cfg.vocab_size, 16).astype(np.int32)

    def mk(n):
        return np.concatenate(
            [prefix, rng.integers(0, cfg.vocab_size, n).astype(np.int32)])

    waves = [[(0, mk(28), {"max_new_tokens": 6})],
             [(1, mk(28), {"max_new_tokens": 6})]]
    off, eng_off = _run_mode(cfg, model, params, waves, caching=False,
                             num_kv_blocks=12)
    on, eng_on = _run_mode(cfg, model, params, waves, caching=True,
                           num_kv_blocks=12)
    assert on == off
    assert all(len(v) == 6 for v in on.values())
    # the tight pool must have stressed SOMETHING: the off leg swaps or
    # evicts nothing (cache off), the on leg reuses the shared prefix
    assert eng_on._state.prefix_cache.hits >= 1


def test_cached_block_eviction_precedes_preemption(served):
    """Pool pressure with idle cached blocks available: the allocator must
    drop parked refcount-0 blocks (free) instead of host-swapping a live
    victim (expensive)."""
    cfg, model, params = served
    engine = make_engine(cfg, model, params, prefix_caching=True,
                         num_kv_blocks=12)
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(23)
    # populate the cache: 40-token prompt -> 5 full blocks parked at flush
    sched.submit(0, rng.integers(0, cfg.vocab_size, 40).astype(np.int32),
                 max_new_tokens=2)
    sched.run_to_completion()
    cache = engine._state.prefix_cache
    assert cache.evictable_blocks >= 5
    # an unrelated large request needs more than the raw free list
    sched.submit(1, rng.integers(0, cfg.vocab_size, 60).astype(np.int32),
                 max_new_tokens=2)
    sched.run_to_completion()
    assert cache.evictions >= 1, "pool pressure must evict cached blocks"
    assert engine._state.swap_outs == 0, \
        "eviction of idle cached blocks must run before any host swap"
