"""LoRA adapters + hybrid-engine fuse/unfuse (reference
``runtime/hybrid_engine.py:126-173`` LoRA flow)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime.lora import (fuse_lora, init_lora, merged_view,
                                        trainable_filter, unfuse_lora)


@pytest.fixture(scope="module")
def llama_setup():
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(scan_layers=False, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.arange(8, dtype=np.int32)[None, :]
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params, ids


def test_init_targets_projections(llama_setup):
    _, _, params, _ = llama_setup
    lora = init_lora(params, rank=4)
    assert lora["adapters"], "no adapted leaves found"
    assert all(k.endswith("kernel") for k in lora["adapters"])
    assert any("q_proj" in k for k in lora["adapters"])
    for ab in lora["adapters"].values():
        assert ab["a"].shape[1] == 4 and ab["b"].shape[0] == 4


def test_fresh_adapters_are_identity(llama_setup):
    _, model, params, ids = llama_setup
    lora = init_lora(params, rank=4)  # b=0 => merged == base
    merged = merged_view(params, lora)
    out_a = model.apply({"params": params}, {"input_ids": ids})
    out_b = model.apply({"params": merged}, {"input_ids": ids})
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b))


def _randomize_b(lora, seed=1):
    rng = jax.random.PRNGKey(seed)
    ad = {}
    for k, ab in lora["adapters"].items():
        rng, sub = jax.random.split(rng)
        ad[k] = {"a": ab["a"],
                 "b": 0.3 * jax.random.normal(sub, ab["b"].shape, ab["b"].dtype)}
    return {"adapters": ad, "scaling": lora["scaling"]}


def test_fuse_unfuse_roundtrip(llama_setup):
    _, _, params, _ = llama_setup
    lora = _randomize_b(init_lora(params, rank=4))
    fused = fuse_lora(params, lora)
    # fused differs on adapted leaves
    tf = trainable_filter(lora)
    flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_f = jax.tree_util.tree_flatten_with_path(fused)[0]
    changed = 0
    for (pa, la), (_, lb) in zip(flat_p, flat_f):
        key = "/".join(str(getattr(p, "key", "")) for p in pa)
        if key in tf:
            assert float(jnp.max(jnp.abs(la - lb))) > 0
            changed += 1
        else:
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
    assert changed == len(tf)
    back = unfuse_lora(fused, lora)
    for (_, la), (_, lb) in zip(flat_p,
                                jax.tree_util.tree_flatten_with_path(back)[0]):
        np.testing.assert_allclose(np.asarray(la, np.float32),
                                   np.asarray(lb, np.float32), atol=2e-6)


def test_hybrid_engine_lora_generation(llama_setup):
    cfg, model, params, ids = llama_setup
    from deepspeed_tpu.parallel import groups
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "hybrid_engine": {"enabled": True, "max_out_tokens": 4}})
    base = np.asarray(engine.generate(jnp.asarray(ids), max_new_tokens=4))
    lora = _randomize_b(init_lora(engine.state.params, rank=4), seed=9)
    engine.configure_lora(lora)
    adapted = np.asarray(engine.generate(jnp.asarray(ids), max_new_tokens=4))
    assert base.shape == adapted.shape
    fused_before = np.asarray(
        jax.tree_util.tree_leaves(engine.state.params)[0])
    engine.fuse_lora_weight()
    engine.unfuse_lora_weight()
    fused_after = np.asarray(
        jax.tree_util.tree_leaves(engine.state.params)[0])
    np.testing.assert_allclose(fused_before, fused_after, atol=2e-6)


def test_no_double_merge_after_fuse(llama_setup):
    """generate() after fuse_lora_weight must not apply the delta twice
    (the fused flag gates the in-trace merge)."""
    cfg, model, params, ids = llama_setup
    from deepspeed_tpu.runtime.lora import merged_view
    from deepspeed_tpu.parallel import groups
    groups.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "hybrid_engine": {"enabled": True, "max_out_tokens": 4}})
    lora = _randomize_b(init_lora(engine.state.params, rank=4), seed=11)
    engine.configure_lora(lora)
    want = np.asarray(jax.tree_util.tree_leaves(
        merged_view(engine.state.params, lora))[0])
    engine.fuse_lora_weight()
    got = np.asarray(jax.tree_util.tree_leaves(engine._inference_params())[0])
    np.testing.assert_allclose(got, want, atol=2e-6)
    with pytest.raises(AssertionError):
        engine.fuse_lora_weight()  # double fuse is refused
    engine.unfuse_lora_weight()
