"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the TPU analog of the reference's
in-process multi-rank harness, ``tests/unit/common.py:373`` DistributedTest with
world_size 1/2/4): ``xla_force_host_platform_device_count=8`` gives eight XLA
CPU devices so every sharding/collective path executes real multi-device code.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# tests target the modern jax.shard_map API; on older jax the compat module
# installs a translating shim (check_vma -> check_rep, axis_names -> auto)
from deepspeed_tpu.utils import jax_compat  # noqa: E402,F401

import pytest  # noqa: E402

_SLOW_LIST = os.path.join(os.path.dirname(__file__), "slow_tests.txt")


def pytest_collection_modifyitems(config, items):
    """Apply the ``slow`` marker from tests/slow_tests.txt (measured nodeids,
    regenerated from ``--durations`` output). The fast lane
    ``pytest -m "not slow"`` is what CI and hosts with the TPU attached run;
    see README "Test lanes"."""
    try:
        with open(_SLOW_LIST) as f:
            slow = {ln.strip() for ln in f if ln.strip() and not ln.startswith("#")}
    except FileNotFoundError:
        return
    # one slow parametrization marks every sibling (same underlying cost)
    slow_prefixes = {s.split("[")[0] for s in slow}
    for item in items:
        if item.nodeid in slow or item.nodeid.split("[")[0] in slow_prefixes:
            item.add_marker(pytest.mark.slow)


def pytest_runtest_setup(item):
    """``onchip``-marked tests queue on the shared chip lease before touching
    the accelerator, so a concurrent bench and pytest serialize instead of
    wedging the TPU. Under the CPU pin above this is a no-op (process_lease
    returns None); the lease is process-wide and released at exit."""
    if item.get_closest_marker("onchip") is not None:
        from deepspeed_tpu.utils import chip_lease
        chip_lease.process_lease(name="pytest")


@pytest.fixture(autouse=True)
def _reset_groups():
    """Each test gets a fresh global topology registry."""
    from deepspeed_tpu.parallel import groups
    groups.reset()
    yield
    groups.reset()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
