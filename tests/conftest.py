"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the TPU analog of the reference's
in-process multi-rank harness, ``tests/unit/common.py:373`` DistributedTest with
world_size 1/2/4): ``xla_force_host_platform_device_count=8`` gives eight XLA
CPU devices so every sharding/collective path executes real multi-device code.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_groups():
    """Each test gets a fresh global topology registry."""
    from deepspeed_tpu.parallel import groups
    groups.reset()
    yield
    groups.reset()


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
