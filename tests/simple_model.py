"""Test model fixtures (mirrors reference ``tests/unit/simple_model.py``)."""

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn


class SimpleModel(nn.Module):
    """2-layer MLP regression; returns MSE loss given batch dict (the reference
    SimpleModel equivalent)."""
    hidden_dim: int = 16

    @nn.compact
    def __call__(self, batch, deterministic=True):
        x, y = batch["x"], batch["y"]
        h = nn.Dense(self.hidden_dim)(x)
        h = nn.relu(h)
        out = nn.Dense(y.shape[-1])(h)
        return jnp.mean((out - y) ** 2)


def random_dataset(n=64, dim=8, out_dim=4, seed=0):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(dim, out_dim)).astype(np.float32)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x @ w + 0.01 * rng.normal(size=(n, out_dim))).astype(np.float32)
    return {"x": x, "y": y}


def random_batches(n_batches, batch_size, dim=8, out_dim=4, seed=0):
    data = random_dataset(n_batches * batch_size, dim, out_dim, seed)
    return [{k: v[i * batch_size:(i + 1) * batch_size] for k, v in data.items()}
            for i in range(n_batches)]


def tiny_gpt2_batches(n_batches, batch_size, seq_len=16, vocab=128, seed=0):
    """Learnable sequences: consecutive tokens mod vocab, so next-token
    prediction has near-zero irreducible loss."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        start = rng.integers(0, vocab, size=(batch_size, 1))
        ids = ((start + np.arange(seq_len)[None, :]) % vocab).astype(np.int32)
        out.append({"input_ids": ids, "labels": ids})
    return out
