"""Fleet elasticity: replica loss recovery, drains, autoscaling, shedding.

The chaos-drill invariants from docs/RESILIENCE.md "Serving elasticity",
pinned as fast CPU tests: a decode replica killed mid-stream loses no
request and no token (survivors AND re-admitted streams stay bit-exact vs
the monolithic run), transport drops are retried and exhausted retries
fall back to re-prefill, the router retires EVERY terminal outcome from
its backlog model (accounting identity), planned scale-downs drain + warm-
pool revive at a NEW lifecycle key, the autoscaler's up/down/floor policy
holds on fakes, the lifecycle state machine survives 300 randomized ops
without losing or double-admitting a request, SLO shed precedence sends
batch/untagged arrivals away while interactive burns, and the whole
elasticity layer does zero telemetry-core work when telemetry is off.
"""

import tracemalloc

import numpy as np
import pytest

import jax

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.v2.fleet import (
    DEAD, DRAINING, LIVE, FailureDetector, FleetAutoscaler,
    PrefillDecodeFleet, ReplicaLifecycle, RequestAdmitted, RequestRejected,
    SLORouter)
from deepspeed_tpu.inference.v2.fleet import lifecycle as lc_mod
from deepspeed_tpu.inference.v2.replica_group import build_replica
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.resilience import faults
from deepspeed_tpu.telemetry import core as telemetry_core

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="elasticity tests need >= 4 devices (2 prefill + 2 decode)")


@pytest.fixture(autouse=True)
def _clean_state():
    faults.reset()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)
    yield
    faults.reset()
    telemetry.close()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)


@pytest.fixture(scope="module")
def served():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


ENG = {"state_manager": {"max_ragged_sequence_count": 9,
                         "max_ragged_batch_size": 64,
                         "max_context": 96,
                         "num_kv_blocks": 96},
       "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}}


def make_fleet(model, params, decode_replicas=2, **kw):
    kw.setdefault("engine_config", ENG)
    kw.setdefault("token_budget", 48)
    return PrefillDecodeFleet(model, params, prefill_replicas=2,
                              decode_replicas=decode_replicas, **kw)


def single_reference(model, params, requests):
    """Monolithic single-replica run of the same requests:
    {uid: (prompt, kwargs)} -> {uid: tokens}."""
    mesh, sched = build_replica(model, params, [jax.devices()[0]],
                                engine_config=ENG, token_budget=48)
    with mesh:
        for uid, (prompt, kwargs) in requests.items():
            sched.submit(uid, prompt, **kwargs)
        return {u: np.asarray(v, np.int32)
                for u, v in sched.run_to_completion().items()}


def _requests(cfg, n=4, seed=5, max_new=6, sampling=False):
    rng = np.random.default_rng(seed)
    out = {}
    for uid in range(n):
        plen = int(rng.integers(5, 60))
        kwargs = {"max_new_tokens": max_new}
        if sampling:
            kwargs.update(temperature=0.9, top_k=5,
                          seed=int(rng.integers(0, 2 ** 30)))
        out[uid] = (rng.integers(0, cfg.vocab_size, plen).astype(np.int32),
                    kwargs)
    return out


def _assert_bit_exact(got, want):
    assert set(got) >= set(want)
    for uid in want:
        np.testing.assert_array_equal(np.asarray(got[uid], np.int32),
                                      want[uid], err_msg=f"uid {uid}")


# ---------------------------------------------------------------------------
# replica loss recovery: bit-exact re-admission, zero page leaks
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("sampling", [False, True],
                         ids=["greedy", "seeded-sampling"])
def test_replica_loss_recovery_bit_exact(served, sampling):
    """Kill decode0 mid-stream (deterministic ``n3`` targeting: the
    ``replica.lost`` point is polled prefill0, prefill1, decode0, decode1
    each round regardless of queue state, so the 3rd hit in the step-3
    window is decode0). Every re-admitted stream resumes at the same
    (seed, position) and the merged output matches the monolithic run
    token for token; the dead pool is census-exempt and nothing leaks."""
    cfg, model, params = served
    requests = _requests(cfg, n=4, seed=11 if sampling else 5,
                         sampling=sampling)
    want = single_reference(model, params, requests)

    fleet = make_fleet(model, params)
    faults.configure("replica.lost:n3@step3")
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    got = fleet.run_to_completion()

    assert fleet.replica_losses == 1
    assert fleet.lifecycle.state(("decode", 0)) == DEAD
    assert fleet.readmitted > 0
    _assert_bit_exact(got, want)
    assert fleet.page_census()["leaked_pages"] == 0
    # the router-facing terminal drain carries nothing here: every lost
    # request re-admitted (never terminally lost)
    assert all(outcome != "lost" for _, outcome in fleet.drain_terminal())


def test_transport_retry_absorbs_transient_drop(served):
    """One injected ``transport.drop`` is retried inside the transport
    (typed retry accounting, no failed handoff) and the run stays
    bit-exact — the retried attempt re-exports because the fault fires
    BEFORE the source pages are released."""
    cfg, model, params = served
    requests = _requests(cfg, n=3, seed=23)
    want = single_reference(model, params, requests)

    fleet = make_fleet(model, params, decode_replicas=1)
    faults.configure("transport.drop:n1")
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    got = fleet.run_to_completion()

    assert fleet.transport.retry_trips >= 1
    assert fleet.transport.failed_handoffs == 0
    assert fleet.handoff_fallbacks == 0
    _assert_bit_exact(got, want)
    assert fleet.page_census()["leaked_pages"] == 0


def test_exhausted_transport_retries_fall_back_to_reprefill(served):
    """``transport.drop:always`` exhausts every retry: the HandoffError
    never escapes ``fleet.step()`` — each handed-off request re-prefills
    on the decode side (prefill compute paid twice, output unchanged) and
    the stranded source pages are flushed, not leaked."""
    cfg, model, params = served
    requests = _requests(cfg, n=3, seed=29)
    want = single_reference(model, params, requests)

    fleet = make_fleet(model, params, decode_replicas=1)
    faults.configure("transport.drop:always")
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    got = fleet.run_to_completion()

    assert fleet.transport.failed_handoffs == len(requests)
    assert fleet.handoff_fallbacks == len(requests)
    assert fleet.readmitted == len(requests)
    assert fleet.transport.pages_bound == 0  # no ship ever completed
    _assert_bit_exact(got, want)
    assert fleet.page_census()["leaked_pages"] == 0


# ---------------------------------------------------------------------------
# router backlog accounting: every terminal outcome retires
# ---------------------------------------------------------------------------

def test_router_accounting_identity_across_terminal_outcomes(served):
    """Finish, cancel and replica loss all retire from the router's
    backlog model: after the drain the accounting identity holds with
    zero in-flight entries and zero phantom backlog tokens."""
    cfg, model, params = served
    fleet = make_fleet(model, params)
    router = SLORouter(fleet, slo_ttft_s=60.0, prefix_affinity=False)
    faults.configure("replica.lost:n3@step4")
    requests = _requests(cfg, n=5, seed=31)
    for uid, (prompt, kwargs) in requests.items():
        assert isinstance(router.submit(uid, prompt, **kwargs),
                          RequestAdmitted)
    router.step()
    assert fleet.cancel(0)  # mid-flight cancel is a terminal outcome too
    out = router.run_to_completion()

    assert fleet.replica_losses == 1
    # survivors all complete; the cancelled uid never grew past its partial
    assert {1, 2, 3, 4} <= set(out)
    assert all(len(out[u]) == 6 for u in (1, 2, 3, 4))
    assert len(out.get(0, ())) < 6
    assert router.terminal_retired >= 1  # at least the cancel
    rep = router.report()
    acc = rep["accounting"]
    assert acc["identity_holds"] is True
    assert acc["in_flight"] == 0
    assert acc["backlog_total"] == 0
    assert rep["backlog_tokens"] == [0] * len(fleet.prefill)


# ---------------------------------------------------------------------------
# planned scale-down: drain, migrate, warm-pool revival at a NEW key
# ---------------------------------------------------------------------------

def test_scale_down_migrates_and_warm_pool_revives_at_new_key(served):
    """Draining a decode replica migrates its in-flight streams (cancel +
    bit-exact re-admission — the recovery path, reused), retires the
    engine to the warm pool, and the next scale-up revives it at a NEW
    lifecycle key: dead keys never flip back to live."""
    cfg, model, params = served
    requests = _requests(cfg, n=4, seed=37, max_new=8)
    want = single_reference(model, params, requests)

    fleet = make_fleet(model, params)
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    # step until some request lives on a decode replica
    for _ in range(50):
        fleet.step()
        busy = [j for j in fleet.live_decode_indices()
                if fleet.decode_active(j) > 0]
        if busy:
            break
    assert busy, "no decode replica ever took work"
    j = busy[0]
    fleet.scale_down_decode(j)

    assert fleet.lifecycle.state(("decode", j)) == DEAD  # idle post-migrate
    assert fleet.readmitted > 0  # migration reused the recovery path
    assert len(fleet._warm_decode) == 1
    k = fleet.scale_up_decode()
    assert k == len(fleet.decode) - 1 and k != j
    assert len(fleet._warm_decode) == 0  # revived compile-free
    assert fleet.lifecycle.is_live(("decode", k))
    assert not fleet.lifecycle.is_live(("decode", j))  # tombstone stays

    got = fleet.run_to_completion()
    _assert_bit_exact(got, want)
    assert fleet.page_census()["leaked_pages"] == 0


# ---------------------------------------------------------------------------
# autoscaler policy (pure host: fakes, no jax)
# ---------------------------------------------------------------------------

class _FakeFleet:
    def __init__(self, decode=1):
        self._next = decode
        self._live = list(range(decode))
        self.active = {j: 0 for j in self._live}
        self.occupancy = {j: 0.0 for j in self._live}

    def live_decode_indices(self):
        return list(self._live)

    def live_prefill_indices(self):
        return [0]

    def decode_active(self, j):
        return self.active[j]

    def decode_occupancy(self, j):
        return self.occupancy[j]

    def scale_up_decode(self):
        j = self._next
        self._next += 1
        self._live.append(j)
        self.active[j] = 0
        self.occupancy[j] = 0.0
        return j

    def scale_down_decode(self, j):
        self._live.remove(j)

    def lose(self, j):
        self._live.remove(j)


class _FakeRouter:
    queue_depth = 0


def test_autoscaler_up_down_floor_and_cooldown():
    fleet = _FakeFleet(decode=1)
    router = _FakeRouter()
    scaler = FleetAutoscaler(fleet, router, min_decode=1, max_decode=3,
                             up_queue_depth=2, up_occupancy=0.85,
                             down_idle_rounds=3, cooldown_rounds=4)
    # quiet fleet at the floor: no action ever
    assert all(scaler.observe() is None for _ in range(6))
    # queue pressure scales up once, then the cooldown gates the repeat
    router.queue_depth = 5
    assert scaler.observe() == ("up", 1)
    assert all(scaler.observe() is None for _ in range(4))  # cooling
    # still saturated after the cooldown: a second replica comes up
    assert scaler.observe() == ("up", 2)
    # at max_decode the scaler holds even under pressure
    for _ in range(5):
        scaler.observe()
    assert len(fleet.live_decode_indices()) == 3
    # pressure gone: the newest idle replica drains after the idle window
    router.queue_depth = 0
    act = [scaler.observe() for _ in range(12)]
    assert ("down", 2) in act
    assert scaler.scale_ups == 2 and scaler.scale_downs >= 1


def test_autoscaler_occupancy_trigger_and_floor_bypasses_cooldown():
    fleet = _FakeFleet(decode=2)
    router = _FakeRouter()
    scaler = FleetAutoscaler(fleet, router, min_decode=2, max_decode=4,
                             up_occupancy=0.85, cooldown_rounds=10)
    # KV saturation alone (no queue) triggers the scale-up
    fleet.occupancy[1] = 0.9
    assert scaler.observe() == ("up", 2)
    assert scaler.observe() is None  # cooldown armed
    # replica loss drops the fleet below the floor: replacement is
    # immediate, cooldown or not — recovery outranks churn damping
    fleet.lose(0)
    fleet.lose(2)
    assert scaler.observe() == ("up", 3)
    assert len(fleet.live_decode_indices()) == 2
    assert scaler.scale_ups == 2


def test_autoscaler_rejects_bad_floor():
    with pytest.raises(ValueError, match="min_decode"):
        FleetAutoscaler(_FakeFleet(), _FakeRouter(), min_decode=0)


# ---------------------------------------------------------------------------
# lifecycle state machine: 300 randomized ops, no request lost
# ---------------------------------------------------------------------------

def test_lifecycle_property_300_random_ops():
    """Randomized live -> draining -> dead churn with an abstract request
    ledger riding on top (the fleet's re-admission contract in miniature):
    after every op, each submitted request is in exactly ONE of in-flight /
    finished / terminally-lost, every in-flight owner still steps, illegal
    transitions raise without corrupting state, and dead keys stay dead."""
    rng = np.random.default_rng(0)
    lcm = ReplicaLifecycle()
    keys = []
    in_flight = {}   # uid -> owner key
    finished, lost = set(), set()
    next_key = next_uid = 0

    def pick(state_pred):
        cand = [k for k in keys if state_pred(lcm.state(k))]
        return cand[int(rng.integers(len(cand)))] if cand else None

    for _ in range(300):
        op = rng.choice(["add", "admit", "admit", "finish", "finish",
                         "drain", "kill", "illegal"])
        if op == "add" or not keys:
            lcm.add(next_key)
            keys.append(next_key)
            with pytest.raises(ValueError, match="already registered"):
                lcm.add(next_key)  # keys are single-use
            next_key += 1
        elif op == "admit":
            k = pick(lambda s: s == LIVE)
            if k is not None:
                assert next_uid not in in_flight  # never double-admitted
                in_flight[next_uid] = k
                next_uid += 1
        elif op == "finish":
            live_uids = [u for u, k in in_flight.items()
                         if lcm.is_stepping(k)]
            if live_uids:
                u = live_uids[int(rng.integers(len(live_uids)))]
                finished.add(u)
                del in_flight[u]
        elif op == "drain":
            k = pick(lambda s: s == LIVE)
            if k is not None:
                lcm.mark_draining(k)  # keeps stepping its in-flight work
        elif op == "kill":
            k = pick(lambda s: s in (LIVE, DRAINING))
            if k is not None:
                lcm.mark_dead(k)
                survivors = [x for x in keys if lcm.is_live(x)]
                for u in [u for u, o in in_flight.items() if o == k]:
                    if survivors:  # re-admit, exactly once, elsewhere
                        in_flight[u] = survivors[
                            int(rng.integers(len(survivors)))]
                    else:          # total outage: terminal loss, accounted
                        lost.add(u)
                        del in_flight[u]
        elif op == "illegal":
            k = pick(lambda s: s == DEAD)
            if k is not None:
                for bad in (lcm.mark_draining, lcm.mark_dead):
                    with pytest.raises(ValueError, match="illegal"):
                        bad(k)
                assert lcm.state(k) == DEAD  # raise left state untouched
            with pytest.raises(KeyError):
                lcm.mark_dead(("never", "registered"))

        # -- invariants, every op --
        assert len(in_flight) + len(finished) + len(lost) == next_uid
        assert finished.isdisjoint(lost)
        assert all(lcm.is_stepping(k) for k in in_flight.values())
        counts = lcm.counts()
        assert sum(counts.values()) == len(keys)
        assert all(not lcm.is_live(k) for k in keys
                   if lcm.state(k) == DEAD)

    assert next_uid > 30 and len(keys) > 10  # the run actually churned
    assert not lost or any(lcm.state(k) != LIVE for k in keys)


# ---------------------------------------------------------------------------
# SLO shed precedence: batch absorbs, interactive keeps the capacity
# ---------------------------------------------------------------------------

SLO_CLASSES = {
    "interactive": {"ttft_target_s": 0.5, "tpot_target_s": 0.25,
                    "attainment_target": 0.9},
    "batch": {"ttft_target_s": 30.0, "tpot_target_s": 2.0,
              "attainment_target": 0.5},
}


def test_shed_precedence_batch_absorbs_while_interactive_burns(served):
    """With the interactive class's burn-rate gauge over 1, batch and
    untagged arrivals shed immediately (typed, per-class accounted) while
    interactive arrivals keep admitting — the precedence never reverses."""
    cfg, model, params = served
    telemetry.configure(enabled=True, sample_sync=False,
                        jax_annotations=False)
    telemetry.set_slo_classes(SLO_CLASSES)
    # 5 violations in 15 observations = rate 1/3 against a 0.1 budget:
    # burn rate ~3.3 — the interactive class is burning
    for _ in range(10):
        telemetry.slo_observe("interactive", "ttft", 0.1)
    for _ in range(5):
        telemetry.slo_observe("interactive", "ttft", 5.0)
    tm = telemetry.get_telemetry()
    assert tm.gauge_value("slo/interactive/ttft_burn_rate") > 1.0

    fleet = make_fleet(model, params, decode_replicas=1)
    router = SLORouter(fleet, slo_ttft_s=60.0, prefix_affinity=False)
    rng = np.random.default_rng(41)

    def prompt():
        return rng.integers(0, cfg.vocab_size, 24).astype(np.int32)

    b = router.submit(0, prompt(), max_new_tokens=3, slo_class="batch")
    u = router.submit(1, prompt(), max_new_tokens=3)
    i = router.submit(2, prompt(), max_new_tokens=3,
                      slo_class="interactive")
    assert isinstance(b, RequestRejected) and "precedence" in b.reason
    assert isinstance(u, RequestRejected) and "precedence" in u.reason
    assert isinstance(i, RequestAdmitted)
    assert router.shed_by_class == {"batch": 1, None: 1}

    out = router.run_to_completion()
    assert set(out) == {2} and len(out[2]) == 3  # only interactive ran
    rep = router.report()
    assert rep["shed_by_class"] == {"batch": 1, "None": 1}
    assert rep["accounting"]["identity_holds"] is True
    flt = telemetry.summary()["fleet"]
    assert flt["events"]["shed"] == 2 and flt["events"]["admitted"] == 1


# ---------------------------------------------------------------------------
# disabled-telemetry zero overhead for the elasticity layer
# ---------------------------------------------------------------------------

def test_disabled_elasticity_zero_clock_reads_and_core_allocs(monkeypatch):
    """Telemetry off, the whole elasticity control loop — lifecycle
    bookkeeping, heartbeat checks on an injected clock, autoscaler
    observe/report ticks — performs ZERO reads of ``lifecycle._now`` and
    ZERO allocations inside the telemetry core."""
    assert not telemetry.enabled()

    def _boom():
        raise AssertionError(
            "disabled elasticity path must not read the wall clock")
    monkeypatch.setattr(lc_mod, "_now", _boom)

    clock = {"t": 0.0}
    fleet = _FakeFleet(decode=2)
    router = _FakeRouter()
    lcm = ReplicaLifecycle()
    det = FailureDetector(timeout_s=5.0, clock=lambda: clock["t"])
    scaler = FleetAutoscaler(fleet, router, min_decode=1, max_decode=4,
                             down_idle_rounds=3, cooldown_rounds=2)
    for j in (0, 1):
        lcm.add(("decode", j))
        det.beat(("decode", j))  # both beat once; decode1 then goes quiet

    tracemalloc.start()
    snap0 = tracemalloc.take_snapshot()
    for round_no in range(50):
        clock["t"] += 1.0
        det.beat(("decode", 0))  # decode1 stops beating: declared dead
        for key in det.check():
            if lcm.is_stepping(key):
                lcm.mark_dead(key)
                det.forget(key)
        router.queue_depth = 5 if round_no % 10 == 0 else 0
        scaler.observe()
        scaler.report()
    snap1 = tracemalloc.take_snapshot()
    tracemalloc.stop()

    assert lcm.state(("decode", 1)) == DEAD  # the detector did fire
    assert scaler.scale_ups > 0              # the scaler did act
    core_filter = [tracemalloc.Filter(True, telemetry_core.__file__)]
    grown = [st for st in
             snap1.filter_traces(core_filter).compare_to(
                 snap0.filter_traces(core_filter), "lineno")
             if st.size_diff > 0]
    assert not grown, f"telemetry core allocated when disabled: {grown}"
