"""Engine end-to-end tests (mirrors reference ``tests/unit/runtime/test_ds_initialize.py``
and ``tests/unit/runtime/zero/test_zero.py`` loss-parity patterns)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from tests.simple_model import SimpleModel, random_batches, tiny_gpt2_batches


def make_engine(config_extra=None, model=None, params=None, seed=0):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(config_extra or {})
    model = model or SimpleModel()
    if params is None:
        batch = random_batches(1, 8)[0]
        params = model.init(jax.random.PRNGKey(seed), batch)["params"]
    engine, opt, loader, sched = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    return engine


def train_losses(engine, batches):
    losses = []
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_initialize_returns_tuple():
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    out = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                   config={"train_batch_size": 8})
    assert len(out) == 4
    engine = out[0]
    assert engine.train_batch_size() == 8
    assert engine.train_micro_batch_size_per_gpu() * engine.topology.data_parallel_size \
        * engine.gradient_accumulation_steps() == 8


def test_loss_decreases():
    engine = make_engine()
    batches = random_batches(5, 8)
    losses = train_losses(engine, batches * 12)  # 12 epochs over 5 batches
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.2, losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stage_loss_parity(stage):
    """All ZeRO stages must produce (nearly) identical optimization traces —
    the partitioning is a layout change, not a math change."""
    batches = random_batches(10, 8, seed=3)
    baseline = train_losses(make_engine({"zero_optimization": {"stage": 0}}), batches)
    engine = make_engine({"zero_optimization": {"stage": stage,
                                                "stage3_param_persistence_threshold": 0}})
    losses = train_losses(engine, batches)
    np.testing.assert_allclose(losses, baseline, rtol=2e-4, atol=2e-5)


def test_zero3_params_are_sharded(eight_devices):
    from jax.sharding import PartitionSpec as P
    engine = make_engine({
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
        "bf16": {"enabled": True},
    })
    specs = [l.sharding.spec for l in jax.tree.leaves(engine.state.params)]
    assert any(s != P() for s in specs), f"no sharded leaves: {specs}"
    # kernels of Dense(16): (8,16) — 16 divisible by 8 => sharded
    master_specs = [l.sharding.spec for l in jax.tree.leaves(engine.state.master)]
    assert any(s != P() for s in master_specs)


def test_gradient_accumulation_boundary():
    engine = make_engine({"train_batch_size": 16, "gradient_accumulation_steps": 2})
    assert engine.gradient_accumulation_steps() == 2
    batches = random_batches(4, 8)
    engine(batches[0]); engine.backward(); engine.step()
    assert not engine.was_step_applied()
    assert engine.global_steps == 0
    engine(batches[1]); engine.backward(); engine.step()
    assert engine.was_step_applied()
    assert engine.global_steps == 1


def test_gas_equals_large_batch():
    """GAS=2 over half-batches must match single-step full-batch updates."""
    big = make_engine({"train_batch_size": 16}, seed=5)
    small = make_engine({"train_batch_size": 16, "gradient_accumulation_steps": 2}, seed=5)
    batches = random_batches(6, 16, seed=7)
    big_losses = train_losses(big, batches)
    for b in batches:
        half1 = {k: v[:8] for k, v in b.items()}
        half2 = {k: v[8:] for k, v in b.items()}
        for h in (half1, half2):
            loss = small(h)
            small.backward(loss)
            small.step()
    p_big = big.get_model_parameters()
    p_small = small.get_model_parameters()
    for a, b_ in zip(jax.tree.leaves(p_big), jax.tree.leaves(p_small)):
        np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-5)


def test_fp16_overflow_skips_step():
    engine = make_engine({"fp16": {"enabled": True, "initial_scale_power": 4,
                                   "hysteresis": 1}})
    batch = random_batches(1, 8)[0]
    # poison the batch to produce inf loss -> inf grads
    bad = {k: (v * np.float32(1e30) if k == "x" else v) for k, v in batch.items()}
    scale_before = engine.cur_scale
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1
    assert engine.cur_scale == scale_before / 2
    # healthy step afterwards works and is applied
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1
    # reference semantics: global_steps counts boundaries, including skipped ones
    assert engine.global_steps == 2


def test_bf16_training():
    engine = make_engine({"bf16": {"enabled": True}})
    losses = train_losses(engine, random_batches(20, 8))
    assert losses[-1] < losses[0]
    assert engine.state.params and engine.state.master is not None
    leaf = jax.tree.leaves(engine.state.params)[0]
    assert leaf.dtype == jnp.bfloat16


def test_gradient_clipping_applied():
    # SGD so the update magnitude is proportional to the clipped grad
    # (Adam self-normalizes, hiding the clip)
    engine = make_engine({"gradient_clipping": 1e-6,
                          "optimizer": {"type": "SGD", "params": {"lr": 1e-2}}})
    batches = random_batches(3, 8)
    p0 = engine.get_model_parameters()
    train_losses(engine, batches)
    p1 = engine.get_model_parameters()
    # with a tiny clip the params barely move
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_allclose(a, b, atol=1e-5)
    assert engine.get_global_grad_norm() > 0


def test_checkpoint_roundtrip(tmp_path):
    engine = make_engine()
    batches = random_batches(8, 8, seed=11)
    train_losses(engine, batches[:4])
    tag_path = engine.save_checkpoint(str(tmp_path))
    assert tag_path
    ref_losses = train_losses(engine, batches[4:])

    engine2 = make_engine(seed=99)  # different init
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert engine2.global_steps == 4
    resumed_losses = train_losses(engine2, batches[4:])
    np.testing.assert_allclose(resumed_losses, ref_losses, rtol=1e-5, atol=1e-6)


def test_checkpoint_roundtrip_bf16(tmp_path):
    """Regression: bfloat16 leaves must survive the npz round-trip (numpy has
    no native bfloat16; the engine byte-views them)."""
    conf = {"bf16": {"enabled": True}}
    engine = make_engine(conf)
    batches = random_batches(6, 8, seed=21)
    train_losses(engine, batches[:3])
    engine.save_checkpoint(str(tmp_path))
    ref = train_losses(engine, batches[3:])
    engine2 = make_engine(conf, seed=123)
    engine2.load_checkpoint(str(tmp_path))
    leaf = jax.tree.leaves(engine2.state.params)[0]
    assert leaf.dtype == jnp.bfloat16
    resumed = train_losses(engine2, batches[3:])
    np.testing.assert_allclose(resumed, ref, rtol=1e-5)


def test_checkpoint_client_state(tmp_path):
    engine = make_engine()
    train_losses(engine, random_batches(1, 8))
    engine.save_checkpoint(str(tmp_path), client_state={"epoch": 7})
    engine2 = make_engine()
    _, client = engine2.load_checkpoint(str(tmp_path))
    assert client["epoch"] == 7


def test_train_batch_api():
    engine = make_engine({"train_batch_size": 16, "gradient_accumulation_steps": 2})
    batches = iter(random_batches(4, 8))
    loss = engine.train_batch(batches)
    assert np.isfinite(loss)
    assert engine.global_steps == 1


def test_gpt2_tiny_end_to_end():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    model = GPT2LMHeadModel(cfg)
    batches = tiny_gpt2_batches(6, 8, seq_len=16, vocab=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                "zero_optimization": {"stage": 2}})
    losses = train_losses(engine, batches * 12)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_eval_batch():
    engine = make_engine()
    batch = random_batches(1, 8)[0]
    loss = engine.eval_batch(batch)
    assert np.isfinite(float(loss))


def test_async_checkpoint_save(tmp_path):
    """Async (Nebula-analog) checkpointing: training continues while the
    write happens; commit + load reproduce the sync checkpoint exactly."""
    import deepspeed_tpu
    from tests.simple_model import SimpleModel, random_batches
    model = SimpleModel(hidden_dim=16)
    batches = random_batches(6, 8, seed=3)
    params = model.init(jax.random.PRNGKey(3), batches[0])["params"]
    cfg = {"train_batch_size": 8,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 1}}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               model_parameters=params,
                                               config=cfg)
    for b in batches[:3]:
        loss = engine(b); engine.backward(loss); engine.step()
    engine.save_checkpoint(str(tmp_path), tag="async_t", async_save=True)
    # training proceeds while the background write runs
    for b in batches[3:]:
        loss = engine(b); engine.backward(loss); engine.step()
    assert engine.commit_checkpoints()

    engine2, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                model_parameters=params,
                                                config=cfg)
    engine2.load_checkpoint(str(tmp_path), tag="async_t")
    assert engine2.global_steps == 3
    # the checkpoint captured the state at step 3, unpolluted by steps 4-6
    l_resumed = float(jax.device_get(engine2.eval_batch(batches[3])))
    engine3, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                model_parameters=params,
                                                config=cfg)
    for b in batches[:3]:
        loss = engine3(b); engine3.backward(loss); engine3.step()
    l_expected = float(jax.device_get(engine3.eval_batch(batches[3])))
    np.testing.assert_allclose(l_resumed, l_expected, rtol=1e-5)


def test_async_checkpoint_error_surfaces(tmp_path):
    from deepspeed_tpu.runtime.checkpoint_engine.native_engine import (
        AsyncCheckpointEngine)
    eng = AsyncCheckpointEngine()
    # unwritable destination -> the failure must surface at commit
    eng.save({"x": np.arange(4)}, "/proc/definitely/not/writable/ckpt")
    with pytest.raises(IOError, match="async checkpoint"):
        eng.commit(None)


def test_async_checkpoint_with_offload(tmp_path):
    """async_save + ZeRO-Offload: host-tier moments land in the published
    checkpoint and resume bitwise (the in-worker extra_writer path)."""
    import deepspeed_tpu
    from tests.simple_model import SimpleModel, random_batches
    model = SimpleModel(hidden_dim=16)
    batches = random_batches(5, 8, seed=4)
    params = model.init(jax.random.PRNGKey(4), batches[0])["params"]
    cfg = {"train_batch_size": 8, "bf16": {"enabled": True},
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 1,
                                 "offload_optimizer": {"device": "cpu"}}}
    e1, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                           config=cfg)
    for b in batches[:3]:
        loss = e1(b); e1.backward(loss); e1.step()
    e1.save_checkpoint(str(tmp_path), tag="off_t", async_save=True)
    for b in batches[3:]:  # host tier mutates masters while the write runs
        loss = e1(b); e1.backward(loss); e1.step()
    assert e1.commit_checkpoints()
    import os
    assert os.path.exists(tmp_path / "off_t" / "host_optimizer_states.npz")
    assert (tmp_path / "latest").read_text() == "off_t"

    e2, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                           config=cfg)
    e2.load_checkpoint(str(tmp_path))  # via latest
    assert e2.global_steps == 3
    e3, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                           config=cfg)
    for b in batches[:3]:
        loss = e3(b); e3.backward(loss); e3.step()
    for k in e2._offload.masters:
        np.testing.assert_allclose(e2._offload.masters[k],
                                   e3._offload.masters[k], atol=1e-7)


def test_remat_policy_config_reaches_models():
    """activation_checkpointing.policy selects the jax.checkpoint policy the
    model blocks trace with (reference ``checkpointing.configure`` analog) and
    training still converges under the "dots" policy."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.runtime.activation_checkpointing import checkpointing

    cfg = GPT2Config.tiny(dtype=jnp.float32)
    model = GPT2LMHeadModel(cfg)
    batches = tiny_gpt2_batches(3, 8, seq_len=16, vocab=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": batches[0]["input_ids"].shape[0],
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "activation_checkpointing": {"policy": "dots"}})
    assert checkpointing._CONFIG["policy"] == "dots"
    # the policy objects must actually differ (wiring, not just parsing)
    assert checkpointing.policy_by_name("dots") is not \
        checkpointing.policy_by_name("everything")
    losses = []
    for b in batches * 3:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]


def test_initialize_accepts_mpu():
    """reference deepspeed.initialize(mpu=...) Megatron interop: the mpu's
    model-parallel world size seeds the mesh's tp axis."""
    from deepspeed_tpu.parallel import groups

    class FakeMPU:
        def get_model_parallel_world_size(self):
            return 2

    groups.reset()
    model = SimpleModel(hidden_dim=16)
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, mpu=FakeMPU(),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    assert engine.topology.get_dim("tp") == 2
    loss = engine(batch); engine.backward(loss); engine.step()


def test_engine_accessors_set_lr_mom_batch():
    """reference accessor parity: set_lr pins the schedule, get_mom reads
    optimizer betas, set_train_batch_size resizes GAS (elasticity hook)."""
    from tests.simple_model import SimpleModel, random_batches
    from deepspeed_tpu.parallel import groups
    groups.reset()
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "optimizer": {"type": "Adam",
                              "params": {"lr": 1e-3, "betas": [0.8, 0.95]}}})
    assert engine.get_mom() == [[0.8, 0.95]]
    engine.set_lr(5e-4)
    loss = engine(batch); engine.backward(loss); engine.step()
    assert abs(engine.get_lr()[0] - 5e-4) < 1e-9
    dp = engine.topology.data_parallel_size
    engine.set_train_batch_size(2 * dp)   # mbs=1 -> gas=2, at a boundary
    assert engine.gradient_accumulation_steps() == 2
    with pytest.raises(ValueError):
        engine.set_train_batch_size(2 * dp + 1)
    steps_before = engine.global_steps
    loss = engine(batch); engine.backward(loss); engine.step()
    assert engine.global_steps == steps_before          # mid-window: no apply
    with pytest.raises(RuntimeError, match="mid-accumulation"):
        engine.set_train_batch_size(4 * dp)
    loss = engine(batch); engine.backward(loss); engine.step()
    assert engine.global_steps == steps_before + 1      # window of 2 closed


def test_gas_offset_survives_checkpoint(tmp_path):
    """A resized accumulation window stays aligned across save/load."""
    from tests.simple_model import SimpleModel, random_batches
    from deepspeed_tpu.parallel import groups

    def build():
        groups.reset()
        model = SimpleModel()
        batch = random_batches(1, 8)[0]
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        eng, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "gradient_accumulation_steps": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
        return eng, batch

    eng, batch = build()
    for _ in range(3):   # 3 windows of gas=1
        loss = eng(batch); eng.backward(loss); eng.step()
    dp = eng.topology.data_parallel_size
    eng.set_train_batch_size(2 * dp)          # rebase at micro_steps=3
    loss = eng(batch); eng.backward(loss); eng.step()   # half-window
    eng.save_checkpoint(str(tmp_path), tag="resized")

    eng2, batch = build()
    dp = eng2.topology.data_parallel_size
    eng2.set_train_batch_size(2 * dp)         # same GAS as at save time
    eng2.load_checkpoint(str(tmp_path), tag="resized")
    assert eng2.micro_steps == 4 and eng2._gas_offset == 3
    # next micro-step closes the 2-window that began before the save
    assert eng2.is_gradient_accumulation_boundary()


def test_engine_prefetch_batches_config():
    """prefetch_batches=N wraps the training dataloader in PrefetchLoader
    and train_batch consumes pre-sharded batches unchanged."""
    import numpy as np
    from deepspeed_tpu.runtime.dataloader import PrefetchLoader
    from tests.simple_model import SimpleModel, random_dataset
    model = SimpleModel(hidden_dim=16)
    data = random_dataset(n=32)
    params = model.init(jax.random.PRNGKey(0),
                        {k: v[:8] for k, v in data.items()})["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, training_data=data,
        config={"train_batch_size": 8, "prefetch_batches": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    assert isinstance(engine.training_dataloader, PrefetchLoader)
    l1 = engine.train_batch()
    l2 = engine.train_batch()
    assert np.isfinite(l1) and np.isfinite(l2)


def test_fused_step_matches_two_phase():
    """fused_step=True must reproduce the two-jit path to float tolerance
    (fusion reorders float ops, so bit-exactness is not expected)."""
    import numpy as np
    from tests.simple_model import SimpleModel, random_batches
    batches = random_batches(6, batch_size=8, seed=3)

    def train(fused):
        model = SimpleModel(hidden_dim=32)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 8, "fused_step": fused,
                    "gradient_clipping": 1.0,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}})
        losses = []
        for b in batches:
            loss = engine(b)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        assert engine.was_step_applied()
        return losses, jax.device_get(engine.state.params), \
            engine.get_global_grad_norm()

    l_fused, p_fused, n_fused = train(True)
    l_plain, p_plain, n_plain = train(False)
    np.testing.assert_allclose(l_fused, l_plain, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(p_fused), jax.tree.leaves(p_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(float(n_fused), float(n_plain), rtol=1e-4)


def test_fused_step_disabled_for_gas():
    """fused_step silently degrades to the two-phase path when GAS > 1."""
    from tests.simple_model import SimpleModel, random_batches
    batches = random_batches(2, batch_size=8, seed=4)
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 16, "train_micro_batch_size_per_gpu": 8 // max(1, jax.device_count() // 1),
                "gradient_accumulation_steps": 2, "fused_step": True,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    assert engine._fused_step_fn is None
    assert engine.was_step_applied()


def test_fused_gas_train_batch_matches_unfused():
    """fused_step at GAS>1: train_batch runs the whole accumulation window as
    one compiled scan; losses and end params must match the per-micro-step
    path to float tolerance."""
    import numpy as np
    from tests.simple_model import SimpleModel, random_batches
    batches = random_batches(8, batch_size=8, seed=7)

    def train(fused):
        model = SimpleModel(hidden_dim=32)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                    "fused_step": fused, "gradient_clipping": 1.0,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}})
        it = iter(batches)
        losses = [engine.train_batch(it) for _ in range(4)]
        assert engine.global_steps == 4
        assert engine.micro_steps == 8
        return losses, jax.device_get(engine.state.params), engine

    l_fused, p_fused, e_fused = train(True)
    l_plain, p_plain, _ = train(False)
    assert e_fused._fused_gas_step_fn is not None
    np.testing.assert_allclose(l_fused, l_plain, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(p_fused), jax.tree.leaves(p_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-7)


def test_fused_gas_fewer_bytes_accessed():
    """Compiler-counter evidence (VERDICT r3 #5): the fused window is a
    STATIC unroll (no lax.scan — a while-loop would carry and copy the
    params-sized accumulator per iteration, and cost_analysis counts a loop
    body only once, making comparisons dishonest). Straight-line bytes are
    directly comparable: the standalone apply-step's full-state read/write
    disappears into the last backward."""
    import numpy as np
    from tests.simple_model import SimpleModel, random_batches
    batches = random_batches(2, batch_size=8, seed=9)
    gas = 2

    def engines(fused):
        model = SimpleModel(hidden_dim=64)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_batch_size": 16, "gradient_accumulation_steps": gas,
                    "fused_step": fused,
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}}})
        engine._compiled()
        return engine

    def bytes_of(lowered):
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("bytes accessed", 0.0))

    e_f = engines(True)
    stacked = e_f._shard_stacked_batches(batches[:gas])
    fused_bytes = bytes_of(e_f._fused_gas_step_fn.lower(
        e_f.state, stacked, jnp.float32(1e-2)))

    e_u = engines(False)
    b0 = e_u._shard_batch(batches[0])
    micro_bytes = bytes_of(e_u._micro_step_fn.lower(e_u.state, b0))
    apply_bytes = bytes_of(e_u._apply_step_fn.lower(e_u.state, jnp.float32(1e-2)))
    if fused_bytes == 0.0 or micro_bytes == 0.0 or apply_bytes == 0.0:
        pytest.skip("cost_analysis reports no byte counts on this backend")
    unfused_total = gas * micro_bytes + apply_bytes
    assert fused_bytes < unfused_total, \
        f"fused window {fused_bytes:.3e}B !< unfused {unfused_total:.3e}B"


def test_fused_step_fp16_overflow_skip():
    """Dynamic loss scaling + overflow skip works inside the fused jit."""
    import numpy as np
    from tests.simple_model import SimpleModel, random_batches
    batches = random_batches(1, batch_size=8, seed=5)
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "fused_step": True,
                "fp16": {"enabled": True, "initial_scale_power": 4},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    bad = {k: v.copy() for k, v in batches[0].items()}
    bad["x"][0, 0] = np.inf  # poison -> overflow -> skip
    before = jax.device_get(engine.state.params)
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1, "overflow must increment the skip counter"
    after = jax.device_get(engine.state.params)
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_flops_profiler_profiles_fused_program():
    """With fused_step on, the profiler must profile the program that runs
    (the fused grad+apply jit), not the unused micro-step."""
    from tests.simple_model import SimpleModel, random_batches
    batches = random_batches(2, batch_size=8, seed=6)
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "fused_step": True,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "flops_profiler": {"enabled": True, "profile_step": 1}})
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    assert engine.flops_profiler is not None
    assert engine.flops_profiler.macs and engine.flops_profiler.macs > 0
