"""Evoformer attention numerics (reference tests/unit/ops/deepspeed4science)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.evoformer_attn import (DS4Sci_EvoformerAttention,
                                              evoformer_attn_reference)


def make_inputs(B=1, S=2, N=32, H=2, D=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    Q = jax.random.normal(ks[0], (B, S, N, H, D))
    K = jax.random.normal(ks[1], (B, S, N, H, D))
    V = jax.random.normal(ks[2], (B, S, N, H, D))
    mask = (jax.random.uniform(ks[3], (B, 1, 1, 1, N)) > 0.1) * 0.0 + \
        jnp.where(jax.random.uniform(ks[3], (B, 1, 1, 1, N)) > 0.1, 0.0, -1e9)
    pair = jax.random.normal(ks[4], (B, 1, H, N, N)) * 0.5
    return Q, K, V, [mask, pair]


def test_matches_reference():
    Q, K, V, biases = make_inputs()
    out = DS4Sci_EvoformerAttention(Q, K, V, biases)
    ref = evoformer_attn_reference(Q, K, V, biases)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=1e-3)


def test_mask_bias_blocks_attention():
    Q, K, V, _ = make_inputs(seed=1)
    B, S, N, H, D = Q.shape
    # mask out the last residue everywhere: output must not depend on its V
    mask = jnp.zeros((B, 1, 1, 1, N)).at[..., -1].set(-1e9)
    out1 = DS4Sci_EvoformerAttention(Q, K, V, [mask])
    V2 = V.at[:, :, -1].set(123.0)
    out2 = DS4Sci_EvoformerAttention(Q, K, V2, [mask])
    np.testing.assert_allclose(np.asarray(out1[:, :, :-1]),
                               np.asarray(out2[:, :, :-1]), atol=1e-5)


def test_pair_bias_only():
    Q, K, V, biases = make_inputs(seed=2)
    out = DS4Sci_EvoformerAttention(Q, K, V, [biases[1]])
    ref = evoformer_attn_reference(Q, K, V, [biases[1]])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=1e-3)


def test_gradients_flow():
    Q, K, V, biases = make_inputs(N=16)

    def loss(q, k, v):
        return jnp.sum(DS4Sci_EvoformerAttention(q, k, v, biases) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(Q, K, V)

    def loss_ref(q, k, v):
        return jnp.sum(evoformer_attn_reference(q, k, v, biases) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(Q, K, V)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=2e-3)


def test_registry_slot():
    from deepspeed_tpu.ops.registry import get_op_builder
    assert get_op_builder("evoformer_attn") is not None
