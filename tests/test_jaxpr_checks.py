"""graftlint Layer B — jaxpr checks over synthetic fixtures AND the real
traced programs (engine micro-step, qgZ scheduled exchange, serving decode
forward). This is the ``lint`` lane (``pytest -m lint``): everything here
traces with ``jax.make_jaxpr`` — no compile, no execution — so the whole
file stays cheap enough for the fast lane too.

The acceptance bar (ISSUE 12): the real programs pass ``check_program``
clean, and the overlap-plan drift check fails LOUDLY when the plan's
collective inventory is perturbed away from what the program traces.
"""

from functools import partial

import numpy as np
import pytest

pytest.importorskip("jax")
from deepspeed_tpu.utils import jax_compat  # noqa: F401 (jax.shard_map shim)
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.analysis import jaxpr_checks as jc

pytestmark = pytest.mark.lint


# ---------------------------------------------------------------------------
# JX001 — bf16 -> f32 upcasts
# ---------------------------------------------------------------------------

def test_upcast_feeding_math_is_flagged():
    def f(x):
        return x.astype(jnp.float32) * 2.0  # re-widened activation math

    closed = jax.make_jaxpr(f)(jnp.zeros((8192,), jnp.bfloat16))
    findings = jc.check_upcasts(closed)
    assert len(findings) == 1
    assert findings[0]["check"] == "JX001"
    assert "8192" in findings[0]["message"]


def test_accumulation_upcast_is_exempt():
    # bf16.sum() MUST accumulate in f32 — convert consumed only by reduce
    def f(x):
        return jnp.sum(x.astype(jnp.float32))

    closed = jax.make_jaxpr(f)(jnp.zeros((8192,), jnp.bfloat16))
    assert jc.check_upcasts(closed) == []


def test_tiny_upcast_below_min_elems_is_noise():
    def f(x):
        return x.astype(jnp.float32) * 2.0

    closed = jax.make_jaxpr(f)(jnp.zeros((8,), jnp.bfloat16))
    assert jc.check_upcasts(closed) == []
    # the threshold is a knob, not a constant
    assert jc.check_upcasts(closed, min_elems=4) != []


# ---------------------------------------------------------------------------
# JX002 — collectives vs shard_map bindings
# ---------------------------------------------------------------------------

def test_unbound_collective_is_flagged():
    def f(x):
        return jax.lax.psum(x, "dp")

    closed = jax.make_jaxpr(f, axis_env=[("dp", 8)])(jnp.zeros((4,)))
    findings = jc.check_collectives(closed)
    assert len(findings) == 1
    assert findings[0]["check"] == "JX002"
    assert "dp" in findings[0]["message"]
    # the caller can vouch for axes bound outside the traced fragment
    assert jc.check_collectives(closed, extra_bound=("dp",)) == []


def test_collective_inside_shard_map_is_bound():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = jax.make_mesh((8,), ("dp",))

    def body(x):
        return jax.lax.psum(x, "dp")

    f = jax.shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P(),
                      check_vma=False)
    closed = jax.make_jaxpr(f)(jnp.zeros((8,), jnp.float32))
    assert jc.check_collectives(closed) == []


# ---------------------------------------------------------------------------
# JX003 — host callbacks in hot programs
# ---------------------------------------------------------------------------

def _echo(a):
    return np.asarray(a)


def test_callback_is_flagged_and_allowlistable():
    def f(x):
        return jax.pure_callback(
            _echo, jax.ShapeDtypeStruct(x.shape, x.dtype), x)

    closed = jax.make_jaxpr(f)(jnp.zeros((4,), jnp.float32))
    findings = jc.check_callbacks(closed)
    assert len(findings) == 1
    assert findings[0]["check"] == "JX003"
    assert jc.check_callbacks(closed, allow=("_echo",)) == []


def test_check_program_composes_all_three():
    def f(x):
        y = x.astype(jnp.float32) * 2.0
        return jax.lax.psum(y, "dp")

    closed = jax.make_jaxpr(f, axis_env=[("dp", 8)])(
        jnp.zeros((8192,), jnp.bfloat16))
    checks = {f["check"] for f in jc.check_program(closed)}
    assert checks == {"JX001", "JX002"}
    # f32 program: JX001 is not meaningful and must be gated off
    checks32 = {f["check"] for f in jc.check_program(closed, dtype="float32")}
    assert checks32 == {"JX002"}


# ---------------------------------------------------------------------------
# plan classes + drift (synthetic)
# ---------------------------------------------------------------------------

def test_op_class_mirrors_overlap_schedule():
    # jaxpr_checks hand-copies the prefetch/bucket/tail/moe mapping so the
    # stdlib CLI never imports the runtime; this is the sync guard
    from deepspeed_tpu.runtime.zero.overlap_schedule import _op_class
    for op in ("all_gather", "gather", "reduce_scatter", "psum_scatter",
               "all_to_all", "exchange", "all_reduce", "ppermute",
               "halo", "send", "a2a_dispatch", "a2a_combine"):
        assert jc.op_class(op) == _op_class(op), op
    # and the moe ops must NOT fall into the generic bucket class
    assert jc.op_class("a2a_dispatch") == "moe_dispatch"
    assert jc.op_class("a2a_combine") == "moe_combine"


def test_merge_inventories_sums_ops_and_classes():
    a = {"ops": {"all_gather": 4}, "classes": {"prefetch": 4}}
    b = {"ops": {"all_gather": 2, "all_to_all": 3},
         "classes": {"prefetch": 2, "bucket": 3}}
    m = jc.merge_inventories(a, b)
    assert m["ops"] == {"all_gather": 6, "all_to_all": 3}
    assert m["classes"] == {"bucket": 3, "prefetch": 6}


def test_plan_drift_synthetic_ok_and_perturbed():
    inv = {"ops": {"all_gather": 4, "reduce_scatter": 2},
           "classes": {"prefetch": 4, "bucket": 2}}
    plan = {"comm_ops": [{"op": "all_gather", "count": 4},
                         {"op": "reduce_scatter", "count": 2}]}
    assert jc.check_plan_drift(plan, inv)["ok"]

    # plan prices a class that never traces -> claims overlap for nothing
    ghost = {"comm_ops": plan["comm_ops"] + [{"op": "all_reduce", "count": 1}]}
    res = jc.check_plan_drift(ghost, inv)
    assert not res["ok"] and res["missing_in_trace"] == ["tail"]

    # traced class the plan omits -> unpriced comm the model never saw
    blind = {"comm_ops": [{"op": "all_gather", "count": 4}]}
    res = jc.check_plan_drift(blind, inv)
    assert not res["ok"] and res["missing_in_plan"] == ["bucket"]


# ---------------------------------------------------------------------------
# real programs
# ---------------------------------------------------------------------------

def _build_scheduled_engine():
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    VOCAB, HID, LAYERS, B, T = 256, 64, 4, 8, 16
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=VOCAB, hidden_size=HID, intermediate_size=2 * HID,
        num_hidden_layers=LAYERS, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=T))
    rng = np.random.RandomState(1)
    ids = rng.randint(0, VOCAB, size=(B, T)).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config={
            "train_batch_size": B,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3,
                                  "zero_quantized_gradients": True},
            "overlap": {"schedule": True, "prefetch_depth": 1,
                        "grad_buckets": 2},
        })
    engine._compiled()  # builds the jitted step fns without running a step
    return engine, batch


@pytest.fixture(scope="module")
def scheduled_traces():
    """(micro_jaxpr, apply_jaxpr) of the overlap-scheduled qgZ engine —
    make_jaxpr only, nothing compiles or runs."""
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    engine, batch = _build_scheduled_engine()
    micro = jax.make_jaxpr(engine._micro_step_fn)(engine.state, batch)
    apply = jax.make_jaxpr(engine._apply_step_fn)(engine.state, 0.01)
    return micro, apply


def test_scheduled_micro_step_is_clean(scheduled_traces):
    micro, _ = scheduled_traces
    # fp32 run: JX001 gated off; every collective must be shard_map-bound;
    # and nothing may have traced a host callback into the step
    assert jc.check_program(micro, dtype="float32") == []


def test_qgz_apply_step_traces_bucket_exchange(scheduled_traces):
    _, apply = scheduled_traces
    assert jc.check_program(apply, dtype="float32") == []
    inv = jc.collective_inventory(apply)
    # the qgZ quantized gradient exchange lowers to all_to_all inside the
    # shard_map — the bucket class the overlap plan prices
    assert inv["ops"].get("all_to_all", 0) > 0
    assert inv["classes"].get("bucket", 0) > 0


def test_plan_drift_against_traced_inventory(scheduled_traces):
    micro, apply = scheduled_traces
    merged = jc.merge_inventories(jc.collective_inventory(micro),
                                  jc.collective_inventory(apply))
    assert merged["classes"], "scheduled round traced no collectives at all"

    # a plan priced from the traced reality agrees with it
    honest = {"comm_ops": [{"op": op, "count": n}
                           for op, n in merged["ops"].items()]}
    res = jc.check_plan_drift(honest, merged)
    assert res["ok"], res

    # perturb the plan inventory -> the gate fails LOUDLY (acceptance bar):
    # (a) a priced class the program never traces
    ghost_op = "all_gather" if "prefetch" not in merged["classes"] else "halo"
    ghost = {"comm_ops": honest["comm_ops"] + [{"op": ghost_op, "count": 8}]}
    res = jc.check_plan_drift(ghost, merged)
    assert not res["ok"] and res["missing_in_trace"], res
    # (b) the plan drops a traced class entirely
    blind = {"comm_ops": [{"op": ghost_op, "count": 8}]}
    res = jc.check_plan_drift(blind, merged)
    assert not res["ok"] and res["missing_in_plan"], res


# ---------------------------------------------------------------------------
# MoE micro-step (ISSUE 15): bound a2a + wire precision
# ---------------------------------------------------------------------------

def _trace_moe_shard(bits):
    """jaxpr of the dropless ep micro-step (shard_map'd _moe_gmm_ep_shard),
    exactly as _gmm_ep_forward wires it — make_jaxpr only."""
    from deepspeed_tpu.moe.sharded_moe import _moe_gmm_ep_shard

    mesh = jax.make_mesh((4, 2), ("dp", "ep"))
    S, D, F, E, k = 32, 256, 256, 4, 2

    def body(xl, gl, el, w1l, w2l, w3l):
        return _moe_gmm_ep_shard(xl, gl, el, w1l, w2l, w3l, n_experts=E,
                                 ep_axis="ep", bits=bits, dtype=jnp.float32,
                                 interpret=True)

    tok = P(("dp", "ep"), None)
    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(tok, tok, tok, P("ep"), P("ep"), P("ep")),
                       out_specs=tok, check_vma=False)
    return jax.make_jaxpr(fn)(
        jnp.zeros((S, D), jnp.float32), jnp.zeros((S, k), jnp.float32),
        jnp.zeros((S, k), jnp.int32), jnp.zeros((E, D, F), jnp.float32),
        jnp.zeros((E, F, D), jnp.float32), jnp.zeros((E, D, F), jnp.float32))


def test_moe_micro_step_a2a_is_bound_and_clean():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    closed = _trace_moe_shard(bits=None)
    # every dispatch/combine all_to_all is shard_map-bound, no callbacks
    assert jc.check_program(closed, dtype="float32") == []
    inv = jc.collective_inventory(closed)
    assert inv["ops"].get("all_to_all", 0) >= 3  # x out, ids, y back


def test_moe_unsharded_a2a_is_flagged():
    # the same body traced WITHOUT a shard_map binding 'ep' — the unbound
    # dispatch/combine a2a the lint lane must catch
    from deepspeed_tpu.moe.sharded_moe import _moe_gmm_ep_shard

    S, D, F, E, k = 16, 128, 128, 4, 2

    def body(xl, gl, el, w1l, w2l, w3l):
        return _moe_gmm_ep_shard(xl, gl, el, w1l, w2l, w3l, n_experts=E,
                                 ep_axis="ep", bits=None, dtype=jnp.float32,
                                 interpret=True)

    closed = jax.make_jaxpr(body, axis_env=[("ep", 2)])(
        jnp.zeros((S, D), jnp.float32), jnp.zeros((S, k), jnp.float32),
        jnp.zeros((S, k), jnp.int32),
        jnp.zeros((E // 2, D, F), jnp.float32),
        jnp.zeros((E // 2, F, D), jnp.float32),
        jnp.zeros((E // 2, D, F), jnp.float32))
    findings = jc.check_collectives(closed)
    assert findings and all(f["check"] == "JX002" for f in findings)
    assert any("all_to_all" in f["eqn"] for f in findings)
    # vouching for the externally-bound axis silences it
    assert jc.check_collectives(closed, extra_bound=("ep",)) == []


def test_moe_wire_quantized_vs_fp_leg():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    # int8 configured AND traced -> clean
    assert jc.check_moe_wire(_trace_moe_shard(bits=8), wire_bits=8) == []
    # int8 configured but the trace ships fp -> JX004, loudly
    findings = jc.check_moe_wire(_trace_moe_shard(bits=None), wire_bits=8)
    assert len(findings) == 1 and findings[0]["check"] == "JX004"
    assert "never materialized" in findings[0]["message"]
    # no bits configured -> nothing to check
    assert jc.check_moe_wire(_trace_moe_shard(bits=None), wire_bits=None) == []


def test_moe_hierarchical_wire_int8_rides_dcn_only():
    if jax.device_count() < 8:
        pytest.skip("needs the 8-device CPU mesh")
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        moe_hierarchical_a2a)

    mesh = jax.make_mesh((4, 2), ("dpr", "ep"))

    def trace(inter_bits):
        fn = jax.shard_map(
            lambda x: moe_hierarchical_a2a(x, intra_axis="ep",
                                           inter_axis="dpr",
                                           inter_bits=inter_bits),
            mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False)
        return jax.make_jaxpr(fn)(
            jnp.zeros((4, 2, 16, 2048), jnp.float32))

    closed = trace(8)
    assert jc.check_program(closed, dtype="float32") == []
    assert jc.check_moe_wire(closed, wire_bits=8, inter_axis="dpr") == []
    # fp over DCN where int8 was configured -> the (b) finding
    findings = jc.check_moe_wire(trace(None), wire_bits=8, inter_axis="dpr")
    assert len(findings) == 1 and findings[0]["check"] == "JX004"


@pytest.fixture(scope="module")
def serving_decode_trace():
    """jaxpr of the v2 ragged decode forward, traced exactly as
    ``_forward_device`` calls it (static model_config partial'd in)."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import \
        RaggedBatchWrapper
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 32,
                          "max_context": 64, "num_kv_blocks": 16},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})

    seq = engine._state.get_or_create_sequence(1)
    engine._state.ensure_capacity(seq, 4)
    sm = engine._config.state_manager
    wrapper = RaggedBatchWrapper(sm.max_ragged_sequence_count,
                                 sm.max_ragged_batch_size,
                                 engine._max_blocks_per_seq,
                                 engine._state.kv_cache.trash_block)
    wrapper.insert_sequence(1, np.array([2, 3, 4, 5], np.int32), 0,
                            seq.kv_blocks)
    arrays = wrapper.build()
    kv = engine._state.kv_cache
    return jax.make_jaxpr(
        partial(engine._ragged_forward, engine._model_config))(
            engine._params, kv.k_pool, kv.v_pool,
            jnp.asarray(arrays["tokens"]), jnp.asarray(arrays["q_len"]),
            jnp.asarray(arrays["seen"]), jnp.asarray(arrays["block_tables"]))


def test_serving_decode_step_is_clean(serving_decode_trace):
    # the decode hot path must trace zero host callbacks (each would be a
    # per-token stall the host_sync audit could never see) and no
    # unbound collectives
    assert jc.check_program(serving_decode_trace, dtype="float32") == []


# ---------------------------------------------------------------------------
# JX005 — the speculative verify forward rides the prefill scan (ISSUE 16)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def verify_parity_traces():
    """(plain ragged_forward jaxpr, ragged_forward_verify jaxpr) over the
    same tiny engine and the same padded batch shapes — make_jaxpr only."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.ragged.ragged_wrapper import \
        RaggedBatchWrapper
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 32,
                          "max_context": 64, "num_kv_blocks": 16},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})
    assert engine.verify_supported

    seq = engine._state.get_or_create_sequence(1)
    engine._state.ensure_capacity(seq, 4)
    sm = engine._config.state_manager
    wrapper = RaggedBatchWrapper(sm.max_ragged_sequence_count,
                                 sm.max_ragged_batch_size,
                                 engine._max_blocks_per_seq,
                                 engine._state.kv_cache.trash_block)
    wrapper.insert_sequence(1, np.array([2, 3, 4, 5], np.int32), 0,
                            seq.kv_blocks)
    arrays = wrapper.build()
    kv = engine._state.kv_cache
    args = (engine._params, kv.k_pool, kv.v_pool,
            jnp.asarray(arrays["tokens"]), jnp.asarray(arrays["q_len"]),
            jnp.asarray(arrays["seen"]), jnp.asarray(arrays["block_tables"]))
    mc = engine._model_config
    plain = jax.make_jaxpr(partial(engine._ragged_forward, mc))(*args)
    verify = jax.make_jaxpr(
        lambda *a: engine._verify_forward(mc, *a, 4))(*args)
    return plain, verify


def test_verify_forward_shares_prefill_scan(verify_parity_traces):
    # the bit-exactness oracle's structural half: draft verification lowers
    # through the IDENTICAL layer scan as plain ragged prefill — no trunk
    # fork, no dense-decode fallback — and the program is itself clean
    plain, verify = verify_parity_traces
    assert jc.check_verify_prefill_parity(plain, verify) == []
    assert jc.check_program(verify, dtype="float32") == []


def test_verify_parity_flags_fork_and_fallback():
    def stacked(x):
        return jax.lax.scan(lambda c, t: (c + t, c), x[0], x)[0]

    def forked(x):
        return jax.lax.scan(lambda c, t: (c * t, c), x[0], x)[0]

    ja = jax.make_jaxpr(stacked)(jnp.arange(4.0))
    jb = jax.make_jaxpr(forked)(jnp.arange(4.0))
    assert jc.check_verify_prefill_parity(ja, ja) == []
    findings = jc.check_verify_prefill_parity(ja, jb)
    assert len(findings) == 1 and findings[0]["check"] == "JX005"
    assert "diverges" in findings[0]["message"]
    # a verify program with no scan at all is the dense-decode fallback
    dense = jax.make_jaxpr(lambda x: x * 2)(jnp.arange(4.0))
    findings = jc.check_verify_prefill_parity(ja, dense)
    assert findings and findings[0]["check"] == "JX005"
    assert "no layer scan" in findings[0]["message"]
