"""ZeRO-Infinity parameter-tier tests (runtime/zero/param_offload.py).

Reference coverage being mirrored: the param-offload/Infinity cases of
``tests/unit/runtime/zero`` (``test_zero_offloadpp.py``,
``test_nvme_checkpointing.py``, stage-3 offload_param configs): a model whose
block parameters live on host DRAM / NVMe must train at loss parity with the
all-in-HBM engine, and the device program must provably NOT hold the streamed
parameters.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


VOCAB, HID, LAYERS, B, T = 512, 64, 4, 8, 16


def _model():
    return LlamaForCausalLM(LlamaConfig(
        vocab_size=VOCAB, hidden_size=HID, intermediate_size=2 * HID,
        num_hidden_layers=LAYERS, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=T))


def _batches(steps, seed=1):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        ids = rng.randint(0, VOCAB, size=(B, T)).astype(np.int32)
        out.append({"input_ids": ids, "labels": ids})
    return out


def _config(gas=1, **zero_extra):
    zero = {"stage": 3}
    zero.update(zero_extra)
    return {
        "train_micro_batch_size_per_gpu": B // 8 if B >= 8 else B,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": zero,
    }


def _train(config, steps=4, seed=0, engine_out=False):
    model = _model()
    batches = _batches(steps)
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=config)
    losses = []
    for bt in batches:
        for _ in range(engine.gradient_accumulation_steps_value):
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
        losses.append(float(jax.device_get(loss)))
    return (engine, losses) if engine_out else losses


def test_param_offload_requires_stage3():
    model = _model()
    batches = _batches(1)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    cfg = _config()
    cfg["zero_optimization"] = {"stage": 2,
                                "offload_param": {"device": "cpu"}}
    with pytest.raises(ValueError, match="stage 3"):
        deepspeed_tpu.initialize(model=model, model_parameters=params, config=cfg)


def test_param_offload_cpu_loss_parity():
    """offload_param.device=cpu: streamed training must track the in-HBM
    engine (bf16 working precision + CPU-vs-optax Adam bound the drift)."""
    base = _train(_config())
    eng, streamed = _train(_config(offload_param={"device": "cpu"}),
                           engine_out=True)
    assert eng._param_store is not None
    assert eng._param_store.device == "cpu"
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)


def test_param_offload_gradient_parity():
    """One micro-step: the host accumulators must hold the SAME gradients the
    in-HBM engine's device accumulator computes (to bf16 rounding) — both for
    the streamed blocks (via the backward io_callback) and the resident
    leaves. This pins the full fetch→vjp→host-write path numerically; the
    multi-step loss test above only bounds trajectory drift."""
    model = _model()
    batches = _batches(1)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    e1, _, _, _ = deepspeed_tpu.initialize(model=_model(), model_parameters=params,
                                           config=_config())
    e1.backward(e1(batches[0]))
    base = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
            jax.tree_util.tree_flatten_with_path(
                jax.device_get(e1.state.grad_acc))[0]}
    e2, _, _, _ = deepspeed_tpu.initialize(model=_model(), model_parameters=params,
                                           config=_config(
                                               offload_param={"device": "cpu"}))
    e2.backward(e2(batches[0]))
    jax.effects_barrier()
    res = {jax.tree_util.keystr(p): np.asarray(l) for p, l in
           jax.tree_util.tree_flatten_with_path(
               jax.device_get(e2.state.grad_acc))[0]}
    for k, g in res.items():
        np.testing.assert_allclose(g, base[k], atol=1e-3, err_msg=k)
    store = e2._param_store
    for j, path in enumerate(store._paths):
        full = base["['layers']['block']" + path]
        for i in range(store.num_blocks):
            got = store._grads[i][store._offsets[j]:store._offsets[j + 1]] \
                .reshape(store.block_shapes[j])
            np.testing.assert_allclose(got, full[i], atol=1e-3,
                                       err_msg=f"block {i} {path}")


def test_param_offload_nvme_loss_parity(tmp_path):
    """offload_param.device=nvme: block files ride the aio handle with
    read-ahead; numerics identical to the cpu tier."""
    cpu_losses = _train(_config(offload_param={"device": "cpu"}))
    eng, nvme_losses = _train(
        _config(offload_param={"device": "nvme",
                               "nvme_path": str(tmp_path),
                               "buffer_count": 3}),
        engine_out=True)
    assert eng._param_store.device == "nvme"
    import os
    files = os.listdir(os.path.join(str(tmp_path), "params"))
    assert len(files) == LAYERS, f"one swap file per scan block: {files}"
    # same host-tier math, different storage: byte-identical losses
    np.testing.assert_allclose(nvme_losses, cpu_losses, rtol=1e-6)


def test_param_offload_gas_accumulation():
    """GAS=2: host grad accumulators sum across micro-steps exactly like the
    device accumulator path."""
    base = _train(_config(gas=2), steps=3)
    streamed = _train(_config(gas=2, offload_param={"device": "cpu"}), steps=3)
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)


def test_streamed_params_not_device_arguments():
    """The HBM-budget proof: the stacked block parameters are NOT inputs (or
    state) of the compiled step — device memory holds the resident leaves
    only, so a model bigger than HBM trains as long as ONE block fits."""
    eng, _ = _train(_config(offload_param={"device": "cpu"}), steps=1,
                    engine_out=True)
    # the engine's device state carries no stacked leaves
    assert "layers" not in eng.state.params
    n_resident = sum(int(np.prod(l.shape))
                     for l in jax.tree.leaves(eng.state.params))
    n_total = eng.module.config.num_parameters()
    n_streamed = n_total - n_resident
    assert n_streamed > 0
    store = eng._param_store
    assert store.num_blocks * store.block_elems == n_streamed


def test_param_offload_checkpoint_roundtrip(tmp_path):
    """save → load → continue must match uninterrupted training (host masters
    + moments round-trip through host_param_tier.npz)."""
    cfg = _config(offload_param={"device": "cpu"})
    model = _model()
    batches = _batches(6)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                            config=cfg)
    for bt in batches[:3]:
        loss = eng(bt)
        eng.backward(loss)
        eng.step()
    eng.save_checkpoint(str(tmp_path), tag="t3")
    cont = []
    for bt in batches[3:]:
        loss = eng(bt)
        eng.backward(loss)
        eng.step()
        cont.append(float(jax.device_get(loss)))

    model2 = _model()
    params2 = model2.init(jax.random.PRNGKey(7), batches[0])["params"]
    eng2, _, _, _ = deepspeed_tpu.initialize(model=model2, model_parameters=params2,
                                             config=cfg)
    eng2.load_checkpoint(str(tmp_path), tag="t3")
    resumed = []
    for bt in batches[3:]:
        loss = eng2(bt)
        eng2.backward(loss)
        eng2.step()
        resumed.append(float(jax.device_get(loss)))
    np.testing.assert_allclose(resumed, cont, rtol=1e-3, atol=1e-3)


def test_param_offload_gpt2_second_family():
    """The streaming protocol is not llama-shaped: GPT-2 (dropout, tied
    embeddings, LayerNorm blocks) trains under offload_param at loss parity
    with its in-HBM engine."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    cfg = GPT2Config(vocab_size=VOCAB, n_positions=T, n_embd=32, n_layer=3,
                     n_head=4)
    batches = _batches(3)

    def train(zero_extra):
        model = GPT2LMHeadModel(cfg)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config=_config(**zero_extra))
        losses = []
        for bt in batches:
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return engine, losses

    eng, streamed = train({"offload_param": {"device": "cpu"}})
    assert eng._param_store is not None
    assert eng._param_store.num_blocks == 3
    _, base = train({})
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)


def test_param_offload_mixtral_moe():
    """MoE under the param tier — the headline ZeRO-Infinity workload: every
    block's attention + ALL experts stream from host; loss (incl. router aux)
    tracks the in-HBM engine."""
    from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM

    cfg = MixtralConfig.tiny()
    batches = [{k: v[:, :16] if v.ndim == 2 else v for k, v in b.items()}
               for b in _batches(3)]
    batches = [{"input_ids": np.clip(b["input_ids"], 0, cfg.vocab_size - 1),
                "labels": np.clip(b["labels"], 0, cfg.vocab_size - 1)}
               for b in batches]

    def train(zero_extra):
        model = MixtralForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=_config(**zero_extra))
        losses = []
        for bt in batches:
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return engine, losses

    eng, streamed = train({"offload_param": {"device": "cpu"}})
    assert eng._param_store is not None
    assert eng._param_store.num_blocks == cfg.num_hidden_layers
    # expert weights are inside the streamed blocks, not device state
    assert not any(k.startswith("layers_") for k in eng.state.params)
    _, base = train({})
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)


@pytest.mark.parametrize("family", ["bloom", "opt"])
def test_param_offload_more_families(family):
    """BLOOM (ALiBi, tied head, embed layernorm) and OPT (learned positions,
    dropout) stream under the param tier at loss parity."""
    if family == "bloom":
        from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
        cfg = BloomConfig(vocab_size=VOCAB, hidden_size=32, num_hidden_layers=3,
                          num_attention_heads=4)
        model_cls = BloomForCausalLM
    else:
        from deepspeed_tpu.models.opt import OPTConfig, OPTForCausalLM
        cfg = OPTConfig(vocab_size=VOCAB, hidden_size=32, ffn_dim=64,
                        num_hidden_layers=3, num_attention_heads=4,
                        max_position_embeddings=T)
        model_cls = OPTForCausalLM
    batches = _batches(2)

    def train(zero_extra):
        model = model_cls(cfg)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=_config(**zero_extra))
        losses = []
        for bt in batches:
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return engine, losses

    eng, streamed = train({"offload_param": {"device": "cpu"}})
    assert eng._param_store is not None
    _, base = train({})
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)


def test_param_offload_universal_checkpoint_cross_tier():
    """Universal checkpoints are tier-independent: fragments saved from a
    streamed (Infinity) engine load into an in-HBM engine and vice versa —
    same canonical names, moments included — and training continues at
    parity (reference ds_to_universal promise at any topology)."""
    import tempfile
    batches = _batches(5)

    def make(zero_extra):
        model = _model()
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=_config(**zero_extra))
        return engine

    def steps(engine, bts):
        out = []
        for bt in bts:
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
            out.append(float(jax.device_get(loss)))
        return out

    # streamed -> universal -> in-HBM
    src = make({"offload_param": {"device": "cpu"}})
    steps(src, batches[:3])
    d = tempfile.mkdtemp()
    src.save_universal_checkpoint(d, tag="u")
    cont_src = steps(src, batches[3:])

    dst = make({})
    import os
    dst.load_universal_checkpoint(os.path.join(d, "u"))
    cont_dst = steps(dst, batches[3:])
    np.testing.assert_allclose(cont_dst, cont_src, rtol=2e-2, atol=2e-2)

    # in-HBM -> universal -> streamed
    src2 = make({})
    steps(src2, batches[:3])
    d2 = tempfile.mkdtemp()
    src2.save_universal_checkpoint(d2, tag="u")
    cont_src2 = steps(src2, batches[3:])
    dst2 = make({"offload_param": {"device": "cpu"}})
    dst2.load_universal_checkpoint(os.path.join(d2, "u"))
    cont_dst2 = steps(dst2, batches[3:])
    np.testing.assert_allclose(cont_dst2, cont_src2, rtol=2e-2, atol=2e-2)


def test_param_offload_eval_matches_train_params():
    """eval_batch streams through the same tier (logits path, no labels)."""
    eng, _ = _train(_config(offload_param={"device": "cpu"}), steps=2,
                    engine_out=True)
    batch = {"input_ids": _batches(1)[0]["input_ids"]}
    logits = eng.eval_batch(batch)
    assert logits.shape == (B, T, VOCAB)
    assert bool(np.isfinite(np.asarray(jax.device_get(logits))).all())


def test_param_offload_fp16_overflow_skip():
    """fp16 dynamic loss scaling under the param tier: a poisoned micro-step
    must skip BOTH tiers (device resident apply and host optimizer), halve
    the scale, and leave masters untouched."""
    model = _model()
    batches = _batches(2)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    cfg = _config(offload_param={"device": "cpu"})
    cfg["bf16"] = {"enabled": False}
    # hysteresis 1: the reference default of 2 absorbs the first overflow
    # without backing the scale off — this test wants the immediate drop
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=cfg)
    # one clean step to materialize state
    loss = engine(batches[0]); engine.backward(loss); engine.step()
    assert not bool(engine._last_stats.overflow)
    scale_before = engine.cur_scale
    store = engine._param_store
    masters_before = {k: v.copy() for k, v in store._opt.masters.items()}
    step_before = store.get_opt_step()

    # poison the host grad accumulator the way a bad batch would
    loss = engine(batches[1]); engine.backward(loss)
    store._grads[0][0] = np.inf
    engine.step()
    assert bool(engine._last_stats.overflow)
    assert engine.cur_scale < scale_before  # dynamic scale backed off
    for k, v in store._opt.masters.items():
        np.testing.assert_array_equal(v, masters_before[k])
    assert store.get_opt_step() == step_before
    assert all((g == 0).all() for g in store._grads)  # window discarded

    # recovery: the next window trains normally
    loss = engine(batches[0]); engine.backward(loss); engine.step()
    assert not bool(engine._last_stats.overflow)


def test_param_offload_parallel_block_families():
    """Falcon (parallel-attn+MLP block) through the shared ParallelBlock
    module — covers falcon/phi/gptj/gpt-neox streaming in one test."""
    from deepspeed_tpu.models.falcon import FalconForCausalLM, tiny_falcon_config

    cfg = tiny_falcon_config(num_hidden_layers=3)
    rng = np.random.RandomState(1)
    batches = [{"input_ids": rng.randint(0, cfg.vocab_size, (B, T)).astype(np.int32)}
               for _ in range(2)]
    for b in batches:
        b["labels"] = b["input_ids"]

    def train(zero_extra):
        model = FalconForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=_config(**zero_extra))
        losses = []
        for bt in batches:
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return engine, losses

    eng, streamed = train({"offload_param": {"device": "cpu"}})
    assert eng._param_store is not None
    assert eng._param_store.num_blocks == 3
    _, base = train({})
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)


def test_param_offload_bert_encoder():
    """The encoder family streams too: masked-LM training with an attention
    mask (broadcast through the streamed scan) at loss parity."""
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM

    cfg = BertConfig(vocab_size=VOCAB, hidden_size=32, intermediate_size=64,
                     num_hidden_layers=3, num_attention_heads=4,
                     max_position_embeddings=64)
    rng = np.random.RandomState(2)
    batches = []
    for _ in range(2):
        ids = rng.randint(0, VOCAB, (B, T)).astype(np.int32)
        labels = np.where(rng.rand(B, T) < 0.15, ids, -100).astype(np.int32)
        mask = np.ones((B, T), np.int32)
        mask[:, -3:] = 0  # padded tail
        batches.append({"input_ids": ids, "labels": labels,
                        "attention_mask": mask})

    def train(zero_extra):
        model = BertForMaskedLM(cfg)
        params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=_config(**zero_extra))
        losses = []
        for bt in batches:
            loss = engine(bt)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return engine, losses

    eng, streamed = train({"offload_param": {"device": "cpu"}})
    assert eng._param_store is not None
    _, base = train({})
    np.testing.assert_allclose(streamed, base, rtol=2e-2, atol=2e-2)
