"""Worker script for the two-process launcher smoke test.

Launched by ``deepspeed_tpu.launcher.runner`` in ``--launcher local`` mode:
consumes the env contract (MASTER_ADDR/PORT, RANK, WORLD_SIZE), forms a real
2-process JAX CPU cluster via ``dist.init_distributed``, runs a cross-process
collective, and writes a per-rank result file the test asserts on.
"""

import os
import sys

# cpu-only BEFORE any backend init: two workers grabbing the TPU would wedge it
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu import dist  # noqa: E402


def main():
    out_dir = sys.argv[1]
    dist.init_distributed()
    rank = dist.get_rank()
    world = dist.get_world_size()
    assert world == 2, f"expected 2 processes, got {world}"
    assert int(os.environ["WORLD_SIZE"]) == 2
    assert int(os.environ["RANK"]) == rank

    # cross-process collective over the global 2-device cpu mesh
    import numpy as np
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray([rank + 1.0]))
    total = float(gathered.sum())
    assert total == 3.0, f"allgather sum {total}"

    dist.barrier()
    with open(os.path.join(out_dir, f"rank{rank}.ok"), "w") as f:
        f.write(f"world={world} sum={total}\n")


if __name__ == "__main__":
    main()
