"""Correctness-guard tests (runtime/guards.py).

Reference coverage mirrored: the safe-mode/trace-invalidation behaviors of
``partitioned_param_coordinator`` (:149 non-static trace detection) and
``stage3.py:1249`` re-verification — translated to the jit failure classes:
donation audit, sharding drift, retrace storms, checkify NaN localization.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.runtime import guards as G
from tests.simple_model import SimpleModel, random_batches


def test_check_donation_reports_undonated():
    """Old-state leaves still alive after a "donating" call are reported —
    the silent copy-instead-of-alias perf bug class."""
    state = {"a": jnp.ones((8,)), "b": jnp.zeros((4,))}
    # non-donating call: every old leaf survives -> all flagged
    new = jax.jit(lambda s: jax.tree.map(lambda x: x + 1, s))(state)
    undonated, dead = G.check_donation(state, new)
    assert dead == []
    assert len(undonated) == 2

    # properly donated call: the runtime deletes the old leaves -> clean audit
    state2 = {"a": jnp.ones((8,)), "b": jnp.zeros((4,))}
    new2 = jax.jit(lambda s: jax.tree.map(lambda x: x + 1, s),
                   donate_argnums=(0,))(state2)
    undonated2, _ = G.check_donation(state2, new2)
    assert undonated2 == []


def test_check_donation_raises_on_dead_new_state():
    state = {"a": jnp.ones((8,))}
    new = {"a": jnp.ones((8,))}
    new["a"].delete()
    with pytest.raises(RuntimeError, match="deleted buffers"):
        G.check_donation(state, new)


def test_sharding_snapshot_detects_drift(eight_devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(eight_devices), ("dp",))
    sharded = jax.device_put(jnp.ones((8, 4)), NamedSharding(mesh, P("dp")))
    state = {"w": sharded}
    snap = G.ShardingSnapshot(state)
    assert snap.verify(state) == {}
    # a replicated reload of the same leaf = memory x8, numerics unchanged
    drifted = {"w": jax.device_put(np.ones((8, 4), np.float32),
                                   NamedSharding(mesh, P()))}
    report = snap.verify(drifted)
    assert "['w']" in report
    with pytest.raises(RuntimeError, match="sharding guard"):
        snap.verify(drifted, raise_on_drift=True)


def test_trace_guard_detects_retrace():
    calls = jax.jit(lambda x: x * 2)
    calls(jnp.ones((4,)))
    g = G.TraceStabilityGuard()
    g.record(step=calls)
    assert g.verify(step=calls) == {}
    calls(jnp.ones((5,)))  # new shape -> retrace
    grew = g.verify(step=calls)
    assert "step" in grew and grew["step"][1] > grew["step"][0]


def test_locate_nonfinite_names_the_op():
    def model_fn(params, batch, rng, training):
        h = batch["x"] @ params["w"]
        h = jnp.log(h)  # negative inputs -> nan HERE
        return h.sum()

    params = {"w": jnp.ones((4, 4))}
    bad = {"x": -jnp.ones((2, 4))}
    report = G.locate_nonfinite(model_fn, params, bad)
    assert report is not None and "nan" in report.lower()
    ok = {"x": jnp.ones((2, 4))}
    assert G.locate_nonfinite(model_fn, params, ok) is None


def test_nonfinite_leaves():
    tree = {"good": jnp.ones((3,)), "bad": jnp.array([1.0, np.inf]),
            "ints": jnp.arange(3)}
    bad = G.nonfinite_leaves(tree)
    assert bad == ["['bad']"]


def test_engine_guards_run_clean():
    """correctness_guards enabled: snapshot is captured at the first boundary
    and verification runs every boundary without tripping on a clean run."""
    batches = random_batches(3, batch_size=8, seed=11)
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "correctness_guards": {"enabled": True, "check_every": 1},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    assert engine._guards["snapshot"] is not None
    assert engine._guards["snapshot"].verify(engine.state) == {}


def test_engine_overflow_localization_fp16():
    """A poisoned batch under fp16 trips the loss scaler; with guards on, the
    overflow is re-verified under checkify and localized to a source op."""
    batches = random_batches(1, batch_size=8, seed=12)
    model = SimpleModel(hidden_dim=16)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "fp16": {"enabled": True, "initial_scale_power": 4},
                "correctness_guards": {"enabled": True},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    bad = {k: np.array(v, dtype=np.float32, copy=True) if v.dtype.kind == "f"
           else v for k, v in batches[0].items()}
    bad["x"][0, 0] = np.inf
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    assert bool(engine._last_stats.overflow)
    report = getattr(engine, "_last_overflow_report", None)
    assert report is not None
    assert "inf" in report.lower() or "nan" in report.lower()
