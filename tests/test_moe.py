"""MoE tests (mirrors reference ``tests/unit/moe/test_moe.py``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn

from deepspeed_tpu.moe.sharded_moe import top1gating, topkgating, MOELayer, TopKGate
from deepspeed_tpu.moe.layer import MoE


class ExpertMLP(nn.Module):
    hidden: int = 32
    d_model: int = 16

    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.d_model)(h)


def test_top1gating_shapes_and_capacity():
    logits = jax.random.normal(jax.random.PRNGKey(0), (32, 4))
    l_aux, combine, dispatch, counts = top1gating(logits, capacity_factor=1.0, min_capacity=4)
    S, E, C = combine.shape
    assert (S, E) == (32, 4)
    assert C == max(int(32 / 4 * 1.0), 4)
    # every dispatched token has exactly one (expert, slot)
    assert dispatch.sum(axis=(1, 2)).max() <= 1
    # no slot is double-booked
    assert dispatch.astype(np.int32).sum(axis=0).max() <= 1
    assert float(l_aux) > 0
    assert counts.sum() <= 32


def test_top1gating_capacity_drops():
    # all tokens to expert 0 -> only `capacity` survive
    logits = jnp.zeros((16, 4)).at[:, 0].set(10.0)
    l_aux, combine, dispatch, counts = top1gating(logits, capacity_factor=1.0, min_capacity=4)
    # exp_counts is PRE-drop routing (reference semantics): overflow observable
    assert int(counts[0]) == 16
    # but only `capacity` = max(16/4, 4) = 4 slots are actually dispatched
    assert int(dispatch.sum()) == 4


def test_topk_gating_two_choices():
    logits = jax.random.normal(jax.random.PRNGKey(1), (64, 8))
    l_aux, combine, dispatch, counts = topkgating(logits, k=2, capacity_factor=2.0)
    # each token dispatched to at most 2 slots
    per_token = dispatch.sum(axis=(1, 2))
    assert per_token.max() <= 2
    # combine weights per token sum to ~1 (normalized) for fully-kept tokens
    w = combine.sum(axis=(1, 2))
    kept = per_token == 2
    np.testing.assert_allclose(np.asarray(w)[np.asarray(kept)], 1.0, rtol=1e-4)


def test_moe_layer_forward_and_grads():
    model = MOELayer(lambda: ExpertMLP(), num_experts=4, k=1, capacity_factor=2.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    (out, l_aux, counts) = model.apply({"params": params}, x)
    assert out.shape == x.shape
    assert np.isfinite(float(l_aux))

    def loss_fn(p):
        o, la, _ = model.apply({"params": p}, x)
        return (o ** 2).mean() + 0.01 * la

    grads = jax.grad(loss_fn)(params)
    gate_g = jax.tree.leaves(grads["gate"])
    assert all(np.isfinite(np.asarray(g)).all() for g in gate_g)
    # expert params stacked on leading expert axis
    expert_kernel = jax.tree.leaves(params["experts"])[0]
    assert expert_kernel.shape[0] == 4


def test_moe_module_residual():
    model = MoE(hidden_size=16, expert_factory=lambda: ExpertMLP(), num_experts=4,
                use_residual=True)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 16))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    out, l_aux, counts = model.apply({"params": params}, x)
    assert out.shape == x.shape
    assert "coefficient" in params


def test_moe_ep_sharded_training(eight_devices):
    """MoE model trains under the engine with experts sharded over ep axis."""
    import deepspeed_tpu
    from deepspeed_tpu.moe.utils import moe_param_specs

    class MoEModel(nn.Module):
        @nn.compact
        def __call__(self, batch, deterministic=True):
            x = batch["x"]
            h = nn.Dense(16)(x)
            out, l_aux, _ = MoE(hidden_size=16,
                                expert_factory=lambda: ExpertMLP(d_model=16),
                                num_experts=4, k=1, capacity_factor=2.0,
                                name="moe")(h, train=not deterministic)
            pred = nn.Dense(4)(out)
            return jnp.mean((pred - batch["y"]) ** 2) + 0.01 * l_aux

    rng = np.random.default_rng(0)
    def batch(i):
        r = np.random.default_rng(i)
        x = r.normal(size=(16, 16)).astype(np.float32)
        return {"x": x, "y": (x[:, :4] * 2).astype(np.float32)}

    model = MoEModel()
    params = model.init(jax.random.PRNGKey(0), batch(0))["params"]
    specs = moe_param_specs(params)
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine
    engine = DeepSpeedEngine(
        model=model, model_parameters=params, param_specs=specs,
        config={"train_batch_size": 16,
                "expert_parallel_size": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1}},
    )
    # expert leaves must actually be ep-sharded via the MoE specs
    from jax.sharding import PartitionSpec as P
    ek = engine.state.params["moe"]["deepspeed_moe"]["experts"]["ExpertMLP_0"]["Dense_0"]["kernel"]
    assert "ep" in jax.tree_util.tree_leaves(
        [ek.sharding.spec], is_leaf=lambda x: isinstance(x, P))[0][0], ek.sharding.spec
    losses = []
    for i in range(15):
        loss = engine(batch(i))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_moe_ep_parity_with_dense_dispatch(eight_devices):
    """Expert-parallel einsum dispatch must equal a per-token dense compute."""
    model = MOELayer(lambda: ExpertMLP(), num_experts=4, k=1, capacity_factor=100.0,
                     min_capacity=64)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 16, 16))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    out, _, counts = model.apply({"params": params}, x)
    # with huge capacity nothing drops: every token routed
    assert int(np.asarray(counts).sum()) == 16

    # manual reference: per-token argmax expert, apply that expert's MLP, scale by gate
    xf = x.reshape(-1, 16)
    wg = np.asarray(params["gate"]["wg"])
    logits = xf @ wg
    gates = jax.nn.softmax(logits, axis=-1)
    choice = np.argmax(np.asarray(logits), axis=-1)
    ek = params["experts"]["ExpertMLP_0"]
    ref = []
    for s in range(16):
        e = int(choice[s])
        h = np.maximum(np.asarray(xf[s]) @ np.asarray(ek["Dense_0"]["kernel"][e]) +
                       np.asarray(ek["Dense_0"]["bias"][e]), 0)
        o = h @ np.asarray(ek["Dense_1"]["kernel"][e]) + np.asarray(ek["Dense_1"]["bias"][e])
        ref.append(o * float(gates[s, e]))
    np.testing.assert_allclose(np.asarray(out).reshape(-1, 16), np.stack(ref),
                               rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# routed (indices) dispatch vs GShard einsum oracle (VERDICT r2 #4)
# ---------------------------------------------------------------------------

def _moe_pair(k, num_experts=4, capacity_factor=2.0, drop_tokens=True):
    mk = lambda mode: MOELayer(lambda: ExpertMLP(), num_experts=num_experts,
                               k=k, capacity_factor=capacity_factor,
                               drop_tokens=drop_tokens, dispatch_mode=mode)
    return mk("indices"), mk("einsum")


@pytest.mark.parametrize("k", [1, 2])
def test_indices_dispatch_matches_einsum(k):
    import numpy as np
    routed, dense = _moe_pair(k)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16))
    params = routed.init(jax.random.PRNGKey(1), x)["params"]
    out_r, laux_r, cnt_r = routed.apply({"params": params}, x)
    out_d, laux_d, cnt_d = dense.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(float(laux_r), float(laux_d), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(cnt_r), np.asarray(cnt_d))


def test_indices_dispatch_matches_einsum_with_drops():
    import numpy as np
    routed, dense = _moe_pair(k=2, capacity_factor=0.5)  # force drops
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, 16))
    params = routed.init(jax.random.PRNGKey(4), x)["params"]
    out_r, *_ = routed.apply({"params": params}, x)
    out_d, *_ = dense.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_d),
                               atol=1e-5, rtol=1e-5)


def test_indices_dispatch_gradients_match_einsum():
    import numpy as np
    routed, dense = _moe_pair(k=2)
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 16, 16))
    params = routed.init(jax.random.PRNGKey(6), x)["params"]

    def loss(mdl):
        def f(p, xx):
            out, laux, _ = mdl.apply({"params": p}, xx)
            return jnp.sum(out ** 2) + 0.01 * laux
        return f

    gr = jax.grad(loss(routed))(params, x)
    gd = jax.grad(loss(dense))(params, x)
    flat_r = jax.tree_util.tree_leaves(gr)
    flat_d = jax.tree_util.tree_leaves(gd)
    for a, b in zip(flat_r, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_indices_dispatch_no_dense_sec_tensor_ep2():
    """The ep>1 sharded lowering must not contain the dense [S, E, C]
    dispatch tensor (VERDICT r2 #4 done-criterion): trace through the real
    process-group topology (ep=2) so expert params carry their ep sharding."""
    import numpy as np
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.topology import MeshTopology

    E, k = 4, 2
    S_tokens = 2 * 16
    routed, dense = _moe_pair(k, num_experts=E)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 16))
    params = routed.init(jax.random.PRNGKey(1), x)["params"]
    topo = MeshTopology(dp=-1, ep=2)
    groups.initialize(mesh_topology=topo)
    try:
        def run(mdl):
            def f(p, xx):
                out, laux, _ = mdl.apply({"params": p}, xx)
                return jnp.sum(out) + laux
            # lower with sharded operands: x over the data axes, expert
            # params over ep (stacked axis 0), everything else replicated
            x_sh = jax.device_put(x, topo.sharding("ep", None, None))
            p_sh = jax.tree_util.tree_map_with_path(
                lambda path, l: jax.device_put(
                    l, topo.sharding("ep", *([None] * (l.ndim - 1)))
                    if "experts" in jax.tree_util.keystr(path)
                    and l.shape[0] == E else topo.replicated()),
                params)
            return jax.jit(f).lower(p_sh, x_sh).as_text()

        cap = int(np.ceil(S_tokens * k / E) * 2.0)  # capacity_factor=2.0
        dense_shape = f"tensor<{S_tokens}x{E}x{cap}xf32>"
        assert dense_shape in run(dense), "oracle lowering should carry [S,E,C]"
        assert dense_shape not in run(routed), \
            f"routed lowering still materializes the dense {dense_shape} dispatch"
    finally:
        groups.reset()


# ---------------------------------------------------------------------------
# megablox grouped-GEMM training backend (VERDICT r2 #4 "call grouped_gemm")
# ---------------------------------------------------------------------------

class GmmExpertMLP(nn.Module):
    """Gated MLP matching the gmm contract (128-aligned dims)."""
    hidden: int = 128
    d_model: int = 128
    GMM_COMPAT = ("w1", "w3", "w2")

    def gmm_shapes(self, d_model):
        return {"w1": (d_model, self.hidden), "w3": (d_model, self.hidden),
                "w2": (self.hidden, d_model)}

    @nn.compact
    def __call__(self, x):
        dense = lambda f, nm: nn.Dense(f, use_bias=False, name=nm)
        return dense(self.d_model, "w2")(
            nn.silu(dense(self.hidden, "w1")(x)) * dense(self.hidden, "w3")(x))


@pytest.mark.parametrize("k", [1, 2])
def test_gmm_backend_matches_indices(k):
    mk = lambda mode: MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=k,
                               capacity_factor=100.0, dispatch_mode=mode)
    gmm, routed = mk("gmm"), mk("indices")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 128))
    params = gmm.init(jax.random.PRNGKey(1), x)["params"]
    # identical param structure -> the vmap/indices path runs the SAME params
    out_g, laux_g, cnt_g = gmm.apply({"params": params}, x)
    out_r, laux_r, cnt_r = routed.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_r),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(float(laux_g), float(laux_r), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(cnt_g), np.asarray(cnt_r))


def test_gmm_backend_gradients_match():
    mk = lambda mode: MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=2,
                               capacity_factor=100.0, dispatch_mode=mode)
    gmm, routed = mk("gmm"), mk("indices")
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 128))
    params = gmm.init(jax.random.PRNGKey(3), x)["params"]

    def loss(mdl):
        def f(p, xx):
            out, laux, _ = mdl.apply({"params": p}, xx)
            return jnp.sum(out ** 2) + 0.01 * laux
        return f

    gg = jax.grad(loss(gmm))(params, x)
    gr = jax.grad(loss(routed))(params, x)
    for a, b in zip(jax.tree_util.tree_leaves(gg),
                    jax.tree_util.tree_leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_gmm_backend_param_tree_matches_vmap():
    """gmm creates kernels at vmap-identical paths (checkpoint/HF compat)."""
    mk = lambda mode: MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=1,
                               dispatch_mode=mode)
    x = jnp.zeros((1, 8, 128))
    pg = mk("gmm").init(jax.random.PRNGKey(0), x)["params"]
    pv = mk("indices").init(jax.random.PRNGKey(0), x)["params"]
    sg = jax.tree_util.tree_structure(pg)
    sv = jax.tree_util.tree_structure(pv)
    assert sg == sv, f"{sg} != {sv}"
    for a, b in zip(jax.tree_util.tree_leaves(pg),
                    jax.tree_util.tree_leaves(pv)):
        assert a.shape == b.shape


def test_gmm_backend_rejects_incompatible_expert():
    layer = MOELayer(lambda: ExpertMLP(), num_experts=4, dispatch_mode="gmm")
    x = jnp.zeros((1, 8, 16))
    with pytest.raises(ValueError, match="gated-MLP"):
        layer.init(jax.random.PRNGKey(0), x)


def test_mixtral_gmm_backend_forward_parity():
    """Mixtral with moe_backend='gmm' matches the default backend on the
    same params (128-aligned tiny config)."""
    from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
    base = dict(vocab_size=256, hidden_size=128, intermediate_size=128,
                num_hidden_layers=1, num_attention_heads=4,
                num_key_value_heads=2, num_local_experts=4,
                max_position_embeddings=64, dtype=jnp.float32)
    m_v = MixtralForCausalLM(MixtralConfig(**base))
    m_g = MixtralForCausalLM(MixtralConfig(**base, moe_backend="gmm"))
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % 256
    params = m_v.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    out_v = m_v.apply({"params": params}, {"input_ids": ids})
    out_g = m_g.apply({"params": params}, {"input_ids": ids})
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_v),
                               atol=3e-4, rtol=3e-4)


def test_gmm_backend_rejects_tp_mesh():
    """gmm must refuse tp meshes instead of silently all-gathering the
    expert stacks (review r3 finding). ep meshes now COMPOSE through the
    explicit dispatch/combine all-to-all (ISSUE 15 dropless path) — only
    tp remains incompatible."""
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.topology import MeshTopology
    groups.initialize(mesh_topology=MeshTopology(dp=-1, tp=2))
    try:
        layer = MOELayer(lambda: GmmExpertMLP(), num_experts=4,
                         dispatch_mode="gmm")
        x = jnp.zeros((1, 8, 128))
        with pytest.raises(ValueError, match="does not compose"):
            layer.init(jax.random.PRNGKey(0), x)
    finally:
        groups.reset()


# ---------------------------------------------------------------------------
# dropless routing + expert-parallel a2a (ISSUE 15)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("k", [1, 2])
def test_dropless_gmm_matches_dense_all_experts(k):
    """drop_tokens=False consults no capacity at all (capacity_factor=inf
    semantics): the grouped-GEMM path must match the dense all-experts
    einsum formulation on the same params, with every routed choice kept."""
    mk = lambda mode: MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=k,
                               drop_tokens=False, dispatch_mode=mode)
    gmm, dense = mk("gmm"), mk("einsum")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 128))
    params = gmm.init(jax.random.PRNGKey(1), x)["params"]
    out_g, laux_g, cnt_g = gmm.apply({"params": params}, x)
    out_d, laux_d, cnt_d = dense.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_d),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(float(laux_g), float(laux_d), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(cnt_g), np.asarray(cnt_d))
    # dropless: every (token, choice) pair survives
    assert int(np.asarray(cnt_g).sum()) == 2 * 16 * k


def test_dropless_skewed_batch_drops_nothing():
    """Adversarial skew (every token's top choice is expert 0): the drop
    path sheds to capacity, the dropless path keeps all — and still matches
    the dense reference."""
    mk = lambda mode, drop: MOELayer(lambda: GmmExpertMLP(), num_experts=4,
                                     k=1, drop_tokens=drop,
                                     dispatch_mode=mode)
    # strictly positive tokens + a gate that weights only expert 0's
    # column: every token's logits are (positive, 0, 0, 0) -> expert 0
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (1, 32, 128))) + 0.1
    params = mk("gmm", False).init(jax.random.PRNGKey(3), x)["params"]
    params["gate"]["wg"] = jnp.zeros_like(
        params["gate"]["wg"]).at[:, 0].set(10.0)
    out_g, _, cnt = mk("gmm", False).apply({"params": params}, x)
    out_d, _, _ = mk("einsum", False).apply({"params": params}, x)
    assert int(np.asarray(cnt)[0]) == 32  # all 32 routed to expert 0, kept
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_d),
                               atol=2e-5, rtol=2e-5)
    # the drop path on the same batch sheds to capacity — the contrast
    # dropless removes
    _, _, cnt_drop = mk("einsum", True).apply({"params": params}, x)
    assert int(np.asarray(cnt_drop)[0]) == 32  # exp_counts stays PRE-drop


@pytest.mark.parametrize("k", [1, 2])
def test_dropless_aux_loss_matches_drop_path_under_capacity(k):
    """topk_routing's aux loss uses PRE-drop counts by design, so on an
    under-capacity batch (nothing would drop) the drop and dropless paths
    must produce IDENTICAL aux loss, router counts, and outputs."""
    mk = lambda drop: MOELayer(lambda: ExpertMLP(), num_experts=4, k=k,
                               capacity_factor=100.0, min_capacity=64,
                               drop_tokens=drop)
    drop, dropless = mk(True), mk(False)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 16, 16))
    params = drop.init(jax.random.PRNGKey(5), x)["params"]
    out_a, laux_a, cnt_a = drop.apply({"params": params}, x)
    out_b, laux_b, cnt_b = dropless.apply({"params": params}, x)
    assert float(laux_a) == float(laux_b)  # bit-identical by construction
    np.testing.assert_array_equal(np.asarray(cnt_a), np.asarray(cnt_b))
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b),
                               atol=1e-6, rtol=1e-6)


def test_dropless_training_trajectory_matches_drop_path():
    """10 SGD steps on an under-capacity batch: the dropless loss
    trajectory tracks the drop path within 1e-5 (ISSUE 15 acceptance)."""
    def run(drop_tokens):
        model = MOELayer(lambda: ExpertMLP(), num_experts=4, k=2,
                         capacity_factor=100.0, min_capacity=64,
                         drop_tokens=drop_tokens)
        x = jax.random.normal(jax.random.PRNGKey(6), (2, 16, 16))
        y = jax.random.normal(jax.random.PRNGKey(7), (2, 16, 16))
        params = model.init(jax.random.PRNGKey(8), x)["params"]

        def loss_fn(p):
            out, laux, _ = model.apply({"params": p}, x)
            return jnp.mean((out - y) ** 2) + 0.01 * laux

        losses = []
        for _ in range(10):
            loss, grads = jax.value_and_grad(loss_fn)(params)
            params = jax.tree.map(lambda p, g: p - 0.1 * g, params, grads)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(True), run(False), atol=1e-5, rtol=0)


def test_gmm_ep_dropless_matches_single_host(eight_devices):
    """The expert-parallel dispatch/combine a2a round-trip (ep=2) must
    reproduce the single-host grouped-GEMM result on the same params."""
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.topology import MeshTopology

    layer = MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=2,
                     drop_tokens=False, dispatch_mode="gmm")
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 128))
    params = layer.init(jax.random.PRNGKey(1), x)["params"]
    out_ref, laux_ref, cnt_ref = layer.apply({"params": params}, x)
    groups.initialize(mesh_topology=MeshTopology(dp=-1, ep=2))
    try:
        out_ep, laux_ep, cnt_ep = layer.apply({"params": params}, x)
    finally:
        groups.reset()
    np.testing.assert_allclose(np.asarray(out_ep), np.asarray(out_ref),
                               atol=1e-6, rtol=1e-6)
    np.testing.assert_allclose(float(laux_ep), float(laux_ref), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(cnt_ep), np.asarray(cnt_ref))


def test_gmm_ep_gradients_flow(eight_devices):
    """bits=None keeps the ep round-trip differentiable end to end: grads
    under the ep mesh match the single-host grads."""
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.topology import MeshTopology

    layer = MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=2,
                     drop_tokens=False, dispatch_mode="gmm")
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 128))
    params = layer.init(jax.random.PRNGKey(3), x)["params"]

    def loss_fn(p):
        out, laux, _ = layer.apply({"params": p}, x)
        return jnp.sum(out ** 2) + 0.01 * laux

    g_ref = jax.grad(loss_fn)(params)
    groups.initialize(mesh_topology=MeshTopology(dp=-1, ep=2))
    try:
        g_ep = jax.grad(loss_fn)(params)
    finally:
        groups.reset()
    for a, b in zip(jax.tree_util.tree_leaves(g_ep),
                    jax.tree_util.tree_leaves(g_ref)):
        assert np.isfinite(np.asarray(a)).all()
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_gmm_ep_quantized_wire_records_telemetry(eight_devices):
    """a2a_wire_bits=8 ships the int8+scales wire: output stays close to
    the fp result and the dispatch/combine wire bytes land in telemetry at
    ~0.25x the logical payload."""
    from deepspeed_tpu import telemetry
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.parallel.topology import MeshTopology

    mk = lambda bits: MOELayer(lambda: GmmExpertMLP(), num_experts=4, k=2,
                               drop_tokens=False, dispatch_mode="gmm",
                               a2a_wire_bits=bits)
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 16, 128))
    params = mk(None).init(jax.random.PRNGKey(5), x)["params"]
    groups.initialize(mesh_topology=MeshTopology(dp=-1, ep=2))
    telemetry.configure(enabled=True)
    telemetry.reset()
    try:
        out_fp, _, _ = mk(None).apply({"params": params}, x)
        out_q, _, _ = mk(8).apply({"params": params}, x)
        summ = telemetry.summary()
    finally:
        telemetry.configure(enabled=False)
        telemetry.reset()
        groups.reset()
    np.testing.assert_allclose(np.asarray(out_q), np.asarray(out_fp),
                               atol=0.05, rtol=0.05)
    ops = summ["comm"]["ops"]
    for op in ("a2a_dispatch", "a2a_combine"):
        st = ops[op]["ep"]
        assert st["bytes"] > 0
        # fp pass records wire==bytes; the int8 pass adds ~0.25x — combined
        # ratio over both passes lands well under 1
        assert st["wire_bytes"] < st["bytes"]


def test_moe_utils_reference_surface():
    """has_moe_layers / split / group helpers (reference moe/utils.py)."""
    from deepspeed_tpu.moe.utils import (configure_moe_param_groups,
                                         has_moe_layers, is_moe_param,
                                         is_moe_param_group,
                                         split_params_into_shared_and_expert_params)
    model = MOELayer(lambda: ExpertMLP(), num_experts=4, k=1)
    x = jnp.zeros((1, 8, 16))
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    found, n = has_moe_layers(params)
    assert found and n > 0
    shared, expert = split_params_into_shared_and_expert_params(params)
    assert expert and shared  # gate wg is shared; expert kernels are expert
    assert all(is_moe_param(k) for k in expert)
    groups = configure_moe_param_groups(params)
    assert len(groups) == 2
    assert not is_moe_param_group(groups[0]) and is_moe_param_group(groups[1])
    dense_only = {"dense": {"kernel": jnp.zeros((4, 4))}}
    assert has_moe_layers(dense_only) == (False, 0)
    assert len(configure_moe_param_groups(dense_only)) == 1
