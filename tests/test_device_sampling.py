"""On-device serving sampler (`inference/v2/sampling.py`): the jitted
temperature/top-k/top-p + categorical draw must honor the same contract as
the host sampler it replaces (greedy at temp 0, support restricted to the
top-k/top-p set, deterministic per (seed, position)), and the scheduler's
device path must agree with the host path on greedy decodes."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.sampling import sample_rows


def _rows(v=97, s=4, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=(s, v)).astype(np.float32))


def _call(logits, temps, top_ks, top_ps, seeds, positions):
    return np.asarray(sample_rows(
        logits, jnp.asarray(temps, jnp.float32),
        jnp.asarray(top_ks, jnp.int32), jnp.asarray(top_ps, jnp.float32),
        jnp.asarray(seeds, jnp.int32), jnp.asarray(positions, jnp.int32)))


def test_greedy_rows_are_argmax():
    logits = _rows()
    ids = _call(logits, [0.0] * 4, [0] * 4, [1.0] * 4, [1, 2, 3, 4],
                [0, 1, 2, 3])
    np.testing.assert_array_equal(ids, np.argmax(np.asarray(logits), -1))


def test_top_k_one_is_argmax_at_any_temperature():
    logits = _rows(seed=1)
    ids = _call(logits, [5.0] * 4, [1] * 4, [1.0] * 4, [7] * 4, [0] * 4)
    np.testing.assert_array_equal(ids, np.argmax(np.asarray(logits), -1))


def test_top_k_restricts_support():
    logits = _rows(s=1, seed=2)
    top5 = set(np.argsort(np.asarray(logits)[0])[::-1][:5].tolist())
    for seed in range(40):
        ids = _call(logits, [2.0], [5], [1.0], [seed], [0])
        assert int(ids[0]) in top5, f"seed {seed} escaped the top-5 set"


def test_tiny_top_p_is_argmax():
    logits = _rows(seed=3)
    ids = _call(logits, [3.0] * 4, [0] * 4, [1e-6] * 4, [9, 8, 7, 6],
                [0] * 4)
    np.testing.assert_array_equal(ids, np.argmax(np.asarray(logits), -1))


def test_top_p_restricts_support():
    """Sampled ids must come from the smallest prefix reaching top_p mass."""
    logits = _rows(s=1, seed=4)
    temp = 1.5
    scaled = np.asarray(logits)[0] / temp
    order = np.argsort(scaled)[::-1]
    probs = np.exp(scaled[order] - scaled[order][0])
    probs /= probs.sum()
    cutoff_idx = int(np.sum(np.cumsum(probs) < 0.5))
    allowed = set(order[:cutoff_idx + 1].tolist())
    for seed in range(40):
        ids = _call(logits, [temp], [0], [0.5], [seed], [0])
        assert int(ids[0]) in allowed, f"seed {seed} escaped the top-p set"


def test_deterministic_per_seed_and_position():
    logits = _rows(seed=5)
    a = _call(logits, [1.0] * 4, [0] * 4, [1.0] * 4, [11, 12, 13, 14],
              [0, 1, 2, 3])
    b = _call(logits, [1.0] * 4, [0] * 4, [1.0] * 4, [11, 12, 13, 14],
              [0, 1, 2, 3])
    np.testing.assert_array_equal(a, b)
    # position changes the draw stream: across 16 positions x 4 rows at
    # temperature 1 over 97 logits, at least one draw must differ
    draws = [_call(logits, [1.0] * 4, [0] * 4, [1.0] * 4, [11, 12, 13, 14],
                   [p] * 4) for p in range(16)]
    assert any(not np.array_equal(draws[0], d) for d in draws[1:]), \
        "position did not perturb the sampling stream"


def test_rows_independent_of_batch_composition():
    """Row i's draw depends only on (its logits, its params) — the contract
    that lets the scheduler fuse arbitrary request mixes into one batch."""
    logits = _rows(s=4, seed=6)
    batch = _call(logits, [0.9, 0.0, 1.7, 1.0], [5, 0, 0, 3],
                  [1.0, 1.0, 0.7, 1.0], [21, 22, 23, 24], [0, 4, 9, 2])
    for i in range(4):
        solo = _call(logits[i:i + 1], [[0.9, 0.0, 1.7, 1.0][i]],
                     [[5, 0, 0, 3][i]], [[1.0, 1.0, 0.7, 1.0][i]],
                     [[21, 22, 23, 24][i]], [[0, 4, 9, 2][i]])
        assert int(solo[0]) == int(batch[i])


@pytest.fixture(scope="module")
def served():
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def _make_sched(served, device_sampling):
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
    cfg, model, params = served
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 16,
                          "max_context": 128,
                          "num_kv_blocks": 64},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})
    return SplitFuseScheduler(engine, token_budget=16,
                              device_sampling=device_sampling)


def test_scheduler_greedy_device_matches_host(served):
    cfg, _, _ = served
    prompt = np.random.default_rng(10).integers(
        0, cfg.vocab_size, 23).astype(np.int32)
    outs = []
    for dev in (True, False):
        sched = _make_sched(served, device_sampling=dev)
        sched.submit(0, prompt, max_new_tokens=6)
        outs.append(sched.run_to_completion()[0].tolist())
    assert outs[0] == outs[1], (
        f"device greedy {outs[0]} != host greedy {outs[1]}")


def test_scheduler_sampled_device_reproducible(served):
    cfg, _, _ = served
    prompt = np.random.default_rng(11).integers(
        0, cfg.vocab_size, 9).astype(np.int32)

    def run(seed):
        sched = _make_sched(served, device_sampling=True)
        sched.submit(0, prompt, max_new_tokens=5, temperature=0.8,
                     top_k=20, seed=seed)
        return sched.run_to_completion()[0].tolist()

    assert run(123) == run(123), "same seed must reproduce on device"
