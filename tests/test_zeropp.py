"""ZeRO++ (qwZ/qgZ/hpZ) + MiCS tests — mirrors reference
``tests/unit/runtime/zero/test_zeropp.py`` coverage plus quantizer numerics
(``tests/unit/ops/quantizer``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

import deepspeed_tpu
from deepspeed_tpu.ops.quantizer import (dequantize, dequantize_lastdim,
                                         quantize, quantize_lastdim)
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.comm.coalesced_collectives import (
    all_to_all_quant_reduce, quantized_all_gather, reduce_scatter_coalesced)
from tests.simple_model import SimpleModel, random_batches


# ---------------------------------------------------------------- quantizer

@pytest.mark.parametrize("bits,rtol", [(8, 1e-2), (4, 2e-1)])
def test_quantize_roundtrip(bits, rtol):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(333, 17)).astype(np.float32))
    q, s = quantize(x, num_bits=bits, group_size=256)
    if bits == 4:
        assert q.dtype == jnp.uint8 and q.size == ((x.size + 255) // 256 * 256) // 2
    else:
        assert q.dtype == jnp.int8
    back = dequantize(q, s, x.shape, num_bits=bits, group_size=256)
    err = np.abs(np.asarray(back - x))
    scale_bound = np.asarray(s).max() * (0.5 if bits == 8 else 0.6)
    assert err.max() <= scale_bound + 1e-6


def test_quantize_lastdim_roundtrip():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(64, 130)).astype(np.float32))  # pad path
    q, s = quantize_lastdim(x, group_size=64)
    assert q.shape == x.shape and q.dtype == jnp.int8
    back = dequantize_lastdim(q, s, group_size=64)
    assert np.abs(np.asarray(back - x)).max() < np.abs(np.asarray(x)).max() / 64


# ---------------------------------------------------------------- collectives

def _mesh2d(eight_devices):
    """4 replica groups x 2-wide shard groups."""
    import numpy as np
    dev = np.asarray(eight_devices).reshape(4, 2)
    return jax.sharding.Mesh(dev, ("dpr", "dp"))


def test_quantized_all_gather(eight_devices):
    mesh = _mesh2d(eight_devices)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))

    f = shard_map(lambda s: quantized_all_gather(s, "dp", group_size=64),
                  mesh=mesh, in_specs=P("dp"), out_specs=P(),
                  check_vma=False)
    out = f(x)
    assert out.shape == x.shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=0.15, rtol=0.05)


def test_all_to_all_quant_reduce_single_axis(eight_devices):
    mesh = _mesh2d(eight_devices)
    rng = np.random.default_rng(3)
    # each dp-group rank holds a distinct full gradient; dpr groups identical
    g_local = rng.normal(size=(2, 64)).astype(np.float32)

    def body(g):
        return all_to_all_quant_reduce(g[0], intra_axis="dp", intra_bits=8,
                                       group_size=32)

    f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                  check_vma=False)
    out = f(jnp.asarray(g_local))  # [2*32] concat of per-rank shards
    expected = g_local.sum(axis=0)
    np.testing.assert_allclose(np.asarray(out).reshape(-1), expected,
                               atol=0.1, rtol=0.05)


def test_all_to_all_quant_reduce_hierarchical(eight_devices):
    mesh = _mesh2d(eight_devices)
    rng = np.random.default_rng(4)
    n = 128
    g_all = rng.normal(size=(4, 2, n)).astype(np.float32)  # [dpr, dp, n]

    def body(g):
        # g: [1, 1, n] local block
        return all_to_all_quant_reduce(g[0, 0], intra_axis="dp",
                                       inter_axis="dpr", intra_bits=4,
                                       inter_bits=8, group_size=32)[None, None]

    f = shard_map(body, mesh=mesh, in_specs=P("dpr", "dp"),
                  out_specs=P("dpr", "dp"), check_vma=False)
    out = np.asarray(f(jnp.asarray(g_all)))  # [4, 2, shard]
    total = g_all.sum(axis=(0, 1))
    shard = n // 8
    # chunk layout: index = intra_idx * inter + inter_idx (see qgZ docstring)
    for e in range(4):      # dpr coord
        for i in range(2):  # dp coord
            c = i * 4 + e
            # int4 stage-1 + int8 stage-2 is lossy by design; bound the error
            # by a few stage-1 quantization steps
            np.testing.assert_allclose(
                out[e, i], total[c * shard:(c + 1) * shard],
                atol=1.0, rtol=0.1)


def test_reduce_scatter_coalesced(eight_devices):
    mesh = _mesh2d(eight_devices)
    rng = np.random.default_rng(5)
    a = rng.normal(size=(2, 64)).astype(np.float32)
    b = rng.normal(size=(2, 30)).astype(np.float32)  # padded path

    def body(a, b):
        ra, rb = reduce_scatter_coalesced([a[0], b[0]], axis_name="dp")
        return ra[None], rb[None]

    f = shard_map(body, mesh=mesh, in_specs=(P("dp"), P("dp")),
                  out_specs=(P("dp"), P("dp")), check_vma=False)
    ra, rb = f(jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(ra).reshape(-1), a.sum(0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(rb).reshape(-1)[:30], b.sum(0),
                               rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------- topology

def test_hierarchical_topology(eight_devices):
    t = MeshTopology(zero_shard_size=2, zero_hierarchy="hpz")
    assert t.dpr_size == 4 and t.dp_size == 2
    assert t.zero_axes == ("dpr", "dp", "ep", "sp")
    assert t.param_zero_axes == ("dp", "ep", "sp")
    assert t.data_parallel_size == 8

    t2 = MeshTopology(zero_shard_size=2, zero_hierarchy="mics")
    assert t2.zero_axes == ("dp", "ep", "sp")


# ---------------------------------------------------------------- engine

def _train(config, steps=3, seed=0):
    model = SimpleModel(hidden_dim=64)
    batches = random_batches(steps, batch_size=8, seed=seed + 1)
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=config)
    losses = []
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


_BASE = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
    "bf16": {"enabled": True},
}


def test_hpz_engine_parity():
    """hpZ changes only *where* shards live, not the math."""
    cfg3 = dict(_BASE, zero_optimization={
        "stage": 3, "stage3_param_persistence_threshold": 0})
    cfg_hpz = dict(_BASE, zero_optimization={
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_hpz_partition_size": 2})
    eng3, l3 = _train(cfg3)
    groups.reset()
    engh, lh = _train(cfg_hpz)
    assert engh.topology.dpr_size == 4 and engh.topology.dp_size == 2
    np.testing.assert_allclose(lh, l3, rtol=1e-5, atol=1e-5)
    # working params shard over 'dp' only (the ICI-local secondary partition)
    for leaf in jax.tree.leaves(engh.state.params):
        spec_axes = {a for e in leaf.sharding.spec if e
                     for a in (e if isinstance(e, tuple) else (e,))}
        assert "dpr" not in spec_axes


def test_mics_engine_parity():
    cfg1 = dict(_BASE, zero_optimization={"stage": 1})
    cfg_m = dict(_BASE, zero_optimization={"stage": 1, "mics_shard_size": 2})
    eng1, l1 = _train(cfg1)
    groups.reset()
    engm, lm = _train(cfg_m)
    assert engm.topology.zero_hierarchy == "mics"
    np.testing.assert_allclose(lm, l1, rtol=1e-5, atol=1e-5)
    # master/opt shard only within the shard group
    for leaf in jax.tree.leaves(engm.state.master):
        spec_axes = {a for e in leaf.sharding.spec if e
                     for a in (e if isinstance(e, tuple) else (e,))}
        assert "dpr" not in spec_axes


def test_qwz_engine():
    """zero_quantized_weights: int8 working copy still trains."""
    cfg = dict(_BASE, zero_optimization={
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_quantized_weights": True})
    engine, losses = _train(cfg, steps=6)
    assert engine.quantized_weights
    qleaves = [l for l in jax.tree.leaves(engine.state.params)
               if hasattr(l, "dtype") and l.dtype == jnp.int8]
    assert qleaves, "expected int8 working weights"
    cfg_ref = dict(_BASE, zero_optimization={
        "stage": 3, "stage3_param_persistence_threshold": 0})
    groups.reset()
    _, losses_ref = _train(cfg_ref, steps=6)
    np.testing.assert_allclose(losses, losses_ref, rtol=0.15, atol=0.15)


def test_qwz_checkpoint_roundtrip(tmp_path):
    cfg = dict(_BASE, zero_optimization={
        "stage": 3, "stage3_param_persistence_threshold": 0,
        "zero_quantized_weights": True})
    engine, _ = _train(cfg, steps=2)
    engine.save_checkpoint(str(tmp_path), tag="t")
    before = engine.get_model_parameters()
    groups.reset()
    engine2, _ = _train(cfg, steps=1, seed=9)
    engine2.load_checkpoint(str(tmp_path), tag="t")
    after = engine2.get_model_parameters()
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


# ---------------------------------------------------------------------------
# TiledLinear (reference runtime/zero/tiling.py; closes the last §2 partial)
# ---------------------------------------------------------------------------

def test_tiled_linear_matches_dense():
    import numpy as np
    import flax.linen as nn
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 24))
    dense = nn.Dense(12)
    dp = dense.init(jax.random.PRNGKey(1), x)["params"]
    tiled = TiledLinear(features=12, in_splits=3, out_splits=2)
    tiles = TiledLinear.from_dense_kernel(dp["kernel"], 3, 2)
    tp = {**tiles, "bias": dp["bias"]}
    got = tiled.apply({"params": tp}, x)
    want = dense.apply({"params": dp}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_tiled_linear_params_shard_independently(eight_devices):
    """Each tile is its own leaf -> the ZeRO partitioner shards tiles
    independently (the point of tiling: no single giant gather)."""
    from deepspeed_tpu.parallel.topology import MeshTopology
    from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
    from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    x = jnp.zeros((2, 32))
    mod = TiledLinear(features=16, in_splits=2, out_splits=2)
    params = mod.init(jax.random.PRNGKey(0), x)["params"]
    assert sum(1 for k in params if k.startswith("tile_")) == 4
    topo = MeshTopology(dp=-1)
    part = ZeroPartitioner(topo, DeepSpeedZeroConfig(
        **{"stage": 3, "stage3_param_persistence_threshold": 0}))
    sh = part.param_sharding(params)
    from jax.sharding import PartitionSpec as P
    tile_specs = [s.spec for k, s in sh.items() if k.startswith("tile_")]
    assert all(s != P() for s in tile_specs), "every tile must be sharded"


def test_tiled_linear_return_bias():
    import numpy as np
    from deepspeed_tpu.runtime.zero.tiling import TiledLinearReturnBias
    x = jax.random.normal(jax.random.PRNGKey(2), (3, 8))
    mod = TiledLinearReturnBias(features=6, in_splits=2, out_splits=3)
    params = mod.init(jax.random.PRNGKey(3), x)["params"]
    y, b = mod.apply({"params": params}, x)
    assert y.shape == (3, 6) and b.shape == (6,)
    # y + b equals the fused TiledLinear on the same params
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    fused = TiledLinear(features=6, in_splits=2, out_splits=3)
    np.testing.assert_allclose(np.asarray(y + b),
                               np.asarray(fused.apply({"params": params}, x)),
                               atol=1e-6)


def test_tiled_linear_rejects_uneven_split():
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    x = jnp.zeros((1, 10))
    with pytest.raises(ValueError):
        TiledLinear(features=8, in_splits=3).init(jax.random.PRNGKey(0), x)


def test_tiled_linear_init_variance_matches_dense():
    """Fresh-init output std must match nn.Dense (full fan-in scaling)."""
    import numpy as np
    from deepspeed_tpu.runtime.zero.tiling import TiledLinear
    x = jax.random.normal(jax.random.PRNGKey(5), (512, 256))
    tiled = TiledLinear(features=128, in_splits=4, use_bias=False)
    tp = tiled.init(jax.random.PRNGKey(6), x)["params"]
    y_t = np.asarray(tiled.apply({"params": tp}, x))
    import flax.linen as nn
    dense = nn.Dense(128, use_bias=False)
    dp = dense.init(jax.random.PRNGKey(6), x)["params"]
    y_d = np.asarray(dense.apply({"params": dp}, x))
    assert abs(np.std(y_t) - np.std(y_d)) < 0.15 * np.std(y_d), \
        (np.std(y_t), np.std(y_d))
