"""Serving-path observability (PR 6): per-request lifecycle tracing,
TTFT/TPOT percentiles, KV-cache & scheduler gauges.

Covers the fixed-bucket histogram primitive, an end-to-end CPU
SplitFuseScheduler run (request lanes in the Chrome trace, finite ordered
percentiles, nonzero KV-occupancy gauge), the preemption/resume counters
under a deliberately tight KV budget, the replica-skew gauge, and the
disabled-noop guarantee for every new hook: zero clock reads, zero
allocations in the telemetry core, zero state mutation per scheduler step.
"""

import json

import numpy as np
import pytest

import jax

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry import core as telemetry_core
from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)
    yield
    telemetry.close()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)


@pytest.fixture(scope="module")
def served():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def make_engine(cfg, model, params, num_kv_blocks=64, max_tokens=16):
    return InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": max_tokens,
                          "max_context": 128,
                          "num_kv_blocks": num_kv_blocks},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})


# ---------------------------------------------------------------------------
# histogram primitive
# ---------------------------------------------------------------------------

def test_hist_percentiles_ordered_and_clamped():
    telemetry.configure(enabled=True)
    rng = np.random.default_rng(0)
    vals = rng.lognormal(-3.0, 1.0, 4000)
    for v in vals:
        telemetry.record_hist("serving/ttft_s", float(v))
    p50, p95, p99 = telemetry.hist_percentiles("serving/ttft_s")
    assert p50 <= p95 <= p99
    assert vals.min() <= p50 <= vals.max()
    assert vals.min() <= p99 <= vals.max()
    # log2 buckets: each estimate within one bucket (2x) of the true value
    true50, true99 = np.quantile(vals, [0.5, 0.99])
    assert true50 / 2 <= p50 <= true50 * 2
    assert true99 / 2 <= p99 <= true99 * 2


def test_hist_single_value_exact():
    telemetry.configure(enabled=True)
    telemetry.record_hist("h", 0.005)
    assert telemetry.hist_percentiles("h") == (0.005, 0.005, 0.005)
    assert telemetry.hist_percentiles("missing") is None


def test_hist_in_summary_and_schema(tmp_path):
    telemetry.configure(enabled=True)
    for v in (0.001, 0.002, 0.01):
        telemetry.record_hist("serving/ttft_s", v)
    telemetry.serving_event("submitted")
    telemetry.serving_gauge("serving/running", 2)
    s = telemetry.summary()
    h = s["serving"]["histograms"]["serving/ttft_s"]
    assert h["count"] == 3 and h["min_s"] == 0.001 and h["max_s"] == 0.01
    assert h["p50_s"] <= h["p95_s"] <= h["p99_s"]
    assert s["serving"]["requests"]["submitted"] == 1
    assert s["serving"]["gauges"]["serving/running"] == {"last": 2, "peak": 2}
    jsonschema = pytest.importorskip("jsonschema")
    import os
    schema_path = os.path.join(
        os.path.dirname(telemetry_core.__file__), "summary.schema.json")
    with open(schema_path) as f:
        jsonschema.validate(s, json.load(f))


# ---------------------------------------------------------------------------
# end-to-end serving stream
# ---------------------------------------------------------------------------

def test_serving_stream_end_to_end(served, tmp_path):
    """A real CPU SplitFuse run: request lanes land in the merged Chrome
    trace, TTFT/TPOT percentiles are finite and ordered, and the
    KV-occupancy gauge saw nonzero occupancy while decoding."""
    cfg, model, params = served
    tr = tmp_path / "trace.json"
    telemetry.configure(enabled=True, chrome_trace_path=str(tr),
                        sample_sync=False, jax_annotations=False)
    engine = make_engine(cfg, model, params)
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(3)
    for uid in range(3):
        sched.submit(uid, rng.integers(0, cfg.vocab_size, 20).astype(np.int32),
                     max_new_tokens=4)
    out = sched.run_to_completion()
    assert all(len(out[u]) == 4 for u in range(3))

    s = telemetry.summary()
    srv = s["serving"]
    assert srv["requests"]["submitted"] == 3
    assert srv["requests"]["finished"] == 3
    ttft = srv["histograms"]["serving/ttft_s"]
    tpot = srv["histograms"]["serving/tpot_s"]
    assert ttft["count"] == 3
    assert tpot["count"] == 3 * 3  # 4 tokens -> 3 inter-token gaps each
    for h in (ttft, tpot, srv["histograms"]["serving/queue_wait_s"],
              srv["histograms"]["serving/e2e_s"]):
        assert np.isfinite([h["p50_s"], h["p99_s"]]).all()
        assert 0 < h["p50_s"] <= h["p99_s"]
    # the last flush empties the pool, so peak (not last) proves decoding
    # actually held blocks
    assert srv["gauges"]["serving/kv_occupancy"]["peak"] > 0
    assert srv["gauges"]["serving/running"]["peak"] >= 1
    assert srv["gauges"]["serving/token_budget_util"]["peak"] > 0

    path = telemetry.export_chrome_trace()
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    lanes = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"
             and e["args"]["name"].startswith("request/")}
    assert lanes == {"request/0", "request/1", "request/2"}
    phases = {e["name"] for e in events if e["name"].startswith("req/")}
    assert {"req/submit", "req/queued", "req/prefill", "req/decode",
            "req/finish"} <= phases
    # request lanes are synthetic tids, disjoint from real-thread lanes
    lane_tids = {e["tid"] for e in events if e["name"].startswith("req/")}
    assert all(t >= 0x10000 for t in lane_tids)


def test_preemption_and_resume_counters(served):
    """10 blocks x 8 tokens with two 44+6-token requests deadlocks the pool
    (see test_scheduler_preempts_under_kv_pressure); the host-swap preemption
    that breaks it must show up in the serving counters."""
    cfg, model, params = served
    telemetry.configure(enabled=True, sample_sync=False,
                        jax_annotations=False)
    engine = make_engine(cfg, model, params, num_kv_blocks=10)
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(7)
    for uid in range(2):
        sched.submit(uid, rng.integers(0, cfg.vocab_size, 44).astype(np.int32),
                     max_new_tokens=6)
    out = sched.run_to_completion()
    assert all(len(out[u]) == 6 for u in range(2))
    srv = telemetry.summary()["serving"]
    assert srv["requests"]["preempted"] >= 1
    assert srv["requests"]["resumed"] >= 1
    assert srv["gauges"]["serving/preempted"]["peak"] >= 1
    # fragmentation gauge exists and stays in [0, 1]
    frag = srv["gauges"]["serving/kv_fragmentation"]
    assert 0.0 <= frag["peak"] <= 1.0


def test_kv_stats_pure_read(served):
    """``kv_stats`` never records (safe to poll anywhere);
    ``sample_kv_stats`` is the recording variant — the PR 4 sample_memory
    pattern."""
    cfg, model, params = served
    engine = make_engine(cfg, model, params)
    stats = engine._state.kv_stats()
    assert stats["total_blocks"] == 64 and stats["free_blocks"] == 64
    assert stats["occupancy"] == 0.0 and stats["fragmentation"] == 0.0
    telemetry.configure(enabled=True)
    engine._state.kv_stats()  # pure read: no gauge recorded
    assert "serving/kv_occupancy" not in telemetry.summary()["serving"]["gauges"]
    engine._state.sample_kv_stats()
    assert "serving/kv_occupancy" in telemetry.summary()["serving"]["gauges"]


def test_max_context_eviction_records_terminal_latency(served, tmp_path):
    """A request retired at max_context never "finishes" — the eviction IS
    its terminal event, so it must record ``serving/e2e_s`` and an evict
    lane phase or replay percentiles silently drop exactly the
    worst-latency requests."""
    cfg, model, params = served
    tr = tmp_path / "trace.json"
    telemetry.configure(enabled=True, chrome_trace_path=str(tr),
                        sample_sync=False, jax_annotations=False)
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 2,
                          "max_ragged_batch_size": 16,
                          "max_context": 16, "num_kv_blocks": 8},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})
    sched = SplitFuseScheduler(engine)
    rng = np.random.default_rng(9)
    sched.submit(0, rng.integers(0, cfg.vocab_size, 12).astype(np.int32),
                 max_new_tokens=10)  # 12 + 10 cannot fit 16: evicted at 4
    out = sched.run_to_completion()
    assert 1 <= len(out[0]) <= 4
    srv = telemetry.summary()["serving"]
    assert srv["requests"]["evicted"] == 1
    assert srv["requests"].get("finished", 0) == 0
    e2e = srv["histograms"]["serving/e2e_s"]
    assert e2e["count"] == 1 and np.isfinite(e2e["p50_s"])
    path = telemetry.export_chrome_trace()
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    assert any(e["name"] == "req/evict" for e in events), \
        "eviction must land in the request lane as the terminal phase"


# ---------------------------------------------------------------------------
# disabled-noop guarantee for the serving hooks
# ---------------------------------------------------------------------------

def test_disabled_serving_hooks_zero_overhead(served, monkeypatch):
    """Telemetry disabled, a full scheduler run performs ZERO clock reads
    (scheduler._now patched to raise), ZERO allocations inside the telemetry
    core, and leaves the telemetry serving state untouched. With the
    ``prefix_caching`` knob off (the default) the same run must also do zero
    prefix-cache work — every ``PrefixCache`` method is patched to raise."""
    import tracemalloc
    from deepspeed_tpu.inference.v2 import scheduler as sched_mod
    from deepspeed_tpu.inference.v2.ragged import prefix_cache as pc_mod

    cfg, model, params = served
    assert not telemetry.enabled()

    def _cache_boom(*a, **kw):
        raise AssertionError(
            "prefix_caching off must mean zero hashing/refcount work")
    for name in ("__init__", "chain_digest", "lookup_chain", "acquire_chain",
                 "insert", "park_if_cached", "evict"):
        monkeypatch.setattr(pc_mod.PrefixCache, name, _cache_boom)

    engine = make_engine(cfg, model, params)
    assert engine._state.prefix_cache is None
    assert engine.prefix_caching is False
    sched = SplitFuseScheduler(engine, token_budget=16)
    assert sched._prefix_caching is False

    def _boom():
        raise AssertionError(
            "disabled serving path must not read the clock")
    monkeypatch.setattr(sched_mod, "_now", _boom)

    rng = np.random.default_rng(5)
    sched.submit(0, rng.integers(0, cfg.vocab_size, 12).astype(np.int32),
                 max_new_tokens=2)
    sched.step()  # warm the jit caches outside the traced window

    sched.submit(1, rng.integers(0, cfg.vocab_size, 12).astype(np.int32),
                 max_new_tokens=3)
    tracemalloc.start()
    snap0 = tracemalloc.take_snapshot()
    while sched.has_work:
        sched.step()
    snap1 = tracemalloc.take_snapshot()
    tracemalloc.stop()
    core_filter = [tracemalloc.Filter(True, telemetry_core.__file__)]
    grown = [st for st in
             snap1.filter_traces(core_filter).compare_to(
                 snap0.filter_traces(core_filter), "lineno")
             if st.size_diff > 0]
    assert not grown, f"telemetry core allocated when disabled: {grown}"

    tm = telemetry.get_telemetry()
    assert tm.hist_stats == {}
    assert tm.serving_counters == {}
    assert tm.serving_gauges == {}
    assert tm._request_lanes == {}
    assert telemetry.summary() == {"enabled": False}


def test_disabled_swap_hooks_zero_clock_reads(served, monkeypatch):
    """The KV host-tier swap timers must be free when telemetry is off: a
    workload that spills AND restores through the host tier performs zero
    clock reads in kv_cache (``kv_cache._now`` patched to raise) and leaves
    the swap histograms unrecorded."""
    from deepspeed_tpu.inference.v2.ragged import kv_cache as kvc_mod

    cfg, model, params = served
    assert not telemetry.enabled()

    def _boom():
        raise AssertionError(
            "disabled swap path must not read the clock")
    monkeypatch.setattr(kvc_mod, "_now", _boom)

    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 16,
                          "max_context": 128, "num_kv_blocks": 12,
                          "host_kv_blocks": 16},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"},
        "prefix_caching": True})
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(21)
    warm = rng.integers(0, cfg.vocab_size, 40).astype(np.int32)
    sched.submit(0, warm, max_new_tokens=2)
    sched.run_to_completion()   # parks warm's full blocks
    sched.submit(1, rng.integers(0, cfg.vocab_size, 60).astype(np.int32),
                 max_new_tokens=2)
    sched.run_to_completion()   # pressure: parked blocks spill to host
    assert engine.kv_stats()["kv_spilled"] >= 1
    sched.submit(2, np.concatenate(
        [warm, rng.integers(0, cfg.vocab_size, 6).astype(np.int32)]),
        max_new_tokens=2)
    sched.run_to_completion()   # shared prefix restores from the host tier
    assert engine.kv_stats()["kv_restored"] >= 1
    assert telemetry.summary() == {"enabled": False}


def test_swap_hists_recorded_when_enabled(served):
    """The enabled counterpart: the same spill/restore workload lands
    ``serving/kv_swap_out_s`` and ``serving/kv_swap_in_s`` samples and the
    ``serving/host_kv_blocks`` gauge."""
    cfg, model, params = served
    telemetry.configure(enabled=True, sample_sync=False,
                        jax_annotations=False)
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 16,
                          "max_context": 128, "num_kv_blocks": 12,
                          "host_kv_blocks": 16},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"},
        "prefix_caching": True})
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(21)
    warm = rng.integers(0, cfg.vocab_size, 40).astype(np.int32)
    sched.submit(0, warm, max_new_tokens=2)
    sched.run_to_completion()
    sched.submit(1, rng.integers(0, cfg.vocab_size, 60).astype(np.int32),
                 max_new_tokens=2)
    sched.run_to_completion()
    sched.submit(2, np.concatenate(
        [warm, rng.integers(0, cfg.vocab_size, 6).astype(np.int32)]),
        max_new_tokens=2)
    sched.run_to_completion()
    srv = telemetry.summary()["serving"]
    out_h = srv["histograms"]["serving/kv_swap_out_s"]
    in_h = srv["histograms"]["serving/kv_swap_in_s"]
    assert out_h["count"] >= 1 and np.isfinite(out_h["p50_s"])
    assert in_h["count"] >= 1 and np.isfinite(in_h["p50_s"])
    assert srv["gauges"]["serving/host_kv_blocks"]["peak"] >= 1


# ---------------------------------------------------------------------------
# replica skew gauge
# ---------------------------------------------------------------------------

def test_replica_group_load_report(served):
    from deepspeed_tpu.inference.v2.replica_group import ReplicaGroup
    cfg, model, params = served
    telemetry.configure(enabled=True, sample_sync=False,
                        jax_annotations=False)
    group = ReplicaGroup(model, params, replica_num=2, tp_size=1,
                         engine_config={
                             "state_manager": {"max_ragged_sequence_count": 4,
                                               "max_ragged_batch_size": 16,
                                               "max_context": 128,
                                               "num_kv_blocks": 64},
                             "kv_cache": {"block_size": 8,
                                          "cache_dtype": "fp32"}},
                         token_budget=16)
    rng = np.random.default_rng(11)
    for uid in range(4):
        group.submit(uid, rng.integers(0, cfg.vocab_size, 10).astype(np.int32),
                     max_new_tokens=2)
    rep = group.load_report()
    assert [p["assigned"] for p in rep["replicas"]] == [2, 2]
    assert rep["active_skew"] == 0.0  # round-robin with even count
    assert "serving/replica_skew" in telemetry.summary()["serving"]["gauges"]
    out = group.run_to_completion()
    assert len(out) == 4


# ---------------------------------------------------------------------------
# speculative decode hooks
# ---------------------------------------------------------------------------

def _spec_engine(model, params, num_kv_blocks=64):
    return InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 16,
                          "max_context": 128,
                          "num_kv_blocks": num_kv_blocks},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"},
        "speculative": {"enabled": True, "max_draft_tokens": 4}})


def _template_prompt(cfg, seed, reps=10):
    rng = np.random.default_rng(seed)
    return np.tile(rng.integers(0, cfg.vocab_size, 4), reps).astype(np.int32)


def test_disabled_spec_hooks_zero_overhead(served, monkeypatch):
    """Telemetry disabled, a SPECULATING run (drafts composed, verify
    chunks dispatched, accept walks + rollbacks retired) performs zero
    clock reads in the scheduler and zero allocations inside the telemetry
    core — the accept-rate EWMA and the always-on draft counters must not
    ride the telemetry path."""
    import tracemalloc
    from deepspeed_tpu.inference.v2 import scheduler as sched_mod

    cfg, model, params = served
    assert not telemetry.enabled()
    engine = _spec_engine(model, params)
    sched = SplitFuseScheduler(engine, token_budget=16)

    def _boom():
        raise AssertionError(
            "disabled speculative path must not read the clock")
    monkeypatch.setattr(sched_mod, "_now", _boom)

    sched.submit(0, _template_prompt(cfg, 5), max_new_tokens=6)
    sched.step()  # warm the prefill jit caches outside the window

    sched.submit(1, _template_prompt(cfg, 5) + 1, max_new_tokens=8)
    tracemalloc.start()
    snap0 = tracemalloc.take_snapshot()
    while sched.has_work:
        sched.step()
    snap1 = tracemalloc.take_snapshot()
    tracemalloc.stop()
    core_filter = [tracemalloc.Filter(True, telemetry_core.__file__)]
    grown = [st for st in
             snap1.filter_traces(core_filter).compare_to(
                 snap0.filter_traces(core_filter), "lineno")
             if st.size_diff > 0]
    assert not grown, f"telemetry core allocated when disabled: {grown}"
    # the router's load signal stays live with telemetry off
    assert sched.speculated_tokens > 0
    assert sched.tokens_per_round() >= 1.0
    assert telemetry.summary() == {"enabled": False}


def test_spec_stream_lands_gauges_events_and_phase(served, tmp_path):
    """Enabled counterpart: a speculating run lands the
    ``speculated_tokens``/``rejected_tokens`` counters, the
    ``serving/accept_rate`` and ``serving/verify_batch_occupancy`` gauges,
    a ``req/speculate`` phase in the request lanes, and the summary still
    validates against summary.schema.json."""
    cfg, model, params = served
    tr = tmp_path / "trace.json"
    telemetry.configure(enabled=True, chrome_trace_path=str(tr),
                        sample_sync=False, jax_annotations=False)
    engine = _spec_engine(model, params)
    sched = SplitFuseScheduler(engine, token_budget=16)
    sched.submit(0, _template_prompt(cfg, 5), max_new_tokens=6)
    sched.submit(1, _template_prompt(cfg, 5) + 1, max_new_tokens=8)
    out = sched.run_to_completion()
    assert len(out[0]) == 6 and len(out[1]) == 8
    assert sched.accepted_tokens > 0, "template workload must accept drafts"

    s = telemetry.summary()
    srv = s["serving"]
    assert srv["requests"]["speculated_tokens"] >= 1
    assert srv["requests"]["speculated_tokens"] == sched.speculated_tokens
    assert srv["requests"].get("rejected_tokens", 0) == sched.rejected_tokens
    acc = srv["gauges"]["serving/accept_rate"]
    assert 0.0 <= acc["last"] <= 1.0 and 0.0 <= acc["peak"] <= 1.0
    occ = srv["gauges"]["serving/verify_batch_occupancy"]
    assert 0.0 < occ["peak"] <= 1.0
    jsonschema = pytest.importorskip("jsonschema")
    import os
    schema_path = os.path.join(
        os.path.dirname(telemetry_core.__file__), "summary.schema.json")
    with open(schema_path) as f:
        jsonschema.validate(s, json.load(f))

    path = telemetry.export_chrome_trace()
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    spec_evts = [e for e in events if e["name"] == "req/speculate"]
    assert spec_evts, "verify rounds must land as a speculate lane phase"
    assert all(e["args"]["tokens"] >= 2 for e in spec_evts), \
        "a speculate phase is by definition a multi-token decode chunk"
    assert all(t >= 0x10000 for t in {e["tid"] for e in spec_evts})
