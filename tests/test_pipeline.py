"""Pipeline-parallel tests (mirrors reference ``tests/unit/runtime/pipe/``:
schedule instruction checks + train parity vs non-pipelined execution)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine, collective_pipeline
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               partition_balanced, partition_uniform)
from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                 InferenceSchedule, LoadMicroBatch,
                                                 OptimizerStep, TrainSchedule)


# --- schedule descriptions (reference tests/unit/runtime/pipe/test_pipe_schedule.py) ---
def test_inference_schedule_ticks():
    sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = list(sched.steps())
    assert len(steps) == 5  # M + S - 1
    assert any(isinstance(c, LoadMicroBatch) for c in steps[0])
    assert any(isinstance(c, ForwardPass) for c in steps[0])


def test_train_schedule_has_all_passes():
    for stage in (0, 1):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=stage)
        steps = list(sched.steps())
        fwd = sum(isinstance(c, ForwardPass) for cmds in steps for c in cmds)
        bwd = sum(isinstance(c, BackwardPass) for cmds in steps for c in cmds)
        opt = sum(isinstance(c, OptimizerStep) for cmds in steps for c in cmds)
        assert fwd == 4 and bwd == 4 and opt == 1
    assert sched.num_pipe_buffers() >= 2


def test_partition_helpers():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 2) == [0, 4, 7]
    parts = partition_balanced([1, 1, 10, 1, 1], 2)
    assert parts[0] == 0 and parts[-1] == 5


# --- collective pipeline numerics ---
class Blk(nn.Module):
    d: int = 8

    @nn.compact
    def __call__(self, x):
        return x + nn.Dense(self.d)(nn.tanh(x))


@pytest.fixture
def pp_mesh(eight_devices):
    return MeshTopology(pp=4).mesh


def test_collective_pipeline_matches_sequential(pp_mesh):
    """Rotating the blocks over 4 stages == applying them sequentially."""
    L, M, B, D = 8, 4, 2, 8
    blk = Blk(D)
    x = jax.random.normal(jax.random.PRNGKey(0), (M, B, D))
    keys = jax.random.split(jax.random.PRNGKey(1), L)
    params = jax.vmap(lambda k: blk.init(k, x[0])["params"])(keys)

    def block_apply(p, a, extra):
        return blk.apply({"params": p}, a)

    out = collective_pipeline(block_apply, params, x, pp_mesh, num_stages=4,
                              remat=False)

    ref = x
    for l in range(L):
        p_l = jax.tree.map(lambda a: a[l], params)
        ref = jax.vmap(lambda xi: blk.apply({"params": p_l}, xi))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_collective_pipeline_grads_match(pp_mesh):
    L, M, B, D = 4, 2, 2, 8
    blk = Blk(D)
    x = jax.random.normal(jax.random.PRNGKey(0), (M, B, D))
    keys = jax.random.split(jax.random.PRNGKey(1), L)
    params = jax.vmap(lambda k: blk.init(k, x[0])["params"])(keys)

    def block_apply(p, a, extra):
        return blk.apply({"params": p}, a)

    def loss_pipe(p):
        return (collective_pipeline(block_apply, p, x, pp_mesh, num_stages=4,
                                    remat=True) ** 2).mean()

    def loss_ref(p):
        y = x
        for l in range(L):
            p_l = jax.tree.map(lambda a: a[l], p)
            y = jax.vmap(lambda xi: blk.apply({"params": p_l}, xi))(y)
        return (y ** 2).mean()

    g1 = jax.grad(loss_pipe)(params)
    g2 = jax.grad(loss_ref)(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


# --- PipelineEngine end-to-end ---
class Embed(nn.Module):
    d: int = 8

    @nn.compact
    def __call__(self, batch):
        return nn.Dense(self.d)(batch["x"])


class Head(nn.Module):
    @nn.compact
    def __call__(self, x, batch):
        pred = nn.Dense(batch["y"].shape[-1])(x)
        return jnp.mean((pred - batch["y"]) ** 2)


def _pipe_batches(n, bsz=8, din=8, dout=4):
    out = []
    for i in range(n):
        r = np.random.default_rng(i)
        x = r.normal(size=(bsz, din)).astype(np.float32)
        out.append({"x": x, "y": (x[:, :dout] * 1.5).astype(np.float32)})
    return out


def test_pipeline_engine_trains(eight_devices):
    topo = MeshTopology(pp=4)
    pipe = PipelineModule(embed=Embed(), block=Blk(), head=Head(), num_layers=8,
                          num_stages=4)
    engine = PipelineEngine(
        config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        model=pipe, mesh=topo)
    batches = _pipe_batches(40)
    it = iter(batches)
    losses = [engine.train_batch(iter([batches[2*i], batches[2*i+1]])) for i in range(20)]
    assert engine.global_steps == 20
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_pipeline_engine_matches_dataparallel(eight_devices):
    """Same model trained pp=4 vs pp=1 must produce the same losses."""
    def build(pp):
        topo = MeshTopology(pp=pp)
        pipe = PipelineModule(embed=Embed(), block=Blk(), head=Head(), num_layers=4,
                              num_stages=pp)
        return PipelineEngine(
            config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
            model=pipe, mesh=topo)

    batches = _pipe_batches(12)
    e1, e4 = build(1), build(4)
    l1 = [e1.train_batch(iter([batches[2*i], batches[2*i+1]])) for i in range(6)]
    l4 = [e4.train_batch(iter([batches[2*i], batches[2*i+1]])) for i in range(6)]
    np.testing.assert_allclose(l1, l4, rtol=2e-4, atol=1e-5)


def test_layer_spec_conversion():
    specs = [LayerSpec(Embed), LayerSpec(Blk), LayerSpec(Blk), LayerSpec(Head)]
    pipe = PipelineModule.from_layer_specs(specs, num_stages=2)
    assert pipe.num_layers == 2
    with pytest.raises(ValueError):
        PipelineModule.from_layer_specs(
            [LayerSpec(Embed), LayerSpec(Blk), LayerSpec(Embed), LayerSpec(Head)],
            num_stages=2)
    # indivisible layer counts are supported via padded masked slots
    pipe7 = PipelineModule(block=Blk(), num_layers=7, num_stages=2)
    assert pipe7.padded_layers() == 8


# --- tied embed/head + non-uniform partitioning (VERDICT next #8) ---
class TokEmbed(nn.Module):
    vocab: int = 64
    d: int = 16

    @nn.compact
    def __call__(self, batch):
        emb = self.param("emb", nn.initializers.normal(0.02), (self.vocab, self.d))
        return emb[batch["input_ids"]]


def tied_lm_head(module, embed_params, acts, batch):
    """Unembed with the tied embedding matrix; next-token cross-entropy."""
    logits = acts @ embed_params["emb"].T
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = labels[:, 1:]
    return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], axis=-1))


def lm_batches(n, batch=4, seq=16, vocab=64, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        start = rng.integers(0, vocab, size=(batch, 1))
        ids = (start + np.arange(seq)) % vocab  # learnable: consecutive tokens
        ids = ids.astype(np.int32)
        out.append({"input_ids": ids, "labels": ids})
    return out


def make_tied_pipe(num_layers=8, num_stages=4):
    from deepspeed_tpu.runtime.pipe.module import TiedLayerSpec
    specs = ([TiedLayerSpec("embed", TokEmbed)]
             + [LayerSpec(Blk, 16) for _ in range(num_layers)]
             + [TiedLayerSpec("embed", TokEmbed, forward_fn=tied_lm_head)])
    return PipelineModule.from_layer_specs(specs, num_stages=num_stages)


def test_tied_pipeline_parity_vs_dp(eight_devices):
    """Tied-embedding pipeline (pp=4) must match a plain DP run step for step
    (reference pipe tied-grad allreduce correctness, pipe/engine.py:266)."""
    batches = lm_batches(4, batch=8)
    pipe = make_tied_pipe()
    params0 = pipe.init_params(jax.random.PRNGKey(3), batches[0])
    cfg = {"train_batch_size": 8, "gradient_accumulation_steps": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}}

    pp_engine = PipelineEngine(config=dict(cfg), model=make_tied_pipe(),
                               mesh=MeshTopology(pp=4),
                               model_parameters=params0)

    # DP twin: same math as one fused callable over the same param tree
    def dp_model(params, batch, rng=None):
        mb = 2
        micro = jax.tree.map(
            lambda x: x.reshape((mb, x.shape[0] // mb) + x.shape[1:]), batch)

        def one(b):
            x = pipe.embed.apply({"params": params["embed"]}, b)

            def layer(h, p):
                return pipe.block.apply({"params": p}, h), None
            real = jax.tree.map(lambda a: a[:pipe.num_layers], params["blocks"])
            x, _ = jax.lax.scan(layer, x, real)
            return tied_lm_head(None, params["embed"], x, b)

        return jnp.mean(jax.vmap(one)(micro))

    dp_engine, _, _, _ = deepspeed_tpu.initialize(
        model=dp_model, model_parameters=params0,
        config={**cfg, "gradient_accumulation_steps": 1,
                "train_batch_size": 8})

    pp_losses, dp_losses = [], []
    for i in range(4):
        b = batches[i % len(batches)]
        halves = [jax.tree.map(lambda x: x[:4], b), jax.tree.map(lambda x: x[4:], b)]
        pp_losses.append(pp_engine.train_batch(iter(halves)))
        loss = dp_engine(b)
        dp_engine.backward(loss)
        dp_engine.step()
        dp_losses.append(float(jax.device_get(loss)))
    np.testing.assert_allclose(pp_losses, dp_losses, rtol=2e-2)
    assert pp_losses[-1] < pp_losses[0]


def test_tied_grads_accumulate_both_paths(eight_devices):
    """The tied embedding leaf's grad includes embed AND unembed terms."""
    batches = lm_batches(1, batch=4)
    pipe = make_tied_pipe(num_layers=4, num_stages=4)
    params = pipe.init_params(jax.random.PRNGKey(0), batches[0])
    engine = PipelineEngine(
        config={"train_batch_size": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        model=pipe, mesh=MeshTopology(pp=4), model_parameters=params)
    before = np.asarray(jax.device_get(engine.state.params["embed"]["emb"]))
    engine.train_batch(iter([batches[0]]))
    after = np.asarray(jax.device_get(engine.state.params["embed"]["emb"]))
    assert not np.allclose(before, after)  # tied leaf updated
    assert engine.was_step_applied()


def test_nonuniform_layer_partitioning(eight_devices):
    """num_layers not divisible by stages: padded masked slots (non-uniform
    stage partitioning, reference pipe/module.py:370 partition methods)."""
    batches = lm_batches(3, batch=4)
    pipe = make_tied_pipe(num_layers=6, num_stages=4)  # 6 layers / 4 stages
    assert pipe.padded_layers() == 8
    params = pipe.init_params(jax.random.PRNGKey(1), batches[0])
    assert jax.tree.leaves(params["blocks"])[0].shape[0] == 8
    engine = PipelineEngine(
        config={"train_batch_size": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}},
        model=pipe, mesh=MeshTopology(pp=4), model_parameters=params)
    losses = [engine.train_batch(iter([batches[i % 3]])) for i in range(5)]
    assert losses[-1] < losses[0], f"not learning: {losses}"

    # parity: the same 6 real layers run unpipelined
    def ref_model(params, batch, rng=None):
        x = pipe.embed.apply({"params": params["embed"]}, batch)

        def layer(h, p):
            return pipe.block.apply({"params": p}, h), None
        real = jax.tree.map(lambda a: a[:6], params["blocks"])
        x, _ = jax.lax.scan(layer, x, real)
        return tied_lm_head(None, params["embed"], x, batch)

    ref_loss = float(jax.device_get(ref_model(
        jax.tree.map(np.asarray, jax.device_get(params)), batches[0])))
    eng2 = PipelineEngine(
        config={"train_batch_size": 4,
                "optimizer": {"type": "Adam", "params": {"lr": 5e-3}}},
        model=make_tied_pipe(num_layers=6, num_stages=4),
        mesh=MeshTopology(pp=4), model_parameters=params)
    first = eng2.train_batch(iter([batches[0]]))
    np.testing.assert_allclose(first, ref_loss, rtol=2e-2)
