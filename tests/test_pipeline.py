"""Pipeline-parallel tests (mirrors reference ``tests/unit/runtime/pipe/``:
schedule instruction checks + train parity vs non-pipelined execution)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import flax.linen as nn
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.pipe.engine import PipelineEngine, collective_pipeline
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               partition_balanced, partition_uniform)
from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                 InferenceSchedule, LoadMicroBatch,
                                                 OptimizerStep, TrainSchedule)


# --- schedule descriptions (reference tests/unit/runtime/pipe/test_pipe_schedule.py) ---
def test_inference_schedule_ticks():
    sched = InferenceSchedule(micro_batches=4, stages=2, stage_id=0)
    steps = list(sched.steps())
    assert len(steps) == 5  # M + S - 1
    assert any(isinstance(c, LoadMicroBatch) for c in steps[0])
    assert any(isinstance(c, ForwardPass) for c in steps[0])


def test_train_schedule_has_all_passes():
    for stage in (0, 1):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=stage)
        steps = list(sched.steps())
        fwd = sum(isinstance(c, ForwardPass) for cmds in steps for c in cmds)
        bwd = sum(isinstance(c, BackwardPass) for cmds in steps for c in cmds)
        opt = sum(isinstance(c, OptimizerStep) for cmds in steps for c in cmds)
        assert fwd == 4 and bwd == 4 and opt == 1
    assert sched.num_pipe_buffers() >= 2


def test_partition_helpers():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 2) == [0, 4, 7]
    parts = partition_balanced([1, 1, 10, 1, 1], 2)
    assert parts[0] == 0 and parts[-1] == 5


# --- collective pipeline numerics ---
class Blk(nn.Module):
    d: int = 8

    @nn.compact
    def __call__(self, x):
        return x + nn.Dense(self.d)(nn.tanh(x))


@pytest.fixture
def pp_mesh(eight_devices):
    return MeshTopology(pp=4).mesh


def test_collective_pipeline_matches_sequential(pp_mesh):
    """Rotating the blocks over 4 stages == applying them sequentially."""
    L, M, B, D = 8, 4, 2, 8
    blk = Blk(D)
    x = jax.random.normal(jax.random.PRNGKey(0), (M, B, D))
    keys = jax.random.split(jax.random.PRNGKey(1), L)
    params = jax.vmap(lambda k: blk.init(k, x[0])["params"])(keys)

    def block_apply(p, a, extra):
        return blk.apply({"params": p}, a)

    out = collective_pipeline(block_apply, params, x, pp_mesh, num_stages=4,
                              remat=False)

    ref = x
    for l in range(L):
        p_l = jax.tree.map(lambda a: a[l], params)
        ref = jax.vmap(lambda xi: blk.apply({"params": p_l}, xi))(ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_collective_pipeline_grads_match(pp_mesh):
    L, M, B, D = 4, 2, 2, 8
    blk = Blk(D)
    x = jax.random.normal(jax.random.PRNGKey(0), (M, B, D))
    keys = jax.random.split(jax.random.PRNGKey(1), L)
    params = jax.vmap(lambda k: blk.init(k, x[0])["params"])(keys)

    def block_apply(p, a, extra):
        return blk.apply({"params": p}, a)

    def loss_pipe(p):
        return (collective_pipeline(block_apply, p, x, pp_mesh, num_stages=4,
                                    remat=True) ** 2).mean()

    def loss_ref(p):
        y = x
        for l in range(L):
            p_l = jax.tree.map(lambda a: a[l], p)
            y = jax.vmap(lambda xi: blk.apply({"params": p_l}, xi))(y)
        return (y ** 2).mean()

    g1 = jax.grad(loss_pipe)(params)
    g2 = jax.grad(loss_ref)(params)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5)


# --- PipelineEngine end-to-end ---
class Embed(nn.Module):
    d: int = 8

    @nn.compact
    def __call__(self, batch):
        return nn.Dense(self.d)(batch["x"])


class Head(nn.Module):
    @nn.compact
    def __call__(self, x, batch):
        pred = nn.Dense(batch["y"].shape[-1])(x)
        return jnp.mean((pred - batch["y"]) ** 2)


def _pipe_batches(n, bsz=8, din=8, dout=4):
    out = []
    for i in range(n):
        r = np.random.default_rng(i)
        x = r.normal(size=(bsz, din)).astype(np.float32)
        out.append({"x": x, "y": (x[:, :dout] * 1.5).astype(np.float32)})
    return out


def test_pipeline_engine_trains(eight_devices):
    topo = MeshTopology(pp=4)
    pipe = PipelineModule(embed=Embed(), block=Blk(), head=Head(), num_layers=8,
                          num_stages=4)
    engine = PipelineEngine(
        config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
        model=pipe, mesh=topo)
    batches = _pipe_batches(40)
    it = iter(batches)
    losses = [engine.train_batch(iter([batches[2*i], batches[2*i+1]])) for i in range(20)]
    assert engine.global_steps == 20
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_pipeline_engine_matches_dataparallel(eight_devices):
    """Same model trained pp=4 vs pp=1 must produce the same losses."""
    def build(pp):
        topo = MeshTopology(pp=pp)
        pipe = PipelineModule(embed=Embed(), block=Blk(), head=Head(), num_layers=4,
                              num_stages=pp)
        return PipelineEngine(
            config={"train_batch_size": 16, "gradient_accumulation_steps": 2,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-2}}},
            model=pipe, mesh=topo)

    batches = _pipe_batches(12)
    e1, e4 = build(1), build(4)
    l1 = [e1.train_batch(iter([batches[2*i], batches[2*i+1]])) for i in range(6)]
    l4 = [e4.train_batch(iter([batches[2*i], batches[2*i+1]])) for i in range(6)]
    np.testing.assert_allclose(l1, l4, rtol=2e-4, atol=1e-5)


def test_layer_spec_conversion():
    specs = [LayerSpec(Embed), LayerSpec(Blk), LayerSpec(Blk), LayerSpec(Head)]
    pipe = PipelineModule.from_layer_specs(specs, num_stages=2)
    assert pipe.num_layers == 2
    with pytest.raises(ValueError):
        PipelineModule.from_layer_specs(
            [LayerSpec(Embed), LayerSpec(Blk), LayerSpec(Embed), LayerSpec(Head)],
            num_stages=2)
    with pytest.raises(ValueError):
        PipelineModule(block=Blk(), num_layers=7, num_stages=2)
