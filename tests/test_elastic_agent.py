"""Elastic agent: relaunch-on-failure with membership change
(reference ``elasticity/elastic_agent.py:32`` capability)."""

import os
import sys
import textwrap

import pytest

from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent


def write_worker(tmp_path, body):
    p = tmp_path / "worker.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_clean_gang_exit(tmp_path):
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        rank = os.environ["RANK"]
        open(os.path.join(out, f"ok{rank}_{os.environ['DS_ELASTIC_RESTART_COUNT']}"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost", "localhost"],
                           max_restarts=2)
    assert agent.run() == 0
    assert agent.restarts == 0
    assert (tmp_path / "ok0_0").exists() and (tmp_path / "ok1_0").exists()


def test_restart_on_failure_then_succeed(tmp_path):
    """First incarnation of rank 0 fails; the relaunched gang succeeds."""
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        flag = os.path.join(out, "failed_once")
        if os.environ["RANK"] == "0" and not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit(3)
        open(os.path.join(out, f"done{os.environ['RANK']}"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost", "localhost"],
                           max_restarts=2, restart_backoff=0.1)
    assert agent.run() == 0
    assert agent.restarts == 1
    assert (tmp_path / "done0").exists() and (tmp_path / "done1").exists()


def test_membership_change_recomputes_batch(tmp_path):
    """Hostfile shrinks between incarnations: the agent revalidates the world
    and exports the recomputed elastic micro-batch."""
    hostfile = tmp_path / "hosts"
    hostfile.write_text("localhost slots=1\nlocalhost2 slots=1\n")
    w = write_worker(tmp_path, """
        import os, sys
        out, hostfile = sys.argv[1], sys.argv[2]
        ws = os.environ["DS_ELASTIC_WORLD_SIZE"]
        mb = os.environ["DS_ELASTIC_MICRO_BATCH"]
        rank = os.environ["RANK"]
        open(os.path.join(out, f"run_ws{ws}_mb{mb}_r{rank}"), "w").close()
        if ws == "2" and rank == "0":
            # simulate a preempted host: shrink membership, then die
            open(hostfile, "w").write("localhost slots=1\\n")
            sys.exit(7)
    """)
    # Worker spawn is local regardless of hostname (launcher='local')
    agent = DSElasticAgent(w, [str(tmp_path), str(hostfile)],
                           ds_config={"elasticity": {
                               "enabled": True, "max_train_batch_size": 64,
                               "micro_batch_sizes": [2, 4, 8],
                               "min_gpus": 1, "max_gpus": 4}},
                           hostfile=str(hostfile), max_restarts=2,
                           restart_backoff=0.1, launcher="local")
    assert agent.run() == 0
    assert agent.world_history == [2, 1]
    runs = sorted(f for f in os.listdir(tmp_path) if f.startswith("run_"))
    assert any(f.startswith("run_ws2_") for f in runs)
    assert any(f.startswith("run_ws1_") for f in runs)


def test_restart_budget_exhausted(tmp_path):
    w = write_worker(tmp_path, """
        import sys
        sys.exit(1)
    """)
    agent = DSElasticAgent(w, [], hosts=["localhost"], max_restarts=1,
                           restart_backoff=0.05)
    assert agent.run() == 1
    assert agent.restarts == 2  # initial + 1 restart, then budget blown


def test_invalid_world_size_rejected(tmp_path):
    w = write_worker(tmp_path, "pass")
    agent = DSElasticAgent(w, [], hosts=["h1", "h2", "h3"],
                           ds_config={"elasticity": {
                               "enabled": True, "max_train_batch_size": 8,
                               "micro_batch_sizes": [4], "min_gpus": 1,
                               "max_gpus": 2}},
                           max_restarts=0, launcher="local")
    assert agent.run() == 1  # 3 hosts not in the compatible set


# ---------------------------------------------------------------------------
# shrink/expand state machine (exit 84 — reshardable slice loss)
# ---------------------------------------------------------------------------

def _fast_backoff():
    from deepspeed_tpu.utils.retry import BackoffPolicy
    return BackoffPolicy(base=0.02, factor=1.0, max_delay=0.02, jitter="none")


def test_reshard_shrinks_to_survivors_budget_free(tmp_path):
    """Half the gang SIGKILLs (the lost slice), the other half exits 84:
    the agent excludes the dead hosts and relaunches the survivors at half
    world WITHOUT burning restart budget, recording the 'reshard' reason
    separately from preemption."""
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        rank = int(os.environ["RANK"])
        world = int(os.environ["DS_ELASTIC_WORLD_SIZE"])
        if os.environ["DS_ELASTIC_RESHARD_COUNT"] == "0":
            sys.exit(9 if rank >= world // 2 else 84)
        open(os.path.join(out, f"gen1_ws{world}_r{rank}"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost"] * 4,
                           max_restarts=1, backoff=_fast_backoff())
    assert agent.run() == 0
    assert agent.world_history == [4, 2]
    assert agent.reshards == 1 and agent.restarts == 0
    assert agent.restart_reasons == ["reshard"]
    assert agent.restart_counts["reshard"] == 1
    assert agent.restart_counts["preemption"] == 0
    gen1 = sorted(f for f in os.listdir(tmp_path) if f.startswith("gen1_"))
    assert gen1 == ["gen1_ws2_r0", "gen1_ws2_r1"]


def test_reshard_exit_84_without_host_loss_relaunches_same_world(tmp_path):
    """Exit 84 with no hard-crashed host (e.g. a transient partition the
    workers flagged): relaunch the same membership, still budget-free."""
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        world = os.environ["DS_ELASTIC_WORLD_SIZE"]
        if os.environ["DS_ELASTIC_RESHARD_COUNT"] == "0":
            sys.exit(84)
        open(os.path.join(out, f"gen1_ws{world}_r{os.environ['RANK']}"),
             "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost"] * 2,
                           max_restarts=0, backoff=_fast_backoff())
    assert agent.run() == 0
    assert agent.world_history == [2, 2]
    assert agent.reshards == 1 and agent.restarts == 0


def test_reshard_disabled_burns_budget(tmp_path):
    """allow_reshard=False restores the old contract: a partial crash is a
    plain failure charged against max_restarts."""
    w = write_worker(tmp_path, """
        import os, sys
        if os.environ["DS_ELASTIC_RESTART_COUNT"] == "0":
            sys.exit(9 if os.environ["RANK"] == "1" else 84)
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost"] * 2,
                           max_restarts=1, allow_reshard=False,
                           backoff=_fast_backoff())
    assert agent.run() == 0
    assert agent.restarts == 1 and agent.reshards == 0
    assert all(r != "reshard" for r in agent.restart_reasons)


def test_excluded_hosts_readmitted_by_probe(tmp_path):
    """The expand leg: once the injectable host probe reports the excluded
    hosts healthy, the next relaunch runs at full world again."""
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        rank = int(os.environ["RANK"])
        world = int(os.environ["DS_ELASTIC_WORLD_SIZE"])
        gen = os.environ["DS_ELASTIC_RESHARD_COUNT"]
        open(os.path.join(out, f"gen{gen}_ws{world}_r{rank}"), "w").close()
        if gen == "0":
            sys.exit(9 if rank >= world // 2 else 84)
        if gen == "1" and world == 2:
            sys.exit(84)  # flag again: by now the probe heals the slice
    """)
    probe_calls = []

    def probe(host):
        probe_calls.append(host)
        return len(probe_calls) > 2  # unhealthy at first, then healed

    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost"] * 4,
                           max_restarts=0, host_probe=probe,
                           backoff=_fast_backoff())
    assert agent.run() == 0
    assert agent.world_history == [4, 2, 4]
    assert agent.reshards == 2 and agent.restarts == 0
    assert probe_calls  # exclusions were actually re-probed
    gen2 = sorted(f for f in os.listdir(tmp_path) if f.startswith("gen2_"))
    assert gen2 == [f"gen2_ws4_r{r}" for r in range(4)]


def test_excluded_hosts_readmitted_on_membership_change(tmp_path):
    """Rewriting the hostfile (the operator healed the slice) clears the
    exclusions even without a probe."""
    hostfile = tmp_path / "hosts"
    hostfile.write_text("localhost slots=1\nlocalhost2 slots=1\n")
    w = write_worker(tmp_path, """
        import os, sys
        out, hostfile = sys.argv[1], sys.argv[2]
        rank = int(os.environ["RANK"])
        world = int(os.environ["DS_ELASTIC_WORLD_SIZE"])
        gen = os.environ["DS_ELASTIC_RESHARD_COUNT"]
        open(os.path.join(out, f"gen{gen}_ws{world}_r{rank}"), "w").close()
        if gen == "0":
            sys.exit(9 if rank == 1 else 84)
        if gen == "1" and world == 1:
            # operator heals the pool: content change re-admits everything
            open(hostfile, "w").write(
                "localhost slots=1\\nlocalhost3 slots=1\\n")
            sys.exit(84)
    """)
    agent = DSElasticAgent(w, [str(tmp_path), str(hostfile)],
                           hostfile=str(hostfile), max_restarts=0,
                           launcher="local", backoff=_fast_backoff())
    assert agent.run() == 0
    assert agent.world_history[0] == 2 and agent.world_history[1] == 1
    assert agent.world_history[-1] >= 2  # healed membership re-admitted
    assert agent.reshards == 2 and agent.restarts == 0
