"""Elastic agent: relaunch-on-failure with membership change
(reference ``elasticity/elastic_agent.py:32`` capability)."""

import os
import sys
import textwrap

import pytest

from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent


def write_worker(tmp_path, body):
    p = tmp_path / "worker.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_clean_gang_exit(tmp_path):
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        rank = os.environ["RANK"]
        open(os.path.join(out, f"ok{rank}_{os.environ['DS_ELASTIC_RESTART_COUNT']}"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost", "localhost"],
                           max_restarts=2)
    assert agent.run() == 0
    assert agent.restarts == 0
    assert (tmp_path / "ok0_0").exists() and (tmp_path / "ok1_0").exists()


def test_restart_on_failure_then_succeed(tmp_path):
    """First incarnation of rank 0 fails; the relaunched gang succeeds."""
    w = write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        flag = os.path.join(out, "failed_once")
        if os.environ["RANK"] == "0" and not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit(3)
        open(os.path.join(out, f"done{os.environ['RANK']}"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost", "localhost"],
                           max_restarts=2, restart_backoff=0.1)
    assert agent.run() == 0
    assert agent.restarts == 1
    assert (tmp_path / "done0").exists() and (tmp_path / "done1").exists()


def test_membership_change_recomputes_batch(tmp_path):
    """Hostfile shrinks between incarnations: the agent revalidates the world
    and exports the recomputed elastic micro-batch."""
    hostfile = tmp_path / "hosts"
    hostfile.write_text("localhost slots=1\nlocalhost2 slots=1\n")
    w = write_worker(tmp_path, """
        import os, sys
        out, hostfile = sys.argv[1], sys.argv[2]
        ws = os.environ["DS_ELASTIC_WORLD_SIZE"]
        mb = os.environ["DS_ELASTIC_MICRO_BATCH"]
        rank = os.environ["RANK"]
        open(os.path.join(out, f"run_ws{ws}_mb{mb}_r{rank}"), "w").close()
        if ws == "2" and rank == "0":
            # simulate a preempted host: shrink membership, then die
            open(hostfile, "w").write("localhost slots=1\\n")
            sys.exit(7)
    """)
    # Worker spawn is local regardless of hostname (launcher='local')
    agent = DSElasticAgent(w, [str(tmp_path), str(hostfile)],
                           ds_config={"elasticity": {
                               "enabled": True, "max_train_batch_size": 64,
                               "micro_batch_sizes": [2, 4, 8],
                               "min_gpus": 1, "max_gpus": 4}},
                           hostfile=str(hostfile), max_restarts=2,
                           restart_backoff=0.1, launcher="local")
    assert agent.run() == 0
    assert agent.world_history == [2, 1]
    runs = sorted(f for f in os.listdir(tmp_path) if f.startswith("run_"))
    assert any(f.startswith("run_ws2_") for f in runs)
    assert any(f.startswith("run_ws1_") for f in runs)


def test_restart_budget_exhausted(tmp_path):
    w = write_worker(tmp_path, """
        import sys
        sys.exit(1)
    """)
    agent = DSElasticAgent(w, [], hosts=["localhost"], max_restarts=1,
                           restart_backoff=0.05)
    assert agent.run() == 1
    assert agent.restarts == 2  # initial + 1 restart, then budget blown


def test_invalid_world_size_rejected(tmp_path):
    w = write_worker(tmp_path, "pass")
    agent = DSElasticAgent(w, [], hosts=["h1", "h2", "h3"],
                           ds_config={"elasticity": {
                               "enabled": True, "max_train_batch_size": 8,
                               "micro_batch_sizes": [4], "min_gpus": 1,
                               "max_gpus": 2}},
                           max_restarts=0, launcher="local")
    assert agent.run() == 1  # 3 hosts not in the compatible set
