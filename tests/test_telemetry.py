"""Unified telemetry pipeline tests (deepspeed_tpu/telemetry/).

Covers the disabled no-op fast path, span/record/counter mechanics, the
Chrome-trace + JSONL exporters, schema validation of ``summary()``, the
kernel-dispatch reason codes, the closed-form Pallas FLOP formulas, and the
acceptance path: one train-loop run on the 8-device CPU mesh with telemetry
on produces a Chrome trace with fwd/bwd/step + collective spans, a JSONL
stream with nonzero comm bytes and a ``sharded`` dispatch outcome, and the
log_summary table.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu import telemetry
from deepspeed_tpu.telemetry.core import _NULL_SPAN

SCHEMA_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "deepspeed_tpu", "telemetry",
    "summary.schema.json")


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test sees a fresh, DISABLED global pipeline with no sinks."""
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)
    yield
    telemetry.close()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)


# ---------------------------------------------------------------------------
# disabled fast path
# ---------------------------------------------------------------------------

def test_disabled_noop_fast_path(tmp_path, monkeypatch):
    """Disabled, every entry point is a constant-time no-op: the SAME null
    span object comes back every time, no jax sync runs, and no file is
    touched even when sink paths are configured."""
    jl = tmp_path / "m.jsonl"
    telemetry.configure(jsonl_path=str(jl), chrome_trace_path="")
    assert not telemetry.enabled()

    def _boom(*a, **k):
        raise AssertionError("block_until_ready must not run when disabled")
    monkeypatch.setattr(jax, "block_until_ready", _boom)

    sp = telemetry.span("fwd", step=1)
    assert sp is _NULL_SPAN
    assert telemetry.span("bwd") is sp, "disabled spans share one null object"
    sp.token = jnp.ones(4)  # absorbed
    with telemetry.span("scoped"):
        pass
    assert sp.end(token=jnp.ones(4)) is None

    telemetry.record("loss", 1.0, step=1)
    telemetry.count("steps")
    telemetry.record_comm("all_reduce", 1 << 20, 0.001, axis="dp")
    telemetry.record_dispatch("flash_mha", "sharded", "data")
    telemetry.record_compile("prog", 1.0)

    # serving-stream entry points (PR 6) ride the same guarantee
    telemetry.record_hist("serving/ttft_s", 0.05)
    assert telemetry.hist_percentiles("serving/ttft_s") is None
    telemetry.serving_event("submitted")
    telemetry.serving_gauge("serving/running", 3)
    telemetry.record_request_phase(0, "decode", 0.0, 0.01, tokens=1)

    # moe-stream entry points (ISSUE 15) ride the same guarantee: no
    # iteration over exp_counts, no gauge state, no sink writes
    telemetry.moe_gauge("moe/expert_load_max_frac", 0.5)
    telemetry.record_moe_step([4, 4, 8, 0], 16, dropped=2,
                              a2a_wire_bytes=1 << 20)
    assert telemetry.get_telemetry().moe_gauges == {}

    # the memory/ledger hooks must be no-ops too — zero device reads
    from deepspeed_tpu.telemetry.core import Telemetry

    def _no_read(*a, **k):
        raise AssertionError("memory_stats must not be read when disabled")
    monkeypatch.setattr(Telemetry, "_read_memory_stats",
                        staticmethod(_no_read))
    assert telemetry.record_memory("step", step=1) is None
    assert telemetry.ledger_step(step=1) is None
    telemetry.ledger_add("stall", 1.0)
    assert telemetry.maybe_oom_postmortem(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory")) is None
    assert telemetry.oom_postmortem(error="x") is None

    # overlap attachment is a no-op too: no validation, no record, no state
    assert telemetry.attach_overlap({"not": "even a valid report"}) is None
    assert telemetry.get_telemetry().overlap_report is None

    # the flight recorder is the ONE always-on hook: Fault/* mirrors into
    # its bounded ring even here, without waking the telemetry pipeline
    from deepspeed_tpu.telemetry import flightrec
    ring = flightrec.get_recorder()
    base = ring.total_count
    telemetry.record("Fault/slice.lost", 1, kind="counter")
    assert ring.total_count == base + 1
    assert ring.events()[-1]["name"] == "Fault/slice.lost"
    assert flightrec.flush_bundle("stall") is None, \
        "no destination configured -> no bundle litter"

    assert not jl.exists(), "disabled record must never open the jsonl sink"
    assert telemetry.summary() == {"enabled": False}
    assert telemetry.monitor_events(1) == []
    assert telemetry.format_summary() == "telemetry disabled"


def test_configure_registers_atexit_once(monkeypatch, tmp_path):
    """configure()/reset() cycles must never stack atexit export hooks —
    each extra hook would re-export (and with multiple instances, clobber)
    the trace file."""
    import atexit
    from deepspeed_tpu.telemetry import core

    calls = []
    monkeypatch.setattr(atexit, "register", lambda fn: calls.append(fn))
    monkeypatch.setattr(core, "_ATEXIT_REGISTERED", False)
    monkeypatch.setattr(core, "_ATEXIT_INSTANCES", [])

    tr = tmp_path / "trace.json"
    for _ in range(5):  # repeated init across reset cycles
        telemetry.configure(enabled=True, chrome_trace_path=str(tr))
        telemetry.reset()
    assert len(calls) == 1, "exactly one atexit hook across reconfigures"
    # even a SECOND instance must not add a second hook
    other = core.Telemetry()
    other.configure(enabled=True, chrome_trace_path=str(tr))
    assert len(calls) == 1
    assert len(core._ATEXIT_INSTANCES) == 2
    # the single hook exports every registered instance without raising
    with telemetry.span("fwd"):
        pass
    core._atexit_export_all()
    assert tr.exists()


# ---------------------------------------------------------------------------
# spans / metrics / counters
# ---------------------------------------------------------------------------

def test_span_records_once_and_syncs_token():
    telemetry.configure(enabled=True)
    synced = []
    with telemetry.span("fwd", step=3) as sp:
        sp.token = jnp.ones((4,)) * 2
    sp.end()  # second end is a no-op
    s = telemetry.summary()
    assert s["spans"]["fwd"]["count"] == 1
    assert s["spans"]["fwd"]["total_s"] >= 0
    # explicit begin/end pair (the engine idiom for cross-method scopes)
    sp2 = telemetry.span_begin("step")
    dt = sp2.end(token=jnp.zeros(2))
    assert dt >= 0
    assert telemetry.summary()["spans"]["step"]["count"] == 1
    del synced


def test_counters_accumulate_per_tag():
    telemetry.configure(enabled=True)
    telemetry.count("retries", kernel="a")
    telemetry.count("retries", n=2, kernel="a")
    telemetry.count("retries", kernel="b")
    telemetry.count("plain")
    c = telemetry.summary()["counters"]
    assert c["retries"]["kernel=a"] == 3
    assert c["retries"]["kernel=b"] == 1
    assert c["plain"]["_"] == 1


def test_record_comm_bandwidth_math():
    """record_comm must agree with calc_bw_log's ring factors."""
    telemetry.configure(enabled=True)
    n = max(jax.device_count(), 1)
    telemetry.record_comm("all_reduce", 10**9, 1.0, axis="dp")
    st = telemetry.summary()["comm"]["ops"]["all_reduce"]["dp"]
    assert st["bytes"] == 10**9
    assert st["algbw_gbs"] == pytest.approx(1.0)
    assert st["busbw_gbs"] == pytest.approx(2 * (n - 1) / n)
    # tuple axes key under "/" join; totals accumulate across ops
    telemetry.record_comm("all_gather", 500, 0.001, axis=("dp", "tp"))
    s = telemetry.summary()["comm"]
    assert s["ops"]["all_gather"]["dp/tp"]["count"] == 1
    assert s["total_bytes"] == 10**9 + 500


def test_jsonl_exporter_lines(tmp_path):
    jl = tmp_path / "metrics.jsonl"
    telemetry.configure(enabled=True, jsonl_path=str(jl))
    telemetry.record("loss", 2.5, step=1)
    with telemetry.span("fwd"):
        pass
    telemetry.record_dispatch("flash_mha", "fallback", "no_mesh")
    telemetry.close()
    lines = [json.loads(ln) for ln in jl.read_text().splitlines()]
    names = [ln["name"] for ln in lines]
    assert "loss" in names and "fwd" in names and "dispatch/flash_mha" in names
    for ln in lines:
        assert "ts" in ln and "kind" in ln and "value" in ln


def test_chrome_trace_export(tmp_path):
    tr = tmp_path / "trace.json"
    telemetry.configure(enabled=True, chrome_trace_path=str(tr))
    with telemetry.span("fwd", step=1):
        pass
    telemetry.record_comm("all_reduce", 4096, 0.002, axis="dp")
    path = telemetry.export_chrome_trace()
    doc = json.load(open(path))
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    by_name = {e["name"]: e for e in evs}
    assert by_name["fwd"]["ph"] == "X" and by_name["fwd"]["cat"] == "span"
    assert by_name["fwd"]["args"] == {"step": 1}
    comm = by_name["comm:all_reduce"]
    assert comm["cat"] == "comm" and comm["args"]["bytes"] == 4096
    assert comm["dur"] == pytest.approx(2000, rel=0.01)  # 2ms in µs
    # one process_name metadata event labels the host track for trace_merge
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(metas) == 1 and metas[0]["name"] == "process_name"
    for e in evs:
        if e["ph"] == "M":
            continue
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)


def test_summary_schema_validation():
    """The checked-in JSON schema accepts both the disabled stub and a fully
    populated summary (the exact object bench.py / aot_tpu_check.py embed)."""
    jsonschema = pytest.importorskip("jsonschema")
    schema = json.load(open(SCHEMA_PATH))
    jsonschema.validate(telemetry.summary(), schema)  # {"enabled": False}
    telemetry.configure(enabled=True)
    with telemetry.span("fwd"):
        pass
    telemetry.record_comm("all_reduce", 4096, 0.001, axis="dp")
    telemetry.record_dispatch("flash_mha", "sharded", "data", mesh_size=8)
    telemetry.record_dispatch("flash_mha", "veto", "accept_veto", mesh_size=8)
    telemetry.record_compile("p1", 2.0, topology="v5e:2x2", cache="miss")
    telemetry.record_compile("p2", 0.1, topology="v5e:2x2", cache="hit")
    telemetry.count("steps", phase="train")
    telemetry.record_moe_step([4, 4, 8, 0], 16, dropped=0,
                              a2a_wire_bytes=1 << 20)
    s = telemetry.summary()
    jsonschema.validate(s, schema)
    assert set(s["moe"]["gauges"]) == {"moe/expert_load_max_frac",
                                       "moe/drop_rate", "moe/a2a_wire_bytes"}
    assert s["compile"]["cache_hits"] == 1 and s["compile"]["cache_misses"] == 1
    # a malformed outcome must be rejected — the schema actually constrains
    bad = json.loads(json.dumps(s))
    bad["dispatch"]["flash_mha"]["exploded"] = bad["dispatch"]["flash_mha"].pop("sharded")
    with pytest.raises(jsonschema.ValidationError):
        jsonschema.validate(bad, schema)


# ---------------------------------------------------------------------------
# moe stream (ISSUE 15)
# ---------------------------------------------------------------------------

def test_moe_gauge_last_and_peak(tmp_path):
    jl = tmp_path / "m.jsonl"
    telemetry.configure(enabled=True, jsonl_path=str(jl))
    telemetry.moe_gauge("moe/expert_load_max_frac", 0.5)
    telemetry.moe_gauge("moe/expert_load_max_frac", 0.25, step=2)
    s = telemetry.summary()
    g = s["moe"]["gauges"]["moe/expert_load_max_frac"]
    assert g == {"last": 0.25, "peak": 0.5}
    # Chrome counter track + JSONL line per sample
    events = [e for e in telemetry.get_telemetry().trace_events
              if e.get("cat") == "moe"]
    assert len(events) == 2 and all(e["ph"] == "C" for e in events)
    telemetry.close()
    lines = [json.loads(ln) for ln in jl.read_text().splitlines()]
    moe_lines = [ln for ln in lines
                 if ln.get("name") == "moe/expert_load_max_frac"]
    assert len(moe_lines) == 2
    assert moe_lines[1]["tags"] == {"step": 2}


def test_record_moe_step_standard_gauges():
    telemetry.configure(enabled=True)
    # 16 (token, choice) assignments, 2 of which overflowed capacity
    telemetry.record_moe_step([4, 4, 8, 0], 16, dropped=2,
                              a2a_wire_bytes=2048)
    g = telemetry.summary()["moe"]["gauges"]
    assert g["moe/expert_load_max_frac"]["last"] == pytest.approx(0.5)
    assert g["moe/drop_rate"]["last"] == pytest.approx(2 / 16)
    assert g["moe/a2a_wire_bytes"]["last"] == 2048.0
    # dropless step: drop_rate pins to 0, wire gauge optional
    telemetry.record_moe_step([8, 8, 0, 0], 16)
    g = telemetry.summary()["moe"]["gauges"]
    assert g["moe/drop_rate"]["last"] == 0.0
    assert g["moe/a2a_wire_bytes"]["last"] == 2048.0  # unchanged


def test_monitor_events_bridge():
    telemetry.configure(enabled=True)
    with telemetry.span("fwd"):
        pass
    telemetry.record_comm("all_reduce", 4096, 0.001, axis="dp")
    telemetry.record_dispatch("flash_mha", "sharded", "data")
    events = telemetry.monitor_events(64)
    names = [e[0] for e in events]
    assert "Telemetry/Span/fwd_mean_ms" in names
    assert "Telemetry/Comm/total_bytes" in names
    assert "Telemetry/Dispatch/flash_mha/sharded" in names
    assert all(e[2] == 64 for e in events)


def test_telemetry_config_plumbing():
    """The ``telemetry`` config section parses into TelemetryConfig."""
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "telemetry": {"enabled": True, "jsonl_path": "/tmp/x.jsonl",
                      "sample_sync": False, "jax_annotations": True}})
    tc = cfg.telemetry_config
    assert tc.enabled and not tc.sample_sync and tc.jax_annotations
    assert tc.jsonl_path == "/tmp/x.jsonl"
    # defaults: fully off
    dflt = DeepSpeedConfig({"train_batch_size": 8}).telemetry_config
    assert not dflt.enabled and dflt.sample_sync and dflt.monitor


# ---------------------------------------------------------------------------
# dispatch reason codes (ops/registry.sharded_kernel_call)
# ---------------------------------------------------------------------------

def _dispatch_counts(kernel):
    return telemetry.summary().get("dispatch", {}).get(kernel, {})


def test_dispatch_reason_codes(eight_devices):
    from deepspeed_tpu.ops.registry import sharded_kernel_call
    from deepspeed_tpu.parallel.topology import use_kernel_mesh
    telemetry.configure(enabled=True)

    def double(x):
        return x * 2

    x = jnp.arange(16.0)
    # no mesh active -> fallback/no_mesh
    with use_kernel_mesh(None):
        out = sharded_kernel_call(double, (x,), (("data",),), ("data",),
                                  name="k")
    np.testing.assert_allclose(out, x * 2)
    assert _dispatch_counts("k")["fallback"]["no_mesh"] == 1

    mesh = Mesh(np.array(eight_devices), ("dp",))
    # accept veto
    with use_kernel_mesh(mesh):
        sharded_kernel_call(double, (x,), (("data",),), ("data",),
                            accept=lambda shapes: False, name="k")
    assert _dispatch_counts("k")["veto"]["accept_veto"] == 1
    # sharded over the data axis
    with use_kernel_mesh(mesh):
        out = sharded_kernel_call(double, (x,), (("data",),), ("data",),
                                  name="k")
    np.testing.assert_allclose(out, x * 2)
    assert _dispatch_counts("k")["sharded"]["data"] == 1
    # indivisible dim -> role dropped -> no_live_role
    y = jnp.arange(6.0)
    with use_kernel_mesh(mesh):
        sharded_kernel_call(double, (y,), (("data",),), ("data",), name="k")
    assert _dispatch_counts("k")["fallback"]["no_live_role"] == 1


# ---------------------------------------------------------------------------
# closed-form kernel FLOP formulas (flops profiler)
# ---------------------------------------------------------------------------

def test_kernel_flop_formulas():
    from deepspeed_tpu.profiling.flops_profiler.profiler import (
        KERNEL_FLOPS, kernel_flops, register_kernel_flops)
    # flash attention: QK^T + PV = 4*B*H*Sq*Skv*D; causal halves it
    full = kernel_flops("flash_mha", batch=2, heads=4, q_len=128,
                        kv_len=128, head_dim=64)
    assert full == 4 * 2 * 4 * 128 * 128 * 64
    causal = kernel_flops("flash_mha", batch=2, heads=4, q_len=128,
                          kv_len=128, head_dim=64, causal=True)
    assert causal == full // 2
    assert kernel_flops("paged_mha", num_seqs=3, heads=8, q_len=1,
                        kv_len=512, head_dim=64) == 4 * 3 * 8 * 512 * 64
    # block-sparse: density scales the dense count
    dense = kernel_flops("sparse_mha", batch=1, heads=2, q_len=256,
                         kv_len=256, head_dim=32)
    assert kernel_flops("sparse_mha", batch=1, heads=2, q_len=256,
                        kv_len=256, head_dim=32, density=0.25) == dense // 4
    # MoE grouped GEMM: up+down proj per routed token-copy
    assert kernel_flops("moe_ffn_gmm", tokens=64, d_model=128, d_ff=512,
                        topk=2) == 4 * 64 * 2 * 128 * 512
    assert kernel_flops("quantized_matmul", m=8, n=16, k=32) == 2 * 8 * 16 * 32
    assert set(KERNEL_FLOPS) >= {"flash_mha", "paged_mha", "sparse_mha",
                                 "moe_ffn_gmm", "quantized_matmul"}
    with pytest.raises(KeyError):
        kernel_flops("not_a_kernel")
    register_kernel_flops("custom", lambda m, n: 7 * m * n)
    assert kernel_flops("custom", m=2, n=3) == 42
    del KERNEL_FLOPS["custom"]


# ---------------------------------------------------------------------------
# acceptance: train loop + collective + kernel dispatch, all three artifacts
# ---------------------------------------------------------------------------

def test_train_loop_acceptance(eight_devices, tmp_path):
    """One engine train run on the 8-device CPU mesh with telemetry on:
    (a) Chrome trace with fwd/bwd/step + collective spans, (b) JSONL with
    nonzero comm bytes and a ``sharded`` dispatch outcome, (c) the
    log_summary table."""
    import deepspeed_tpu
    from deepspeed_tpu import comm as dist
    from deepspeed_tpu.parallel.topology import use_kernel_mesh
    from deepspeed_tpu.utils import jax_compat
    from tests.simple_model import SimpleModel, random_batches

    jl = tmp_path / "metrics.jsonl"
    tr = tmp_path / "trace.json"
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "telemetry": {"enabled": True, "jsonl_path": str(jl),
                              "chrome_trace_path": str(tr)}})
    assert telemetry.enabled(), "engine config must switch the pipeline on"

    def _loop():
        for b in random_batches(2, 8):
            loss = engine(b)
            engine.backward(loss)
            engine.step()
    _loop()

    # an explicit collective through the comm shim inside jit/shard_map —
    # traced at trace time with bytes from the tracer aval
    mesh = Mesh(np.array(eight_devices), ("dp",))
    f = jax.jit(jax_compat.shard_map(
        lambda x: dist.all_reduce(x, axis_name="dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P("dp"), check_vma=False))
    jax.block_until_ready(f(jnp.ones((8, 4), jnp.float32)))

    # a Pallas kernel entry point dispatching ``sharded`` over the mesh
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (8, 128, 4, 64), jnp.float32)
    k = jax.random.normal(ks[1], (8, 128, 2, 64), jnp.float32)
    v = jax.random.normal(ks[2], (8, 128, 2, 64), jnp.float32)
    with use_kernel_mesh(mesh):
        jax.block_until_ready(flash_mha(q, k, v, causal=True, interpret=True))

    # (a) chrome trace: train-phase spans + at least one collective span
    telemetry.export_chrome_trace()
    doc = json.load(open(tr))
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"fwd", "bwd", "step"} <= names, names
    assert any(n.startswith("comm:") for n in names), names

    # (b) jsonl: nonzero comm bytes + a sharded dispatch outcome
    telemetry.close()
    lines = [json.loads(ln) for ln in jl.read_text().splitlines()]
    comm_lines = [ln for ln in lines if ln["name"].startswith("comm/")]
    assert comm_lines and sum(ln["value"] for ln in comm_lines) > 0
    sharded = [ln for ln in lines if ln["name"].startswith("dispatch/")
               and ln["tags"]["outcome"] == "sharded"]
    assert sharded, [ln for ln in lines if ln["name"].startswith("dispatch/")]
    assert sharded[0]["name"] == "dispatch/flash_mha"

    # (c) summary table over all streams
    table = telemetry.log_summary(print_log=False)
    assert "fwd" in table and "Span" in table
    assert "Comm. Op" in table and "Kernel" in table

    # and the aggregate passes the checked-in schema
    jsonschema = pytest.importorskip("jsonschema")
    jsonschema.validate(telemetry.summary(), json.load(open(SCHEMA_PATH)))
    s = telemetry.summary()
    assert s["comm"]["total_bytes"] > 0
    assert "sharded" in s["dispatch"]["flash_mha"]


def test_engine_monitor_gets_telemetry_events(tmp_path):
    """At steps_per_print cadence the engine folds telemetry aggregates into
    the monitor event stream (Telemetry/* rows land in the csv backend)."""
    import deepspeed_tpu
    from tests.simple_model import SimpleModel, random_batches
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "steps_per_print": 1,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "telemetry": {"enabled": True},
                "csv_monitor": {"enabled": True, "output_path": str(tmp_path),
                                "job_name": "tele"}})
    for _ in range(2):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    files = [f for root, _, fs in os.walk(tmp_path) for f in fs]
    assert any(f.startswith("Telemetry_Span_fwd") for f in files), files


def test_bench_style_payload_embeds_summary_schema(tmp_path):
    """The exact embedding bench.py / aot_tpu_check.py perform: the summary
    object dropped into an artifact validates against the checked-in
    schema after a JSON round-trip."""
    jsonschema = pytest.importorskip("jsonschema")
    telemetry.configure(enabled=True)
    with telemetry.span("fwd"):
        pass
    telemetry.record_compile("llama_tp2xdp2_zero_fwd_bwd", 12.5,
                             topology="v5e:2x2", cache="miss")
    payload = {"metric": "tokens_per_sec", "value": 1.0,
               "extra": {"telemetry": telemetry.summary()}}
    out = tmp_path / "BENCH_test.json"
    out.write_text(json.dumps(payload))
    back = json.loads(out.read_text())
    schema = json.load(open(SCHEMA_PATH))
    jsonschema.validate(back["extra"]["telemetry"], schema)
    assert back["extra"]["telemetry"]["compile"]["programs"][
        "llama_tp2xdp2_zero_fwd_bwd"]["cache"] == "miss"
