"""SplitFuse scheduler: chunked prefill + fused decode must produce exactly
the greedy continuation of an unchunked run (FastGen SplitFuse invariant)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def served():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def make_engine(cfg, model, params, max_tokens=16):
    return InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": max_tokens,
                          "max_context": 128,
                          "num_kv_blocks": 64},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})


def greedy_reference(model, params, prompt, n_new):
    """Full-recompute greedy decode through the training forward."""
    cur = np.asarray(prompt, np.int32)[None, :]
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, {"input_ids": jnp.asarray(cur)})
        tok = int(np.argmax(np.asarray(logits[0, -1], np.float32)))
        out.append(tok)
        cur = np.concatenate([cur, [[tok]]], axis=1)
    return np.asarray(out, np.int32)


def assert_near_greedy(got, model, params, prompt, margin=1e-2):
    """Every engine-emitted token must be (near-)argmax of the full-recompute
    distribution over the engine's own context. Incremental-KV and
    full-recompute forwards differ by ~1e-4 in reduction order, so exact
    token equality is only required when the top-2 margin exceeds ``margin``
    (random tiny models hit genuine near-ties)."""
    cur = np.asarray(prompt, np.int32)[None, :]
    for i, tok in enumerate(np.asarray(got).tolist()):
        logits = model.apply({"params": params}, {"input_ids": jnp.asarray(cur)})
        l = np.asarray(logits[0, -1], np.float32)
        best = int(np.argmax(l))
        assert tok == best or l[best] - l[tok] < margin, (
            f"step {i}: engine chose {tok} but argmax {best} leads by "
            f"{l[best] - l[tok]:.5f}")
        cur = np.concatenate([cur, [[tok]]], axis=1)  # follow engine context


def test_single_long_prompt_chunked(served):
    cfg, model, params = served
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 37).astype(np.int32)  # > budget
    engine = make_engine(cfg, model, params, max_tokens=16)
    sched = SplitFuseScheduler(engine, token_budget=16)
    sched.submit(0, prompt, max_new_tokens=5)
    got = sched.run_to_completion()[0]
    assert len(got) == 5
    assert_near_greedy(got, model, params, prompt)


def test_mixed_prefill_and_decode(served):
    """Three staggered requests: long/short prompts chunk and fuse with
    running decodes; every output must equal its unbatched greedy run."""
    cfg, model, params = served
    rng = np.random.default_rng(2)
    prompts = {0: rng.integers(0, cfg.vocab_size, 29).astype(np.int32),
               1: rng.integers(0, cfg.vocab_size, 5).astype(np.int32),
               2: rng.integers(0, cfg.vocab_size, 18).astype(np.int32)}
    engine = make_engine(cfg, model, params, max_tokens=12)
    sched = SplitFuseScheduler(engine, token_budget=12)
    for uid, p in prompts.items():
        sched.submit(uid, p, max_new_tokens=4)
    got = sched.run_to_completion()
    for uid, p in prompts.items():
        assert len(got[uid]) == 4, f"uid {uid} incomplete"
        assert_near_greedy(got[uid], model, params, p)


def test_eos_stops_early(served):
    cfg, model, params = served
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, 9).astype(np.int32)
    # find what greedy emits first, then use it as the eos token
    first = int(greedy_reference(model, params, prompt, 1)[0])
    engine = make_engine(cfg, model, params)
    sched = SplitFuseScheduler(engine)
    sched.submit(0, prompt, max_new_tokens=8, eos_token_id=first)
    got = sched.run_to_completion()[0]
    assert got.tolist() == [first]


def test_budget_respected(served):
    cfg, model, params = served
    engine = make_engine(cfg, model, params, max_tokens=8)
    sched = SplitFuseScheduler(engine, token_budget=8)
    rng = np.random.default_rng(4)
    sched.submit(0, rng.integers(0, cfg.vocab_size, 21).astype(np.int32),
                 max_new_tokens=2)
    sched.submit(1, rng.integers(0, cfg.vocab_size, 20).astype(np.int32),
                 max_new_tokens=2)
    # intercept the shared forward (put and put_sampled both route through
    # it) to check per-round token totals
    orig_fwd = engine._forward_device
    totals = []

    def spy(uids, chunks):
        totals.append(sum(len(c) for c in chunks))
        return orig_fwd(uids, chunks)

    engine._forward_device = spy
    sched.run_to_completion()
    assert totals and all(t <= 8 for t in totals)


def test_context_capacity_retires_request(served):
    """A request that hits max_context is retired with what it has instead of
    wedging the scheduler (and oversized prompts are rejected at submit)."""
    cfg, model, params = served
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 2,
                          "max_ragged_batch_size": 16,
                          "max_context": 16, "num_kv_blocks": 8},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})
    sched = SplitFuseScheduler(engine)
    with pytest.raises(ValueError, match="cannot fit max_context"):
        sched.submit(9, np.arange(16, dtype=np.int32))
    rng = np.random.default_rng(5)
    sched.submit(0, rng.integers(0, cfg.vocab_size, 12).astype(np.int32),
                 max_new_tokens=10)
    got = sched.run_to_completion()[0]
    # 12 prompt + 4 generated fills the 16-token context; retired early
    assert 1 <= len(got) <= 4


def test_sampled_decode_reproducible_and_valid(served):
    """Per-request temperature sampling: deterministic per seed, tokens in
    vocab, different seeds may diverge."""
    cfg, model, params = served
    rng = np.random.default_rng(6)
    prompt = rng.integers(0, cfg.vocab_size, 7).astype(np.int32)

    def run(seed):
        engine = make_engine(cfg, model, params)
        sched = SplitFuseScheduler(engine)
        sched.submit(0, prompt, max_new_tokens=6, temperature=0.8,
                     top_k=20, seed=seed)
        return sched.run_to_completion()[0].tolist()

    a1, a2, b = run(1), run(1), run(2)
    assert a1 == a2, "same seed must reproduce"
    assert all(0 <= t < cfg.vocab_size for t in a1 + b)
    assert len(a1) == 6 and len(b) == 6


def test_sampling_param_validation(served):
    cfg, model, params = served
    engine = make_engine(cfg, model, params)
    sched = SplitFuseScheduler(engine)
    p = np.arange(5, dtype=np.int32) + 1
    with pytest.raises(ValueError, match="temperature"):
        sched.submit(0, p, temperature=-0.5)
    with pytest.raises(ValueError, match="top_p"):
        sched.submit(1, p, temperature=0.5, top_p=0.0)
    with pytest.raises(ValueError, match="top_k"):
        sched.submit(2, p, temperature=0.5, top_k=-1)


# ---------------------------------------------------------------- KV swap

def test_kv_cache_swap_roundtrip():
    """Host swap tier (ZeRO-Inference KV offload analog): block contents
    survive a swap_out → swap_in cycle bit-exactly, and the ids are reusable
    by others in between."""
    from deepspeed_tpu.inference.v2.ragged.kv_cache import BlockedKVCache
    kv = BlockedKVCache(num_layers=2, num_blocks=6, block_size=4,
                        num_kv_heads=2, head_dim=8, dtype="fp32")
    rng = np.random.default_rng(0)
    blocks = kv.reserve(3)
    fill_k = rng.standard_normal((2, 3, 2, 4, 8)).astype(np.float32)
    fill_v = rng.standard_normal((2, 3, 2, 4, 8)).astype(np.float32)
    idx = jnp.asarray(blocks)
    kv.update(kv.k_pool.at[:, idx].set(fill_k), kv.v_pool.at[:, idx].set(fill_v))
    free_before = kv.free_blocks
    handle = kv.swap_out(blocks)
    assert kv.free_blocks == free_before + 3
    # someone else takes (and dirties) the freed ids
    other = kv.reserve(3)
    kv.update(kv.k_pool.at[:, jnp.asarray(other)].set(-1.0), kv.v_pool)
    new_blocks = kv.swap_in(handle)
    np.testing.assert_array_equal(
        np.asarray(kv.k_pool[:, jnp.asarray(new_blocks)]), fill_k)
    np.testing.assert_array_equal(
        np.asarray(kv.v_pool[:, jnp.asarray(new_blocks)]), fill_v)


def test_scheduler_preempts_under_kv_pressure(served):
    """A KV pool too small for all requests at once: the scheduler host-swaps
    a decode's cache instead of starving, resumes it later, and every
    completion still matches its unbatched greedy run."""
    cfg, model, params = served
    rng = np.random.default_rng(7)
    prompts = {0: rng.integers(0, cfg.vocab_size, 44).astype(np.int32),
               1: rng.integers(0, cfg.vocab_size, 44).astype(np.int32)}
    # 10 blocks x 8 tokens: each request needs 44 + 6 = 50 tokens = 7 blocks.
    # Request 0 prefills to 6 blocks, request 1 stalls at the 4 remaining;
    # when 0's decode crosses into its 7th block nothing can schedule — the
    # deadlock the host-swap preemption exists to break (pre-swap behavior:
    # starvation RuntimeError after 3 rounds)
    engine = InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": 16,
                          "max_context": 128,
                          "num_kv_blocks": 10},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})
    sched = SplitFuseScheduler(engine, token_budget=16)
    for uid, p in prompts.items():
        sched.submit(uid, p, max_new_tokens=6)
    outs = sched.run_to_completion()
    assert all(len(outs[u]) == 6 for u in prompts)
    stats = engine.swap_stats
    assert stats["swap_outs"] >= 1 and stats["swap_ins"] >= 1, stats
    for uid, p in prompts.items():
        assert_near_greedy(outs[uid], model, params, p)


def test_engine_rejects_swapped_sequence(served):
    """The ENGINE owns the swap invariant: a swapped-out sequence cannot be
    scheduled (attention over zeroed blocks) until resume()."""
    cfg, model, params = served
    engine = make_engine(cfg, model, params)
    prompt = np.arange(10, dtype=np.int32)
    engine.put([7], [prompt])
    engine.preempt(7)
    verdict = engine.can_schedule([7], [1])
    assert not verdict.success and "swapped" in verdict.reason
    with pytest.raises(RuntimeError, match="swapped"):
        engine.put([7], [np.asarray([1], np.int32)])
    engine.resume(7)
    assert engine.can_schedule([7], [1]).success
