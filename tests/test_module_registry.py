"""Serving modules registry (`inference/v2/modules/module_registry.py`):
named implementations per interface, heuristic auto-selection, and loud
config pins — the reference's DSModuleRegistryBase/heuristics seam
(``deepspeed/inference/v2/modules/module_registry.py``)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.modules import module_registry as mr
from deepspeed_tpu.inference.v2.modules.heuristics import (
    instantiate_attention, instantiate_linear, instantiate_moe)


# -- registry mechanics -----------------------------------------------------

def test_registered_interfaces_complete():
    for iface in ("attention", "moe", "linear", "embedding", "unembed"):
        assert mr.registered(iface), f"no impls for {iface}"


def test_unknown_interface_raises():
    with pytest.raises(mr.UnknownModuleError, match="registered interfaces"):
        mr.registered("conv3d")


def test_unknown_impl_name_raises():
    with pytest.raises(mr.UnknownModuleError, match="registered:"):
        mr.select("attention", preference="flashinfer",
                  q_shape=(1, 1, 4, 64), pool_shape=(8, 2, 8, 64))


def test_duplicate_registration_raises():
    with pytest.raises(ValueError, match="duplicate"):
        mr.register_module("attention", "dense")(lambda **_: None)


def test_priority_order():
    names = [i.name for i in mr.registered("attention")]
    assert names.index("pallas_paged") < names.index("dense")


# -- auto selection ---------------------------------------------------------

def test_attention_auto_good_shapes(monkeypatch):
    # H % KV == 0, Dh <= 256, block_size % 8 == 0: kernel-eligible
    monkeypatch.setenv("DS_TPU_PALLAS_INTERPRET", "1")
    name, fn = instantiate_attention((4, 8, 8, 64), (16, 2, 8, 64))
    assert name == "pallas_paged" and fn is not None


def test_attention_auto_bad_shapes_falls_back():
    # block_size 6 violates the (8, 128) tiling rule
    name, fn = instantiate_attention((4, 8, 8, 64), (16, 2, 6, 64))
    assert name == "dense" and fn is None


def test_attention_disabled_pallas_falls_back(monkeypatch):
    monkeypatch.setenv("DS_TPU_DISABLE_PALLAS", "1")
    name, _ = instantiate_attention((4, 8, 8, 64), (16, 2, 8, 64))
    assert name == "dense"


def test_moe_auto_and_fallback(monkeypatch):
    monkeypatch.setenv("DS_TPU_PALLAS_INTERPRET", "1")
    assert instantiate_moe(128, 256)[0] == "megablox"
    assert instantiate_moe(100, 256)[0] == "einsum"  # not 128-tileable


# -- pins: loud, never silent -----------------------------------------------

def test_pin_dense_overrides_eligible_kernel():
    name, fn = instantiate_attention((4, 8, 8, 64), (16, 2, 8, 64),
                                     preference="dense")
    assert name == "dense" and fn is None


def test_pin_unsupported_raises_with_reason(monkeypatch):
    monkeypatch.setenv("DS_TPU_PALLAS_INTERPRET", "1")
    with pytest.raises(mr.UnsupportedModuleError, match="tiling"):
        instantiate_attention((4, 8, 8, 64), (16, 2, 6, 64),
                              preference="pallas_paged")


def test_pin_disabled_backend_raises(monkeypatch):
    monkeypatch.setenv("DS_TPU_DISABLE_PALLAS", "1")
    with pytest.raises(mr.UnsupportedModuleError, match="disabled"):
        instantiate_attention((4, 8, 8, 64), (16, 2, 8, 64),
                              preference="pallas_paged")


def test_pin_moe_unsupported_raises(monkeypatch):
    monkeypatch.setenv("DS_TPU_PALLAS_INTERPRET", "1")
    with pytest.raises(mr.UnsupportedModuleError, match="tileable"):
        instantiate_moe(100, 256, preference="megablox")


# -- linear interface through QuantizedParameter ----------------------------

def test_quantized_matmul_impl_swap_parity(monkeypatch):
    monkeypatch.setenv("DS_TPU_PALLAS_INTERPRET", "1")
    from deepspeed_tpu.inference.quantization import quantize_param_tree
    w = np.random.default_rng(0).normal(size=(512, 512)).astype(np.float32)
    qp = quantize_param_tree({"k": {"kernel": w}}, num_bits=8,
                             group_size=128)["k"]["kernel"]
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 512)),
                    jnp.float32)
    dense = np.asarray(qp.matmul(x, impl="dense_dequant"))
    fused = np.asarray(qp.matmul(x, impl="fused_dequant"))
    auto = np.asarray(qp.matmul(x))
    np.testing.assert_allclose(dense, fused, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(auto, dense, rtol=2e-2, atol=2e-2)


def test_quantized_matmul_bad_pin_raises():
    from deepspeed_tpu.inference.quantization import quantize_param_tree
    w = np.random.default_rng(0).normal(size=(100, 60)).astype(np.float32)
    qp = quantize_param_tree({"k": {"kernel": w}}, num_bits=8,
                             group_size=20)["k"]["kernel"]
    x = jnp.ones((4, 100), jnp.float32)
    with pytest.raises(mr.UnsupportedModuleError):
        qp.matmul(x, impl="fused_dequant")
    assert qp.matmul(x, impl="dense_dequant").shape == (4, 60)


# -- config-driven swap through a real engine -------------------------------

@pytest.fixture(scope="module")
def served():
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def _engine(served, modules=None):
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    cfg, model, params = served
    conf = {"state_manager": {"max_ragged_sequence_count": 4,
                              "max_ragged_batch_size": 16,
                              "max_context": 128, "num_kv_blocks": 64},
            "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}}
    if modules:
        conf["modules"] = modules
    return InferenceEngineV2(model, params, config=conf)


def test_engine_config_pin_attention_dense(served):
    """modules: {attention: dense} must flow config -> engine -> static model
    cfg -> trace-time selection, giving identical numerics (the dense path is
    the kernel's numerics twin) AND a distinct jit cache entry."""
    cfg, model, params = served
    prompt = np.random.default_rng(3).integers(
        0, cfg.vocab_size, 11).astype(np.int32)

    pinned = _engine(served, modules={"attention": "dense"})
    assert dict(pinned._model_config.serve_modules) == {"attention": "dense"}
    auto = _engine(served)
    assert auto._model_config.serve_modules is None

    mr.SELECTIONS.clear()
    a = pinned.put([7], [prompt])
    assert ("attention", "dense") in mr.SELECTIONS or not mr.SELECTIONS, \
        "pinned trace must select dense (empty = cached trace, see below)"
    b = auto.put([7], [prompt])
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_engine_unknown_pin_raises_at_construction(served):
    """A typo'd pin must fail before the KV pool is allocated."""
    with pytest.raises(mr.UnknownModuleError, match="flashinfer"):
        _engine(served, modules={"attention": "flashinfer"})


def test_engine_linear_pin_rejected(served):
    """The v2 ragged forwards carry fp weights — a linear pin nothing would
    consume must refuse loudly, not silently no-op."""
    with pytest.raises(mr.UnsupportedModuleError, match="quantized"):
        _engine(served, modules={"linear": "fused_dequant"})


def test_engine_moe_pin_rejected_on_dense_model(served):
    """A moe pin on a model with no MoE layer must refuse at construction."""
    with pytest.raises(mr.UnsupportedModuleError, match="no MoE layer"):
        _engine(served, modules={"moe": "megablox"})
