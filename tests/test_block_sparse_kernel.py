"""Pallas block-sparse attention kernel vs the masked-dense path.

Mirrors the reference's sparse-attention kernel tests
(``tests/unit/ops/sparse_attention``): every supported layout family must
match the dense masked softmax exactly, including causal and gradients."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.pallas.block_sparse_attention import (compact_layout,
                                                             sparse_mha)
from deepspeed_tpu.ops.sparse_attention import (BigBirdSparsityConfig,
                                                FixedSparsityConfig,
                                                sparse_attention)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    blockwise_sparse_attention)


def make_qkv(B=2, H=4, S=256, D=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (jax.random.normal(ks[0], (B, H, S, D)),
            jax.random.normal(ks[1], (B, H, S, D)),
            jax.random.normal(ks[2], (B, H, S, D)))


def layouts(S, block=16):
    fixed = FixedSparsityConfig(num_heads=4, block=block).make_layout(S)
    bird = BigBirdSparsityConfig(num_heads=4, block=block).make_layout(S)
    return {"fixed": fixed, "bigbird": bird}


@pytest.mark.parametrize("name", ["fixed", "bigbird"])
@pytest.mark.parametrize("causal", [False, True])
def test_kernel_matches_dense(name, causal):
    q, k, v = make_qkv()
    layout = layouts(256)[name]
    block = 16
    out_k = sparse_mha(q, k, v, layout, block, causal=causal, interpret=True)
    out_d = sparse_attention(q, k, v, layout, block, causal=causal)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_d),
                               atol=2e-4, rtol=1e-3)


def test_gradients_match_dense():
    q, k, v = make_qkv(B=1, H=4, S=128)
    layout = FixedSparsityConfig(num_heads=4, block=16).make_layout(128)

    def loss_k(q, k, v):
        return jnp.sum(sparse_mha(q, k, v, layout, 16, causal=True,
                                  interpret=True) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(sparse_attention(q, k, v, layout, 16, causal=True) ** 2)

    gk = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gk, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=2e-3)


def test_compaction_is_o_enabled():
    """The compacted schedule touches only enabled blocks (plus causal cut)."""
    layout = FixedSparsityConfig(num_heads=4, block=16).make_layout(256)
    cols, counts = compact_layout(layout, causal=True, block=16)
    dense_steps = 4 * (256 // 16) * (256 // 16)
    assert counts.sum() < dense_steps * 0.6  # genuinely sparse schedule
    H, nq, nk = np.asarray(layout).shape
    for h in range(H):
        for iq in range(nq):
            c = counts[h, iq]
            assert np.all(cols[h, iq, :c] <= iq)  # causal folded in


def test_blockwise_and_kernel_agree():
    q, k, v = make_qkv(B=1, H=4, S=128)
    layout = BigBirdSparsityConfig(num_heads=4, block=16).make_layout(128)
    out_k = sparse_mha(q, k, v, layout, 16, interpret=True)
    out_b = blockwise_sparse_attention(q, k, v, layout, 16)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_b),
                               atol=2e-4, rtol=1e-3)
