"""Steady-state host-sync contract (docs/AUTOTUNING.md, "host-sync-free
stepping"): between ``steps_per_print``/monitor boundaries the engine must
issue ZERO blocking device->host transfers — the loss, overflow flag,
grad norm and skipped counter all stay device-resident, and every fetch the
engine does issue goes through ``_host_fetch`` so ``host_sync_count`` audits
it.

Enforcement is layered because the CPU backend's arrays are host-visible
(zero-copy, so jax's transfer guard never fires there):

1. ``jax.transfer_guard_device_to_host("disallow_explicit")`` wraps the
   steady-state region — on a real TPU any d2h transfer (including an
   explicit ``jax.device_get``) raises;
2. ``jax.device_get`` is monkeypatched to count calls — effective on CPU CI;
3. ``engine.host_sync_count`` must stay flat across steady-state steps and
   tick exactly once per accounted boundary fetch.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from tests.simple_model import SimpleModel, random_batches

NEVER = 10 ** 9  # steps_per_print cadence that a short test never reaches


def _make_engine(extra=None, seed=0):
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": NEVER,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(extra or {})
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(seed), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    return engine


class _GetCounter:
    """Counting wrapper around jax.device_get (calls through)."""

    def __init__(self):
        self.calls = 0
        self._orig = jax.device_get

    def __call__(self, x):
        self.calls += 1
        return self._orig(x)


@pytest.fixture
def counted_device_get(monkeypatch):
    counter = _GetCounter()
    monkeypatch.setattr(jax, "device_get", counter)
    return counter


def test_steady_state_step_has_no_host_sync(counted_device_get):
    engine = _make_engine()
    batches = random_batches(8, 8)
    # warmup: compile + let output weak-types settle OUTSIDE the guard
    for b in batches[:2]:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    jax.block_until_ready(engine.state.params)

    base_syncs = engine.host_sync_count
    base_gets = counted_device_get.calls
    with jax.transfer_guard_device_to_host("disallow_explicit"):
        for b in batches[2:]:
            loss = engine(b)
            engine.backward(loss)
            engine.step()
    assert engine.host_sync_count == base_syncs, \
        "steady-state step() issued an accounted host sync"
    assert counted_device_get.calls == base_gets, \
        "steady-state step() called jax.device_get"
    assert engine.global_steps == len(batches)
    # the result is still correct once the caller pays the sync
    assert np.isfinite(float(jax.device_get(loss)))


def test_boundary_fetches_are_counted(counted_device_get):
    engine = _make_engine()
    b = random_batches(1, 8)[0]
    loss = engine(b)
    engine.backward(loss)
    engine.step()

    base = engine.host_sync_count
    engine.get_lr()
    assert engine.host_sync_count == base + 1
    _ = engine.cur_scale
    assert engine.host_sync_count == base + 2
    _ = engine.skipped_steps
    assert engine.host_sync_count == base + 3
    engine.get_global_grad_norm()
    assert engine.host_sync_count == base + 4
    # every accounted fetch went through exactly one device_get
    assert counted_device_get.calls >= 4


def test_steps_per_print_boundary_syncs():
    """The log_dist boundary (steps_per_print=1 -> every step) fetches
    skipped/lr/scale through the accounted path."""
    engine = _make_engine({"steps_per_print": 1})
    b = random_batches(1, 8)[0]
    loss = engine(b)
    engine.backward(loss)
    base = engine.host_sync_count
    engine.step()
    assert engine.host_sync_count > base


def test_train_batch_returns_device_resident_loss(counted_device_get):
    engine = _make_engine({"train_batch_size": 16,
                           "gradient_accumulation_steps": 2})
    batches = random_batches(8, 8)
    it = iter(batches)
    engine.train_batch(it)  # warmup window (compile)

    base_gets = counted_device_get.calls
    base_syncs = engine.host_sync_count
    with jax.transfer_guard_device_to_host("disallow_explicit"):
        mean = engine.train_batch(it)
    assert isinstance(mean, jax.Array), \
        "train_batch must return the device-resident window mean"
    assert counted_device_get.calls == base_gets
    assert engine.host_sync_count == base_syncs
    assert np.isfinite(float(jax.device_get(mean)))


def test_fused_gas_train_batch_no_steady_state_sync(counted_device_get):
    engine = _make_engine({"train_batch_size": 16,
                           "gradient_accumulation_steps": 2,
                           "fused_step": True})
    batches = random_batches(8, 8)
    it = iter(batches)
    engine.train_batch(it)  # warmup: compiles the fused GAS scan

    base_gets = counted_device_get.calls
    with jax.transfer_guard_device_to_host("disallow_explicit"):
        mean = engine.train_batch(it)
    assert isinstance(mean, jax.Array)
    assert counted_device_get.calls == base_gets
    assert engine._fused_gas_step_fn is not None
    assert np.isfinite(float(jax.device_get(mean)))


def test_host_sync_counter_in_telemetry(tmp_path):
    """When telemetry is on, accounted fetches land in the host_sync
    counter (bench surfaces the same number via extra.host_sync_count)."""
    from deepspeed_tpu import telemetry
    telemetry.configure(enabled=True)
    try:
        engine = _make_engine()
        b = random_batches(1, 8)[0]
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        engine.get_lr()
        counters = telemetry.summary()["counters"]
        assert "host_sync" in counters
        assert any("get_lr" in tag for tag in counters["host_sync"])
    finally:
        telemetry.configure(enabled=False)
