"""Worker for the cross-process 1-bit exchange test (VERDICT r4 #8).

The reference's compressed allreduce runs over NCCL/MPI process boundaries
(``deepspeed/runtime/comm/nccl.py:51``); this worker proves our in-trace
analog does the same over a REAL ``jax.distributed`` CPU cluster: two OS
processes, one device each, a GLOBAL 2-device mesh, and
``compressed_allreduce`` inside ``shard_map`` — every packed-sign
all_to_all/all_gather crosses the process boundary.

Asserts, and writes per-rank result files for the launcher test:
1. exact case — identical constant-magnitude (+/-c) gradients compress
   losslessly, so compressed == dense mean bitwise-close; a full onebit-Adam
   step driven by each exchange produces identical parameters.
2. error-feedback case — different random gradients per rank, constant over
   steps: the cumulative compressed average converges to the dense mean
   (residual stays bounded, so relative error shrinks ~1/T).
"""

import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402
from deepspeed_tpu.utils.jax_compat import shard_map  # noqa: E402

from deepspeed_tpu import dist  # noqa: E402
from deepspeed_tpu.runtime.comm.compressed import (  # noqa: E402
    compressed_allreduce, init_error_buffers)


def main():
    out_dir = sys.argv[1]
    dist.init_distributed()
    rank, world = int(dist.get_rank()), int(dist.get_world_size())
    assert world == 2, f"expected 2 processes, got {world}"
    devices = jax.devices()
    D = len(devices)                       # global mesh size (devices may be
    nloc = jax.local_device_count()        # forced >1 per process via XLA_FLAGS)
    assert D == world * nloc and D >= 2
    mesh = Mesh(np.array(devices), ("dp",))
    n = 1024

    def global_rows(local_rows):
        """[local, n] process-local -> [D, n] global array sharded over dp."""
        sharding = NamedSharding(mesh, P("dp"))
        return jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(local_rows.reshape(nloc, n)),
            (D, n))

    def exchange(x, we, se):
        def f(x, we, se):
            out, we2, se2 = compressed_allreduce(
                x[0], we[0], se[0], axis_name="dp")
            return out[None], we2[None], se2[None]
        return shard_map(f, mesh=mesh,
                         in_specs=(P("dp"), P("dp"), P("dp")),
                         out_specs=(P("dp"), P("dp"), P("dp")),
                         check_vma=False)(x, we, se)

    def dense_mean(x):
        f = lambda x: jax.lax.pmean(x[0], "dp")[None]
        return shard_map(f, mesh=mesh, in_specs=(P("dp"),),
                         out_specs=P("dp"), check_vma=False)(x)

    we0, se0 = init_error_buffers(n, D)
    we = global_rows(np.tile(np.asarray(we0), (nloc, 1)))
    se = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")),
        np.tile(np.asarray(se0), (nloc, 1)), (D, se0.size))

    # --- 1. exact case: +/-c entries, identical across ranks ---------------
    rng = np.random.default_rng(7)
    signs = np.where(rng.normal(size=n) >= 0, 1.0, -1.0).astype(np.float32)
    g_exact = 0.25 * signs
    x = global_rows(np.tile(g_exact, (nloc, 1)))
    out, we1, se1 = exchange(x, we, se)
    local = np.asarray(out.addressable_data(0)).reshape(-1)
    dm = np.asarray(dense_mean(x).addressable_data(0)).reshape(-1)
    exact_err = float(np.max(np.abs(local - dm)))
    assert exact_err < 1e-5, f"exact-case exchange error {exact_err}"

    # onebit-Adam step parity on the exact exchange (host-side optax step,
    # same averaged gradient -> same update)
    from deepspeed_tpu.ops.onebit import onebit_adam
    opt = onebit_adam(learning_rate=1e-2, freeze_step=1)
    params = {"w": jnp.asarray(rng.normal(size=n), jnp.float32)}
    st = opt.init(params)
    up_c, _ = opt.update({"w": jnp.asarray(local)}, st, params)
    up_d, _ = opt.update({"w": jnp.asarray(dm)}, st, params)
    opt_err = float(np.max(np.abs(np.asarray(up_c["w"]) - np.asarray(up_d["w"]))))
    assert opt_err < 1e-6, f"onebit-Adam update diverged: {opt_err}"

    # --- 2. error feedback: per-device random grads, constant over steps ---
    g_all = rng.normal(size=(D, n)).astype(np.float32)  # same seed both ranks
    x = global_rows(g_all[rank * nloc:(rank + 1) * nloc])
    target = np.asarray(dense_mean(x).addressable_data(0)).reshape(-1)
    csum = np.zeros(n, np.float64)
    rel = {}
    for t in range(1, 49):
        out, we, se = exchange(x, we, se)
        csum += np.asarray(out.addressable_data(0)).reshape(-1)
        if t in (2, 12, 48):
            rel[t] = float(np.linalg.norm(csum / t - target) /
                           np.linalg.norm(target))
    # residual bound: |csum/T - target| = |e_T|/T -> ~1/T decay (the target
    # norm is shrunk ~sqrt(D)x by averaging D independent vectors, so the
    # relative scale needs the longer horizon)
    assert rel[48] < rel[12] < rel[2], f"error feedback not converging: {rel}"
    assert rel[48] < 0.1, f"cumulative relative error too high: {rel}"

    dist.barrier()
    with open(os.path.join(out_dir, f"rank{rank}.ok"), "w") as f:
        f.write(f"world={world} exact_err={exact_err:.2e} "
                f"opt_err={opt_err:.2e} rel2={rel[2]:.4f} rel48={rel[48]:.4f}\n")


if __name__ == "__main__":
    main()
