"""Long-context KV capacity tiering: int8 KV pages + the host-DRAM spill
tier.

Pins the two capacity axes end to end: (a) int8 paged KV — the fused
dequant-on-read Pallas kernel against its dense twin on identical quantized
pages, write-side quantization through the jitted forwards, bit-exact
generated-token parity int8 vs fp (greedy and seeded sampling) on the
8-device CPU mesh, and the >= 2x blocks-per-budget capacity claim; (b) the
host tier — prefix blocks spilled under pressure revive with their contents
intact (generation parity through a spill/restore round trip), live
sequences are never swapped while parked blocks can pay instead
(``swap_outs_live == 0``), the double-buffered ``HostKVSwapper`` bounds
in-flight landings, and every landing routes through the engine's accounted
``host_fetch``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.model_implementations.llama import (
    _paged_attention_dense)
from deepspeed_tpu.inference.v2.ragged.ragged_manager import DSStateManager
from deepspeed_tpu.inference.v2.scheduler import SplitFuseScheduler
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.ops.pallas.paged_attention import paged_mha
from deepspeed_tpu.ops.pallas.quant_collective import _quantize_rows_ref
from deepspeed_tpu.runtime.swap_tensor.kv_swapper import HostKVSwapper


@pytest.fixture(scope="module")
def served():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def make_engine(cfg, model, params, kv_dtype="fp", host_kv_blocks=0,
                prefix_caching=False, num_kv_blocks=64, max_tokens=16,
                max_context=128):
    return InferenceEngineV2(model, params, config={
        "state_manager": {"max_ragged_sequence_count": 4,
                          "max_ragged_batch_size": max_tokens,
                          "max_context": max_context,
                          "num_kv_blocks": num_kv_blocks,
                          "kv_dtype": kv_dtype,
                          "host_kv_blocks": host_kv_blocks},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"},
        "prefix_caching": prefix_caching})


# ---------------------------------------------------------------------------
# fused dequant-on-read kernel vs dense twin
# ---------------------------------------------------------------------------

def _quantize_pool(pool):
    """fp pool [NB, KV, bs, Dh] -> (int8 pool, fp32 scales [NB, KV, 1, bs])
    in the cache's per-token-row wire format."""
    NB, KV, bs, Dh = pool.shape
    q, scale = _quantize_rows_ref(pool.reshape(-1, Dh), 8)
    return (q.reshape(pool.shape),
            scale.reshape(NB, KV, bs)[:, :, None, :].astype(jnp.float32))


def make_int8_case(S=3, Q=1, H=4, KV=2, Dh=64, NB=10, bs=16, MB=4, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (S, Q, H, Dh), jnp.float32)
    kq, kscale = _quantize_pool(
        jax.random.normal(ks[1], (NB, KV, bs, Dh), jnp.float32))
    vq, vscale = _quantize_pool(
        jax.random.normal(ks[2], (NB, KV, bs, Dh), jnp.float32))
    rng = np.random.default_rng(seed)
    bt = rng.permutation((NB - 1) * MB)[: S * MB].reshape(S, MB) % (NB - 1)
    block_tables = jnp.asarray(bt, jnp.int32)
    seen = jnp.asarray(rng.integers(0, MB * bs - Q, size=S), jnp.int32)
    q_len = jnp.full((S,), Q, jnp.int32)
    return q, (kq, kscale), (vq, vscale), block_tables, seen, q_len


def valid_rows(out, q_len):
    S, Q = out.shape[:2]
    mask = np.arange(Q)[None, :] < np.asarray(q_len)[:, None]
    return np.asarray(out)[mask]


@pytest.mark.parametrize("Q", [1, 4])
def test_int8_kernel_matches_dense_dequant(Q):
    """The kernel's in-VMEM dequant (int8 pages + [1, bs] scale rows folded
    into score/probability columns) must match the dense gather-then-
    dequantize twin on identical quantized pages."""
    q, (kq, ks), (vq, vs), bt, seen, q_len = make_int8_case(Q=Q)
    out_k = paged_mha(q, kq, vq, bt, seen, q_len, k_scale=ks, v_scale=vs,
                      interpret=True)
    out_d = _paged_attention_dense(q, (kq, ks), (vq, vs), bt, seen,
                                   kq.shape[2])
    np.testing.assert_allclose(valid_rows(out_k, q_len),
                               valid_rows(out_d, q_len),
                               atol=2e-4, rtol=1e-3)


def test_int8_kernel_tracks_fp_reference():
    """Dequantized attention must stay close to attention over the
    dequantized fp pools — int8 costs precision, not correctness."""
    q, (kq, ks), (vq, vs), bt, seen, q_len = make_int8_case(seed=3)
    out_k = paged_mha(q, kq, vq, bt, seen, q_len, k_scale=ks, v_scale=vs,
                      interpret=True)
    # reconstruct the fp pools the quantizer saw (scale rows broadcast back)
    k_fp = kq.astype(jnp.float32) * jnp.swapaxes(ks, -1, -2)
    v_fp = vq.astype(jnp.float32) * jnp.swapaxes(vs, -1, -2)
    out_ref = _paged_attention_dense(q, k_fp, v_fp, bt, seen, kq.shape[2])
    np.testing.assert_allclose(valid_rows(out_k, q_len),
                               valid_rows(out_ref, q_len),
                               atol=2e-4, rtol=1e-3)


def test_int8_kernel_sliding_window():
    q, (kq, ks), (vq, vs), bt, seen, q_len = make_int8_case(S=2, Q=2, seed=5)
    out_k = paged_mha(q, kq, vq, bt, seen, q_len, k_scale=ks, v_scale=vs,
                      window=16, interpret=True)
    out_d = _paged_attention_dense(q, (kq, ks), (vq, vs), bt, seen,
                                   kq.shape[2], window=16)
    np.testing.assert_allclose(valid_rows(out_k, q_len),
                               valid_rows(out_d, q_len),
                               atol=2e-4, rtol=1e-3)


# ---------------------------------------------------------------------------
# int8 vs fp generation parity (the ISSUE's bit-parity generation gate)
# ---------------------------------------------------------------------------

def _drive(cfg, model, params, kv_dtype, kw_fn, **engine_kw):
    engine = make_engine(cfg, model, params, kv_dtype=kv_dtype, **engine_kw)
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(31)
    prefix = rng.integers(0, cfg.vocab_size, 16).astype(np.int32)
    for uid in range(3):
        tail = rng.integers(0, cfg.vocab_size, 6 + 5 * uid).astype(np.int32)
        sched.submit(uid, np.concatenate([prefix, tail]), **kw_fn(uid))
    got = sched.run_to_completion()
    return {u: got[u].tolist() for u in got}, engine


def test_generation_parity_int8_vs_fp_greedy(served, eight_devices):
    """Greedy decode, int8 KV vs fp KV: generated token ids must match
    exactly — the parity gate for the quantized tier."""
    cfg, model, params = served
    kw = lambda u: {"max_new_tokens": 5}  # noqa: E731
    fp, _ = _drive(cfg, model, params, "fp", kw)
    q, engine = _drive(cfg, model, params, "int8", kw)
    assert q == fp
    assert engine._state.kv_cache.quantized


def test_generation_parity_int8_vs_fp_sampled(served, eight_devices):
    """Seeded per-request sampling: identical sampled ids at fixed seeds —
    int8's logit perturbation must not cross any draw threshold here."""
    cfg, model, params = served

    def kw(uid):
        return {"max_new_tokens": 5, "temperature": 0.7, "top_k": 8,
                "seed": 400 + uid * 17}

    fp, _ = _drive(cfg, model, params, "fp", kw)
    q, _ = _drive(cfg, model, params, "int8", kw)
    assert q == fp


def test_int8_pool_capacity_multiplier(served):
    """At equal HBM budget int8 pages (+ scales) hold >= 2x the blocks of
    the fp pool — measured on the REAL pool arrays, not the formula."""
    cfg, model, params = served
    fp_eng = make_engine(cfg, model, params, kv_dtype="fp")
    q_eng = make_engine(cfg, model, params, kv_dtype="int8")

    def pool_bytes(kv):
        total = kv.k_pool.nbytes + kv.v_pool.nbytes
        if kv.quantized:
            total += kv.k_scale.nbytes + kv.v_scale.nbytes
        return total

    fp_bytes = pool_bytes(fp_eng._state.kv_cache)
    q_bytes = pool_bytes(q_eng._state.kv_cache)
    assert fp_bytes / q_bytes >= 2.0, \
        f"int8 pages must at least halve KV bytes/block ({fp_bytes}/{q_bytes})"
    # and the budget-derived block count reflects it
    kv_cfg = fp_eng._config.kv_cache
    fp_blocks = DSStateManager._blocks_from_memory_budget(
        2, 2, 64, kv_cfg, kv_dtype="fp")
    q_blocks = DSStateManager._blocks_from_memory_budget(
        2, 2, 64, kv_cfg, kv_dtype="int8")
    assert q_blocks >= 2 * fp_blocks


# ---------------------------------------------------------------------------
# host-DRAM tier at the engine level
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["fp", "int8"])
def test_prefix_blocks_spill_and_revive_without_live_swaps(served, kv_dtype):
    """Under pool pressure parked prefix blocks spill to the host tier and a
    later shared-prefix request revives them — with the restored generation
    bit-identical to an unpressured engine's and ``swap_outs_live == 0``
    (no live sequence ever paid the preemption path)."""
    cfg, model, params = served
    rng = np.random.default_rng(47)
    warm = rng.integers(0, cfg.vocab_size, 40).astype(np.int32)
    filler = rng.integers(0, cfg.vocab_size, 60).astype(np.int32)
    reuse = np.concatenate(
        [warm, rng.integers(0, cfg.vocab_size, 6).astype(np.int32)])

    engine = make_engine(cfg, model, params, kv_dtype=kv_dtype,
                         prefix_caching=True, num_kv_blocks=12,
                         host_kv_blocks=16)
    sched = SplitFuseScheduler(engine, token_budget=16)
    sched.submit(0, warm, max_new_tokens=2)
    sched.run_to_completion()   # parks warm's full blocks
    sched.submit(1, filler, max_new_tokens=2)
    sched.run_to_completion()   # pressure: parked blocks spill to host
    stats = engine.kv_stats()
    assert stats["kv_spilled"] >= 1, "pressure must spill parked blocks"
    assert stats["host_kv_blocks"] >= 1
    # host-resident blocks hold no HBM: total/occupancy/occupied stay the
    # DEVICE census, so spilling can't inflate the ratcheted occupancy gauge
    alloc = engine._state.kv_cache.allocator
    assert stats["total_blocks"] == alloc.num_blocks
    assert stats["occupied_blocks"] == alloc.live_blocks
    assert stats["occupancy"] == pytest.approx(
        alloc.live_blocks / alloc.num_blocks)
    assert 0.0 <= stats["peak_occupancy"] <= 1.0
    sched.submit(2, reuse, max_new_tokens=4)
    out = sched.run_to_completion()[2].tolist()
    stats = engine.kv_stats()
    assert stats["kv_restored"] >= 1, "the shared prefix must restore"
    assert stats["swap_outs_live"] == 0, \
        "parked blocks must pay for pressure before any live swap"
    assert stats["kv_spilled"] == stats["kv_restored"] + \
        stats["kv_dropped"] + stats["host_kv_blocks"]
    assert sched.prefill_tokens_saved > 0

    # parity: an unpressured engine generates the same tokens for uid 2 —
    # the spill/restore round trip preserved the KV bytes exactly
    ref_engine = make_engine(cfg, model, params, kv_dtype=kv_dtype,
                             num_kv_blocks=64)
    ref = SplitFuseScheduler(ref_engine, token_budget=16)
    ref.submit(2, reuse, max_new_tokens=4)
    assert ref.run_to_completion()[2].tolist() == out


def test_spill_landings_route_through_accounted_host_fetch(served):
    """Every device->host landing of spill traffic goes through the
    engine's ``host_fetch`` — the host-sync ratchet and graftlint see KV
    swaps like any other boundary."""
    cfg, model, params = served
    engine = make_engine(cfg, model, params, prefix_caching=True,
                         num_kv_blocks=12, host_kv_blocks=16)
    sched = SplitFuseScheduler(engine, token_budget=16)
    rng = np.random.default_rng(48)
    sched.submit(0, rng.integers(0, cfg.vocab_size, 40).astype(np.int32),
                 max_new_tokens=2)
    sched.run_to_completion()
    base = engine.host_sync_count
    sched.submit(1, rng.integers(0, cfg.vocab_size, 60).astype(np.int32),
                 max_new_tokens=2)
    sched.run_to_completion()
    assert engine.kv_stats()["kv_spilled"] >= 1
    # force the pending double-buffered landings through
    engine._state.kv_cache.swapper.drain()
    assert engine._state.kv_cache.swapper.landings >= 1
    assert engine.host_sync_count > base + 2, \
        "spill landings must be accounted (not bare device_get)"


def test_host_kv_stats_fields(served):
    cfg, model, params = served
    engine = make_engine(cfg, model, params, host_kv_blocks=8)
    stats = engine.kv_stats()
    assert stats["host_kv_capacity"] == 8
    assert stats["host_kv_blocks"] == 0
    assert stats["host_kv_occupancy"] == 0.0
    assert stats["swap_outs_live"] == 0
    assert stats["kv_spilled"] == stats["kv_restored"] == \
        stats["kv_dropped"] == 0


# ---------------------------------------------------------------------------
# HostKVSwapper double buffering
# ---------------------------------------------------------------------------

def test_swapper_bounds_pending_and_preserves_payloads():
    landed = []

    def fetch(arrays, what):
        landed.append(what)
        return tuple(np.asarray(a) for a in arrays)

    sw = HostKVSwapper(fetch, buffer_count=2)
    p1 = sw.submit((np.ones(4),))
    p2 = sw.submit((np.full(4, 2.0),))
    assert sw.pending == 2 and not landed    # within the buffer: deferred
    p3 = sw.submit((np.full(4, 3.0),))
    assert sw.pending == 2 and len(landed) == 1  # oldest landed to make room
    out = sw.land(p1)                         # already landed: cached
    assert np.all(out[0] == 1.0) and len(landed) == 1
    out = sw.land(p3)                         # jump the queue: force-land
    assert np.all(out[0] == 3.0) and len(landed) == 2
    sw.drain()
    assert sw.pending == 0 and len(landed) == 3
    assert sw.landings == 3
    out = sw.land(p2)                         # landed by drain
    assert np.all(out[0] == 2.0)


def test_swapper_uses_accounted_fetch_tag():
    tags = []

    def fetch(arrays, what):
        tags.append(what)
        return arrays

    sw = HostKVSwapper(fetch, buffer_count=1)
    sw.submit((np.zeros(2),))
    sw.drain()
    assert tags == ["kv_cache/spill"]
