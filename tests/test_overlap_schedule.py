"""Overlap scheduling pass tests (runtime/zero/overlap_schedule.py,
ROADMAP item 2).

Three layers, mirroring the module: the stdlib analytic scheduler (the
two-resource timeline must strictly beat the serialized worst case and
stay conserved), the planner (advisor-seeded candidates, the chip-free
autotuner's overlap dimension), and the runtime (scheduled_scan parity,
the engine's scheduled qgZ micro-step reproducing the unscheduled loss
trajectory exactly, the SimpleModel fallback). The perf_gate ratchet over
the checked-in baseline is driven in-process via the script's own loader.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu.runtime.zero import overlap_schedule as osched
from deepspeed_tpu.runtime.zero.qgz import QgzPlan
from deepspeed_tpu.telemetry import overlap as ov_mod
from tests.simple_model import SimpleModel, random_batches

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: a ZeRO-3-shaped inventory where compute is big enough to hide most comm —
#: the regime the scheduling pass exists for
COMPUTE_S = 1e-3
COMM_OPS = [
    {"op": "all_gather", "axis": "dp", "bytes": 1 << 22, "seconds": 2e-4},
    {"op": "reduce_scatter", "axis": "dp", "bytes": 1 << 22,
     "seconds": 3e-4},
    {"op": "all_reduce", "axis": "dp", "bytes": 4096, "seconds": 5e-6},
]


def serialized_exposed(compute_s=COMPUTE_S, comm_ops=COMM_OPS):
    att = ov_mod.attribute(ov_mod.analytic_intervals(compute_s, comm_ops))
    return att["totals"]["exposed_comm_s"]


# ---------------------------------------------------------------------------
# analytic scheduler (stdlib)
# ---------------------------------------------------------------------------

def test_overlap_plan_validates():
    with pytest.raises(ValueError, match="prefetch_depth"):
        osched.OverlapPlan(prefetch_depth=-1)
    with pytest.raises(ValueError, match="grad_buckets"):
        osched.OverlapPlan(grad_buckets=0)
    with pytest.raises(ValueError, match="n_layers"):
        osched.OverlapPlan(n_layers=0)
    with pytest.raises(ValueError, match="fwd_fraction"):
        osched.OverlapPlan(fwd_fraction=1.5)
    plan = osched.OverlapPlan(prefetch_depth=2, grad_buckets=4, n_layers=12)
    assert osched.OverlapPlan.from_dict(plan.to_dict()).to_dict() == \
        plan.to_dict()


def test_scheduled_strictly_below_serialized():
    """The acceptance criterion's shape: a prefetching, bucketized schedule
    must expose strictly less than the serialized worst case."""
    ser = serialized_exposed()
    plan = osched.OverlapPlan(prefetch_depth=1, grad_buckets=4, n_layers=8)
    sched = osched.plan_exposure(COMPUTE_S, COMM_OPS, plan)
    assert sched < ser, f"scheduled {sched} not below serialized {ser}"
    # compute-rich inventory: the pipeline should hide well over 30%
    assert sched <= 0.7 * ser


def test_scheduled_timeline_conserves_comm():
    """Splitting never loses comm time: per-chunk seconds sum back to the
    originals up to the per-call latency floor the split re-pays."""
    plan = osched.OverlapPlan(prefetch_depth=1, grad_buckets=4, n_layers=8)
    per_device = osched.scheduled_intervals(COMPUTE_S, COMM_OPS, plan)
    ivs = next(iter(per_device.values()))
    comm_total = sum(iv["end"] - iv["start"] for iv in ivs
                     if iv["kind"] == "comm")
    orig = sum(s["seconds"] for s in COMM_OPS)
    extra_calls = plan.n_layers + plan.grad_buckets  # re-paid latency floors
    assert comm_total >= orig - 1e-12
    assert comm_total <= orig + extra_calls * plan.latency_s + 1e-12
    # and the whole thing still validates through the attribution algebra
    report = ov_mod.overlap_report(per_device, mode="analytic")
    assert not ov_mod.validate_report(report)


def test_depth_zero_is_serialized_fill():
    """depth 0 = gather at each layer boundary: every gather chunk stays
    exposed, so deeper prefetch must do no worse."""
    d0 = osched.plan_exposure(
        COMPUTE_S, COMM_OPS, osched.OverlapPlan(prefetch_depth=0,
                                                grad_buckets=1, n_layers=8))
    d1 = osched.plan_exposure(
        COMPUTE_S, COMM_OPS, osched.OverlapPlan(prefetch_depth=1,
                                                grad_buckets=1, n_layers=8))
    assert d1 <= d0


def test_candidate_plans_hint_seeding():
    gather_hint = [{"op": "all_gather", "axis": "dp",
                    "potential_saving_s": 1e-4,
                    "hint": "prefetch all_gather over axis dp"}]
    reduce_hint = [{"op": "reduce_scatter", "axis": "dp",
                    "potential_saving_s": 1e-4,
                    "hint": "prefetch reduce_scatter over axis dp"}]
    by_gather = osched.candidate_plans(gather_hint, n_layers=8)
    assert by_gather[0].prefetch_depth == max(osched.DEFAULT_DEPTHS)
    by_reduce = osched.candidate_plans(reduce_hint, n_layers=8)
    assert by_reduce[0].grad_buckets == max(osched.DEFAULT_BUCKETS)
    # no hints: shallow/cheap first, full ladder still covered
    plain = osched.candidate_plans(None, n_layers=8)
    assert plain[0].prefetch_depth == min(osched.DEFAULT_DEPTHS)
    assert len(plain) == len(osched.DEFAULT_DEPTHS) * \
        len(osched.DEFAULT_BUCKETS)
    # depth capped by layer count
    shallow = osched.candidate_plans(None, n_layers=2)
    assert max(p.prefetch_depth for p in shallow) <= 1


def test_best_plan_minimizes_exposure():
    plan, exposed, ranking = osched.best_plan(COMPUTE_S, COMM_OPS,
                                              n_layers=8)
    assert exposed == min(r["exposed_comm_s"] for r in ranking)
    assert ranking == sorted(ranking, key=lambda r: (r["exposed_comm_s"],
                                                     r["prefetch_depth"],
                                                     r["grad_buckets"]))
    assert plan.prefetch_depth == ranking[0]["prefetch_depth"]
    assert exposed <= serialized_exposed()


def test_scheduled_report_and_validate_schedule():
    plan = osched.OverlapPlan(prefetch_depth=1, grad_buckets=4, n_layers=8)
    rep = osched.scheduled_report({}, COMM_OPS, plan, compute_s=COMPUTE_S)
    assert not ov_mod.validate_report(rep)
    sched = rep["schedule"]
    assert not osched.validate_schedule(sched)
    ser = sched["serialized_exposed_comm_s"]
    assert rep["exposed_comm_s"] < ser
    assert sched["exposed_reduction_fraction"] == pytest.approx(
        (ser - rep["exposed_comm_s"]) / ser, abs=1e-5)
    # every comm_ops entry carries seconds (the stdlib re-derivation input)
    assert all("seconds" in s for s in sched["comm_ops"])
    # validator catches the mutations perf_gate must refuse
    assert osched.validate_schedule({})
    assert osched.validate_schedule(dict(sched, comm_ops=[]))
    assert osched.validate_schedule(dict(sched, compute_s=float("nan")))


def test_bucketize_contiguous_and_balanced():
    sizes = [100, 1, 1, 100, 1, 1, 100, 1]
    groups = QgzPlan._bucketize(sizes, 3)
    assert len(groups) == 3
    assert [j for g in groups for j in g] == list(range(len(sizes)))
    # more buckets than leaves degrades to one leaf per group; skewed sizes
    # still yield exactly min(buckets, leaves) groups
    assert QgzPlan._bucketize([1.0, 2.0], 8) == [[0], [1]]
    assert QgzPlan._bucketize([1.0, 100.0, 1.0], 3) == [[0], [1], [2]]
    assert QgzPlan._bucketize([5.0], 1) == [[0]]


# ---------------------------------------------------------------------------
# planner: the chip-free autotuner's overlap dimension
# ---------------------------------------------------------------------------

def _make_config_tuner():
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    return Autotuner(
        model, params, {"train_batch_size": 8},
        lambda mbs: random_batches(1, max(mbs, 1))[0],
        tuning_space={"zero_stage": [1, 2],
                      "remat_policy": ["nothing"]})


def test_chip_free_planner_co_decides_overlap():
    """tune_chip_free carries each feasible candidate's best overlap plan
    and the winning config gains the matching ``overlap`` section."""
    tuner = _make_config_tuner()

    class Mem:
        temp_size_in_bytes = 1 << 20
        output_size_in_bytes = 1 << 20

    def fake(fn, abstract):
        return {"flops": 1e9, "bytes accessed": 1e8}, Mem()

    hints = [{"op": "reduce_scatter", "axis": "dp",
              "potential_saving_s": 1e-4, "hint": "prefetch reduce_scatter"}]
    cfg, ranking = tuner.tune_chip_free(compile_fn=fake,
                                        device_kind="tpu v5 lite",
                                        overlap_hints=hints)
    feasible = [e for e in ranking if e["feasible"]]
    assert feasible
    # v5e:2x2 -> dp world 4 -> every stage has a collective inventory
    for e in feasible:
        assert "overlap" in e, e
        assert e["overlap"]["exposed_comm_s"] <= \
            e["overlap"]["serialized_comm_s"] + 1e-12
        assert e["overlap"]["prefetch_depth"] >= 0
    assert "overlap" in cfg and cfg["overlap"]["schedule"] is True
    best = ranking[0]
    assert cfg["overlap"]["prefetch_depth"] == \
        best["overlap"]["prefetch_depth"]
    assert cfg["overlap"]["grad_buckets"] == best["overlap"]["grad_buckets"]


# ---------------------------------------------------------------------------
# MoE chunked-a2a timeline (ISSUE 15)
# ---------------------------------------------------------------------------

MOE_COMPUTE_S = 6e-4
MOE_COMM_OPS = [
    {"op": "a2a_dispatch", "axis": "ep", "bytes": 1 << 21, "seconds": 2e-4},
    {"op": "a2a_combine", "axis": "ep", "bytes": 1 << 21, "seconds": 2e-4},
]


def test_moe_op_classes_do_not_fall_into_bucket():
    assert osched._op_class("a2a_dispatch") == "moe_dispatch"
    assert osched._op_class("a2a_combine") == "moe_combine"
    assert osched._op_class("all_to_all") == "bucket"
    # moe ops through the NON-moe scheduler stay serialized at the tail
    # instead of KeyError-ing (unknown classes degrade, never crash)
    plan = osched.OverlapPlan(n_layers=4)
    per_device = osched.scheduled_intervals(MOE_COMPUTE_S, MOE_COMM_OPS,
                                            plan)
    ivs = next(iter(per_device.values()))
    assert any(iv["kind"] == "comm" for iv in ivs)


def test_moe_single_chunk_is_fully_serialized():
    """a2a_chunks=1: the whole dispatch must land before any expert math
    starts, so nothing hides — the worst case the ratchet measures from."""
    exposed = osched.moe_plan_exposure(MOE_COMPUTE_S, MOE_COMM_OPS,
                                       osched.OverlapPlan(a2a_chunks=1))
    ser = serialized_exposed(MOE_COMPUTE_S, MOE_COMM_OPS)
    assert exposed == pytest.approx(ser, rel=1e-6)


def test_moe_chunking_monotonically_hides_a2a():
    ser = serialized_exposed(MOE_COMPUTE_S, MOE_COMM_OPS)
    prev = float("inf")
    for a in (1, 2, 4, 8):
        e = osched.moe_plan_exposure(MOE_COMPUTE_S, MOE_COMM_OPS,
                                     osched.OverlapPlan(a2a_chunks=a))
        assert e <= prev + 1e-12, f"a2a_chunks={a} exposed MORE: {e} > {prev}"
        prev = e
    # the acceptance ratchet's shape (ISSUE 15): 4 chunks hide >= 30%
    e4 = osched.moe_plan_exposure(MOE_COMPUTE_S, MOE_COMM_OPS,
                                  osched.OverlapPlan(a2a_chunks=4))
    assert e4 <= 0.7 * ser


def test_moe_plan_roundtrip_and_legacy_default():
    with pytest.raises(ValueError, match="a2a_chunks"):
        osched.OverlapPlan(a2a_chunks=0)
    plan = osched.OverlapPlan(a2a_chunks=4)
    assert osched.OverlapPlan.from_dict(plan.to_dict()).a2a_chunks == 4
    # pre-moe plan dicts (no a2a_chunks key) default to the serialized 1
    legacy = plan.to_dict()
    legacy.pop("a2a_chunks")
    assert osched.OverlapPlan.from_dict(legacy).a2a_chunks == 1


def test_best_moe_a2a_chunks_ranking_carries_base_plan():
    base = osched.OverlapPlan(prefetch_depth=2, grad_buckets=4)
    plan, exposed, ranking = osched.best_moe_a2a_chunks(
        MOE_COMPUTE_S, MOE_COMM_OPS, base_plan=base)
    assert exposed == min(r["exposed_comm_s"] for r in ranking)
    assert plan.a2a_chunks == ranking[0]["a2a_chunks"]
    # chunk count is co-decided ON TOP of the main sweep's dimensions
    assert plan.prefetch_depth == 2 and plan.grad_buckets == 4
    assert ranking == sorted(ranking, key=lambda r: (r["exposed_comm_s"],
                                                     r["a2a_chunks"]))


def test_moe_scheduled_report_and_validate_schedule():
    plan = osched.OverlapPlan(a2a_chunks=4)
    rep = osched.moe_scheduled_report({}, MOE_COMM_OPS, plan,
                                      compute_s=MOE_COMPUTE_S)
    assert not ov_mod.validate_report(rep)
    sched = rep["schedule"]
    assert not osched.validate_schedule(sched)
    assert sched["a2a_chunks"] == 4
    assert rep["exposed_comm_s"] < sched["serialized_exposed_comm_s"]
    # the class membership check_moe_baseline uses to refuse inventories
    # that are not MoE-shaped
    assert any(osched._op_class(s["op"]) in ("moe_dispatch", "moe_combine")
               for s in sched["comm_ops"])
    # a2a_chunks is optional in the schema (legacy baselines) but bad
    # values are refused
    legacy = dict(sched)
    legacy.pop("a2a_chunks")
    assert not osched.validate_schedule(legacy)
    assert osched.validate_schedule(dict(sched, a2a_chunks=0))
    assert osched.validate_schedule(dict(sched, a2a_chunks=True))


def test_moe_chunked_scan_matches_direct():
    import jax.numpy as jnp
    n_chunks, rows, d = 4, 8, 16
    xs = jax.random.normal(jax.random.PRNGKey(0), (n_chunks, rows, d))
    w = jax.random.normal(jax.random.PRNGKey(1), (d, d))

    def dispatch(c):
        return jax.lax.dynamic_index_in_dim(xs, c, axis=0, keepdims=False)

    def expert_fn(r, c):
        return jnp.tanh(r @ w) * (1.0 + 0.1 * jnp.float32(c))

    want = jnp.stack([expert_fn(xs[c], c) for c in range(n_chunks)])
    for depth in (0, 1, 2):
        got = osched.moe_chunked_scan(expert_fn, dispatch, n_chunks,
                                      depth=depth)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, err_msg=f"depth={depth}")

    # streaming form stays jit- and grad-compatible (remat checkpointing)
    def loss(w_):
        def efn(r, c):
            return jnp.tanh(r @ w_)
        y = osched.moe_chunked_scan(efn, dispatch, n_chunks, depth=1)
        return jnp.sum(y ** 2)

    g = jax.jit(jax.grad(loss))(w)
    assert np.isfinite(np.asarray(g)).all()


def test_chip_free_planner_co_decides_a2a_chunks():
    """With a ``moe`` section in the base config, tune_chip_free prices the
    expert a2a inventory and co-decides the chunk count on every feasible
    candidate."""
    from deepspeed_tpu.autotuning.autotuner import Autotuner
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    tuner = Autotuner(
        model, params,
        {"train_batch_size": 8,
         "moe": {"num_experts": 4, "expert_parallel_size": 2,
                 "hidden_size": 64, "seq_len": 16, "top_k": 2,
                 "num_moe_layers": 2, "a2a_wire_bits": 8}},
        lambda mbs: random_batches(1, max(mbs, 1))[0],
        tuning_space={"zero_stage": [1], "remat_policy": ["nothing"]})

    class Mem:
        temp_size_in_bytes = 1 << 20
        output_size_in_bytes = 1 << 20

    def fake(fn, abstract):
        return {"flops": 1e9, "bytes accessed": 1e8}, Mem()

    cfg, ranking = tuner.tune_chip_free(compile_fn=fake,
                                        device_kind="tpu v5 lite")
    feasible = [e for e in ranking if e["feasible"]]
    assert feasible
    for e in feasible:
        assert e["overlap"]["a2a_chunks"] >= 1, e
        assert e["overlap"]["moe_exposed_comm_s"] <= \
            e["overlap"]["moe_serialized_comm_s"] + 1e-12
    assert cfg["overlap"]["a2a_chunks"] == \
        ranking[0]["overlap"]["a2a_chunks"]


# ---------------------------------------------------------------------------
# perf_gate ratchet over the checked-in baseline
# ---------------------------------------------------------------------------

def _load_perf_gate():
    spec = importlib.util.spec_from_file_location(
        "_perf_gate", os.path.join(REPO_ROOT, "scripts", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_gate_schedule_check_passes_on_checked_in_baseline():
    pg = _load_perf_gate()
    report, errors = pg.check_overlap_schedule()
    assert not errors, errors
    assert "skipped" not in report, \
        "onchip_results/overlap_analytic_baseline.json must be checked in"
    # the acceptance ratchet: >= 30% below the serialized worst case
    assert report["exposed_comm_s"] <= \
        pg.OVERLAP_SCHEDULE_MAX_RATIO * report["serialized_exposed_comm_s"]
    assert report["reduction_fraction"] >= 0.3


def test_perf_gate_schedule_check_refuses_drift(tmp_path):
    """A baseline whose payload value and schedule block disagree — or whose
    schedule no longer beats the ratchet — must fail the dry-run lane."""
    pg = _load_perf_gate()
    with open(pg.OVERLAP_BASELINE_PATH) as f:
        doc = json.load(f)

    drifted = json.loads(json.dumps(doc))
    drifted["value"] = drifted["value"] * 3
    drifted["extra"]["overlap"]["exposed_comm_s"] = drifted["value"]
    p = tmp_path / "drifted.json"
    p.write_text(json.dumps(drifted))
    _, errors = pg.check_overlap_schedule(str(p))
    assert errors and "does not match" in errors[0]

    slow = json.loads(json.dumps(doc))
    # shrink compute until nothing can hide: recomputed exposure blows the
    # ratchet even though the recorded numbers are internally consistent
    slow["extra"]["overlap"]["schedule"]["compute_s"] = 0.0
    p2 = tmp_path / "slow.json"
    p2.write_text(json.dumps(slow))
    _, errors = pg.check_overlap_schedule(str(p2))
    assert errors
    assert any("does not match" in e or "x serialized" in e for e in errors)


def test_perf_gate_moe_baseline_passes_on_checked_in_baseline():
    pg = _load_perf_gate()
    report, errors = pg.check_moe_baseline()
    assert not errors, errors
    assert "skipped" not in report, \
        "onchip_results/moe_overlap_baseline.json must be checked in"
    # ISSUE 15 acceptance: analytic a2a exposure <= 0.7x serialized
    assert report["exposed_comm_s"] <= \
        pg.OVERLAP_SCHEDULE_MAX_RATIO * report["serialized_exposed_comm_s"]
    assert report["a2a_chunks"] >= 2


def test_perf_gate_moe_baseline_refuses_drift(tmp_path):
    pg = _load_perf_gate()
    with open(pg.MOE_OVERLAP_BASELINE_PATH) as f:
        doc = json.load(f)

    # recorded exposure disagreeing with the re-derived timeline
    drifted = json.loads(json.dumps(doc))
    drifted["extra"]["overlap"]["exposed_comm_s"] *= 3
    p = tmp_path / "drifted.json"
    p.write_text(json.dumps(drifted))
    _, errors = pg.check_moe_baseline(str(p))
    assert errors and any("does not match" in e for e in errors)

    # an inventory with no moe-class ops is not an MoE baseline at all
    nomoe = json.loads(json.dumps(doc))
    for s in nomoe["extra"]["overlap"]["schedule"]["comm_ops"]:
        s["op"] = "all_gather"
    p2 = tmp_path / "nomoe.json"
    p2.write_text(json.dumps(nomoe))
    _, errors = pg.check_moe_baseline(str(p2))
    assert errors and any("MoE" in e for e in errors)

    # compute shrunk to zero: internally consistent, but the recomputed
    # exposure blows the <= 0.7x serialized ratchet
    slow = json.loads(json.dumps(doc))
    slow["extra"]["overlap"]["schedule"]["compute_s"] = 0.0
    p3 = tmp_path / "slow.json"
    p3.write_text(json.dumps(slow))
    _, errors = pg.check_moe_baseline(str(p3))
    assert errors
    assert any("does not match" in e or "x serialized" in e for e in errors)


# ---------------------------------------------------------------------------
# runtime: scheduled_scan + engine parity
# ---------------------------------------------------------------------------

def test_scheduled_scan_matches_plain_loop():
    import jax.numpy as jnp
    blocks = jnp.arange(1.0, 7.0).reshape(6, 1)

    def fetch(i):
        return jax.lax.dynamic_index_in_dim(blocks, i, axis=0,
                                            keepdims=False)

    def block_fn(c, b, i):
        return jnp.tanh(c + b) * (1.0 + 0.1 * jnp.float32(i))

    want = jnp.zeros((1,))
    for i in range(6):
        want = block_fn(want, blocks[i], i)
    for depth in (0, 1, 2, 3):
        got = osched.scheduled_scan(block_fn, jnp.zeros((1,)), 6, fetch,
                                    prefetch_depth=depth)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, err_msg=f"depth={depth}")


def _llama_engine(overlap, seed=0, steps=10):
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    VOCAB, HID, LAYERS, B, T = 256, 64, 4, 8, 16
    model = LlamaForCausalLM(LlamaConfig(
        vocab_size=VOCAB, hidden_size=HID, intermediate_size=2 * HID,
        num_hidden_layers=LAYERS, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=T))
    rng = np.random.RandomState(1)
    batches = [{"input_ids": (ids := rng.randint(
        0, VOCAB, size=(B, T)).astype(np.int32)), "labels": ids}
        for _ in range(steps)]
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    cfg = {"train_batch_size": B,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
           "zero_optimization": {"stage": 3,
                                 "zero_quantized_gradients": True}}
    if overlap:
        cfg["overlap"] = {"schedule": True, "prefetch_depth": 1,
                          "grad_buckets": 2}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    return engine, batches


def _train(engine, batches):
    losses = []
    for bt in batches:
        loss = engine(bt)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_engine_scheduled_loss_parity(eight_devices):
    """The tentpole's correctness bar: double-buffered prefetch + bucketized
    exchange is a pure reordering — the scheduled qgZ stage-3 step must
    reproduce the unscheduled loss trajectory exactly, 10 steps, 8 devices."""
    base = _train(*_llama_engine(overlap=False))
    sched = _train(*_llama_engine(overlap=True))
    assert base == sched, f"trajectories diverged:\n{base}\n{sched}"
    # the trajectories must be live training, not a frozen constant
    assert len(set(base)) > 1 and all(np.isfinite(base))


def test_engine_fallback_without_streaming_protocol(eight_devices):
    """SimpleModel has no streaming protocol: overlap.schedule must fall back
    to the unscheduled micro-step (warn, not crash) while the bucketized grad
    exchange — plain reordering — still gives exact parity."""
    def make(overlap):
        model = SimpleModel(hidden_dim=32)
        batches = random_batches(8, 8, seed=0)
        params = model.init(jax.random.PRNGKey(7), batches[0])["params"]
        cfg = {"train_batch_size": 8,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
               "zero_optimization": {"stage": 2,
                                     "zero_quantized_gradients": True}}
        if overlap:
            cfg["overlap"] = {"schedule": True, "grad_buckets": 3}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config=cfg)
        return engine, batches

    base = _train(*[x for x in make(False)][:2])
    sched = _train(*[x for x in make(True)][:2])
    np.testing.assert_allclose(base, sched, rtol=0, atol=0)


def test_overlap_config_defaults():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    cfg = DeepSpeedConfig({"train_batch_size": 8})
    assert cfg.overlap_config.schedule is False
    assert cfg.overlap_config.prefetch_depth == 1
    assert cfg.overlap_config.grad_buckets == 2
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "overlap": {"schedule": True, "prefetch_depth": 2,
                                       "grad_buckets": 4}})
    assert cfg.overlap_config.schedule is True
    assert cfg.overlap_config.prefetch_depth == 2
    assert cfg.overlap_config.grad_buckets == 4
