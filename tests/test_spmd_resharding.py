"""No involuntary full rematerialization in the sp x tp ZeRO-3 step.

Regression for the GSPMD storage-sharding leak: stage-3 params are stored
sharded over the zero axes (dp, sp); without the use-sharding constraint in
the jitted step (engine.py _build_micro_step), XLA propagated the hidden-dim
storage split into activation shardings and fell back to full replication at
every layer boundary ("Involuntary full rematerialization",
spmd_partitioner.cc:652). The reference's Ulysses path is all-to-all, never
replication (deepspeed/sequence/layer.py:44-109) — so must ours be.

Runs the compile in a subprocess to capture XLA's C++ stderr.
"""

import os
import subprocess
import sys

import pytest

_SCRIPT = r"""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
# the axon sitecustomize ignores JAX_PLATFORMS from the environment — pin the
# platform from Python BEFORE any backend use or a wedged chip hangs the test
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.parallel.topology import MeshTopology

topo = MeshTopology(dp=-1, tp=2, sp=2)
cfg = LlamaConfig.tiny()
model = LlamaForCausalLM(cfg)
rng = np.random.default_rng(0)
ids = rng.integers(0, cfg.vocab_size, size=(4, 64)).astype(np.int32)
batch = {"input_ids": ids, "labels": ids}
engine, _, _, _ = deepspeed_tpu.initialize(
    model=model, mesh=topo,
    config={"train_batch_size": 4,
            "bf16": {"enabled": True},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 3,
                                  "stage3_param_persistence_threshold": 0}})
loss = engine(batch)
engine.backward(loss)
engine.step()
print("STEP_OK", float(jax.device_get(loss)))
"""


@pytest.mark.slow
def test_sp_tp_zero3_step_has_no_involuntary_remat():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run([sys.executable, "-c", _SCRIPT], env=env,
                          capture_output=True, text=True, timeout=900,
                          cwd=os.path.dirname(os.path.dirname(__file__)))
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-4000:]
    assert "STEP_OK" in proc.stdout, out[-4000:]
    assert "Involuntary full rematerialization" not in out, (
        "GSPMD fell back to full replication at a sharding transition:\n"
        + "\n".join(l for l in out.splitlines()
                    if "Involuntary" in l)[:2000])
