"""Ulysses + ring attention tests (mirrors reference
``tests/unit/model_parallelism`` sequence-parallel tests; ring attention is the
TPU-native context-parallel capability — numerics vs full attention)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.flash_attention import mha_reference
from deepspeed_tpu.ops.ring_attention import ring_attention, ring_attention_sharded
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.sequence.layer import DistributedAttention, seq_all_to_all


@pytest.fixture
def sp_mesh(eight_devices):
    return MeshTopology(sp=8).mesh


def _qkv(B=2, T=32, H=8, Dh=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, T, H, Dh)
    return (jax.random.normal(ks[0], shape), jax.random.normal(ks[1], shape),
            jax.random.normal(ks[2], shape))


def test_seq_all_to_all_roundtrip(sp_mesh):
    q, _, _ = _qkv()
    spec = P(None, "sp", None, None)

    def body(x):
        y = seq_all_to_all(x, "sp", scatter_axis=2, gather_axis=1)
        return seq_all_to_all(y, "sp", scatter_axis=1, gather_axis=2)

    f = jax.shard_map(body, mesh=sp_mesh, in_specs=spec, out_specs=spec)
    np.testing.assert_allclose(f(q), q, rtol=1e-6)


def test_ulysses_attention_matches_full(sp_mesh):
    """DistributedAttention == plain attention on the gathered sequence."""
    q, k, v = _qkv()
    expected = mha_reference(q, k, v, causal=True)
    spec = P(None, "sp", None, None)

    dattn = DistributedAttention(lambda q_, k_, v_: mha_reference(q_, k_, v_, causal=True))
    f = jax.shard_map(dattn, mesh=sp_mesh, in_specs=(spec, spec, spec), out_specs=spec)
    out = f(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)


def test_ulysses_head_distribution(sp_mesh):
    """Inside the wrapped attention each rank must see full seq, H/sp heads."""
    q, k, v = _qkv(T=32, H=8)
    seen = {}

    def local_attn(q_, k_, v_):
        seen["shape"] = q_.shape
        return q_

    spec = P(None, "sp", None, None)
    f = jax.shard_map(DistributedAttention(local_attn), mesh=sp_mesh,
                      in_specs=(spec, spec, spec), out_specs=spec)
    f(q, k, v)
    assert seen["shape"] == (2, 32, 1, 16)  # full T=32, H=8/8=1


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(sp_mesh, causal):
    q, k, v = _qkv(T=64)
    expected = mha_reference(q, k, v, causal=causal)
    out = ring_attention_sharded(q, k, v, sp_mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4)


def test_ring_attention_grads_finite(sp_mesh):
    q, k, v = _qkv(T=32)

    def loss(q_, k_, v_):
        return (ring_attention_sharded(q_, k_, v_, sp_mesh) ** 2).mean()

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    for arr in g:
        assert np.isfinite(np.asarray(arr)).all()

    # grads must match full-attention grads
    def loss_ref(q_, k_, v_):
        return (mha_reference(q_, k_, v_, causal=True) ** 2).mean()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4)


def test_ring_attention_jit_under_mesh(sp_mesh):
    """ring attention compiles inside jit+shard_map composition."""
    q, k, v = _qkv(T=32)
    spec = P(None, "sp", None, None)
    f = jax.jit(jax.shard_map(
        lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
        mesh=sp_mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False))
    out = f(q, k, v)
    assert out.shape == q.shape


def test_long_context_zero3_sp_training_step():
    """Long-context composition: ZeRO-3 x sequence parallelism in ONE engine
    step at 2k tokens on the virtual mesh (the VERDICT's 'long-context and
    distributed are first-class' claim, end to end)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=4, max_position_embeddings=2048,
                      scan_layers=True, remat=True, dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(2, 2048)).astype(np.int32)
    batch = {"input_ids": ids, "labels": ids}
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 1,
                "sequence_parallel_size": 2,
                "zero_optimization": {"stage": 3},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
    assert engine.topology.get_dim("sp") == 2
    assert engine.topology.get_dim("dp") == 4
    losses = []
    for _ in range(2):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert np.isfinite(losses).all()
    # params stayed ZeRO-3 sharded through the sp step
    leaf = jax.tree_util.tree_leaves(engine.state.params)[0]
    assert len(leaf.sharding.device_set) == 8


def test_ring_attention_as_model_backend():
    """attention_impl='ring' is a config switch on the llama family: the
    whole training step runs with ring (context-parallel) attention over the
    sp axis, at loss parity with the flash/XLA path."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.parallel import groups

    def train(impl):
        import dataclasses
        groups.reset()
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2, max_position_embeddings=64,
                          attention_impl=impl)
        model = LlamaForCausalLM(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 256, (4, 64)).astype(np.int32)
        batch = {"input_ids": ids, "labels": ids}
        # init through the flash path: the param tree is impl-independent and
        # the sp topology only exists once the engine installs it
        params = LlamaForCausalLM(
            dataclasses.replace(cfg, attention_impl=None)).init(
                jax.random.PRNGKey(0), batch)["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params,
            config={"train_micro_batch_size_per_gpu": 1,
                    "sequence_parallel_size": 2,
                    "bf16": {"enabled": True},
                    "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}}})
        losses = []
        for _ in range(3):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        return losses

    ring = train("ring")
    flash = train(None)
    np.testing.assert_allclose(ring, flash, rtol=2e-2, atol=2e-2)


def test_ring_backend_requires_sp_axis():
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.parallel import groups
    import pytest as _pytest

    groups.reset()  # default topology: sp=1
    cfg = LlamaConfig.tiny(attention_impl="ring")
    model = LlamaForCausalLM(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    with _pytest.raises(ValueError, match="sp mesh axis"):
        model.init(jax.random.PRNGKey(0), {"input_ids": ids, "labels": ids})
