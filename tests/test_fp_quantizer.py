"""FP6/FP12 quantizer numerics (reference ``csrc/fp_quantizer`` capability,
mirroring ``tests/unit/ops/fp_quantizer``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.fp_quantizer import (dequantize_fp, quantize_fp,
                                            _FORMATS, _decode, _encode)
from deepspeed_tpu.ops.quantizer import quantize, dequantize


@pytest.mark.parametrize("bits", [6, 12])
def test_roundtrip_error_bounded(bits):
    rng = np.random.default_rng(0)
    x = rng.normal(size=4096).astype(np.float32) * 0.05  # weight-like
    packed, scale = quantize_fp(x, bits=bits, group_size=512)
    back = np.asarray(dequantize_fp(packed, scale, x.shape, bits=bits,
                                    group_size=512))
    rel = np.abs(back - x) / (np.abs(x) + 1e-6)
    # e3m2 : 2 mantissa bits -> <=12.5% step; e5m6 -> <=0.8%
    assert np.median(rel) < (0.09 if bits == 6 else 0.006)


def test_packed_size_is_true_bitwidth():
    x = np.ones(4096, np.float32)
    p6, s6 = quantize_fp(x, bits=6, group_size=4096)
    p12, s12 = quantize_fp(x, bits=12, group_size=4096)
    assert p6.nbytes == 4096 * 6 // 8       # 3 bytes per 4 values
    assert p12.nbytes == 4096 * 12 // 8


def test_exact_values_roundtrip():
    """Values exactly representable in e3m2 decode bit-exact."""
    vals = np.array([0.0, 1.0, -1.0, 1.5, 0.75, -0.375, 12.0, -14.0], np.float32)
    e, m, b = _FORMATS[6]
    codes = _encode(jnp.asarray(vals), e, m, b)
    back = np.asarray(_decode(codes, e, m, b))
    np.testing.assert_array_equal(back, vals)


def test_overflow_clamps_underflow_flushes():
    e, m, b = _FORMATS[6]
    big = _decode(_encode(jnp.asarray([1e6], jnp.float32), e, m, b), e, m, b)
    assert float(big[0]) == 28.0   # e3m2 max: 2^4 * 1.75
    tiny = _decode(_encode(jnp.asarray([1e-6], jnp.float32), e, m, b), e, m, b)
    assert float(tiny[0]) == 0.0


def test_fp6_beats_int4_on_gaussian_weights():
    rng = np.random.default_rng(1)
    x = rng.normal(size=8192).astype(np.float32)
    p, s = quantize_fp(x, bits=6, group_size=1024)
    fp6 = np.asarray(dequantize_fp(p, s, x.shape, bits=6, group_size=1024))
    q, qs = quantize(jnp.asarray(x), num_bits=4, group_size=1024)
    i4 = np.asarray(dequantize(q, qs, x.shape, num_bits=4, group_size=1024))
    err_fp6 = np.mean((fp6 - x) ** 2)
    err_i4 = np.mean((i4 - x) ** 2)
    assert err_fp6 < err_i4, (err_fp6, err_i4)


def test_quantized_parameter_fp6_serving():
    """ZeRO-Inference weight quantization path with num_bits=6 (FP6-LLM)."""
    from deepspeed_tpu.inference.quantization.quantization import QuantizedParameter
    rng = np.random.default_rng(2)
    w = rng.normal(size=(128, 64)).astype(np.float32) * 0.1
    qp = QuantizedParameter.from_array(jnp.asarray(w), num_bits=6, group_size=512)
    assert qp.nbytes < w.nbytes / 4  # ~6/32 + scales
    back = np.asarray(qp.dequantized(dtype=jnp.float32))
    rel = np.abs(back - w) / (np.abs(w) + 1e-6)
    assert np.median(rel) < 0.09


def test_registry_slot():
    from deepspeed_tpu.ops.registry import get_op_builder
    b = get_op_builder("fp_quantizer")()
    fn = b.load()
    p, s = fn(jnp.ones(256), bits=6, group_size=256)
    assert p.dtype == jnp.uint8
