"""qgZ (ZeRO++ zero_quantized_gradients) engine wiring tests.

Mirrors the reference's ZeRO++ tests (``tests/unit/runtime/zero/test_zeropp.py``)
for the gradient-quantization leg: the config key must actually change the
grad path (stacked local accumulation + quantized boundary exchange,
``runtime/zero/qgz.py``) and training must stay within tolerance of the
unquantized engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import MeshTopology
from tests.simple_model import SimpleModel, random_batches


def make_engine(qgz, stage=2, topo=None, gas=1, seed=7):
    model = SimpleModel(hidden_dim=32)
    batches = random_batches(8, 8, seed=0)
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        model_parameters=params,
        mesh=topo,
        config={"train_batch_size": 8 * gas,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": stage,
                                      "zero_quantized_gradients": qgz}})
    return engine, batches


def train(engine, batches, steps=6):
    losses = []
    for i in range(steps):
        loss = engine(batches[i % len(batches)])
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_qgz_stacked_grad_buffer(eight_devices):
    engine, batches = make_engine(qgz=True)
    loss = engine(batches[0]); engine.backward(loss)
    world = engine.topology.data_parallel_size
    for leaf, ref in zip(jax.tree.leaves(engine.state.grad_acc),
                         jax.tree.leaves(engine.state.master)):
        assert leaf.shape == (world,) + ref.shape  # stacked local grads
    # the stacked buffer holds *different* local grads per device
    g = jax.device_get(jax.tree.leaves(engine.state.grad_acc)[0])
    assert not np.allclose(g[0], g[1])
    engine.step()


def test_qgz_loss_parity(eight_devices):
    engine_q, batches = make_engine(qgz=True)
    engine_r, _ = make_engine(qgz=False)
    lq = train(engine_q, batches)
    lr = train(engine_r, batches)
    assert lq[-1] < lq[0], f"qgZ run not learning: {lq}"
    # int4/int8 grad quantization: same trajectory within tolerance
    np.testing.assert_allclose(lq, lr, rtol=0.15)


def test_qgz_gas_accumulation(eight_devices):
    engine_q, batches = make_engine(qgz=True, gas=2)
    engine_r, _ = make_engine(qgz=False, gas=2)
    lq = train(engine_q, batches, steps=6)
    lr = train(engine_r, batches, steps=6)
    np.testing.assert_allclose(lq, lr, rtol=0.15)


def test_qgz_hierarchical_dp_dpr(eight_devices):
    """dpr (DCN) x dp (ICI) two-stage exchange via mics-style hierarchy."""
    topo = MeshTopology(dp=8, zero_shard_size=4, zero_hierarchy="hpz")
    assert topo.dpr_size == 2 and topo.dp_size == 4
    engine_q, batches = make_engine(qgz=True, topo=topo)
    engine_r, _ = make_engine(qgz=False,
                              topo=MeshTopology(dp=8, zero_shard_size=4,
                                                zero_hierarchy="hpz"))
    lq = train(engine_q, batches)
    lr = train(engine_r, batches)
    np.testing.assert_allclose(lq, lr, rtol=0.15)


def test_qgz_requires_stage2(eight_devices):
    with pytest.raises(ValueError, match="stage >= 2"):
        make_engine(qgz=True, stage=1)


def test_qgz_grad_values_match_unquantized(eight_devices):
    """One step: master weights after a qgZ step track the exact-grad step."""
    engine_q, batches = make_engine(qgz=True)
    engine_r, _ = make_engine(qgz=False)
    for e in (engine_q, engine_r):
        loss = e(batches[0]); e.backward(loss); e.step()
    mq = jax.device_get(engine_q.state.master)
    mr = jax.device_get(engine_r.state.master)
    for a, b in zip(jax.tree.leaves(mq), jax.tree.leaves(mr)):
        np.testing.assert_allclose(a, b, atol=5e-4)


def test_qgz_with_fp16_loss_scaling(eight_devices):
    """qgZ under fp16 dynamic loss scaling: the manual-mode grad path must
    unscale at the boundary like the auto path (loss-scale factor folded
    into the denom), and training stays finite and converging."""
    model = SimpleModel(hidden_dim=32)
    batches = random_batches(8, 8, seed=0)
    params = model.init(jax.random.PRNGKey(7), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 16,
                "gradient_accumulation_steps": 2,
                "fp16": {"enabled": True, "initial_scale_power": 8},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 2,
                                      "zero_quantized_gradients": True}})
    losses = train(engine, batches, steps=8)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    assert engine.skipped_steps == 0
