"""Multinode runner tests (launcher/multinode_runner.py).

Reference coverage mirrored: ``tests/unit/launcher/test_multinode_runner.py``
— command construction per backend, export handling, and the runtime rank
discovery each backend relies on (``comm.discover_process_env``).
"""

import collections

import pytest

from deepspeed_tpu.comm.comm import discover_process_env
from deepspeed_tpu.launcher.multinode_runner import (IMPIRunner, MPICHRunner,
                                                     OpenMPIRunner, PDSHRunner,
                                                     SlurmRunner, build_runner)
from deepspeed_tpu.launcher.runner import decode_world_info

POOL = collections.OrderedDict([("worker-0", 4), ("worker-1", 4)])
PROG = ["python", "train.py", "--deepspeed", "cfg with space.json"]


def _mk(cls):
    return cls(POOL, "worker-0", 29500)


def test_slurm_cmd():
    r = _mk(SlurmRunner)
    r.add_export("TOKENIZERS_PARALLELISM", "false")
    cmd = r.get_cmd(PROG)
    assert cmd[0] == "srun"
    assert cmd[cmd.index("-n") + 1] == "2"
    assert "--ntasks-per-node=1" in cmd
    assert cmd[cmd.index("--nodelist") + 1] == "worker-0,worker-1"
    exports = [t for t in cmd if t.startswith("--export=ALL,")][0]
    assert "MASTER_ADDR=worker-0" in exports
    assert "MASTER_PORT=29500" in exports
    assert "WORLD_SIZE=2" in exports
    assert "TOKENIZERS_PARALLELISM=false" in exports
    assert cmd[-len(PROG):] == PROG


def test_openmpi_cmd():
    cmd = _mk(OpenMPIRunner).get_cmd(PROG)
    assert cmd[0] == "mpirun"
    assert cmd[cmd.index("-n") + 1] == "2"
    assert cmd[cmd.index("--host") + 1] == "worker-0:1,worker-1:1"
    xs = [cmd[i + 1] for i, t in enumerate(cmd) if t == "-x"]
    assert any(x.startswith("MASTER_ADDR=") for x in xs)
    assert any(x.startswith("DS_WORLD_INFO=") for x in xs)
    assert cmd[-len(PROG):] == PROG


@pytest.mark.parametrize("cls,name", [(MPICHRunner, "mpich"), (IMPIRunner, "impi")])
def test_hydra_cmd(cls, name):
    r = _mk(cls)
    assert r.name == name
    cmd = r.get_cmd(PROG)
    assert cmd[0] == "mpirun"
    assert cmd[cmd.index("-hosts") + 1] == "worker-0,worker-1"
    assert cmd[cmd.index("-ppn") + 1] == "1"
    genvs = {cmd[i + 1]: cmd[i + 2] for i, t in enumerate(cmd) if t == "-genv"}
    assert genvs["MASTER_PORT"] == "29500"
    assert cmd[-len(PROG):] == PROG


def test_pdsh_cmd():
    cmd = _mk(PDSHRunner).get_cmd(PROG)
    assert cmd[:2] == ["pdsh", "-S"]
    assert cmd[cmd.index("-w") + 1] == "worker-0,worker-1"
    remote = cmd[-1]
    assert "export MASTER_ADDR=worker-0;" in remote
    assert "export DS_WORLD_INFO=" in remote
    # args with spaces survive the remote shell
    assert "'cfg with space.json'" in remote


def test_build_runner_rejects_unknown():
    with pytest.raises(ValueError, match="unknown launcher"):
        build_runner("kubectl", POOL, "h", 1)


def test_pdsh_world_info_roundtrip():
    r = _mk(PDSHRunner)
    env = r.base_env()
    assert decode_world_info(env["DS_WORLD_INFO"]) == dict(POOL)


# ---------------------------------------------------------------- discovery

def test_discover_explicit_rank_wins():
    env = {"MASTER_ADDR": "m", "WORLD_SIZE": "4", "RANK": "3",
           "SLURM_PROCID": "9"}
    assert discover_process_env(env) == ("m", 4, 3)


def test_discover_slurm():
    env = {"SLURM_PROCID": "2", "SLURM_NTASKS": "8",
           "SLURM_JOB_NODELIST": "node0,node1"}
    assert discover_process_env(env) == ("node0", 8, 2)


def test_discover_openmpi():
    env = {"MASTER_ADDR": "m", "OMPI_COMM_WORLD_RANK": "5",
           "OMPI_COMM_WORLD_SIZE": "16"}
    assert discover_process_env(env) == ("m", 16, 5)


def test_discover_pmi():
    env = {"MASTER_ADDR": "m", "PMI_RANK": "1", "PMI_SIZE": "2"}
    assert discover_process_env(env) == ("m", 2, 1)


def test_discover_pdsh_hostname(monkeypatch):
    import socket
    r = _mk(PDSHRunner)
    env = dict(r.base_env())
    monkeypatch.setattr(socket, "gethostname", lambda: "worker-1.cluster.local")
    assert discover_process_env(env) == ("worker-0", 2, 1)


def test_discover_single_process_default():
    assert discover_process_env({}) == (None, 1, 0)


def test_discover_pdsh_unmatched_hostname_raises(monkeypatch):
    """Defaulting an unmatched node to rank 0 would hang the whole cluster at
    coordinator startup — it must fail loudly instead."""
    import socket
    env = dict(_mk(PDSHRunner).base_env())
    monkeypatch.setattr(socket, "gethostname", lambda: "10.0.0.99")
    with pytest.raises(RuntimeError, match="not found in the launcher's"):
        discover_process_env(env)


def test_openmpi_iface_via_env(monkeypatch):
    monkeypatch.setenv("DS_MPI_TCP_IF_INCLUDE", "ens8")
    cmd = _mk(OpenMPIRunner).get_cmd(PROG)
    assert "btl_tcp_if_include" in cmd and cmd[cmd.index("btl_tcp_if_include") + 1] == "ens8"
    monkeypatch.delenv("DS_MPI_TCP_IF_INCLUDE")
    cmd = _mk(OpenMPIRunner).get_cmd(PROG)
    assert "btl_tcp_if_include" not in cmd
