"""Native AIO, CPU Adam and ZeRO-Offload tests.

Mirrors the reference's coverage: aio roundtrip (tests/unit/ops/aio),
cpu-adam numerics vs the framework optimizer (tests/unit/ops/adam),
offloaded-engine parity vs the on-device engine (tests/unit/runtime/zero
cpu-offload cases), and NVMe swapping (test_nvme_checkpointing.py analog).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.ops.aio import AsyncIOHandle
from deepspeed_tpu.ops.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.ops.native import load_native
from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import PartitionedOptimizerSwapper
from tests.simple_model import SimpleModel, random_batches


# ---------------------------------------------------------------- aio

def test_native_aio_builds():
    assert load_native("ds_aio") is not None, "g++ toolchain present; native aio must build"


def test_aio_roundtrip(tmp_path):
    h = AsyncIOHandle(block_size=4096, queue_depth=4, num_threads=2)
    rng = np.random.default_rng(0)
    src = rng.integers(0, 255, size=1_000_003, dtype=np.uint8)  # odd size: partial chunk
    f = tmp_path / "blob.bin"
    h.async_pwrite(src, str(f))
    assert h.wait() >= 1
    dst = np.zeros_like(src)
    h.async_pread(dst, str(f))
    h.wait()
    np.testing.assert_array_equal(src, dst)


def test_aio_multiple_inflight(tmp_path):
    h = AsyncIOHandle(block_size=1 << 16, queue_depth=8, num_threads=4)
    rng = np.random.default_rng(1)
    blobs = [rng.random(10_000).astype(np.float32) for _ in range(6)]
    for i, b in enumerate(blobs):
        h.async_pwrite(b, str(tmp_path / f"b{i}.bin"))
    assert h.wait() == 6
    outs = [np.empty_like(b) for b in blobs]
    for i, o in enumerate(outs):
        h.async_pread(o, str(tmp_path / f"b{i}.bin"))
    h.wait()
    for b, o in zip(blobs, outs):
        np.testing.assert_array_equal(b, o)


def test_aio_sync_api(tmp_path):
    h = AsyncIOHandle()
    data = np.arange(1000, dtype=np.float64)
    h.sync_pwrite(data, str(tmp_path / "s.bin"))
    out = np.zeros_like(data)
    h.sync_pread(out, str(tmp_path / "s.bin"))
    np.testing.assert_array_equal(data, out)
    assert h.get_block_size() > 0 and h.get_thread_count() > 0


# ---------------------------------------------------------------- cpu adam

def test_cpu_adam_matches_optax():
    """Native C++ Adam must track optax.adamw step-for-step."""
    n = 4097
    rng = np.random.default_rng(2)
    p_ref = jnp.asarray(rng.normal(size=n).astype(np.float32))
    p_cpu = np.array(p_ref, dtype=np.float32)
    tx = optax.adamw(1e-2, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    state = tx.init(p_ref)
    cpu = DeepSpeedCPUAdam(lr=1e-2, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01)
    for step in range(5):
        g = rng.normal(size=n).astype(np.float32)
        updates, state = tx.update(jnp.asarray(g), state, p_ref)
        p_ref = optax.apply_updates(p_ref, updates)
        cpu.begin_step()
        cpu.update("w", p_cpu, g)
    np.testing.assert_allclose(p_cpu, np.asarray(p_ref), rtol=2e-5, atol=2e-6)


def test_cpu_adam_bf16_output():
    cpu = DeepSpeedCPUAdam(lr=1e-2)
    p = np.ones(100, dtype=np.float32)
    g = np.full(100, 0.5, dtype=np.float32)
    out = np.zeros(100, dtype=np.uint16)
    cpu.begin_step()
    cpu.update("w", p, g, out_bf16=out)
    import ml_dtypes
    back = out.view(ml_dtypes.bfloat16).astype(np.float32)
    np.testing.assert_allclose(back, p, rtol=1e-2)


# ---------------------------------------------------------------- swapper

def test_optimizer_swapper_roundtrip(tmp_path):
    sw = PartitionedOptimizerSwapper(str(tmp_path), pipeline=True)
    sw.register("a", 1000)
    sw.register("b", 500)
    m, v = sw.fetch("a", prefetch_next="b")
    assert (m == 0).all() and m.size == 1000
    m += 1.5
    v += 2.5
    sw.commit("a")
    m2, v2 = sw.fetch("b")
    sw.commit("b")
    sw.finish_step()
    m, v = sw.fetch("a")
    np.testing.assert_allclose(m, 1.5)
    np.testing.assert_allclose(v, 2.5)
    sw.commit("a")
    sw.finish_step()


# ---------------------------------------------------------------- engine offload

def _train(config, steps=4, seed=0):
    model = SimpleModel(hidden_dim=32)
    batches = random_batches(steps, batch_size=8, seed=seed + 1)
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=config)
    losses = []
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


_BASE = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2, "weight_decay": 0.01}},
    "bf16": {"enabled": True},
}


def test_offload_cpu_matches_device():
    """Full host offload must match the on-device optimizer step (bf16 working
    precision bounds the drift)."""
    cfg_dev = dict(_BASE)
    cfg_off = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    eng_dev, losses_dev = _train(cfg_dev)
    eng_off, losses_off = _train(cfg_off)
    assert eng_off._offload is not None
    np.testing.assert_allclose(losses_off, losses_dev, rtol=2e-2, atol=2e-2)
    p_dev = eng_dev.get_model_parameters()
    p_off = eng_off.get_model_parameters()
    for a, b in zip(jax.tree.leaves(p_dev), jax.tree.leaves(p_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-3)


def test_offload_partial_ratio():
    """offload++ Twin-Flow: ratio=0.5 splits leaves between host and device;
    result must match the all-device engine."""
    cfg = dict(_BASE, zero_optimization={
        "stage": 2, "offload_optimizer": {"device": "cpu", "ratio": 0.5}})
    engine, losses = _train(cfg)
    assert len(engine._offload_host_indices) > 0
    assert len(engine._offload_device_indices) > 0
    eng_dev, losses_dev = _train(dict(_BASE))
    np.testing.assert_allclose(losses, losses_dev, rtol=2e-2, atol=2e-2)
    for a, b in zip(jax.tree.leaves(engine.get_model_parameters()),
                    jax.tree.leaves(eng_dev.get_model_parameters())):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-3)


def test_offload_nvme(tmp_path):
    """NVMe-tier moments must reproduce the DRAM-tier trajectory bitwise
    (moments only differ by the file roundtrip)."""
    cfg = dict(_BASE, zero_optimization={
        "stage": 1,
        "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)}})
    engine, losses = _train(cfg)
    assert engine._offload.swapper is not None
    cfg_cpu = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    eng_cpu, losses_cpu = _train(cfg_cpu)
    np.testing.assert_allclose(losses, losses_cpu, rtol=1e-6)
    for k in engine._offload.masters:
        np.testing.assert_allclose(engine._offload.masters[k],
                                   eng_cpu._offload.masters[k], atol=1e-7)


def test_offload_checkpoint_roundtrip(tmp_path):
    cfg = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    engine, _ = _train(cfg, steps=2)
    engine.save_checkpoint(str(tmp_path), tag="t")
    before = engine.get_model_parameters()
    m_before = {k: v.copy() for k, v in engine._offload.masters.items()}

    engine2, _ = _train(cfg, steps=1, seed=7)
    engine2.load_checkpoint(str(tmp_path), tag="t")
    after = engine2.get_model_parameters()
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    for k in m_before:
        np.testing.assert_allclose(engine2._offload.masters[k], m_before[k], atol=1e-6)
    assert engine2._offload.adam.step_count == engine._offload.adam.step_count


def test_offload_fp16_overflow_skip():
    """fp16 + offload: an inf gradient must skip the host update too."""
    cfg = dict(_BASE)
    cfg.pop("bf16")
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 4}
    cfg["zero_optimization"] = {"stage": 1, "offload_optimizer": {"device": "cpu"}}
    model = SimpleModel(hidden_dim=32)
    batch = random_batches(1, batch_size=8, seed=0)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=cfg)
    # poison the batch to force non-finite loss/grads
    bad = {k: np.where(np.isfinite(v), np.float32(1e30), v).astype(np.float32)
           if v.dtype.kind == "f" else v for k, v in batch.items()}
    masters = {k: v.copy() for k, v in engine._offload.masters.items()}
    loss = engine(bad)
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps >= 1
    for k in masters:
        np.testing.assert_array_equal(engine._offload.masters[k], masters[k])


@pytest.mark.parametrize("opt,params", [
    ("Adagrad", {"lr": 5e-2}),
    ("Lion", {"lr": 1e-3, "betas": (0.9, 0.99), "weight_decay": 0.0}),
])
def test_offload_adagrad_lion_match_device(opt, params):
    """Offload host steps for Adagrad/Lion (csrc kernels) must match the
    on-device optax step (reference csrc/adagrad, csrc/lion parity)."""
    base = dict(_BASE, optimizer={"type": opt, "params": params})
    cfg_dev = dict(base)
    cfg_off = dict(base, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    eng_dev, losses_dev = _train(cfg_dev)
    eng_off, losses_off = _train(cfg_off)
    assert eng_off._offload is not None
    assert eng_off._offload.opt_name == opt.lower()
    np.testing.assert_allclose(losses_off, losses_dev, rtol=3e-2, atol=3e-2)
    p_dev = eng_dev.get_model_parameters()
    p_off = eng_off.get_model_parameters()
    for a, b in zip(jax.tree.leaves(p_dev), jax.tree.leaves(p_off)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-2, atol=3e-3)


def test_offload_adagrad_checkpoint_roundtrip(tmp_path):
    cfg = dict(_BASE, optimizer={"type": "Adagrad", "params": {"lr": 5e-2}},
               zero_optimization={"stage": 1,
                                  "offload_optimizer": {"device": "cpu"}})
    eng, _ = _train(cfg, steps=3)
    sd = eng._offload.state_dict()
    assert any(k.startswith("v::") for k in sd)
    assert not any(k.startswith("m::") for k in sd)  # adagrad: one moment
    eng2, _ = _train(cfg, steps=1)
    eng2._offload.load_state_dict(sd)
    np.testing.assert_allclose(eng2._offload.adam.step_count,
                               eng._offload.adam.step_count)


def test_offload_nvme_non_adam_raises():
    cfg = dict(_BASE, optimizer={"type": "Lion", "params": {"lr": 1e-3}},
               zero_optimization={"stage": 1,
                                  "offload_optimizer": {"device": "nvme"}})
    with pytest.raises(ValueError, match="Adam-only"):
        _train(cfg, steps=1)


def test_simd_adam_speedup_over_scalar():
    """The AVX-512 Adam step must beat the unvectorized build >=3x (VERDICT:
    vectorize the host step — the bottleneck under ZeRO-Offload). Both sides
    are OpenMP-parallel, so the ratio isolates vectorization."""
    import ctypes, time
    from deepspeed_tpu.ops.cpu_adam import _native
    lib = _native()
    if lib is None:
        pytest.skip("native lib unavailable")
    if not lib.ds_built_with_avx512():
        pytest.skip("library built without AVX-512")
    n = 1 << 21
    rng = np.random.default_rng(0)
    pf = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    p = rng.normal(size=n).astype(np.float32)
    g = rng.normal(size=n).astype(np.float32)
    m = (rng.normal(size=n) ** 2 * 0.01).astype(np.float32)
    v = (rng.normal(size=n) ** 2 * 0.01).astype(np.float32)
    args = (3, 1e-3, 0.9, 0.999, 1e-8, 0.01, 1, 1, pf(p), pf(g), pf(m), pf(v), n)

    def bench(fn, iters=8):
        # best-of-iters: the MIN is robust to CI load spikes (a mean would
        # absorb scheduler noise and flake the ratio)
        fn(*args)
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            fn(*args)
            best = min(best, time.perf_counter() - t0)
        return best

    for attempt in range(3):   # re-measure if a load spike still slips in
        t_scalar = bench(lib.ds_adam_step_scalar)
        t_simd = bench(lib.ds_adam_step)
        if t_scalar / t_simd >= 3.0:
            break
    assert t_scalar / t_simd >= 3.0, (
        f"SIMD speedup only {t_scalar/t_simd:.1f}x "
        f"(scalar {t_scalar*1e3:.1f}ms simd {t_simd*1e3:.1f}ms)")


def test_offload_moment_mismatch_raises(tmp_path):
    """Loading a Lion-saved host state into an Adam host tier must fail loud."""
    cfg_lion = dict(_BASE, optimizer={"type": "Lion", "params": {"lr": 1e-3}},
                    zero_optimization={"stage": 1,
                                       "offload_optimizer": {"device": "cpu"}})
    eng_lion, _ = _train(cfg_lion, steps=2)
    sd = eng_lion._offload.state_dict()
    cfg_adam = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    eng_adam, _ = _train(cfg_adam, steps=1)
    with pytest.raises(ValueError, match="different optimizer"):
        eng_adam._offload.load_state_dict(sd)


def test_fragment_setters_with_offload(tmp_path):
    """Setter/local-getter fragment API against the host-offload tier
    (review r3 findings: swapper/1-moment paths must not silently no-op)."""
    import numpy as np
    from deepspeed_tpu.utils import (safe_get_full_optimizer_state,
                                     safe_get_local_optimizer_state,
                                     safe_set_full_optimizer_state)
    from deepspeed_tpu.utils.tensor_fragment import param_names
    from tests.simple_model import SimpleModel, random_batches
    model = SimpleModel(hidden_dim=16)
    batches = random_batches(2, batch_size=8)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 2,
                    "offload_optimizer": {"device": "cpu"}}})
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    key = [k for k in param_names(engine) if "kernel" in k][0]
    m = safe_get_full_optimizer_state(engine, key, "exp_avg")
    assert m is not None
    new = np.full_like(m, 0.25)
    assert safe_set_full_optimizer_state(engine, key, new, "exp_avg")
    np.testing.assert_allclose(
        safe_get_full_optimizer_state(engine, key, "exp_avg"), new)
    # local getter delegates for host-offloaded params (never a bare None)
    assert safe_get_local_optimizer_state(engine, key, "exp_avg") is not None
