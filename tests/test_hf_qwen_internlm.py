"""Qwen-v1 + InternLM interop (VERDICT r4 #9).

Both are trust_remote_code families — no transformers model class exists in
this image — so the logits oracle is a compact hand-rolled torch
implementation of each architecture (matching the public modeling_qwen.py /
modeling_internlm.py math: RMSNorm, rotate_half rotary, causal attention,
Qwen's swapped-gate MLP w1(x)*silu(w2(x)), InternLM's biased q/k/v/o).
Reference policies: deepspeed/module_inject/containers/{qwen,internlm}.py.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint import hf as hf_interop


def _rms(x, w, eps):
    v = x.pow(2).mean(-1, keepdim=True)
    return x * torch.rsqrt(v + eps) * w


def _rotate_half(x):
    h = x.shape[-1] // 2
    return torch.cat([-x[..., h:], x[..., :h]], dim=-1)


def _rope(q, k, base):
    # [B, T, H, Dh] neox-style rotate_half, matching HF llama / qwen-v1
    Dh = q.shape[-1]
    T = q.shape[1]
    inv = 1.0 / (base ** (torch.arange(0, Dh, 2).float() / Dh))
    freqs = torch.outer(torch.arange(T).float(), inv)
    emb = torch.cat([freqs, freqs], dim=-1)
    cos, sin = emb.cos()[None, :, None, :], emb.sin()[None, :, None, :]
    return q * cos + _rotate_half(q) * sin, k * cos + _rotate_half(k) * sin


def _causal_attention(q, k, v):
    B, T, H, Dh = q.shape
    att = torch.einsum("bqhd,bkhd->bhqk", q, k) / (Dh ** 0.5)
    mask = torch.triu(torch.ones(T, T, dtype=torch.bool), 1)
    att = att.masked_fill(mask, float("-inf")).softmax(-1)
    return torch.einsum("bhqk,bkhd->bqhd", att, v).reshape(B, T, H * Dh)


def _write_ckpt(tmp_path, sd, cfg_json):
    d = tmp_path / "ckpt"
    d.mkdir()
    hf_interop.save_safetensors(
        {k: np.asarray(v, np.float32) for k, v in sd.items()}, str(d))
    (d / "config.json").write_text(json.dumps(cfg_json))
    return str(d)


# ---------------------------------------------------------------- qwen v1

def _qwen_reference(sd, cfg, ids):
    D, H = cfg["hidden_size"], cfg["num_attention_heads"]
    Dh = D // H
    eps, base = cfg["layer_norm_epsilon"], cfg["rotary_emb_base"]
    t = {k: torch.from_numpy(np.asarray(v, np.float32)) for k, v in sd.items()}
    x = t["transformer.wte.weight"][torch.from_numpy(ids).long()]
    B, T = ids.shape
    for i in range(cfg["num_hidden_layers"]):
        p = f"transformer.h.{i}."
        h = _rms(x, t[p + "ln_1.weight"], eps)
        qkv = h @ t[p + "attn.c_attn.weight"].T + t[p + "attn.c_attn.bias"]
        q, k, v = (s.reshape(B, T, H, Dh) for s in qkv.split(D, dim=-1))
        q, k = _rope(q, k, base)
        x = x + _causal_attention(q, k, v) @ t[p + "attn.c_proj.weight"].T
        h = _rms(x, t[p + "ln_2.weight"], eps)
        a1 = h @ t[p + "mlp.w1.weight"].T
        a2 = h @ t[p + "mlp.w2.weight"].T
        x = x + (a1 * torch.nn.functional.silu(a2)) @ t[p + "mlp.c_proj.weight"].T
    x = _rms(x, t["transformer.ln_f.weight"], eps)
    return (x @ t["lm_head.weight"].T).numpy()


def _qwen_ckpt(rng, V=97, D=32, H=4, L=2, FF=64):
    cfg = {"model_type": "qwen", "vocab_size": V, "hidden_size": D,
           "num_attention_heads": H, "num_hidden_layers": L,
           "intermediate_size": FF * 2, "layer_norm_epsilon": 1e-6,
           "rotary_emb_base": 10000.0, "seq_length": 64, "no_bias": True}
    n = lambda *s: rng.normal(0, 0.1, s).astype(np.float32)
    sd = {"transformer.wte.weight": n(V, D),
          "transformer.ln_f.weight": 1 + 0.1 * n(D),
          "lm_head.weight": n(V, D)}
    for i in range(L):
        p = f"transformer.h.{i}."
        sd.update({p + "ln_1.weight": 1 + 0.1 * n(D),
                   p + "ln_2.weight": 1 + 0.1 * n(D),
                   p + "attn.c_attn.weight": n(3 * D, D),
                   p + "attn.c_attn.bias": n(3 * D),
                   p + "attn.c_proj.weight": n(D, D),
                   p + "mlp.w1.weight": n(FF, D),
                   p + "mlp.w2.weight": n(FF, D),
                   p + "mlp.c_proj.weight": n(D, FF)})
    return sd, cfg


def test_qwen_v1_exact_logits(tmp_path):
    rng = np.random.default_rng(0)
    sd, cfg = _qwen_ckpt(rng)
    d = _write_ckpt(tmp_path, sd, cfg)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.attention_bias and not model.config.attention_out_bias
    assert model.config.intermediate_size == 64    # ff = intermediate // 2
    fcfg = type(model.config)(**{**model.config.__dict__,
                                 "dtype": jnp.float32, "remat": False})
    ids = rng.integers(0, cfg["vocab_size"], size=(2, 12)).astype(np.int32)
    ours = np.asarray(type(model)(fcfg).apply({"params": params},
                                              {"input_ids": ids}), np.float32)
    ref = _qwen_reference(sd, cfg, ids)
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=1e-3)


def test_qwen_v1_roundtrip_exact(tmp_path):
    rng = np.random.default_rng(1)
    sd, cfg = _qwen_ckpt(rng)
    d = _write_ckpt(tmp_path, sd, cfg)
    model, params = hf_interop.load_pretrained(d)
    back = hf_interop.qwen_from_flax(params, model.config)
    assert set(back) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(back[k], sd[k], err_msg=k)


# ---------------------------------------------------------------- internlm

def _internlm_reference(sd, cfg, ids):
    D, H = cfg["hidden_size"], cfg["num_attention_heads"]
    Dh = D // H
    eps = cfg["rms_norm_eps"]
    t = {k: torch.from_numpy(np.asarray(v, np.float32)) for k, v in sd.items()}
    x = t["model.embed_tokens.weight"][torch.from_numpy(ids).long()]
    B, T = ids.shape
    for i in range(cfg["num_hidden_layers"]):
        p = f"model.layers.{i}."
        h = _rms(x, t[p + "input_layernorm.weight"], eps)
        lin = lambda nm: h @ t[p + nm + ".weight"].T + t[p + nm + ".bias"]
        q = lin("self_attn.q_proj").reshape(B, T, H, Dh)
        k = lin("self_attn.k_proj").reshape(B, T, H, Dh)
        v = lin("self_attn.v_proj").reshape(B, T, H, Dh)
        q, k = _rope(q, k, 10000.0)
        o = _causal_attention(q, k, v)
        x = x + o @ t[p + "self_attn.o_proj.weight"].T + \
            t[p + "self_attn.o_proj.bias"]
        h = _rms(x, t[p + "post_attention_layernorm.weight"], eps)
        gate = torch.nn.functional.silu(h @ t[p + "mlp.gate_proj.weight"].T)
        up = h @ t[p + "mlp.up_proj.weight"].T
        x = x + (gate * up) @ t[p + "mlp.down_proj.weight"].T
    x = _rms(x, t["model.norm.weight"], eps)
    return (x @ t["lm_head.weight"].T).numpy()


def _internlm_ckpt(rng, V=97, D=32, H=4, L=2, FF=64):
    cfg = {"model_type": "internlm", "vocab_size": V, "hidden_size": D,
           "num_attention_heads": H, "num_hidden_layers": L,
           "intermediate_size": FF, "rms_norm_eps": 1e-6, "bias": True,
           "max_position_embeddings": 64}
    n = lambda *s: rng.normal(0, 0.1, s).astype(np.float32)
    sd = {"model.embed_tokens.weight": n(V, D),
          "model.norm.weight": 1 + 0.1 * n(D),
          "lm_head.weight": n(V, D)}
    for i in range(L):
        p = f"model.layers.{i}."
        # HF llama q/k weights are stored in rotate_half layout; the
        # permuted import handles that — our synthetic dict IS that layout
        sd.update({p + "input_layernorm.weight": 1 + 0.1 * n(D),
                   p + "post_attention_layernorm.weight": 1 + 0.1 * n(D)})
        for nm in ("q_proj", "k_proj", "v_proj", "o_proj"):
            sd[p + f"self_attn.{nm}.weight"] = n(D, D)
            sd[p + f"self_attn.{nm}.bias"] = n(D)
        sd.update({p + "mlp.gate_proj.weight": n(FF, D),
                   p + "mlp.up_proj.weight": n(FF, D),
                   p + "mlp.down_proj.weight": n(D, FF)})
    return sd, cfg


def test_internlm_exact_logits(tmp_path):
    rng = np.random.default_rng(2)
    sd, cfg = _internlm_ckpt(rng)
    d = _write_ckpt(tmp_path, sd, cfg)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.attention_bias and model.config.attention_out_bias
    fcfg = type(model.config)(**{**model.config.__dict__,
                                 "dtype": jnp.float32, "remat": False})
    ids = rng.integers(0, cfg["vocab_size"], size=(2, 12)).astype(np.int32)
    ours = np.asarray(type(model)(fcfg).apply({"params": params},
                                              {"input_ids": ids}), np.float32)
    ref = _internlm_reference(sd, cfg, ids)
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=1e-3)


def test_internlm_export_roundtrip(tmp_path):
    """Our tree -> internlm layout -> reload -> identical logits; config
    carries model_type internlm + bias."""
    rng = np.random.default_rng(3)
    sd, cfg = _internlm_ckpt(rng)
    d = _write_ckpt(tmp_path, sd, cfg)
    model, params = hf_interop.load_pretrained(d)

    out = tmp_path / "export"
    hf_interop.export_pretrained(params, model.config, str(out))
    with open(out / "config.json") as f:
        exported = json.load(f)
    assert exported["model_type"] == "internlm" and exported["bias"] is True

    model2, params2 = hf_interop.load_pretrained(str(out))
    ids = rng.integers(0, cfg["vocab_size"], size=(1, 9)).astype(np.int32)
    fcfg = type(model.config)(**{**model.config.__dict__,
                                 "dtype": jnp.float32, "remat": False})
    a = np.asarray(type(model)(fcfg).apply({"params": params},
                                           {"input_ids": ids}), np.float32)
    b = np.asarray(type(model)(fcfg).apply({"params": params2},
                                           {"input_ids": ids}), np.float32)
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_internlm_serves_through_v2(tmp_path):
    """The ragged engine applies the o_proj bias (InternLM path): last-token
    serving logits match the training forward."""
    rng = np.random.default_rng(4)
    sd, cfg = _internlm_ckpt(rng)
    d = _write_ckpt(tmp_path, sd, cfg)
    model, params = hf_interop.load_pretrained(d)
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    fcfg = type(model.config)(**{**model.config.__dict__,
                                 "dtype": jnp.float32, "remat": False})
    fmodel = type(model)(fcfg)
    engine = InferenceEngineV2(fmodel, params, config={
        "state_manager": {"max_ragged_sequence_count": 2,
                          "max_ragged_batch_size": 16,
                          "max_context": 64, "num_kv_blocks": 32},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}})
    prompt = rng.integers(0, cfg["vocab_size"], size=12).astype(np.int32)
    served = engine.put([0], [prompt])[0]
    train = np.asarray(fmodel.apply(
        {"params": params}, {"input_ids": prompt[None]}), np.float32)[0, -1]
    np.testing.assert_allclose(served, train, atol=1e-3, rtol=1e-3)


def test_internlm_through_factory(tmp_path):
    """build_hf_engine must accept the new families (factory gate)."""
    rng = np.random.default_rng(5)
    sd, cfg = _internlm_ckpt(rng)
    d = _write_ckpt(tmp_path, sd, cfg)
    from deepspeed_tpu.inference.v2.engine_factory import build_hf_engine
    engine = build_hf_engine(d, engine_config={
        "state_manager": {"max_ragged_sequence_count": 2,
                          "max_ragged_batch_size": 16,
                          "max_context": 64, "num_kv_blocks": 32},
        "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}},
        dtype=np.float32)
    prompt = rng.integers(0, cfg["vocab_size"], size=7).astype(np.int32)
    logits = engine.put([0], [prompt])
    assert logits.shape == (1, cfg["vocab_size"])
    assert np.isfinite(logits).all()
