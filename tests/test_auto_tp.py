"""AutoTP name-heuristic TP inference vs the models' hand-written specs
(reference ``tests/unit/module_inject`` auto-TP analogs)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.module_inject import AutoTP, infer_tp_specs


def flat_named(tree):
    return {jax.tree_util.keystr(p): s for p, s in
            jax.tree_util.tree_flatten_with_path(
                tree, is_leaf=lambda x: x is None or isinstance(x, P))[0]}


def test_matches_llama_handwritten_specs():
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    inferred = flat_named(infer_tp_specs(params))
    exact = flat_named(model.param_specs(params))
    for k, want in exact.items():
        assert inferred[k] == want, f"{k}: inferred {inferred[k]} != {want}"


def test_matches_bloom_handwritten_specs():
    from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
    cfg = BloomConfig.tiny(dtype=jnp.float32)
    model = BloomForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    inferred = flat_named(infer_tp_specs(params))
    exact = flat_named(model.param_specs(params))
    for k, want in exact.items():
        assert inferred[k] == want, f"{k}: inferred {inferred[k]} != {want}"


def test_unknown_model_gets_sane_policy():
    """An arbitrary tree with conventional names: paired column/row splits
    and replicated norms (the AutoTP graph-walk role for unseen archs)."""
    params = {
        "encoder": {"layers_0": {
            "attn": {"qkv": {"kernel": np.zeros((64, 192)),
                             "bias": np.zeros(192)},
                     "wo": {"kernel": np.zeros((64, 64))}},
            "mlp": {"wi": {"kernel": np.zeros((64, 256))},
                    "wo": {"kernel": np.zeros((256, 64))}},
            "ln": {"scale": np.zeros(64)}}},
        "shared": np.zeros((1000, 64)),
    }
    specs = flat_named(infer_tp_specs(params))
    assert specs["['encoder']['layers_0']['attn']['qkv']['kernel']"] == P(None, "tp")
    assert specs["['encoder']['layers_0']['attn']['wo']['kernel']"] == P("tp", None)
    assert specs["['encoder']['layers_0']['mlp']['wi']['kernel']"] == P(None, "tp")
    assert specs["['encoder']['layers_0']['mlp']['wo']['kernel']"] == P("tp", None)
    assert specs["['encoder']['layers_0']['attn']['qkv']['bias']"] is None
    assert specs["['encoder']['layers_0']['ln']['scale']"] is None
    assert specs["['shared']"] == P("tp", None)


def test_autotp_prefers_exact_specs():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    model = GPT2LMHeadModel(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    via_autotp = flat_named(AutoTP.get_policy(model, params))
    exact = flat_named(model.param_specs(params))
    assert via_autotp == exact


def test_hf_flax_digit_nesting_not_mistaken_for_scan():
    """HF-Flax trees nest per-layer dicts under digit keys (layers/0/...) —
    those are NOT scan-stacked; and a genuinely stacked 3D kernel is."""
    params = {"model": {"layers": {"0": {"self_attn": {
        "q_proj": {"kernel": np.zeros((64, 64))}}}}}}
    specs = flat_named(infer_tp_specs(params))
    key = "['model']['layers']['0']['self_attn']['q_proj']['kernel']"
    assert specs[key] == P(None, "tp")
    stacked = {"blocks": {"q_proj": {"kernel": np.zeros((4, 64, 64))}}}
    s2 = flat_named(infer_tp_specs(stacked))
    assert s2["['blocks']['q_proj']['kernel']"] == P(None, None, "tp")


def test_auto_tp_bert_encoder():
    """BERT (encoder) TP policy (VERDICT r2 #8): AutoTP routes through the
    model's exact param_specs; the name fallback classifies the HF-flax-style
    encoder names (query/key/value/intermediate nested kernels) too."""
    import numpy as np
    import jax
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    from deepspeed_tpu.module_inject.auto_tp import AutoTP, infer_tp_specs
    from jax.sharding import PartitionSpec as P
    cfg = BertConfig.tiny()
    model = BertForMaskedLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    specs = AutoTP.get_policy(model, params)
    blk = specs["bert"]["layers"]["block"]
    assert blk["query"]["kernel"] == P(None, None, "tp")
    assert blk["key"]["kernel"] == P(None, None, "tp")
    assert blk["value"]["kernel"] == P(None, None, "tp")
    assert blk["intermediate"]["kernel"] == P(None, None, "tp")
    assert blk["attn_out"]["kernel"] == P(None, "tp", None)
    assert blk["output"]["kernel"] == P(None, "tp", None)
    assert specs["bert"]["word_embeddings"] == P("tp", None)

    # name-heuristic fallback on an HF-flax-shaped tree (no param_specs)
    foreign = {
        "attention": {"query": {"kernel": np.zeros((8, 8))},
                      "output": {"dense": {"kernel": np.zeros((8, 8))}}},
        "intermediate": {"dense": {"kernel": np.zeros((8, 16))}},
    }
    inf = infer_tp_specs(foreign)
    assert inf["attention"]["query"]["kernel"] == P(None, "tp")
    assert inf["attention"]["output"]["dense"]["kernel"] == P("tp", None)
    assert inf["intermediate"]["dense"]["kernel"] == P(None, "tp")
