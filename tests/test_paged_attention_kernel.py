"""Pallas paged (blocked-flash) attention kernel vs the dense gather path.

Mirrors the reference's ragged-ops kernel tests
(``tests/unit/inference/v2/kernels/ragged_ops/test_blocked_flash.py``):
same numerics as the dense path across decode (Q=1), chunked prefill (Q>1),
GQA, and ragged ``seen`` lengths.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.v2.model_implementations.llama import (
    _paged_attention_dense)
from deepspeed_tpu.ops.pallas.paged_attention import is_supported, paged_mha


def make_case(S=3, Q=1, H=4, KV=2, Dh=64, NB=10, bs=16, MB=4, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (S, Q, H, Dh), jnp.float32)
    k_pool = jax.random.normal(ks[1], (NB, KV, bs, Dh), jnp.float32)
    v_pool = jax.random.normal(ks[2], (NB, KV, bs, Dh), jnp.float32)
    rng = np.random.default_rng(seed)
    # distinct blocks per sequence (last pool block is the trash block)
    bt = rng.permutation((NB - 1) * MB)[: S * MB].reshape(S, MB) % (NB - 1)
    block_tables = jnp.asarray(bt, jnp.int32)
    seen = jnp.asarray(rng.integers(0, MB * bs - Q, size=S), jnp.int32)
    q_len = jnp.full((S,), Q, jnp.int32)
    return q, k_pool, v_pool, block_tables, seen, q_len


def run_both(case):
    q, kp, vp, bt, seen, q_len = case
    bs = kp.shape[2]
    out_k = paged_mha(q, kp, vp, bt, seen, q_len, interpret=True)
    out_d = _paged_attention_dense(q, kp, vp, bt, seen, bs)
    return out_k, out_d


def valid_rows(out, q_len):
    # rows past q_len are padding; compare only live ones
    S, Q = out.shape[:2]
    mask = np.arange(Q)[None, :] < np.asarray(q_len)[:, None]
    return np.asarray(out)[mask]


@pytest.mark.parametrize("Q", [1, 4])
def test_matches_dense(Q):
    case = make_case(Q=Q)
    out_k, out_d = run_both(case)
    np.testing.assert_allclose(valid_rows(out_k, case[5]),
                               valid_rows(out_d, case[5]), atol=2e-4, rtol=1e-3)


def test_mha_no_gqa():
    case = make_case(H=4, KV=4)
    out_k, out_d = run_both(case)
    np.testing.assert_allclose(valid_rows(out_k, case[5]),
                               valid_rows(out_d, case[5]), atol=2e-4, rtol=1e-3)


def test_zero_seen_decode_first_token():
    q, kp, vp, bt, seen, q_len = make_case(S=2, Q=1)
    seen = jnp.zeros_like(seen)
    out_k = paged_mha(q, kp, vp, bt, seen, q_len, interpret=True)
    out_d = _paged_attention_dense(q, kp, vp, bt, seen, kp.shape[2])
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_d),
                               atol=2e-4, rtol=1e-3)


def test_bf16():
    q, kp, vp, bt, seen, q_len = make_case(Dh=128)
    q, kp, vp = (x.astype(jnp.bfloat16) for x in (q, kp, vp))
    out_k = paged_mha(q, kp, vp, bt, seen, q_len, interpret=True)
    out_d = _paged_attention_dense(q, kp, vp, bt, seen, kp.shape[2])
    assert out_k.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        valid_rows(out_k, q_len).astype(np.float32),
        valid_rows(out_d, q_len).astype(np.float32), atol=3e-2, rtol=3e-2)


def test_is_supported():
    assert is_supported((2, 1, 8, 64), (8, 2, 16, 64))
    assert not is_supported((2, 1, 8, 64), (8, 3, 16, 64))   # H % KV
    assert not is_supported((2, 1, 8, 512), (8, 2, 16, 512))  # Dh
    assert not is_supported((2, 1, 8, 64), (8, 2, 12, 64))   # bs % 8


@pytest.mark.parametrize("window", [8, 24])
def test_sliding_window_matches_dense(window):
    """Mistral-style windowed masking in the kernel (the only path serving
    windowed models on real TPU) vs the dense twin."""
    from deepspeed_tpu.inference.v2.model_implementations.llama import (
        _paged_attention_dense)
    q, kp, vp, bt, seen, q_len = make_case(S=3, Q=2, seed=7)
    out_k = paged_mha(q, kp, vp, bt, seen, q_len, window=window, interpret=True)
    out_d = _paged_attention_dense(q, kp, vp, bt, seen, kp.shape[2],
                                   window=window)
    np.testing.assert_allclose(valid_rows(out_k, q_len),
                               valid_rows(out_d, q_len), atol=2e-4, rtol=1e-3)
