"""zero.Init analog: partition-at-construction initialization.

Mirrors the reference's ``tests/unit/runtime/zero/test_zero_context*.py``: a
model whose full parameter tree would not fit a single device's budget must be
constructible, because every leaf is materialized directly into its shard.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner
from deepspeed_tpu.runtime.zero.sharded_init import (Init, abstract_params,
                                                     materialize_sharded)


def tiny_batch(batch=4, seq=32, vocab=512):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(batch, seq)).astype(np.int32)
    return {"input_ids": ids, "labels": ids}


def test_abstract_params_allocates_nothing(eight_devices):
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    tree = abstract_params(model, tiny_batch(vocab=cfg.vocab_size))
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in jax.tree.leaves(tree))


def test_params_born_sharded(eight_devices):
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    batch = tiny_batch(vocab=cfg.vocab_size)
    topo = MeshTopology(dp=8)
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    ds = DeepSpeedConfig({"train_batch_size": 8,
                          "zero_optimization": {"stage": 3,
                                                "stage3_param_persistence_threshold": 0}})
    part = ZeroPartitioner(topo, ds.zero_config,
                           param_specs=model.param_specs(
                               abstract_params(model, batch)))
    params = materialize_sharded(model, batch, part, jax.random.PRNGKey(0))

    total = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(params))
    # no single device may hold the full tree: per-device bytes must be well
    # below the total (this is the "bigger than one device's budget" property
    # stated shard-wise, which is what makes 70B-class init possible)
    per_dev = {}
    for leaf in jax.tree.leaves(params):
        for sh in leaf.addressable_shards:
            per_dev[sh.device.id] = per_dev.get(sh.device.id, 0) + sh.data.size * leaf.dtype.itemsize
    assert max(per_dev.values()) < 0.35 * total, (
        f"one device holds {max(per_dev.values())} of {total} bytes")
    # the big 2D leaves must actually be partitioned
    big = [l for l in jax.tree.leaves(params) if l.ndim >= 2 and l.size >= 512]
    assert big and all(not l.sharding.is_fully_replicated for l in big)


def test_engine_lazy_init_is_sharded(eight_devices):
    """initialize() without model_parameters materializes sharded on first batch."""
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    batch = tiny_batch(batch=8, vocab=cfg.vocab_size)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3,
                                      "stage3_param_persistence_threshold": 0}})
    losses = []
    for _ in range(3):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], f"no learning: {losses}"
    # master (fp32) tree is the sharded layout
    big = [l for l in jax.tree.leaves(engine.state.master)
           if l.ndim >= 2 and l.size >= 512]
    assert big and all(not l.sharding.is_fully_replicated for l in big)


def test_init_context_manager(eight_devices):
    cfg = LlamaConfig.tiny()
    batch = tiny_batch(vocab=cfg.vocab_size)
    with deepspeed_tpu.zero.Init(
            config={"train_batch_size": 8,
                    "zero_optimization": {"stage": 3,
                                          "stage3_param_persistence_threshold": 0}},
            mesh=MeshTopology(dp=8)) as zinit:
        model = LlamaForCausalLM(cfg)
    params = zinit.materialize(model, batch)
    big = [l for l in jax.tree.leaves(params) if l.ndim >= 2 and l.size >= 512]
    assert big and all(not l.sharding.is_fully_replicated for l in big)


def test_sharded_init_matches_unsharded_numerics(eight_devices):
    """Born-sharded params match plain init (same rng; tolerance covers
    XLA fusion differences between the sharded and unsharded compiles)."""
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    batch = tiny_batch(vocab=cfg.vocab_size)
    topo = MeshTopology(dp=8)
    from deepspeed_tpu.runtime.config import DeepSpeedConfig
    ds = DeepSpeedConfig({"train_batch_size": 8,
                          "zero_optimization": {"stage": 1}})
    part = ZeroPartitioner(topo, ds.zero_config)
    sharded = materialize_sharded(model, batch, part, jax.random.PRNGKey(7))
    plain = model.init(jax.random.PRNGKey(7), batch)["params"]
    flat_s = jax.tree.leaves(sharded)
    flat_p = jax.tree.leaves(plain)
    for a, b in zip(flat_s, flat_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
