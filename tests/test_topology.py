"""Mesh topology tests (mirrors reference ``tests/unit/runtime/pipe/test_topology.py``)."""

import pytest

from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import MeshTopology


def test_default_all_dp(eight_devices):
    t = MeshTopology()
    assert t.dp_size == 8
    assert t.world_size() == 8
    assert t.mesh.shape == {"pp": 1, "dpr": 1, "dp": 8, "ep": 1, "sp": 1, "tp": 1}


def test_mixed_axes(eight_devices):
    t = MeshTopology(pp=2, tp=2)
    assert t.dp_size == 2 * 1  # 8/(2*2)=2
    assert t.pp_size == 2 and t.tp_size == 2
    assert t.data_parallel_size == 2


def test_indivisible_raises(eight_devices):
    with pytest.raises(AssertionError):
        MeshTopology(pp=3)


def test_rank_coord_roundtrip(eight_devices):
    t = MeshTopology(pp=2, dp=2, tp=2)
    for r in range(8):
        c = t.get_coord(r)
        assert t.get_rank(**c) == r


def test_groups_registry(eight_devices):
    groups.initialize(ep_size=2)
    assert groups.get_expert_parallel_world_size() == 2
    assert groups.get_data_parallel_world_size() == 8  # dp*ep*sp
    assert groups.get_expert_data_parallel_world_size() == 4
    assert groups.get_world_size() == 8


def test_batch_spec(eight_devices):
    t = MeshTopology(dp=4, sp=2)
    spec = t.batch_spec
    assert spec == __import__("jax").sharding.PartitionSpec(("dpr", "dp", "ep"), "sp")
    assert t.data_parallel_size == 8
