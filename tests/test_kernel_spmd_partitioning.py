"""Sharded-vs-single-device numerics parity for every SPMD-dispatched Pallas
kernel (the GSPMD-partitionability tentpole).

GSPMD cannot auto-partition Mosaic kernels — compiling one under a
multi-device sharding fails with "Mosaic kernels cannot be automatically
partitioned. Please wrap the call in a shard_map." — so every Pallas kernel
wrapper routes through ``ops/registry.sharded_kernel_call``, which shard_maps
the invocation over the active mesh (``parallel/topology.use_kernel_mesh``).

These tests run the kernels in interpret mode on the 8-virtual-CPU-device
mesh and assert (a) the dispatcher really emits a ``shard_map`` (jaxpr
inspection — parity alone could pass through the unsharded fallback) and
(b) sharded output == single-device output. Real-Mosaic *lowering* of the
same dispatch layer is covered by ``scripts/aot_tpu_check.py``'s multichip
legs (tests/test_aot_tpu_lowering.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from deepspeed_tpu.parallel import groups, topology
from deepspeed_tpu.parallel.topology import use_kernel_mesh


def _mesh(axes, shape, devices=None):
    devices = devices if devices is not None else jax.devices()
    n = int(np.prod(shape))
    return Mesh(np.array(devices[:n]).reshape(shape), axes)


def _assert_dispatched(fn, *args):
    """The kernel call must go through shard_map (not the unsharded
    fallback) under the active mesh."""
    jaxpr = str(jax.make_jaxpr(fn)(*args))
    assert "shard_map" in jaxpr, "kernel was not routed through shard_map"


def _close(a, b, tol=0.0):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32), atol=tol, rtol=tol)


# --------------------------------------------------------------------- flash

def _flash_inputs():
    B, T, H, KV, Dh = 4, 128, 4, 2, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, KV, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, KV, Dh), jnp.float32)
    return q, k, v


def test_flash_fwd_bwd_parity(eight_devices):
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha
    q, k, v = _flash_inputs()

    def loss(q, k, v):
        return jnp.sum(flash_mha(q, k, v, causal=True, interpret=True) ** 2)

    ref = flash_mha(q, k, v, causal=True, interpret=True)
    gref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    mesh = _mesh(("dp", "tp"), (2, 2))
    with use_kernel_mesh(mesh):
        _assert_dispatched(
            lambda q, k, v: flash_mha(q, k, v, causal=True, interpret=True),
            q, k, v)
        out = flash_mha(q, k, v, causal=True, interpret=True)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    _close(ref, out)
    for a, b in zip(gref, g):
        _close(a, b)


def test_flash_dispatch_via_global_topology(eight_devices):
    """No explicit context: engines install the groups topology and kernels
    must pick it up (batch over dpr*dp*ep, heads over tp)."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha
    q, k, v = _flash_inputs()
    ref = flash_mha(q, k, v, causal=True, interpret=True)
    groups.initialize(mesh_topology=topology.MeshTopology(dp=4, tp=2))
    _assert_dispatched(
        lambda q, k, v: flash_mha(q, k, v, causal=True, interpret=True),
        q, k, v)
    out = flash_mha(q, k, v, causal=True, interpret=True)
    _close(ref, out)
    # an explicit None context must disable dispatch again
    with use_kernel_mesh(None):
        jaxpr = str(jax.make_jaxpr(
            lambda q, k, v: flash_mha(q, k, v, causal=True,
                                      interpret=True))(q, k, v))
    assert "shard_map" not in jaxpr


def test_flash_no_double_wrap_inside_shard_map(eight_devices):
    """Inside an explicit shard_map (Ulysses pattern) every mesh axis is
    already manual — the dispatcher must detect that and not nest."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha
    from deepspeed_tpu.utils import jax_compat
    from jax.sharding import PartitionSpec as P
    q, k, v = _flash_inputs()
    ref = flash_mha(q, k, v, causal=True, interpret=True)
    mesh = _mesh(("dp", "tp"), (2, 2))
    with use_kernel_mesh(mesh):
        out = jax_compat.shard_map(
            lambda q_, k_, v_: flash_mha(q_, k_, v_, causal=True,
                                         interpret=True),
            mesh=mesh, in_specs=(P("dp"),) * 3, out_specs=P("dp"),
            check_vma=False)(q, k, v)
    _close(ref, out)


def test_flash_indivisible_falls_back(eight_devices):
    """KV heads not divisible by tp: the head role must be dropped (not
    crash, not shard unevenly); batch still shards."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_mha
    B, T, H, KV, Dh = 4, 128, 3, 3, 64
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, T, H, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, KV, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, KV, Dh), jnp.float32)
    ref = flash_mha(q, k, v, causal=True, interpret=True)
    with use_kernel_mesh(_mesh(("dp", "tp"), (2, 2))):
        out = flash_mha(q, k, v, causal=True, interpret=True)
    _close(ref, out)


# --------------------------------------------------------------------- paged

def test_paged_mha_parity(eight_devices):
    from deepspeed_tpu.ops.pallas.paged_attention import paged_mha
    S, Q, H, KV, Dh, NB, bs, MB = 4, 2, 4, 2, 64, 10, 16, 4
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (S, Q, H, Dh), jnp.float32)
    kp = jax.random.normal(ks[1], (NB, KV, bs, Dh), jnp.float32)
    vp = jax.random.normal(ks[2], (NB, KV, bs, Dh), jnp.float32)
    bt = (jnp.arange(S * MB, dtype=jnp.int32).reshape(S, MB)) % NB
    seen = jnp.array([10, 20, 30, 5], jnp.int32)
    ql = jnp.full((S,), Q, jnp.int32)
    ref = paged_mha(q, kp, vp, bt, seen, ql, interpret=True)
    with use_kernel_mesh(_mesh(("dp", "tp"), (2, 2))):
        _assert_dispatched(
            lambda *a: paged_mha(*a, interpret=True), q, kp, vp, bt, seen, ql)
        out = paged_mha(q, kp, vp, bt, seen, ql, interpret=True)
    _close(ref, out)


# -------------------------------------------------------------- block-sparse

def test_block_sparse_parity(eight_devices):
    from deepspeed_tpu.ops.pallas.block_sparse_attention import sparse_mha
    B, H, S, D, block = 4, 2, 256, 64, 128
    nq = S // block
    rng = np.random.default_rng(0)
    layout = ((rng.random((H, nq, nq)) < 0.6)
              | np.eye(nq, dtype=bool)[None]).astype(np.int32)
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.float32)

    def loss(q, k, v):
        return jnp.sum(sparse_mha(q, k, v, layout, block, causal=True,
                                  interpret=True) ** 2)

    ref = sparse_mha(q, k, v, layout, block, causal=True, interpret=True)
    gref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # batch shards over data axes; heads stay replicated (host-side layout
    # closure is indexed by global head) — see sparse_mha
    with use_kernel_mesh(_mesh(("dp", "tp"), (2, 2))):
        _assert_dispatched(
            lambda q, k, v: sparse_mha(q, k, v, layout, block, causal=True,
                                       interpret=True), q, k, v)
        out = sparse_mha(q, k, v, layout, block, causal=True, interpret=True)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    _close(ref, out)
    for a, b in zip(gref, g):
        _close(a, b, tol=1e-5)


# -------------------------------------------------------------- grouped gemm

def test_grouped_gemm_parity(eight_devices):
    from deepspeed_tpu.ops.pallas.grouped_gemm import moe_ffn_gmm
    T, D, F, E, k = 64, 128, 256, 4, 2
    ks = jax.random.split(jax.random.PRNGKey(4), 6)
    x = jax.random.normal(ks[0], (T, D), jnp.float32)
    tv = jax.nn.softmax(jax.random.normal(ks[1], (T, k)))
    ti = jax.random.randint(ks[2], (T, k), 0, E)
    w1 = jax.random.normal(ks[3], (E, D, F)) * 0.02
    w2 = jax.random.normal(ks[4], (E, F, D)) * 0.02
    w3 = jax.random.normal(ks[5], (E, D, F)) * 0.02

    def run(x, tv, ti):
        return moe_ffn_gmm(x, tv, ti, w1, w2, w3, n_experts=E,
                           dtype=jnp.float32, interpret=True)

    ref = run(x, tv, ti)
    # tokens shard over dp AND ep jointly — the expert world is carved out
    # of the data-parallel world
    with use_kernel_mesh(_mesh(("dp", "ep"), (2, 2))):
        _assert_dispatched(run, x, tv, ti)
        out = run(x, tv, ti)
    _close(ref, out, tol=1e-5)


# ---------------------------------------------------------- quantized matmul

def test_quantized_matmul_parity(eight_devices):
    from deepspeed_tpu.ops.pallas.quantized_matmul import quantized_matmul
    M, K, N, G = 16, 512, 512, 128
    ks = jax.random.split(jax.random.PRNGKey(5), 3)
    x = jax.random.normal(ks[0], (M, K), jnp.float32)
    qw = jax.random.randint(ks[1], (K, N), -128, 127, jnp.int8)
    sc = (jax.random.uniform(ks[2], (K, N // G)) + 0.5).astype(jnp.float32)
    ref = quantized_matmul(x, qw, sc, G, interpret=True)
    # rows over dp, output features (+ scale columns) over tp: per-shard
    # N=256 == BN keeps the kernel's block constraints satisfied
    with use_kernel_mesh(_mesh(("dp", "tp"), (1, 2), jax.devices()[:2])):
        _assert_dispatched(
            lambda x, q, s: quantized_matmul(x, q, s, G, interpret=True),
            x, qw, sc)
        out = quantized_matmul(x, qw, sc, G, interpret=True)
    _close(ref, out)


def test_quantized_matmul_vetoes_bad_blocks(eight_devices):
    """tp=4 would leave per-shard N=128 < BN: the accept hook must veto the
    head role and fall back rather than emit an invalid grid."""
    from deepspeed_tpu.ops.pallas.quantized_matmul import quantized_matmul
    M, K, N, G = 16, 512, 512, 128
    ks = jax.random.split(jax.random.PRNGKey(6), 3)
    x = jax.random.normal(ks[0], (M, K), jnp.float32)
    qw = jax.random.randint(ks[1], (K, N), -128, 127, jnp.int8)
    sc = (jax.random.uniform(ks[2], (K, N // G)) + 0.5).astype(jnp.float32)
    ref = quantized_matmul(x, qw, sc, G, interpret=True)
    with use_kernel_mesh(_mesh(("dp", "tp"), (2, 4))):
        out = quantized_matmul(x, qw, sc, G, interpret=True)
    _close(ref, out)
