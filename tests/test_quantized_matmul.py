"""Fused dequant-matmul kernel vs XLA dequant + matmul (reference
``tests/unit/ops/quantizer`` / cuda_linear analogs). Interpret mode; real-TPU
lowering covered by scripts/tpu_kernel_smoke.py."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.quantization.quantization import (
    QuantizedParameter)
from deepspeed_tpu.ops.pallas.quantized_matmul import (is_supported,
                                                       quantized_matmul)


def make_case(M=16, K=512, N=256, G=128, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    x = jax.random.normal(ks[0], (M, K), jnp.float32)
    w = jax.random.normal(ks[1], (K, N), jnp.float32) * 0.1
    qp = QuantizedParameter.from_array(np.asarray(w), num_bits=8, group_size=G)
    return x, w, qp


@pytest.mark.parametrize("M", [8, 16])
def test_matches_xla_dequant(M):
    x, w, qp = make_case(M=M)
    got = quantized_matmul(x, qp.q, qp.scale, qp.group_size, interpret=True)
    want = x @ qp.dequantized(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
    # and the quantization error itself is small vs the fp weight
    np.testing.assert_allclose(np.asarray(got), np.asarray(x @ w),
                               rtol=1e-1, atol=1e-1)


def test_multi_kblock_accumulation():
    x, w, qp = make_case(K=1024, seed=2)   # nk = 2: accumulator correctness
    got = quantized_matmul(x, qp.q, qp.scale, qp.group_size, interpret=True)
    want = x @ qp.dequantized(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_is_supported_gate():
    assert is_supported(16, 512, 256, 128, 8)
    assert not is_supported(16, 512, 256, 128, 4)   # int4 -> fallback
    assert not is_supported(15, 512, 256, 128, 8)   # M % 8
    assert not is_supported(16, 500, 256, 128, 8)   # K % BK
    assert not is_supported(16, 512, 200, 128, 8)   # N % BN
    assert not is_supported(16, 512, 256, 512, 8)   # G > BN


def test_param_matmul_fallback_on_cpu():
    """On CPU the .matmul helper must silently use the XLA path."""
    x, w, qp = make_case(M=4)  # M=4 unsupported anyway
    out = qp.matmul(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(x @ qp.dequantized(jnp.float32)),
                               rtol=1e-5, atol=1e-5)
