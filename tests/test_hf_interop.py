"""HF checkpoint interop: convert real transformers checkpoints and match
their logits exactly (the only test that catches transposes, rotary
conventions, and GQA layouts all at once).

Mirrors the reference's HF-loading coverage
(``tests/unit/inference/test_checkpoint_sharding.py`` and the module_inject
injection tests) with torch-cpu transformers as the oracle.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint import hf as hf_interop


def hf_logits(model, ids):
    with torch.no_grad():
        return model(torch.from_numpy(ids)).logits.float().numpy()


def our_logits(model, params, ids):
    out = model.apply({"params": params}, {"input_ids": ids})
    return np.asarray(out, np.float32)


def assert_logits_close(a, b, atol=2e-3):
    np.testing.assert_allclose(a, b, atol=atol, rtol=1e-3)


def save_hf(model, cfg, tmp_path):
    d = str(tmp_path / "ckpt")
    model.save_pretrained(d, safe_serialization=True)
    cfg.save_pretrained(d)
    return d


@pytest.mark.parametrize("kv_heads", [4, 2])
def test_llama_roundtrip_logits(tmp_path, kv_heads):
    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads, max_position_embeddings=64,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)

    model, params = hf_interop.load_pretrained(d)
    # fp32 end to end for an exact comparison
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_llama_scan_and_unscanned_agree(tmp_path):
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=False)
    torch.manual_seed(1)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    ids = np.arange(16, dtype=np.int32).reshape(1, 16) % 128

    m1, p1 = hf_interop.load_pretrained(d, scan_layers=True)
    m2, p2 = hf_interop.load_pretrained(d, scan_layers=False)
    c1 = type(m1.config)(**{**m1.config.__dict__, "dtype": jnp.float32, "remat": False})
    c2 = type(m2.config)(**{**m2.config.__dict__, "dtype": jnp.float32, "remat": False})
    l1 = our_logits(type(m1)(c1), p1, ids)
    l2 = our_logits(type(m2)(c2), p2, ids)
    assert_logits_close(l1, l2, atol=1e-4)


def test_qwen2_bias_logits(tmp_path):
    cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        max_position_embeddings=32, tie_word_embeddings=False)
    torch.manual_seed(2)
    hf_model = transformers.Qwen2ForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.attention_bias
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(2).integers(0, 128, size=(1, 12)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_gpt2_logits(tmp_path):
    cfg = transformers.GPT2Config(vocab_size=128, n_positions=32, n_embd=32,
                                  n_layer=2, n_head=2)
    torch.manual_seed(3)
    hf_model = transformers.GPT2LMHeadModel(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(3).integers(0, 128, size=(2, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_opt_logits(tmp_path):
    cfg = transformers.OPTConfig(vocab_size=128, hidden_size=32, ffn_dim=64,
                                 num_hidden_layers=2, num_attention_heads=2,
                                 max_position_embeddings=32,
                                 do_layer_norm_before=True,
                                 word_embed_proj_dim=32)
    torch.manual_seed(4)
    hf_model = transformers.OPTForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(4).integers(0, 128, size=(2, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_mixtral_logits(tmp_path):
    cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=32, tie_word_embeddings=False)
    torch.manual_seed(5)
    hf_model = transformers.MixtralForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(5).integers(0, 128, size=(1, 8)).astype(np.int32)
    # MoE top-k routing can tie-break differently; compare with a looser tol
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids), atol=2e-2)


def test_export_roundtrip_via_transformers(tmp_path):
    """our params -> export_pretrained -> transformers.from_pretrained -> same
    logits (the save_16bit_model interop direction)."""
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=False)
    torch.manual_seed(6)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)

    out = str(tmp_path / "export")
    hf_interop.export_pretrained(params, model.config, out)
    hf2 = transformers.AutoModelForCausalLM.from_pretrained(out).eval()
    ids = np.random.default_rng(6).integers(0, 128, size=(1, 8)).astype(np.int32)
    assert_logits_close(hf_logits(hf2, ids), hf_logits(hf_model, ids), atol=1e-5)


def test_engine_save_16bit_writes_hf_checkpoint(tmp_path):
    """save_16bit_model emits a real HF checkpoint for known families."""
    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((8, 16), np.int32)
    batch = {"input_ids": ids, "labels": ids}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8, "bf16": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2}})
    loss = engine(batch); engine.backward(loss); engine.step()
    out = str(tmp_path / "hf_out")
    path = engine.save_16bit_model(out)
    assert path.endswith("model.safetensors")
    hf = transformers.AutoModelForCausalLM.from_pretrained(out).eval()
    assert hf.config.model_type == "llama"


def test_engine_load_hf_weights(tmp_path):
    """HF checkpoint -> live training engine (load_module_only analog)."""
    import deepspeed_tpu
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(9)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)

    model, _ = hf_interop.load_pretrained(d)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=type(model)(model.config),
        config={"train_batch_size": 8, "bf16": {"enabled": True},
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3,
                                      "stage3_param_persistence_threshold": 0}})
    engine.load_hf_weights(d)
    # engine now computes the HF model's loss (teacher-forced next-token)
    ids = np.random.default_rng(9).integers(0, 128, size=(8, 16)).astype(np.int32)
    loss = float(jax.device_get(engine({"input_ids": ids, "labels": ids})))
    with torch.no_grad():
        t = torch.from_numpy(ids.astype(np.int64))
        hf_loss = float(hf_model(t, labels=t).loss)
    assert abs(loss - hf_loss) < 0.05, (loss, hf_loss)


def test_inference_engine_from_hf_dir(tmp_path):
    """init_inference(checkpoint=<HF dir>) serves converted weights."""
    import deepspeed_tpu
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(10)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    eng = deepspeed_tpu.init_inference(model=None, config={"checkpoint": d})
    assert eng.module is not None and eng.params is not None
    ids = np.random.default_rng(10).integers(0, 128, size=(1, 8)).astype(np.int32)
    fcfg = type(eng.module.config)(**{**eng.module.config.__dict__,
                                      "dtype": jnp.float32, "remat": False})
    ours = our_logits(type(eng.module)(fcfg),
                      jax.device_get(eng.params), ids)
    assert_logits_close(ours, hf_logits(hf_model, ids))


def test_explicit_head_dim_logits(tmp_path):
    """Mistral-Nemo-style checkpoints: head_dim != hidden_size // heads."""
    cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=1,
        head_dim=32, max_position_embeddings=64, sliding_window=None,
        tie_word_embeddings=False)
    torch.manual_seed(11)
    hf_model = transformers.MistralForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.head_dim == 32
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(11).integers(0, 128, size=(1, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_mistral_export_keeps_window(tmp_path):
    """Export writes model_type mistral + sliding_window when windowed."""
    from deepspeed_tpu.models.mistral import tiny_mistral_config
    from deepspeed_tpu.models.llama import LlamaForCausalLM
    cfg = tiny_mistral_config()
    assert cfg.sliding_window
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        {"input_ids": np.zeros((1, 8), np.int32)})["params"]
    out = str(tmp_path / "mistral_out")
    hf_interop.export_pretrained(jax.device_get(params), cfg, out)
    import json as _json
    with open(out + "/config.json") as f:
        hf_cfg = _json.load(f)
    assert hf_cfg["model_type"] == "mistral"
    assert hf_cfg["sliding_window"] == cfg.sliding_window


def test_falcon_logits(tmp_path):
    cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, multi_query=True,
        new_decoder_architecture=False, parallel_attn=True, bias=False,
        alibi=False, max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(12)
    hf_model = transformers.FalconForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.num_key_value_heads == 1  # MQA
    import dataclasses
    fcfg = dataclasses.replace(model.config, dtype=jnp.float32, remat=False)
    ids = np.random.default_rng(12).integers(0, 128, size=(2, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_phi_logits(tmp_path):
    cfg = transformers.PhiConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        partial_rotary_factor=0.5, max_position_embeddings=64,
        tie_word_embeddings=False)
    torch.manual_seed(13)
    hf_model = transformers.PhiForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.rotary_dim == 8  # 0.5 * head_dim 16
    import dataclasses
    fcfg = dataclasses.replace(model.config, dtype=jnp.float32, remat=False)
    ids = np.random.default_rng(13).integers(0, 128, size=(2, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_falcon_phi_trainable():
    """New families train through the engine (loss decreases)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.falcon import tiny_falcon_config
    from deepspeed_tpu.models.phi import tiny_phi_config
    from deepspeed_tpu.models.parallel_block import ParallelBlockForCausalLM
    for cfg in (tiny_falcon_config(), tiny_phi_config()):
        model = ParallelBlockForCausalLM(cfg)
        ids = (np.arange(8 * 16) % cfg.vocab_size).astype(np.int32).reshape(8, 16)
        batch = {"input_ids": ids, "labels": ids}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model,
            config={"train_batch_size": 8, "bf16": {"enabled": True},
                    "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
                    "zero_optimization": {"stage": 2}})
        losses = []
        for _ in range(5):
            loss = engine(batch); engine.backward(loss); engine.step()
            losses.append(float(jax.device_get(loss)))
        assert losses[-1] < losses[0], (type(cfg).__name__, losses)


def test_falcon_mha_interleaved_and_bias_logits(tmp_path):
    """multi_query=False (per-head interleaved fused QKV) + bias=True."""
    cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, multi_query=False,
        new_decoder_architecture=False, parallel_attn=True, bias=True,
        alibi=False, max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(14)
    hf_model = transformers.FalconForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.num_key_value_heads == 4 and model.config.use_bias
    import dataclasses
    fcfg = dataclasses.replace(model.config, dtype=jnp.float32, remat=False)
    ids = np.random.default_rng(14).integers(0, 128, size=(2, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_falcon_sequential_residual_rejected(tmp_path):
    cfg = transformers.FalconConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=1,
        num_attention_heads=2, multi_query=True,
        new_decoder_architecture=False, parallel_attn=False, alibi=False,
        bias=True, max_position_embeddings=32)
    torch.manual_seed(15)
    m = transformers.FalconForCausalLM(cfg)
    d = save_hf(m, cfg, tmp_path)
    with pytest.raises(ValueError, match="parallel_attn"):
        hf_interop.load_pretrained(d)


def test_bloom_logits(tmp_path):
    """BLOOM: ALiBi bias + interleaved fused QKV + embedding layernorm —
    logits parity vs transformers (v1-injection family in the reference)."""
    cfg = transformers.BloomConfig(vocab_size=128, hidden_size=32, n_layer=2,
                                   n_head=4)
    torch.manual_seed(11)
    hf_model = transformers.BloomForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(11).integers(0, 128, size=(2, 9)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_bloom_export_roundtrip(tmp_path):
    """flax -> HF safetensors -> transformers loads it and logits agree."""
    from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
    cfg = BloomConfig.tiny(dtype=jnp.float32, remat=False)
    model = BloomForCausalLM(cfg)
    ids = np.random.default_rng(12).integers(0, cfg.vocab_size,
                                             size=(1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(12), {"input_ids": ids})["params"]
    out_dir = str(tmp_path / "export")
    hf_interop.export_pretrained(params, cfg, out_dir)
    hf_model = transformers.AutoModelForCausalLM.from_pretrained(out_dir).eval()
    assert_logits_close(our_logits(model, params, ids), hf_logits(hf_model, ids))


def test_gptneox_logits(tmp_path):
    """GPT-NeoX: dual-LN parallel residual, fused interleaved QKV, partial
    half-split rotary permuted to our convention."""
    cfg = transformers.GPTNeoXConfig(vocab_size=128, hidden_size=32,
                                     num_hidden_layers=2,
                                     num_attention_heads=4,
                                     intermediate_size=64, rotary_pct=0.25,
                                     max_position_embeddings=64)
    torch.manual_seed(13)
    hf_model = transformers.GPTNeoXForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(13).integers(0, 128, size=(2, 9)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_gptj_logits(tmp_path):
    """GPT-J: shared-LN parallel residual, un-biased attn + biased MLP,
    interleaved partial rotary (our native convention — no permutation)."""
    cfg = transformers.GPTJConfig(vocab_size=128, n_embd=32, n_layer=2,
                                  n_head=4, rotary_dim=4, n_positions=64)
    torch.manual_seed(14)
    hf_model = transformers.GPTJForCausalLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(14).integers(0, 128, size=(2, 9)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


@pytest.mark.parametrize("family,make_cfg", [
    ("falcon", lambda: __import__("deepspeed_tpu.models.falcon",
                                  fromlist=["tiny_falcon_config"]
                                  ).tiny_falcon_config(remat=False)),
    ("phi", lambda: __import__("deepspeed_tpu.models.phi",
                               fromlist=["tiny_phi_config"]
                               ).tiny_phi_config(remat=False)),
    ("gpt_neox", lambda: __import__("deepspeed_tpu.models.gptneox",
                                    fromlist=["tiny_gptneox_config"]
                                    ).tiny_gptneox_config(remat=False)),
    ("gptj", lambda: __import__("deepspeed_tpu.models.gptj",
                                fromlist=["tiny_gptj_config"]
                                ).tiny_gptj_config(remat=False)),
])
def test_parallel_block_export_roundtrip(tmp_path, family, make_cfg):
    """flax -> HF safetensors for every parallel-residual family; transformers
    loads the export and the logits agree (the 'both directions' guarantee)."""
    from deepspeed_tpu.models.parallel_block import ParallelBlockForCausalLM
    cfg = type(make_cfg())(**{**make_cfg().__dict__, "dtype": jnp.float32})
    model = ParallelBlockForCausalLM(cfg)
    ids = np.random.default_rng(21).integers(0, cfg.vocab_size,
                                             size=(1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(21), {"input_ids": ids})["params"]
    out_dir = str(tmp_path / family)
    hf_interop.export_pretrained(params, cfg, out_dir)
    import json as _json
    with open(out_dir + "/config.json") as f:
        assert _json.load(f)["model_type"] == family
    hf_model = transformers.AutoModelForCausalLM.from_pretrained(out_dir).eval()
    assert_logits_close(our_logits(model, params, ids), hf_logits(hf_model, ids))


def test_bert_mlm_logits(tmp_path):
    """Encoder family oracle: exact logits vs HF BertForMaskedLM (closes the
    encoder hole vs reference module_inject/containers/{bert,distil_bert}.py)."""
    cfg = transformers.BertConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=64, type_vocab_size=2)
    torch.manual_seed(0)
    hf_model = transformers.BertForMaskedLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)

    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(0).integers(0, 256, size=(2, 16)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_bert_mlm_logits_with_token_types(tmp_path):
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=128,
        max_position_embeddings=32, type_vocab_size=2)
    torch.manual_seed(1)
    hf_model = transformers.BertForMaskedLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 128, size=(2, 12)).astype(np.int32)
    tt = (np.arange(12)[None] >= 6).astype(np.int32).repeat(2, axis=0)
    ours = np.asarray(type(model)(fcfg).apply(
        {"params": params}, {"input_ids": ids, "token_type_ids": tt}), np.float32)
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids),
                          token_type_ids=torch.from_numpy(tt)).logits.float().numpy()
    assert_logits_close(ours, theirs)


def test_bert_export_roundtrip(tmp_path):
    """load -> export -> HF reload gives identical logits; unsupported
    lineages are rejected."""
    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=128,
        max_position_embeddings=32, type_vocab_size=2)
    torch.manual_seed(2)
    hf_model = transformers.BertForMaskedLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)

    out = str(tmp_path / "export")
    hf_interop.export_pretrained(params, model.config, out)
    re_model = transformers.BertForMaskedLM.from_pretrained(out).eval()
    ids = np.random.default_rng(2).integers(0, 128, size=(1, 8)).astype(np.int32)
    assert_logits_close(hf_logits(re_model, ids), hf_logits(hf_model, ids))

    # unsupported lineages raise instead of silently mis-mapping
    bad = transformers.BertConfig(vocab_size=64, hidden_size=32,
                                  num_hidden_layers=1, num_attention_heads=2,
                                  intermediate_size=64,
                                  max_position_embeddings=16,
                                  hidden_act="relu")
    torch.manual_seed(3)
    d2 = save_hf(transformers.BertForMaskedLM(bad).eval(), bad,
                 tmp_path / "bad")
    with pytest.raises(hf_interop.UnsupportedModelError):
        hf_interop.load_pretrained(str(tmp_path / "bad" / "ckpt"))


def test_roberta_mlm_logits(tmp_path):
    """RoBERTa through the BERT encoder (renames + position offset 2)."""
    cfg = transformers.RobertaConfig(
        vocab_size=256, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=66, type_vocab_size=1, pad_token_id=1)
    torch.manual_seed(4)
    hf_model = transformers.RobertaForMaskedLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(4).integers(4, 256, size=(2, 12)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_distilbert_mlm_logits(tmp_path):
    """DistilBERT through the BERT encoder (no token types, renamed
    modules, vocab_* MLM head) — reference containers/distil_bert.py."""
    cfg = transformers.DistilBertConfig(
        vocab_size=128, dim=64, n_layers=2, n_heads=4, hidden_dim=128,
        max_position_embeddings=32, activation="gelu", dropout=0.0,
        attention_dropout=0.0)
    torch.manual_seed(5)
    hf_model = transformers.DistilBertForMaskedLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    assert model.config.type_vocab_size == 0
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    ids = np.random.default_rng(5).integers(0, 128, size=(2, 10)).astype(np.int32)
    assert_logits_close(our_logits(type(model)(fcfg), params, ids),
                        hf_logits(hf_model, ids))


def test_roberta_padded_positions_match_hf(tmp_path):
    """Pad-aware RoBERTa positions: suffix padding matches HF exactly at the
    real-token rows."""
    cfg = transformers.RobertaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=128,
        max_position_embeddings=34, type_vocab_size=1, pad_token_id=1)
    torch.manual_seed(6)
    hf_model = transformers.RobertaForMaskedLM(cfg).eval()
    d = save_hf(hf_model, cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    fcfg = type(model.config)(**{**model.config.__dict__, "dtype": jnp.float32,
                                 "remat": False})
    rng = np.random.default_rng(6)
    ids = rng.integers(4, 128, size=(1, 12)).astype(np.int32)
    ids[:, 9:] = 1  # suffix padding
    mask = (ids != 1).astype(np.int32)
    ours = np.asarray(type(model)(fcfg).apply(
        {"params": params},
        {"input_ids": ids, "attention_mask": mask}), np.float32)
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(ids),
                          attention_mask=torch.from_numpy(mask)).logits.float().numpy()
    np.testing.assert_allclose(ours[:, :9], theirs[:, :9], atol=2e-3, rtol=1e-3)


def test_encoder_variant_export_is_guarded(tmp_path):
    """RoBERTa/DistilBERT-loaded trees are load-only: export raises instead
    of writing a corrupt plain-BERT checkpoint."""
    cfg = transformers.RobertaConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=1,
        num_attention_heads=2, intermediate_size=128,
        max_position_embeddings=34, type_vocab_size=1, pad_token_id=1)
    torch.manual_seed(7)
    d = save_hf(transformers.RobertaForMaskedLM(cfg).eval(), cfg, tmp_path)
    model, params = hf_interop.load_pretrained(d)
    with pytest.raises(hf_interop.UnsupportedModelError, match="load-only"):
        hf_interop.export_pretrained(params, model.config, str(tmp_path / "x"))
