"""Model family tests: Llama + Mixtral forward/train, TP specs, flops calc."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM, llama_flops_per_token
from deepspeed_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
from tests.simple_model import tiny_gpt2_batches


def test_llama_forward_logits():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((2, 16), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    logits = model.apply({"params": params}, {"input_ids": ids})
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_llama_gqa_heads():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)  # 4 heads, 2 kv heads
    assert cfg.num_key_value_heads == 2
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    blk = params["layers"]["block"]["self_attn"] if cfg.scan_layers else \
        params["layers_0"]["self_attn"]
    assert blk["k_proj"]["kernel"].shape[-1] == 2 * cfg.head_dim
    assert blk["q_proj"]["kernel"].shape[-1] == 4 * cfg.head_dim


def test_llama_trains_under_engine():
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    batches = tiny_gpt2_batches(5, 8, seq_len=16, vocab=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "zero_optimization": {"stage": 3},
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}}})
    losses = []
    for b in batches * 8:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_llama_param_count_7b():
    cfg = LlamaConfig.llama2_7b()
    n = cfg.num_parameters()
    assert 6.5e9 < n < 7.0e9, n  # llama-2-7b is 6.74B
    assert llama_flops_per_token(cfg, 4096) > 6 * n


def test_llama_tp_specs(eight_devices):
    from jax.sharding import PartitionSpec as P
    cfg = LlamaConfig.tiny(dtype=jnp.float32)
    model = LlamaForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    specs = model.param_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs, is_leaf=lambda x: x is None or isinstance(x, P))[0]
    by_name = {jax.tree_util.keystr(p): s for p, s in flat}
    assert any("embed_tokens" in k and s == P("tp", None) for k, s in by_name.items())
    q = [s for k, s in by_name.items() if "q_proj" in k][0]
    assert q[-1] == "tp"  # column parallel
    o = [s for k, s in by_name.items() if "o_proj" in k][0]
    assert "tp" in tuple(o)[:-1] or o[-2] == "tp"  # row parallel


def test_mixtral_forward_and_train():
    cfg = MixtralConfig.tiny(dtype=jnp.float32, remat=False)
    model = MixtralForCausalLM(cfg)
    batches = tiny_gpt2_batches(4, 8, seq_len=16, vocab=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    # experts stacked on expert axis
    w1 = params["layers_0"]["block_sparse_moe"]["experts"]["MixtralExpertMLP_0"]["w1"]["kernel"]
    assert w1.shape[0] == cfg.num_local_experts

    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "expert_parallel_size": 2,
                "zero_optimization": {"stage": 2},
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}}})
    losses = []
    for b in batches * 6:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_bloom_trains_under_engine():
    """BLOOM (ALiBi + embedding LN): scan+remat training convergence under
    ZeRO-2 and greedy KV-cache decode agreeing with the full forward."""
    from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
    from deepspeed_tpu.parallel import groups
    groups.reset()
    cfg = BloomConfig.tiny(dtype=jnp.float32)
    model = BloomForCausalLM(cfg)
    batches = tiny_gpt2_batches(5, 8, seq_len=16, vocab=cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batches[0])["params"]
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "zero_optimization": {"stage": 2},
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}}})
    losses = []
    for b in batches * 8:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_bloom_tp_specs():
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
    cfg = BloomConfig.tiny(dtype=jnp.float32)
    model = BloomForCausalLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    specs = model.param_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: x is None or isinstance(x, P))[0]
    by_name = {jax.tree_util.keystr(p): s for p, s in flat}
    assert any("word_embeddings" in k and s == P("tp", None)
               for k, s in by_name.items())
    qkv = [s for k, s in by_name.items()
           if "query_key_value" in k and "kernel" in k][0]
    assert qkv[-1] == "tp"
    row = [s for k, s in by_name.items()
           if "dense_4h_to_h" in k and "kernel" in k][0]
    assert "tp" in tuple(row)[:-1]


def test_bloom_kv_cache_decode_matches_full_forward():
    """BLOOM greedy decode over the KV cache (scan-layout params, the
    load_pretrained default) agrees with full-recompute argmax."""
    from deepspeed_tpu.inference import generate
    from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
    cfg = BloomConfig.tiny(dtype=jnp.float32, remat=False, scan_layers=True,
                           max_position_embeddings=64)
    model = BloomForCausalLM(cfg)
    ids = np.random.default_rng(7).integers(0, cfg.vocab_size,
                                            size=(1, 6)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(7), {"input_ids": ids})["params"]
    out = np.asarray(generate(model, params, ids, max_new_tokens=4,
                              temperature=0.0))
    cur = ids
    want = []
    for _ in range(4):
        logits = model.apply({"params": params}, {"input_ids": jnp.asarray(cur)})
        tok = int(np.argmax(np.asarray(logits[0, -1], np.float32)))
        want.append(tok)
        cur = np.concatenate([cur, [[tok]]], axis=1)
    np.testing.assert_array_equal(out[0], want)


# ---------------------------------------------------------------------------
# BERT encoder family (VERDICT r2 #8)
# ---------------------------------------------------------------------------

def test_bert_forward_logits_and_masking():
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertForMaskedLM(cfg)
    ids = np.arange(32, dtype=np.int32).reshape(2, 16) % cfg.vocab_size
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    logits = model.apply({"params": params}, {"input_ids": ids})
    assert logits.shape == (2, 16, cfg.vocab_size)
    # bidirectional: flipping a FUTURE token must change an earlier position's
    # logits (a causal model would be invariant)
    ids2 = ids.copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % cfg.vocab_size
    logits2 = model.apply({"params": params}, {"input_ids": ids2})
    assert np.abs(np.asarray(logits[:, 0]) - np.asarray(logits2[:, 0])).max() > 1e-6


def test_bert_mlm_trains_under_engine():
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertForMaskedLM(cfg)
    rng = np.random.default_rng(0)
    ids = (np.arange(16)[None, :] + rng.integers(0, 64, size=(8, 1))).astype(np.int32) % 64
    mask_pos = rng.random(ids.shape) < 0.3
    labels = np.where(mask_pos, ids, -100).astype(np.int32)
    inputs = np.where(mask_pos, cfg.vocab_size - 1, ids).astype(np.int32)  # [MASK]
    batch = {"input_ids": inputs, "labels": labels}
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8, "zero_optimization": {"stage": 1},
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}}})
    losses = []
    for _ in range(12):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_bert_tp_specs(eight_devices):
    from jax.sharding import PartitionSpec as P
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertForMaskedLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    specs = model.param_specs(params)
    blk = specs["bert"]["layers"]["block"]
    assert blk["query"]["kernel"] == P(None, None, "tp")
    assert blk["attn_out"]["kernel"] == P(None, "tp", None)
    assert blk["output"]["kernel"] == P(None, "tp", None)
    assert blk["intermediate"]["kernel"] == P(None, None, "tp")
    assert specs["bert"]["word_embeddings"] == P("tp", None)


def test_bert_attention_mask_blocks_padding():
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    cfg = BertConfig.tiny(dtype=jnp.float32)
    model = BertForMaskedLM(cfg)
    ids = np.arange(16, dtype=np.int32)[None, :] % cfg.vocab_size
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    mask = np.ones((1, 16), np.int32)
    mask[:, 12:] = 0
    base = model.apply({"params": params},
                       {"input_ids": ids, "attention_mask": mask})
    ids2 = ids.copy()
    ids2[:, 12:] = (ids2[:, 12:] + 7) % cfg.vocab_size  # mutate PAD region
    out2 = model.apply({"params": params},
                       {"input_ids": ids2, "attention_mask": mask})
    # logits at real positions must not see the padding change
    np.testing.assert_allclose(np.asarray(base[:, :12]),
                               np.asarray(out2[:, :12]), atol=1e-5)


def test_bert_dropout_under_scan():
    """dropout > 0 must work with scan_layers (deterministic rides as a
    broadcast input, not a carried bool — review r3 finding)."""
    from deepspeed_tpu.models.bert import BertConfig, BertForMaskedLM
    cfg = BertConfig.tiny(dtype=jnp.float32, dropout=0.1)
    model = BertForMaskedLM(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    out_det = model.apply({"params": params}, {"input_ids": ids},
                          deterministic=True)
    assert np.isfinite(np.asarray(out_det)).all()
    out_drop = model.apply({"params": params}, {"input_ids": ids},
                           deterministic=False,
                           rngs={"dropout": jax.random.PRNGKey(1)})
    assert np.abs(np.asarray(out_det) - np.asarray(out_drop)).max() > 1e-6
