"""Universal checkpoint, tensor fragments, activation checkpointing tests.

Mirrors reference coverage: ``tests/unit/checkpoint/test_universal_checkpoint.py``
(save at one topology, load at another), ``test_zero_tensor_fragment.py``
(safe get/set across stages), ``runtime/activation_checkpointing``.
"""

import os
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.checkpoint import (get_fp32_state_dict_from_zero_checkpoint,
                                      load_universal_checkpoint,
                                      save_universal_checkpoint)
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.parallel.topology import MeshTopology
from deepspeed_tpu.runtime.activation_checkpointing import checkpointing
from deepspeed_tpu.utils.tensor_fragment import (param_names,
                                                 safe_get_full_fp32_param,
                                                 safe_get_full_grad,
                                                 safe_get_full_optimizer_state,
                                                 safe_set_full_fp32_param)
from tests.simple_model import SimpleModel, random_batches

_BASE = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
    "bf16": {"enabled": True},
}


def _train(config, steps=3, seed=0, mesh=None):
    model = SimpleModel(hidden_dim=64)
    batches = random_batches(steps, batch_size=8, seed=seed + 1)
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, model_parameters=params,
                                               config=config, mesh=mesh)
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
    return engine


# ------------------------------------------------------------ tensor fragments

@pytest.mark.parametrize("stage", [0, 1, 3])
def test_fragment_get_set(stage):
    cfg = dict(_BASE, zero_optimization={
        "stage": stage, "stage3_param_persistence_threshold": 0})
    engine = _train(cfg)
    names = param_names(engine)
    assert names
    key = [k for k in names if "kernel" in k][0]
    w = safe_get_full_fp32_param(engine, key)
    assert w is not None and w.dtype == np.float32
    m = safe_get_full_optimizer_state(engine, key, "exp_avg")
    v = safe_get_full_optimizer_state(engine, key, "exp_avg_sq")
    assert m is not None and m.shape == w.shape
    assert v is not None and (v >= 0).all()
    # set: master changes, next refresh propagates to working copy
    new_w = np.zeros_like(w)
    assert safe_set_full_fp32_param(engine, key, new_w)
    engine._refresh_working_from_master()
    assert np.abs(safe_get_full_fp32_param(engine, key)).max() == 0.0


def test_fragment_get_set_offload():
    cfg = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    engine = _train(cfg)
    key = [k for k in param_names(engine) if "kernel" in k][0]
    w = safe_get_full_fp32_param(engine, key)
    m = safe_get_full_optimizer_state(engine, key, "exp_avg")
    assert w is not None and m is not None and m.shape == w.shape
    safe_set_full_fp32_param(engine, key, np.ones_like(w))
    assert (safe_get_full_fp32_param(engine, key) == 1.0).all()


def test_fragment_grad():
    engine = _train(dict(_BASE, gradient_accumulation_steps=2,
                         train_batch_size=16), steps=1)
    # after 1 micro step (gas=2), grads are staged in the accumulation buffer
    key = [k for k in param_names(engine) if "kernel" in k][0]
    g = safe_get_full_grad(engine, key)
    assert g is not None and np.abs(g).max() > 0


# ------------------------------------------------------------ universal ckpt

def test_universal_roundtrip_across_stages(tmp_path):
    """Save at ZeRO-3 on the full mesh, resume at ZeRO-1 — different state
    layout, same names."""
    cfg3 = dict(_BASE, zero_optimization={
        "stage": 3, "stage3_param_persistence_threshold": 0})
    e3 = _train(cfg3, steps=3)
    before = e3.get_model_parameters()
    save_universal_checkpoint(e3, str(tmp_path / "uni"))
    step_saved = e3.global_steps

    groups.reset()
    cfg1 = dict(_BASE, zero_optimization={"stage": 1})
    e1 = _train(cfg1, steps=1, seed=7)
    n = load_universal_checkpoint(e1, str(tmp_path / "uni"))
    assert n == len(param_names(e1))
    assert e1.global_steps == step_saved
    after = e1.get_model_parameters()
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    # moments restored too
    k = [k for k in param_names(e1) if "kernel" in k][0]
    np.testing.assert_allclose(safe_get_full_optimizer_state(e1, k, "exp_avg"),
                               safe_get_full_optimizer_state(e3, k, "exp_avg"),
                               atol=1e-6)


def test_universal_into_offload(tmp_path):
    """Universal fragments load into a cpu-offload engine (host tier)."""
    e = _train(dict(_BASE, zero_optimization={"stage": 1}), steps=2)
    save_universal_checkpoint(e, str(tmp_path / "uni"))
    before = e.get_model_parameters()

    groups.reset()
    cfg_off = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu"}})
    eo = _train(cfg_off, steps=1, seed=5)
    load_universal_checkpoint(eo, str(tmp_path / "uni"))
    after = eo.get_model_parameters()
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_universal_different_mesh(tmp_path):
    """Resume on a different mesh factorization (dp8 -> dp4 x tp2)."""
    e = _train(dict(_BASE, zero_optimization={"stage": 1}), steps=2)
    save_universal_checkpoint(e, str(tmp_path / "uni"))
    before = e.get_model_parameters()
    groups.reset()
    e2 = _train(dict(_BASE, zero_optimization={"stage": 1}), steps=1, seed=3,
                mesh=MeshTopology(tp=2))
    load_universal_checkpoint(e2, str(tmp_path / "uni"))
    after = e2.get_model_parameters()
    for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_zero_to_fp32_extraction(tmp_path):
    e = _train(dict(_BASE, zero_optimization={"stage": 3,
                                              "stage3_param_persistence_threshold": 0}))
    save_universal_checkpoint(e, str(tmp_path / "uni"))
    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path / "uni"))
    ref = e.get_model_parameters()
    keyed = {jax.tree_util.keystr(p): l
             for p, l in jax.tree_util.tree_flatten_with_path(ref)[0]}
    assert set(sd) == set(keyed)
    for k in sd:
        np.testing.assert_allclose(sd[k], np.asarray(keyed[k]), atol=1e-6)


def test_universal_offload_partial_moments(tmp_path):
    """ratio<1: moments for BOTH the host tier and the device remainder must
    be saved and restored (regression: dict-keyed opt paths never matched
    string suffixes)."""
    cfg = dict(_BASE, zero_optimization={
        "stage": 1, "offload_optimizer": {"device": "cpu", "ratio": 0.5}})
    e = _train(cfg, steps=2)
    assert e._offload_device_indices, "test needs a device remainder"
    save_universal_checkpoint(e, str(tmp_path / "uni"))
    import numpy as _np
    data = _np.load(str(tmp_path / "uni" / "universal_fragments.npz"))
    for k in param_names(e):
        assert f"{k}::exp_avg" in data.files, f"missing moments for {k}"

    groups.reset()
    e2 = _train(cfg, steps=1, seed=11)
    load_universal_checkpoint(e2, str(tmp_path / "uni"))
    for k in param_names(e2):
        np.testing.assert_allclose(
            safe_get_full_optimizer_state(e2, k, "exp_avg"),
            safe_get_full_optimizer_state(e, k, "exp_avg"), atol=1e-6)
    # host Adam bias-correction step restored from counters
    assert e2._offload.adam.step_count == e.global_steps


def test_universal_restores_adam_step_count(tmp_path):
    """Bias correction must resume at the saved optimizer step (regression:
    the optax count leaf was never saved/restored)."""
    from deepspeed_tpu.checkpoint.universal import _opt_step_count
    e = _train(dict(_BASE, zero_optimization={"stage": 1}), steps=3)
    assert _opt_step_count(e.state.opt_state) == 3
    save_universal_checkpoint(e, str(tmp_path / "uni"))
    groups.reset()
    e2 = _train(dict(_BASE, zero_optimization={"stage": 1}), steps=1, seed=13)
    load_universal_checkpoint(e2, str(tmp_path / "uni"))
    assert _opt_step_count(e2.state.opt_state) == 3


def test_moment_matching_disambiguation():
    """A param whose path is a suffix of another's must not capture its
    moments (regression for string-suffix matching)."""
    import optax
    from deepspeed_tpu.utils.tensor_fragment import (moment_leaves,
                                                     param_paths_by_key)
    params = {"dense": {"kernel": jnp.ones((2,))},
              "block": {"dense": {"kernel": jnp.full((3,), 2.0)}}}
    tx = optax.adam(1e-3)
    state = tx.init(params)
    # make moments distinguishable
    g = jax.tree.map(jnp.ones_like, params)
    _, state = tx.update(g, state, params)
    frags = moment_leaves(state, param_paths_by_key(params))
    k_short = "['dense']['kernel']"
    k_long = "['block']['dense']['kernel']"
    assert frags[f"{k_short}::exp_avg"][1].shape == (2,)
    assert frags[f"{k_long}::exp_avg"][1].shape == (3,)


# ------------------------------------------------------------ activation ckpt

def test_checkpoint_function_grads_match():
    """checkpoint() must be gradient-transparent."""
    def f(x):
        return jnp.sum(jnp.tanh(x @ x.T) ** 2)

    x = jnp.asarray(np.random.default_rng(0).normal(size=(16, 16)),
                    dtype=jnp.float32)
    g_plain = jax.grad(f)(x)
    g_remat = jax.grad(lambda x: checkpointing.checkpoint(f, x))(x)
    # remat recomputes the forward inside the backward program, where XLA
    # fuses it differently — float32 agrees semantically but not bitwise
    # (observed max rel diff ~1e-5 on CPU), so rtol must sit above that
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_remat),
                               rtol=1e-4)


def test_checkpoint_policies_and_configure():
    checkpointing.configure(partition_activations=True, checkpoint_in_cpu=False)
    assert checkpointing._CONFIG["partition_activations"]
    for name in ("everything", "dots", "nothing"):
        assert checkpointing.policy_by_name(name) is not None
    assert checkpointing.policy_by_name("everything", checkpoint_in_cpu=True) \
        is not None


def test_checkpoint_wrapper_flax():
    import flax.linen as nn

    class Blk(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(jnp.tanh(x))

    Wrapped = checkpointing.checkpoint_wrapper(Blk)
    m = Wrapped()
    x = jnp.ones((4, 8))
    p = m.init(jax.random.PRNGKey(0), x)
    ref = Blk().apply(p, x)
    np.testing.assert_allclose(np.asarray(m.apply(p, x)), np.asarray(ref),
                               rtol=1e-6)


def test_rng_tracker():
    checkpointing.model_parallel_cuda_manual_seed(123)
    tr = checkpointing.get_cuda_rng_tracker()
    with tr.fork() as k1:
        pass
    with tr.fork() as k2:
        pass
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    with pytest.raises(Exception):
        tr.add("model-parallel-rng", 1)


def test_zero_to_fp32_cli(tmp_path):
    """bin/ds_tpu_zero_to_fp32 consolidates universal fragments offline
    (reference utils/zero_to_fp32.py analog)."""
    import subprocess
    import sys

    from tests.simple_model import SimpleModel, random_batches
    from deepspeed_tpu.parallel import groups
    from deepspeed_tpu.checkpoint.universal import save_universal_checkpoint

    groups.reset()
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(0), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params,
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}})
    loss = engine(batch); engine.backward(loss); engine.step()
    udir = save_universal_checkpoint(engine, str(tmp_path / "uni"))

    out = tmp_path / "consolidated.npz"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, os.path.join(repo, "bin", "ds_tpu_zero_to_fp32"),
                        udir, str(out)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    data = np.load(out)
    ref = engine.get_model_parameters(dtype=np.float32)
    import jax as _jax
    n_leaves = len(_jax.tree_util.tree_leaves(ref))
    assert len(data.files) == n_leaves
    total = sum(data[k].size for k in data.files)
    assert total == sum(l.size for l in _jax.tree_util.tree_leaves(ref))


def test_fragment_api_utils_exports_and_setters():
    """reference deepspeed.utils surface: safe_get/set full + local variants
    importable from deepspeed_tpu.utils; optimizer-state setter round-trips."""
    from deepspeed_tpu.utils import (safe_get_full_optimizer_state,
                                     safe_get_local_fp32_param,
                                     safe_get_local_grad,
                                     safe_get_local_optimizer_state,
                                     safe_set_full_optimizer_state)
    cfg = dict(_BASE, zero_optimization={"stage": 1})
    engine = _train(cfg)
    key = [k for k in param_names(engine) if "kernel" in k][0]
    m = safe_get_full_optimizer_state(engine, key, "exp_avg")
    assert m is not None
    new = np.full_like(m, 0.5)
    assert safe_set_full_optimizer_state(engine, key, new, "exp_avg")
    np.testing.assert_allclose(
        safe_get_full_optimizer_state(engine, key, "exp_avg"), new)
    # local variants return the addressable shard (smaller or equal)
    local = safe_get_local_fp32_param(engine, key)
    full = safe_get_full_fp32_param(engine, key)
    assert local is not None and local.size <= full.size
    lm = safe_get_local_optimizer_state(engine, key, "exp_avg")
    assert lm is not None and lm.size <= m.size
    loss = engine(random_batches(1, batch_size=8)[0])
    engine.backward(loss)
    g = safe_get_local_grad(engine, key)
    assert g is not None
