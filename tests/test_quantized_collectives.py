"""ZeRO++ finished: fused quant kernels, error feedback, wire-byte telemetry.

Covers the ``ops/pallas/quant_collective`` kernel pair (wire format, packing,
non-divisible tails, interpret-vs-jnp parity), ``exchange_reduce`` error
feedback (the residual is exactly what the wire lost), engine-level loss
parity of qgZ against the fp32 psum baseline (feedback must tighten it), and
the wire-byte telemetry acceptance bound: quantized DCN traffic at or below
0.3x the logical fp32 bytes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

import deepspeed_tpu
from deepspeed_tpu import telemetry
from deepspeed_tpu.ops.pallas.quant_collective import (
    block_dequantize, block_dequantize_reduce, block_quantize, wire_nbytes)
from deepspeed_tpu.parallel import groups
from deepspeed_tpu.runtime.comm.coalesced_collectives import exchange_reduce
from tests.simple_model import SimpleModel, random_batches


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)
    yield
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)


# ---------------------------------------------------------------- kernels

@pytest.mark.parametrize("bits", [8, 4])
def test_block_quantize_roundtrip_nondivisible_tail(bits):
    """M=5000 with group 512: 10 groups per row, 120-element padded tail."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(16, 5000)).astype(np.float32))
    q, s = block_quantize(x, num_bits=bits, group_size=512)
    if bits == 8:
        assert q.dtype == jnp.int8 and q.shape == (16, 5120)
    else:
        assert q.dtype == jnp.uint8 and q.shape == (16, 2560)
    assert s.shape == (16, 10)
    back = block_dequantize(q, s, num_bits=bits, group_size=512, out_len=5000)
    assert back.shape == x.shape
    err = np.abs(np.asarray(back - x))
    # symmetric round-to-nearest: error <= scale/2 per group (margin 0.6)
    bound = np.asarray(s).max() * (0.51 if bits == 8 else 0.6)
    assert err.max() <= bound + 1e-6


@pytest.mark.parametrize("bits", [8, 4])
def test_interpret_kernel_matches_jnp_twin(bits):
    """Pallas interpret path and the jnp fallback share one wire format."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 4096)).astype(np.float32))
    q_ref, s_ref = block_quantize(x, num_bits=bits, group_size=2048,
                                  interpret=False)      # jnp twin on CPU
    q_k, s_k = block_quantize(x, num_bits=bits, group_size=2048,
                              interpret=True)           # Pallas interpret
    np.testing.assert_array_equal(np.asarray(q_k), np.asarray(q_ref))
    np.testing.assert_allclose(np.asarray(s_k), np.asarray(s_ref), rtol=1e-6)
    d_ref = block_dequantize(q_ref, s_ref, num_bits=bits, group_size=2048,
                             interpret=False)
    d_k = block_dequantize(q_k, s_k, num_bits=bits, group_size=2048,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(d_k), np.asarray(d_ref), atol=1e-5)


def test_int4_half_split_packing():
    """Byte j carries element j in the low nibble and element j + gs/2 in
    the high nibble (contiguous lane-aligned halves, not interleaved)."""
    vals = (np.arange(256) % 15 - 7).astype(np.float32)  # amax 7 -> scale 1
    q, s = block_quantize(jnp.asarray(vals), num_bits=4, group_size=256)
    assert float(s[0]) == pytest.approx(1.0)
    iv = vals.astype(np.int64)
    expected = ((iv[:128] & 0xF) | ((iv[128:] & 0xF) << 4)).astype(np.uint8)
    np.testing.assert_array_equal(np.asarray(q), expected)


@pytest.mark.parametrize("bits", [8, 4])
def test_dequantize_reduce_sums_peers(bits):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 1000)).astype(np.float32))  # 4 peers
    q, s = block_quantize(x, num_bits=bits, group_size=256)
    out = block_dequantize_reduce(q, s, num_bits=bits, group_size=256,
                                  out_len=1000)
    per_peer = block_dequantize(q, s, num_bits=bits, group_size=256,
                                out_len=1000)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(per_peer).sum(axis=0), atol=1e-5)
    # and it approximates the fp32 sum within the quantization budget
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(axis=0),
                               atol=(0.1 if bits == 8 else 1.0))


def test_wire_nbytes():
    assert wire_nbytes(2048, 8, 2048) == 2048 + 4          # 1 group
    assert wire_nbytes(2048, 4, 2048) == 1024 + 4          # packed half
    assert wire_nbytes(2049, 8, 2048) == 2 * 2048 + 8      # padded tail
    assert wire_nbytes(100, 4, 2048) == 1024 + 4


# ---------------------------------------------------------------- feedback

def _mesh2d(eight_devices):
    dev = np.asarray(eight_devices).reshape(4, 2)
    return jax.sharding.Mesh(dev, ("dpr", "dp"))


def test_exchange_reduce_error_is_wire_loss(eight_devices):
    """``err`` must be exactly input minus what the peers reconstruct: the
    all-to-all of ``blocks - err`` re-summed matches the quantized output."""
    mesh = _mesh2d(eight_devices)
    rng = np.random.default_rng(3)
    m = 256
    g_all = rng.normal(size=(4, 2, 2, m)).astype(np.float32)  # [dpr,dp,P,m]

    def body(g):
        blocks = g[0, 0]                               # [2, m]
        out, err = exchange_reduce(blocks, "dp", 4, group_size=256,
                                   return_error=True)
        out_plain = exchange_reduce(blocks, "dp", 4, group_size=256)
        deq = blocks - err                             # what crossed the wire
        recv = jax.lax.all_to_all(deq, "dp", split_axis=0, concat_axis=0)
        return (out[None, None], out_plain[None, None],
                err[None, None], recv.sum(axis=0)[None, None])

    f = shard_map(body, mesh=mesh, in_specs=P("dpr", "dp"),
                  out_specs=(P("dpr", "dp"),) * 4, check_vma=False)
    out, out_plain, err, resum = (np.asarray(a) for a in
                                  f(jnp.asarray(g_all)))
    # return_error must not change the reduction itself
    np.testing.assert_allclose(out, out_plain, atol=1e-6)
    # residual identity: dequantized sends re-sum to the fused reduce output
    np.testing.assert_allclose(resum, out, atol=1e-5)
    # int4 rounding: |err| <= scale/2 = amax/14 per group (margin to amax/7)
    assert np.abs(err).max() <= np.abs(g_all).max() / 7.0
    # and the quantized sum tracks the fp32 sum: device (e, i) reduces the
    # chunks destined to dp-rank i within replica group e
    np.testing.assert_allclose(out, g_all.sum(axis=1), atol=1.0, rtol=0.1)


# ---------------------------------------------------------------- engine

def _train(config, steps=3, seed=0):
    model = SimpleModel(hidden_dim=64)
    batches = random_batches(steps, batch_size=8, seed=seed + 1)
    params = model.init(jax.random.PRNGKey(seed), batches[0])["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               model_parameters=params,
                                               config=config)
    losses = []
    for b in batches:
        loss = engine(b)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


_BASE = {
    "train_batch_size": 8,
    "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
    "bf16": {"enabled": True},
}

_Z3 = {"stage": 3, "stage3_param_persistence_threshold": 0}


def test_qgz_loss_parity_feedback_tightens():
    """qgZ tracks the fp32 psum baseline; error feedback must track it
    STRICTLY tighter (measured: 0.042 no-feedback vs 0.031 with, int4
    intra stage on the 8-way dp world)."""
    _, l_ref = _train(dict(_BASE, zero_optimization=dict(_Z3)), steps=6)
    groups.reset()
    _, l_q = _train(dict(_BASE, zero_optimization=dict(
        _Z3, zero_quantized_gradients=True)), steps=6)
    groups.reset()
    eng, l_fb = _train(dict(_BASE, zero_optimization=dict(
        _Z3, zero_quantized_gradients=True,
        zero_quantized_gradients_error_feedback=True)), steps=6)

    div_q = max(abs(a - b) for a, b in zip(l_q, l_ref))
    div_fb = max(abs(a - b) for a, b in zip(l_fb, l_ref))
    assert div_q <= 0.2, (l_q, l_ref)
    assert div_fb <= 0.1, (l_fb, l_ref)          # the tighter documented bound
    assert div_fb < div_q, (div_fb, div_q)
    # the carry is real: residual leaves are populated after stepping
    res = jax.tree.leaves(eng.state.qgz_residual)
    assert res and any(float(jnp.abs(r).max()) > 0 for r in res)


def test_qgz_feedback_requires_quantized_gradients():
    cfg = dict(_BASE, zero_optimization=dict(_Z3, zero_quantized_gradients=True,
                                             zero_quantized_gradients_error_feedback=True))
    eng, _ = _train(cfg, steps=1)
    assert eng.state.qgz_residual is not None
    groups.reset()
    eng2, _ = _train(dict(_BASE, zero_optimization=dict(_Z3)), steps=1)
    assert eng2.state.qgz_residual is None


# ---------------------------------------------------------------- telemetry

def test_qgz_dcn_wire_ratio_bound(eight_devices):
    """The acceptance bound: at realistic payload (>= one full quant group
    per chunk) the DCN (dpr, int8) leg moves <= 0.3x the fp32 bytes and the
    ICI (dp, int4) leg less still. Trace-only — the lowering itself fires
    the traced record_comm calls."""
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        all_to_all_quant_reduce)
    telemetry.configure(enabled=True, sample_sync=False)
    mesh = _mesh2d(eight_devices)
    grad = jax.ShapeDtypeStruct((8, 8192), jnp.float32)
    fn = shard_map(lambda g: all_to_all_quant_reduce(
        g, intra_axis="dp", inter_axis="dpr"),
        mesh=mesh, in_specs=P(), out_specs=P(("dpr", "dp")), check_vma=False)
    jax.jit(fn).lower(grad)
    a2a = telemetry.summary()["comm"]["ops"]["all_to_all_quant"]
    assert "dpr" in a2a and "dp" in a2a, sorted(a2a)
    for axis in ("dpr", "dp"):
        st = a2a[axis]
        assert 0 < st["wire_bytes"] <= 0.3 * st["bytes"], (axis, st)
    assert a2a["dp"]["wire_bytes"] / a2a["dp"]["bytes"] \
        < a2a["dpr"]["wire_bytes"] / a2a["dpr"]["bytes"]  # int4 < int8


def test_qgz_hpz_wire_bytes_telemetry():
    """Composed qwZ+qgZ+hpZ engine run: quantized collectives report true
    wire bytes on both hierarchy axes, and the hpZ primary exchange crosses
    DCN quantized. (The toy model's chunks are smaller than one quant group,
    so padding dominates here — the 0.3x ratio bound lives in
    test_qgz_dcn_wire_ratio_bound and scripts/perf_gate.py at real sizes.)"""
    telemetry.configure(enabled=True, sample_sync=False)
    cfg = dict(_BASE, zero_optimization=dict(
        _Z3, zero_hpz_partition_size=2, zero_quantized_gradients=True,
        zero_quantized_weights=True))
    _train(cfg, steps=1)
    s = telemetry.summary()
    ops = s["comm"]["ops"]
    a2a = ops["all_to_all_quant"]
    assert "dpr" in a2a and "dp" in a2a, sorted(a2a)
    for axis in ("dpr", "dp"):
        assert a2a[axis]["wire_bytes"] > 0, (axis, a2a[axis])
        assert a2a[axis]["wire_bytes"] != a2a[axis]["bytes"]
    hpz = ops["hpz_primary_exchange"]["dpr"]
    assert 0 < hpz["wire_bytes"] < hpz["bytes"], hpz
    assert s["comm"]["total_wire_bytes"] != s["comm"]["total_bytes"]
