"""MMap indexed dataset round-trip (reference
``tests/unit/runtime/data_pipeline`` indexed-dataset analog)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.indexed_dataset import (
    MMapIndexedDataset, MMapIndexedDatasetBuilder)


def build(tmp_path, name, samples, dtype=np.int32):
    b = MMapIndexedDatasetBuilder(str(tmp_path / name), dtype=dtype)
    for s in samples:
        b.add_item(s)
    b.finalize()
    return MMapIndexedDataset(str(tmp_path / name))


def test_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    samples = [rng.integers(0, 50000, size=n).astype(np.int32)
               for n in (5, 1, 128, 17)]
    ds = build(tmp_path, "corpus", samples)
    assert len(ds) == 4
    assert ds.num_tokens == sum(s.size for s in samples)
    for got, want in zip(ds, samples):
        np.testing.assert_array_equal(got, want)
    # in-sample slicing (curriculum truncation)
    np.testing.assert_array_equal(ds.get(2, offset=10, length=20),
                                  samples[2][10:30])
    np.testing.assert_array_equal(ds.get(2, offset=120), samples[2][120:])


def test_dtypes_and_merge(tmp_path):
    s1 = [np.array([1, 2, 3], np.uint16), np.array([9], np.uint16)]
    s2 = [np.array([7, 8], np.uint16)]
    build(tmp_path, "a", s1, dtype=np.uint16)
    build(tmp_path, "b", s2, dtype=np.uint16)
    m = MMapIndexedDatasetBuilder(str(tmp_path / "merged"), dtype=np.uint16)
    m.merge_file(str(tmp_path / "a"))
    m.merge_file(str(tmp_path / "b"))
    m.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "merged"))
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[0], s1[0])
    np.testing.assert_array_equal(ds[2], s2[0])
    assert ds.dtype == np.uint16


def test_bad_magic(tmp_path):
    (tmp_path / "x.idx").write_bytes(b"NOTMAGIC" + b"\0" * 24)
    (tmp_path / "x.bin").write_bytes(b"")
    with pytest.raises(ValueError, match="bad magic"):
        MMapIndexedDataset(str(tmp_path / "x"))


def test_dtype_mismatch_merge(tmp_path):
    build(tmp_path, "a32", [np.array([1], np.int32)])
    m = MMapIndexedDatasetBuilder(str(tmp_path / "m16"), dtype=np.uint16)
    with pytest.raises(ValueError, match="dtype mismatch"):
        m.merge_file(str(tmp_path / "a32"))
    m.finalize()


def test_empty_dataset(tmp_path):
    ds = build(tmp_path, "empty", [])
    assert len(ds) == 0 and ds.num_tokens == 0
    m = MMapIndexedDatasetBuilder(str(tmp_path / "m"), dtype=np.int32)
    m.merge_file(str(tmp_path / "empty"))  # merging an empty shard is fine
    m.add_item(np.array([5], np.int32))
    m.finalize()
    assert len(MMapIndexedDataset(str(tmp_path / "m"))) == 1
