"""Inference v1: KV cache correctness + generation (reference
``tests/unit/inference/test_inference.py`` analog, sized for CI)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.inference import InferenceEngine, generate, sample_logits
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


def test_cached_prefill_matches_full_forward(tiny_llama):
    cfg, model, params = tiny_llama
    ids = np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)).astype(np.int32)
    full = model.apply({"params": params}, {"input_ids": ids})
    from deepspeed_tpu.inference.generation import init_cache
    cache = init_cache(model, ids)
    cached, _ = model.apply({"params": params, "cache": cache},
                            {"input_ids": ids}, use_cache=True,
                            mutable=["cache"])
    np.testing.assert_allclose(np.asarray(full), np.asarray(cached),
                               rtol=2e-2, atol=2e-2)


def test_incremental_decode_matches_prefill(tiny_llama):
    cfg, model, params = tiny_llama
    rng = np.random.default_rng(2)
    ids = rng.integers(0, cfg.vocab_size, (1, 6)).astype(np.int32)
    full = model.apply({"params": params}, {"input_ids": ids})
    from deepspeed_tpu.inference.generation import init_cache
    cache = init_cache(model, ids)
    outs = []
    for t in range(ids.shape[1]):
        logits, vars_ = model.apply(
            {"params": params, "cache": cache},
            {"input_ids": ids[:, t:t + 1]}, use_cache=True,
            positions=jnp.full((1, 1), t, jnp.int32), mutable=["cache"])
        cache = vars_["cache"]
        outs.append(np.asarray(logits[:, 0]))
    step = np.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), step, rtol=5e-2, atol=5e-2)


def test_greedy_generate_matches_naive_loop(tiny_llama):
    cfg, model, params = tiny_llama
    ids = np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 5)).astype(np.int32)
    out = generate(model, params, ids, max_new_tokens=6, temperature=0.0)
    # naive: full forward over the growing sequence each step
    cur = ids
    naive = []
    for _ in range(6):
        logits = model.apply({"params": params}, {"input_ids": cur})
        nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1)).astype(np.int32)
        naive.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.stack(naive, axis=1))


def test_eos_early_stop(tiny_llama):
    cfg, model, params = tiny_llama
    ids = np.random.default_rng(4).integers(0, cfg.vocab_size, (1, 4)).astype(np.int32)
    greedy = generate(model, params, ids, max_new_tokens=5, temperature=0.0)
    eos = int(np.asarray(greedy)[0, 1])  # force eos at step 2
    out = np.asarray(generate(model, params, ids, max_new_tokens=5,
                              temperature=0.0, eos_token_id=eos))
    assert (out[0, 2:] == eos).all()


def test_sampling_respects_top_k():
    logits = jnp.array([[0.0, 1.0, 2.0, 3.0]])
    for seed in range(5):
        tok = sample_logits(logits, jax.random.PRNGKey(seed),
                            temperature=1.0, top_k=2)
        assert int(tok[0]) in (2, 3)


def test_engine_api(tiny_llama):
    cfg, model, params = tiny_llama
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 1}})
    engine.set_params(params)
    ids = np.random.default_rng(5).integers(0, cfg.vocab_size, (1, 4)).astype(np.int32)
    logits = engine(ids)
    assert logits.shape == (1, 4, cfg.vocab_size)
    out = engine.generate(ids, max_new_tokens=3)
    assert out.shape == (1, 3)


def test_dp_replicated_tp_serving_mesh(tiny_llama, eight_devices):
    """replica_num x tp serving mesh (VERDICT r2 weak #7): weights replicate
    across dp, batches shard over it, logits match the single-replica run."""
    cfg, model, params = tiny_llama
    single = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2}})
    single.set_params(params)
    multi = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 2},
                       "replica_num": 2})
    multi.set_params(params)
    assert dict(multi.mesh.shape) == {"dp": 2, "tp": 2}
    # params carry no dp axis (replicated across replicas)
    leaf_sh = jax.tree.leaves(
        jax.tree.map(lambda l: l.sharding.spec, multi.params))
    assert all("dp" not in str(s) for s in leaf_sh)

    ids = np.random.default_rng(7).integers(
        0, cfg.vocab_size, (4, 8)).astype(np.int32)
    got = np.asarray(multi(ids), np.float32)
    want = np.asarray(single(ids), np.float32)
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-3)
    # the batch really is dp-sharded on the multi mesh
    sharded = multi._shard_batch({"input_ids": jnp.asarray(ids)})
    assert "dp" in str(sharded["input_ids"].sharding.spec)


def test_replica_clamping(tiny_llama):
    cfg, model, params = tiny_llama
    eng = deepspeed_tpu.init_inference(
        model, config={"dtype": "fp32", "tensor_parallel": {"tp_size": 4},
                       "replica_num": 64})
    assert eng.mesh.shape["dp"] * eng.mesh.shape["tp"] <= len(jax.devices())
