"""Resilience pins (docs/RESILIENCE.md): fault-injection grammar and
triggers, retry/backoff policy, crash-consistent verified checkpoints,
corrupt-tag quarantine + fallback, preemption-aware save with the
clean-preemption exit code, the step watchdog, and the elastic agent's
budget-free preemption relaunch. Everything runs on CPU — the fault
points make every TPU failure mode drillable in-process."""

import hashlib
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.resilience import (EXIT_CLEAN_PREEMPTION,
                                      EXIT_RESHARD_SLICE_LOSS,
                                      EXIT_WATCHDOG_ABORT,
                                      CorruptCheckpointError, InjectedFault,
                                      PreemptionHandler, StepWatchdog, faults)
from deepspeed_tpu.runtime.checkpoint_engine.native_engine import (
    AsyncCheckpointEngine, NativeCheckpointEngine, atomic_write_text)
from deepspeed_tpu.utils.retry import (BackoffPolicy, RetryError, retry_call,
                                       retryable)
from tests.simple_model import SimpleModel, random_batches

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# fault spec grammar + triggers
# ---------------------------------------------------------------------------

def test_parse_spec_full_grammar():
    rules = faults.parse_spec(
        "ckpt.write:once@step3; comm.collective:p0.25 ,"
        "step.hang:n2@step1-9!sleep2.5;worker.exit:always!exit7")
    by_point = {r.point: r for r in rules}
    assert set(by_point) == {"ckpt.write", "comm.collective", "step.hang",
                             "worker.exit"}
    r = by_point["ckpt.write"]
    assert (r.mode, r.lo, r.hi, r.action) == ("once", 3, 3, "raise")
    r = by_point["comm.collective"]
    assert (r.mode, r.prob, r.lo) == ("prob", 0.25, None)
    r = by_point["step.hang"]
    assert (r.mode, r.nth, r.lo, r.hi, r.action, r.arg) == \
        ("nth", 2, 1, 9, "sleep", 2.5)
    r = by_point["worker.exit"]
    assert (r.mode, r.action, r.arg) == ("always", "exit", 7)


def test_parse_spec_default_actions():
    """step.hang stalls and worker.exit crashes even without an !action."""
    by_point = {r.point: r for r in faults.parse_spec(
        "step.hang:once;worker.exit:once;ckpt.write:once")}
    assert by_point["step.hang"].action == "sleep"
    assert by_point["worker.exit"].action == "exit"
    assert by_point["ckpt.write"].action == "raise"


@pytest.mark.parametrize("bad", [
    "ckpt.write",                # no mode
    "nope.nope:once",            # unknown point must not silently disarm
    "ckpt.write:n0",             # n<K> is 1-based
    "ckpt.write:p1.5",           # probability out of range
    "ckpt.write:once@step5-3",   # empty window
    "ckpt.write:oops",           # unknown mode
    "ckpt.write:once!boom",      # unknown action
])
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_once_and_nth_triggers():
    inj = faults.FaultInjector()
    inj.configure("ckpt.write:once;ckpt.publish:n3")
    with pytest.raises(InjectedFault):
        inj.maybe_fail("ckpt.write")
    inj.maybe_fail("ckpt.write")  # once means once
    assert inj.trip_count("ckpt.write") == 1
    inj.maybe_fail("ckpt.publish")
    inj.maybe_fail("ckpt.publish")
    with pytest.raises(InjectedFault):
        inj.maybe_fail("ckpt.publish")  # 3rd hit
    inj.maybe_fail("ckpt.publish")      # and only the 3rd
    assert inj.trip_count("ckpt.publish") == 1
    inj.maybe_fail("io.host")  # unarmed point is a no-op


def test_step_window_gating():
    inj = faults.FaultInjector()
    inj.configure("ckpt.write:always@step2-4")
    inj.maybe_fail("ckpt.write")  # step unknown: window can't match
    inj.set_step(1)
    inj.maybe_fail("ckpt.write")
    inj.set_step(3)
    with pytest.raises(InjectedFault):
        inj.maybe_fail("ckpt.write")
    inj.set_step(5)
    inj.maybe_fail("ckpt.write")
    assert inj.trip_count() == 1


def test_probability_trigger_is_seeded():
    def trips(seed):
        inj = faults.FaultInjector()
        inj.configure("comm.collective:p0.5", seed=seed)
        fired = []
        for i in range(64):
            try:
                inj.maybe_fail("comm.collective")
                fired.append(0)
            except InjectedFault:
                fired.append(1)
        return fired
    a, b = trips(7), trips(7)
    assert a == b, "same seed must reproduce the same fault schedule"
    assert 10 < sum(a) < 54  # p=0.5 over 64 hits, loose bounds
    assert trips(8) != a


def test_sleep_action_stalls_then_continues():
    inj = faults.FaultInjector()
    inj.configure("step.hang:once!sleep0.05")
    t0 = time.monotonic()
    inj.maybe_fail("step.hang")  # no raise — stalls and returns
    assert time.monotonic() - t0 >= 0.05
    assert inj.trip_count("step.hang") == 1


def test_env_arming_and_explicit_config_precedence(monkeypatch):
    monkeypatch.setenv(faults.ENV_SPEC, "io.host:once")
    inj = faults.FaultInjector()
    with pytest.raises(InjectedFault):
        inj.maybe_fail("io.host")  # env spec armed lazily on first use
    inj2 = faults.FaultInjector()
    inj2.configure("ckpt.write:once")  # explicit config wins over the env
    inj2.maybe_fail("io.host")
    with pytest.raises(InjectedFault):
        inj2.maybe_fail("ckpt.write")


def test_env_typo_fails_loud(monkeypatch):
    monkeypatch.setenv(faults.ENV_SPEC, "ckpt.wirte:once")
    inj = faults.FaultInjector()
    with pytest.raises(ValueError, match="unknown fault point"):
        inj.maybe_fail("ckpt.write")


def test_module_singleton_reset():
    faults.configure("ckpt.write:always")
    assert faults.armed()
    with pytest.raises(InjectedFault):
        faults.maybe_fail("ckpt.write")
    faults.reset()
    assert not faults.armed()
    faults.maybe_fail("ckpt.write")


# ---------------------------------------------------------------------------
# retry / backoff
# ---------------------------------------------------------------------------

def test_backoff_policy_ladder_and_jitter():
    p = BackoffPolicy(base=0.5, factor=2.0, max_delay=3.0, jitter="none")
    assert [p.cap(a) for a in (1, 2, 3, 4, 5)] == [0.5, 1.0, 2.0, 3.0, 3.0]
    assert p.delay(2) == 1.0  # jitter=none → deterministic ladder
    import random
    pj = BackoffPolicy(base=0.5, factor=2.0, max_delay=3.0, jitter="full",
                       rng=random.Random(0))
    for a in range(1, 8):
        assert 0.0 <= pj.delay(a) <= pj.cap(a)
    with pytest.raises(ValueError):
        BackoffPolicy(factor=0.5)
    with pytest.raises(ValueError):
        BackoffPolicy(jitter="half")
    with pytest.raises(ValueError):
        p.cap(0)


def test_retry_eventually_succeeds_with_backoff():
    calls, slept = [], []
    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("blip")
        return "ok"
    assert retry_call(flaky, retries=3, base_delay=0.5, jitter="none",
                      sleep=slept.append) == "ok"
    assert len(calls) == 3
    assert slept == [0.5, 1.0]


def test_retry_exhaustion_chains_last_error():
    def always():
        raise OSError("down")
    with pytest.raises(RetryError) as ei:
        retry_call(always, retries=2, base_delay=0.0, sleep=lambda s: None)
    assert ei.value.attempts == 3  # first attempt + 2 retries
    assert isinstance(ei.value.last, OSError)
    assert isinstance(ei.value.__cause__, OSError)


def test_retry_deadline_refuses_to_oversleep():
    t = [0.0]
    def always():
        t[0] += 1.0  # each attempt costs 1s of fake time
        raise OSError("down")
    with pytest.raises(RetryError, match="deadline"):
        retry_call(always, retries=10, base_delay=4.0, jitter="none",
                   deadline=3.0, clock=lambda: t[0], sleep=lambda s: None)


def test_retry_non_matching_exception_propagates():
    def boom():
        raise ValueError("not transient")
    with pytest.raises(ValueError):
        retry_call(boom, retries=5, retry_on=(OSError,),
                   sleep=lambda s: None)


def test_retryable_decorator_and_on_retry_hook():
    seen = []
    calls = []

    @retryable(retries=2, base_delay=0.0, sleep=lambda s: None,
               on_retry=lambda a, e, d: seen.append((a, type(e).__name__)))
    def flaky(x):
        calls.append(1)
        if len(calls) < 2:
            raise OSError("blip")
        return x * 2

    assert flaky(21) == 42
    assert seen == [(1, "OSError")]


# ---------------------------------------------------------------------------
# crash-consistent verified checkpoints
# ---------------------------------------------------------------------------

def _tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (4, 4), dtype=jnp.float32),
            "b": np.arange(3, dtype=np.float32) + seed, "step": 7 + seed}


def _dir_hashes(path):
    out = {}
    for name in sorted(os.listdir(path)):
        p = os.path.join(path, name)
        if os.path.isfile(p):
            out[name] = hashlib.sha256(open(p, "rb").read()).hexdigest()
    return out


def _assert_tree_equal(got, want):
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))


def test_native_save_verify_load_roundtrip(tmp_path):
    eng = NativeCheckpointEngine()
    path = str(tmp_path / "tag")
    eng.save(_tree(), path, meta={"note": "v1"})
    manifest = eng.verify(path)
    assert manifest["format_version"] == NativeCheckpointEngine.FORMAT_VERSION
    assert set(manifest["checksums"]) >= {"arrays.npz", "aux.pkl",
                                          "meta_state.pkl"}
    _assert_tree_equal(eng.load(path, template=_tree()), _tree())
    assert eng.load_meta(path) == {"note": "v1"}


def test_crash_mid_write_leaves_previous_tag_intact(tmp_path):
    eng = NativeCheckpointEngine()
    path = str(tmp_path / "tag")
    eng.save(_tree(0), path)
    before = _dir_hashes(path)
    faults.configure("ckpt.write:once")
    with pytest.raises(InjectedFault):
        eng.save(_tree(1), path)
    # crash window cleanup: no tmp litter, old tag byte-identical and loadable
    assert [n for n in os.listdir(tmp_path) if ".tmp." in n] == []
    assert _dir_hashes(path) == before
    _assert_tree_equal(eng.load(path, template=_tree()), _tree(0))


@pytest.mark.parametrize("kind", ["native", "async"])
def test_crash_at_publish_previous_tag_byte_identical(tmp_path, kind):
    """The drill's kill window: a complete new tmp exists, the publish dies.
    The live tag must remain byte-for-byte the pre-crash checkpoint."""
    path = str(tmp_path / "tag")
    if kind == "native":
        eng = NativeCheckpointEngine()
        eng.save(_tree(0), path)
        before = _dir_hashes(path)
        faults.configure("ckpt.publish:once")
        with pytest.raises(InjectedFault):
            eng.save(_tree(1), path)
    else:
        eng = AsyncCheckpointEngine()
        eng.save(_tree(0), path)
        eng.commit(None)
        before = _dir_hashes(path)
        faults.configure("ckpt.publish:once")
        eng.save(_tree(1), path)  # background worker hits the fault
        with pytest.raises(IOError, match="InjectedFault"):
            eng.commit(None)
    assert _dir_hashes(path) == before
    loaded = NativeCheckpointEngine().load(path, template=_tree())
    _assert_tree_equal(loaded, _tree(0))


def test_bitflip_caught_by_checksum_and_named(tmp_path):
    eng = NativeCheckpointEngine()
    path = str(tmp_path / "tag")
    eng.save(_tree(), path)
    shard = os.path.join(path, "arrays.npz")
    raw = bytearray(open(shard, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(shard, "wb").write(bytes(raw))
    with pytest.raises(CorruptCheckpointError) as ei:
        eng.verify(path)
    assert ei.value.file == "arrays.npz"
    assert "checksum" in ei.value.reason
    with pytest.raises(CorruptCheckpointError):
        eng.load(path, template=_tree())


def test_missing_pieces_raise_typed_errors(tmp_path):
    eng = NativeCheckpointEngine()
    path = str(tmp_path / "tag")
    eng.save(_tree(), path)
    # a missing directory is CorruptCheckpointError, not FileNotFoundError
    err = pytest.raises(CorruptCheckpointError,
                        eng.load, str(tmp_path / "ghost"), template=_tree())
    assert isinstance(err.value, IOError)
    os.remove(os.path.join(path, "meta.json"))
    with pytest.raises(CorruptCheckpointError, match="manifest"):
        eng.load(path, template=_tree())


def test_truncated_unverified_checkpoint_wrapped(tmp_path):
    """Format-1 manifests (no checksums) skip verification — a truncated
    shard must still surface as CorruptCheckpointError, not BadZipFile."""
    import json
    eng = NativeCheckpointEngine()
    path = str(tmp_path / "tag")
    eng.save(_tree(), path)
    meta_p = os.path.join(path, "meta.json")
    meta = json.load(open(meta_p))
    meta.pop("checksums")
    meta["format_version"] = 1
    json.dump(meta, open(meta_p, "w"))
    shard = os.path.join(path, "arrays.npz")
    raw = open(shard, "rb").read()
    open(shard, "wb").write(raw[:len(raw) // 2])
    with pytest.raises(CorruptCheckpointError):
        eng.load(path, template=_tree())


def test_io_host_fault_absorbed_by_retry(tmp_path):
    """A transient host-I/O blip (one injected failure) is retried away —
    the save still succeeds and the trip is accounted."""
    eng = NativeCheckpointEngine()
    path = str(tmp_path / "tag")
    faults.configure("io.host:once")
    eng.save(_tree(), path)
    assert faults.trip_count("io.host") == 1
    _assert_tree_equal(eng.load(path, template=_tree()), _tree())


def test_atomic_write_text(tmp_path):
    p = str(tmp_path / "latest")
    atomic_write_text(p, "global_step1")
    atomic_write_text(p, "global_step2")
    assert open(p).read() == "global_step2"
    assert [n for n in os.listdir(tmp_path) if n != "latest"] == []


def test_comm_collective_fault_point():
    from deepspeed_tpu.comm import comm
    faults.configure("comm.collective:once")
    with pytest.raises(InjectedFault, match="all_reduce"):
        comm.all_reduce(np.ones(4, dtype=np.float32))
    assert faults.trip_count("comm.collective") == 1


def test_parse_spec_slice_loss_grammar():
    """The elastic fault points ride the existing grammar — windows, modes
    and actions all apply; typos stay loud (a drill that silently doesn't
    arm proves nothing)."""
    by_point = {r.point: r for r in faults.parse_spec(
        "slice.lost:once@step5; comm.partition:n2@step1-9")}
    r = by_point["slice.lost"]
    assert (r.mode, r.lo, r.hi, r.action) == ("once", 5, 5, "raise")
    r = by_point["comm.partition"]
    assert (r.mode, r.nth, r.lo, r.hi) == ("nth", 2, 1, 9)
    assert set(faults.SLICE_LOSS_POINTS) <= set(faults.KNOWN_POINTS)
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.parse_spec("slice.gone:once")
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.parse_spec("slice.lost@step5")


def test_comm_partition_fault_point():
    """comm.partition trips at the comm shim, same site as comm.collective
    — models a DCN partition dropping a slice out of the gang."""
    from deepspeed_tpu.comm import comm
    faults.configure("comm.partition:once")
    with pytest.raises(InjectedFault) as ei:
        comm.all_reduce(np.ones(4, dtype=np.float32))
    assert ei.value.point == "comm.partition"
    assert faults.trip_count("comm.partition") == 1


def test_slice_lost_fault_point_no_half_applied_step():
    """slice.lost fires BEFORE the optimizer apply: the fault can never
    leave a half-applied step behind (elastic disabled -> it propagates)."""
    engine = make_engine()
    faults.configure("slice.lost:once")
    b = random_batches(1, 8)[0]
    loss = engine(b)
    engine.backward(loss)
    with pytest.raises(InjectedFault) as ei:
        engine.step()
    assert ei.value.point == "slice.lost"
    assert engine.global_steps == 0  # the apply never ran


def test_slice_lost_elastic_saves_and_exits_84(tmp_path):
    """With resilience.elastic enabled the engine performs the process-level
    hand-off: emergency universal checkpoint (durable tag + pointer), then
    SystemExit with the reshardable-slice-loss code."""
    from deepspeed_tpu.checkpoint.universal import latest_universal_tag
    engine = make_engine({"resilience": {"elastic": {
        "enabled": True, "save_dir": str(tmp_path / "emergency")}}})
    train_steps(engine, 2)
    faults.configure("slice.lost:once")
    b = random_batches(1, 8, seed=9)[0]
    loss = engine(b)
    engine.backward(loss)
    with pytest.raises(SystemExit) as ei:
        engine.step()
    assert ei.value.code == EXIT_RESHARD_SLICE_LOSS == 84
    root = str(tmp_path / "emergency")
    tag = latest_universal_tag(root)
    assert tag == "ustep2"  # saved at the last committed step
    assert os.path.exists(os.path.join(root, tag, "universal_fragments.npz"))


def test_universal_publish_crash_preserves_prior_tag(tmp_path):
    """The universal save is crash-consistent: a crash at the publish
    instant leaves the previous durable tag AND the latest pointer intact,
    and no torn tmp dir survives for the reshard path to trip over."""
    from deepspeed_tpu.checkpoint.universal import (latest_universal_tag,
                                                    save_universal_checkpoint)
    engine = make_engine()
    train_steps(engine, 1)
    root = str(tmp_path / "uni")
    save_universal_checkpoint(engine, root, tag="ustep1")
    assert latest_universal_tag(root) == "ustep1"
    train_steps(engine, 1, seed=3)
    faults.configure("ckpt.publish:once")
    with pytest.raises(InjectedFault):
        save_universal_checkpoint(engine, root, tag="ustep2")
    faults.reset()
    assert latest_universal_tag(root) == "ustep1"
    assert not os.path.exists(os.path.join(root, "ustep2"))
    assert not [d for d in os.listdir(root) if ".tmp." in d]
    # and the surviving tag still restores
    from deepspeed_tpu.checkpoint import load_universal_checkpoint
    assert load_universal_checkpoint(engine, os.path.join(root, "ustep1")) > 0


# ---------------------------------------------------------------------------
# engine: quarantine + fallback, atomic latest, preemption, watchdog
# ---------------------------------------------------------------------------

def make_engine(config_extra=None, seed=0):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
    }
    cfg.update(config_extra or {})
    model = SimpleModel()
    batch = random_batches(1, 8)[0]
    params = model.init(jax.random.PRNGKey(seed), batch)["params"]
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)
    return engine


def train_steps(engine, n, seed=0):
    for b in random_batches(n, 8, seed=seed):
        loss = engine(b)
        engine.backward(loss)
        engine.step()


def _bitflip(path):
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))


def test_engine_latest_is_atomic(tmp_path):
    engine = make_engine()
    train_steps(engine, 1)
    engine.save_checkpoint(str(tmp_path))
    assert (tmp_path / "latest").read_text() == "global_step1"
    assert not [n for n in os.listdir(tmp_path) if n.startswith("latest.tmp")]


def test_engine_corrupt_tag_quarantined_and_fallback(tmp_path):
    """Acceptance pin: a bit-flip in the newest tag is caught by the
    checksum, the tag is quarantined, the load transparently falls back to
    the prior tag, and 'latest' is repaired to the tag that loads."""
    engine = make_engine()
    train_steps(engine, 1)
    engine.save_checkpoint(str(tmp_path))           # global_step1
    train_steps(engine, 1, seed=1)
    engine.save_checkpoint(str(tmp_path))           # global_step2
    assert (tmp_path / "latest").read_text() == "global_step2"
    _bitflip(str(tmp_path / "global_step2" / "arrays.npz"))
    path, _ = engine.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step1")
    assert engine.global_steps == 1
    assert (tmp_path / "global_step2.corrupt").is_dir()  # forensic evidence
    assert not (tmp_path / "global_step2").exists()
    assert (tmp_path / "latest").read_text() == "global_step1"


def test_engine_all_tags_corrupt_raises_typed(tmp_path):
    engine = make_engine()
    train_steps(engine, 1)
    engine.save_checkpoint(str(tmp_path))
    _bitflip(str(tmp_path / "global_step1" / "arrays.npz"))
    with pytest.raises(CorruptCheckpointError):
        engine.load_checkpoint(str(tmp_path))
    assert (tmp_path / "global_step1.corrupt").is_dir()


def test_preemption_emergency_save_and_exit_code(tmp_path):
    """Acceptance pin: preemption request → emergency checkpoint at the next
    step boundary → SystemExit with the clean-preemption code (83)."""
    engine = make_engine({"resilience": {"preemption": {
        "enabled": True, "save_dir": str(tmp_path), "tag": "emergency"}}})
    try:
        assert engine._preemption is not None
        train_steps(engine, 1)
        engine._preemption.request()  # the metadata-watcher path
        with pytest.raises(SystemExit) as ei:
            train_steps(engine, 1, seed=1)
        assert ei.value.code == EXIT_CLEAN_PREEMPTION
        assert (tmp_path / "emergency" / "meta.json").exists()
        assert (tmp_path / "latest").read_text() == "emergency"
    finally:
        engine._preemption.uninstall()
    # the emergency tag must actually resume a fresh engine
    engine2 = make_engine()
    path, _ = engine2.load_checkpoint(str(tmp_path))
    assert path.endswith("emergency")
    assert engine2.global_steps == 2


def test_preemption_handler_catches_sigterm_in_process():
    h = PreemptionHandler().install()
    try:
        assert h.installed and not h.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not h.requested() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h.requested()
        assert h.signal_received == signal.SIGTERM
        h.clear()
        assert not h.requested()
    finally:
        h.uninstall()


def test_real_sigterm_subprocess_exits_clean_preemption(tmp_path):
    """End-to-end: a real training process gets a real SIGTERM and must exit
    with the clean-preemption code after writing the emergency tag."""
    script = textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax
        import deepspeed_tpu
        from tests.simple_model import SimpleModel, random_batches

        out = sys.argv[1]
        model = SimpleModel()
        batch = random_batches(1, 8)[0]
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, model_parameters=params, config={{
                "train_batch_size": 8,
                "optimizer": {{"type": "Adam", "params": {{"lr": 1e-2}}}},
                "resilience": {{"preemption": {{
                    "enabled": True, "save_dir": out, "tag": "emergency"}}}},
            }})
        batches = random_batches(4, 8)
        i = 0
        while True:
            b = batches[i % 4]; i += 1
            loss = engine(b)
            engine.backward(loss)
            engine.step()
            open(os.path.join(out, "ready"), "w").close()
    """)
    worker = tmp_path / "worker.py"
    worker.write_text(script)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, str(worker), str(tmp_path)],
                         env=env)
    try:
        deadline = time.monotonic() + 180
        while not (tmp_path / "ready").exists():
            assert p.poll() is None, "worker died before first step"
            assert time.monotonic() < deadline, "worker never reached a step"
            time.sleep(0.1)
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=120)
    finally:
        if p.poll() is None:
            p.kill()
    assert rc == EXIT_CLEAN_PREEMPTION
    assert (tmp_path / "emergency" / "meta.json").exists()
    assert (tmp_path / "latest").read_text() == "emergency"


def test_watchdog_threshold_fire_and_relatch():
    t = [0.0]
    wd = StepWatchdog(hang_factor=3.0, min_interval_s=0.1,
                      poll_interval_s=0.05, window=8, clock=lambda: t[0])
    wd._last_beat = t[0]  # drive check() directly; no poll thread
    for _ in range(5):
        t[0] += 0.2
        wd.beat()
    assert wd.threshold() == pytest.approx(0.6)  # 3.0 x median(0.2)
    t[0] += 0.5
    assert wd.check() is None                    # idle 0.5 <= 0.6
    t[0] += 0.2
    report = wd.check()                          # idle 0.7 > 0.6
    assert report is not None and wd.fired == 1
    assert "no step progress" in report and "--- thread" in report
    assert wd.check() is None                    # latched until the next beat
    wd.beat()
    t[0] += 5.0
    assert wd.check() is not None                # re-armed
    assert wd.fired == 2


def test_watchdog_on_hang_and_dump_file(tmp_path):
    t = [0.0]
    hangs = []
    dump = str(tmp_path / "hang.txt")
    wd = StepWatchdog(hang_factor=2.0, min_interval_s=0.1, window=4,
                      clock=lambda: t[0], on_hang=hangs.append,
                      dump_file=dump)
    wd._last_beat = t[0]
    wd.beat(step_seconds=0.05)
    t[0] += 1.0
    assert wd.check() is not None
    assert len(hangs) == 1
    assert "no step progress" in open(dump).read()


def test_exit_code_contract_is_distinct():
    codes = {0, 1, EXIT_CLEAN_PREEMPTION, EXIT_WATCHDOG_ABORT}
    assert len(codes) == 4
    assert EXIT_CLEAN_PREEMPTION == 83 and EXIT_WATCHDOG_ABORT == 85


def test_watchdog_flags_injected_hang(tmp_path):
    """Acceptance pin: an injected step.hang stall is flagged within one
    heartbeat. hang_factor is tiny so min_interval_s (0.3s) dominates the
    threshold regardless of compile-time step samples."""
    engine = make_engine({"resilience": {
        "faults": "step.hang:once@step2!sleep1.2",
        "watchdog": {"enabled": True, "min_interval_s": 0.3,
                     "poll_interval_s": 0.05, "hang_factor": 1e-3},
    }})
    try:
        train_steps(engine, 3)
        assert engine._watchdog.fired >= 1
        assert "no step progress" in engine._watchdog.last_report
        assert faults.trip_count("step.hang") == 1
    finally:
        engine._watchdog.stop()


# ---------------------------------------------------------------------------
# elastic agent: preemption is budget-free, failures are accounted
# ---------------------------------------------------------------------------

def _write_worker(tmp_path, body):
    p = tmp_path / "worker.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_elastic_agent_preemption_budget_free(tmp_path):
    """Exit 83 relaunches without consuming max_restarts (here: 0)."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    w = _write_worker(tmp_path, f"""
        import os, sys
        out = sys.argv[1]
        flag = os.path.join(out, "preempted_once")
        if not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit({EXIT_CLEAN_PREEMPTION})
        open(os.path.join(out, "done"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost"],
                           max_restarts=0,
                           backoff=BackoffPolicy(base=0.01, jitter="none"))
    assert agent.run() == 0
    assert agent.restarts == 0
    assert agent.preemptions == 1
    assert agent.restart_reasons == ["preemption"]
    assert (tmp_path / "done").exists()


def test_elastic_agent_failure_reason_recorded(tmp_path):
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    w = _write_worker(tmp_path, """
        import os, sys
        out = sys.argv[1]
        flag = os.path.join(out, "failed_once")
        if not os.path.exists(flag):
            open(flag, "w").close()
            sys.exit(5)
        open(os.path.join(out, "done"), "w").close()
    """)
    agent = DSElasticAgent(w, [str(tmp_path)], hosts=["localhost"],
                           max_restarts=1,
                           backoff=BackoffPolicy(base=0.01, jitter="none"))
    assert agent.run() == 0
    assert agent.restarts == 1
    assert agent.preemptions == 0
    assert agent.restart_reasons == ["worker_exit_5"]


def test_resilience_config_section():
    from deepspeed_tpu.runtime.config import ResilienceConfig
    cfg = ResilienceConfig({
        "faults": "ckpt.write:once@step3", "fault_seed": 11,
        "preemption": {"enabled": True, "save_dir": "/tmp/x"},
        "watchdog": {"enabled": True, "hang_factor": 4.0, "abort": True},
    })
    assert cfg.faults == "ckpt.write:once@step3" and cfg.fault_seed == 11
    assert cfg.preemption.enabled and cfg.preemption.exit_code == \
        EXIT_CLEAN_PREEMPTION
    assert cfg.watchdog.abort and cfg.watchdog.exit_code == \
        EXIT_WATCHDOG_ABORT
    dflt = ResilienceConfig({})
    assert not dflt.preemption.enabled and not dflt.watchdog.enabled
