"""Offline compile-cache verification: are AOT topology compiles addressable
by the live backend's cache, without silicon?

``scripts/aot_tpu_check.py`` claims (module docstring, payoff #3) that its
chip-free v5e compiles prewarm ``.jax_cache`` so on-chip runs load instead of
compiling. That claim has two checkable halves:

1. KEY ADDRESSABILITY — the persistent-cache key (``jax._src.cache_key.get``:
   a hash over the HLO module, device/topology fingerprint, compile options
   + XLA flags, and compiler version) must be deterministic across fresh
   lowerings AND across processes, and must be sensitive to the things that
   make an executable non-portable (different topology/device assignment,
   different compiler flags, CPU backend vs TPU topology). Proven below by
   recording the keys the cache layer actually computes.

2. ARTIFACT WRITE — the compile must actually serialize into the cache dir.
   On this jax/jaxlib the topology path DISPROVES the payoff: the
   compile-only client cannot serialize executables
   (``serialize_executable(): incompatible function arguments`` from
   ``CompileOnlyPyClient``), so AOT runs compute correct keys but write NO
   entries — prewarming currently only validates lowering, it does not save
   the chip a cold compile. This test pins that fact; if a jax upgrade fixes
   serialization, the pinned count below fails and the docs should flip.

Runs topology compiles in subprocesses because the compile-only TPU topology
client and the test session's CPU backend must not share process-global
backend state (same reason as tests/test_aot_tpu_lowering.py).
"""

import json
import os
import subprocess
import sys

_PROBE = r"""
import json, os, sys
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
cache_dir = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax._src import cache_key as _ck

recorded = []
_orig = _ck.get

def _wrapper(module, devices, compile_options, backend, *a, **k):
    key = _orig(module, devices, compile_options, backend, *a, **k)
    recorded.append({
        "key": key,
        "platform": backend.platform,
        # the compiler-version half of the key's inputs
        "platform_version": str(backend.platform_version),
        # the topology-fingerprint half
        "n_devices": int(np.asarray(devices).size),
        "num_partitions": compile_options.num_partitions,
    })
    return key

_ck.get = _wrapper

from jax.experimental import topologies
topo = topologies.get_topology_desc(platform="tpu", topology_name="v5e:2x2")
x = jax.ShapeDtypeStruct((256, 256), jnp.float32)

def mk():
    return lambda x: jnp.sin(x) @ jnp.cos(x).T

def compile_on(mesh_shape, compiler_options=None):
    mesh = Mesh(np.array(topo.devices).reshape(*mesh_shape), ("dp", "tp"))
    jax.clear_caches()   # force a fresh lowering -> a fresh cache-key probe
    lowered = jax.jit(mk(), in_shardings=NamedSharding(mesh, P("dp", "tp"))).lower(x)
    if compiler_options is None:
        lowered.compile()
    else:
        lowered.compile(compiler_options=compiler_options)
    return recorded[-1]

r1 = compile_on((2, 2))
r2 = compile_on((2, 2))                       # same program, fresh lowering
r_topo = compile_on((4, 1))                   # different device assignment
r_flags = compile_on((2, 2), {"xla_embed_ir_in_executable": True})
# the config hash covers jax_compilation_cache_dir itself on this jax:
# prewarm and live run must point at the SAME cache path or keys diverge
jax.config.update("jax_compilation_cache_dir", cache_dir + "_alt")
r_dir = compile_on((2, 2))
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.clear_caches()
cpu_mesh = Mesh(np.array(jax.devices("cpu")[:1]).reshape(1, 1), ("dp", "tp"))
jax.jit(mk(), in_shardings=NamedSharding(cpu_mesh, P())).lower(x).compile()
r_cpu = recorded[-1]

print("PROBE_JSON " + json.dumps({
    "same1": r1, "same2": r2, "topo_change": r_topo,
    "flags_change": r_flags, "dir_change": r_dir, "cpu": r_cpu,
    "cache_entries": sorted(os.listdir(cache_dir))
                     if os.path.isdir(cache_dir) else [],
}))
"""


def _run_probe(tmp_path, tag):
    # NOTE: the cache-dir path is shared between probe runs on purpose — the
    # config hash folds jax_compilation_cache_dir into the key (see
    # dir_change below), so cross-process key equality requires it fixed,
    # exactly as onchip_sequence.sh fixes .jax_cache for prewarm + live run.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cache = tmp_path / "cache_shared"
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run([sys.executable, "-c", _PROBE, str(cache)],
                          env=env, capture_output=True, text=True,
                          timeout=600, cwd=repo)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("PROBE_JSON ")]
    assert line, proc.stdout[-2000:]
    return json.loads(line[0][len("PROBE_JSON "):])


def test_aot_topology_cache_key_inputs(tmp_path):
    a = _run_probe(tmp_path, "a")
    b = _run_probe(tmp_path, "b")

    # determinism: same program + topology + flags -> same key, within a
    # process across fresh lowerings AND across processes (the property that
    # makes prewarmed entries addressable by a later live-backend run at all)
    assert a["same1"]["key"] == a["same2"]["key"]
    assert a["same1"]["key"] == b["same1"]["key"]

    # sensitivity: every non-portability axis must change the key —
    # device assignment (topology fingerprint), compiler flags, and the
    # live-CPU-backend arm (different platform + compiler version)
    keys = {a["same1"]["key"], a["topo_change"]["key"],
            a["flags_change"]["key"], a["dir_change"]["key"],
            a["cpu"]["key"]}
    assert len(keys) == 5, keys

    # the recorded key inputs explain WHY the cpu arm can never hit a
    # TPU-prewarmed entry: different platform and compiler version string
    assert a["same1"]["platform"] != a["cpu"]["platform"]
    assert a["same1"]["platform_version"] != a["cpu"]["platform_version"]
    assert a["same1"]["num_partitions"] == 4
    assert a["cpu"]["num_partitions"] == 1

    # artifact write — PINNED CURRENT BEHAVIOR: the topology (compile-only)
    # client computes keys but cannot serialize executables on this
    # jax/jaxlib, so the ONLY cache entry is the live-CPU compile's. The
    # prewarm payoff claimed by aot_tpu_check.py is therefore currently
    # key-validation only. If this assert fails after a jax upgrade,
    # serialization got fixed: flip the docs (README "AOT validation" and
    # scripts/aot_tpu_check.py payoff #3) and strengthen this to == 5.
    cpu_key = a["cpu"]["key"]
    entries = a["cache_entries"]
    assert all(cpu_key.split("-")[-1] in e or a["same1"]["key"] not in e
               for e in entries)
    tpu_keys = {a["same1"]["key"], a["topo_change"]["key"],
                a["flags_change"]["key"], a["dir_change"]["key"]}
    assert not any(k in e for e in entries for k in tpu_keys), (
        "topology compiles started writing cache entries — prewarm works "
        "now; update README/aot_tpu_check docs and this pin")
