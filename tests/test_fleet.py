"""Serving fleet: SLO-aware router + prefill/decode disaggregation.

The load-bearing invariant is BIT-EXACTNESS: a request admitted through the
router, prefilled on a prefill-only replica, shipped (KV pages) to a decode
replica and finished there must emit exactly the tokens the monolithic
single-replica path emits — greedy and seeded sampling alike. Around that:
typed admission outcomes under saturation, page conservation across
handoffs, cancellation without KV leaks, and the public load-signal
accessors the router runs on.
"""

import numpy as np
import pytest

import jax

from deepspeed_tpu import telemetry
from deepspeed_tpu.inference.v2.fleet import (
    PrefillDecodeFleet, RequestAdmitted, RequestQueued, RequestRejected,
    SLORouter)
from deepspeed_tpu.inference.v2.replica_group import build_replica
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 3,
    reason="fleet tests need >= 3 devices (2 prefill + 1 decode)")


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)
    yield
    telemetry.close()
    telemetry.reset()
    telemetry.configure(enabled=False, jsonl_path="", chrome_trace_path="",
                        sample_sync=True, jax_annotations=False)


@pytest.fixture(scope="module")
def served():
    cfg = LlamaConfig.tiny(scan_layers=True, remat=False)
    model = LlamaForCausalLM(cfg)
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (1, 8)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), {"input_ids": ids})["params"]
    return cfg, model, params


ENG = {"state_manager": {"max_ragged_sequence_count": 9,
                         "max_ragged_batch_size": 64,
                         "max_context": 96,
                         "num_kv_blocks": 96},
       "kv_cache": {"block_size": 8, "cache_dtype": "fp32"}}


def make_fleet(model, params, **kw):
    kw.setdefault("engine_config", ENG)
    kw.setdefault("token_budget", 48)
    return PrefillDecodeFleet(model, params, prefill_replicas=2,
                              decode_replicas=1, **kw)


def single_reference(model, params, requests):
    """Monolithic single-replica run of the same requests:
    {uid: (prompt, kwargs)} -> {uid: tokens}."""
    mesh, sched = build_replica(model, params, [jax.devices()[0]],
                                engine_config=ENG, token_budget=48)
    with mesh:
        for uid, (prompt, kwargs) in requests.items():
            sched.submit(uid, prompt, **kwargs)
        return {u: np.asarray(v, np.int32)
                for u, v in sched.run_to_completion().items()}


def _requests(cfg, n=4, seed=5, max_new=6, sampling=False):
    """Mixed-length prompts, several longer than the prefill chunk so the
    SplitFuse chunking and the handoff both run."""
    rng = np.random.default_rng(seed)
    out = {}
    for uid in range(n):
        plen = int(rng.integers(5, 60))
        kwargs = {"max_new_tokens": max_new}
        if sampling:
            kwargs.update(temperature=0.9, top_k=5,
                          seed=int(rng.integers(0, 2 ** 30)))
        out[uid] = (rng.integers(0, cfg.vocab_size, plen).astype(np.int32),
                    kwargs)
    return out


# ---------------------------------------------------------------------------
# bit-exact disaggregation
# ---------------------------------------------------------------------------

def test_fleet_greedy_bit_exact_vs_single(served):
    """Greedy fleet output (prefill -> ship -> decode) must equal the
    monolithic single-replica run token for token."""
    cfg, model, params = served
    requests = _requests(cfg, n=4, seed=5)
    want = single_reference(model, params, requests)

    fleet = make_fleet(model, params)
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    got = fleet.run_to_completion()
    assert set(got) == set(want)
    for uid in want:
        np.testing.assert_array_equal(np.asarray(got[uid], np.int32),
                                      want[uid], err_msg=f"uid {uid}")
    # every multi-token request crossed the prefill->decode boundary
    assert fleet.transport.handoffs == len(requests)
    assert fleet.transport.pages_shipped == fleet.transport.pages_bound > 0
    # batched: never more device copies than handed-off requests
    assert 0 < fleet.transport.transfers <= fleet.transport.handoffs


def test_fleet_seeded_sampling_bit_exact_vs_single(served):
    """Seeded stochastic sampling is deterministic per (seed, position), so
    the decode side inherits the prefill side's stream mid-request and the
    fleet still matches the monolithic run exactly."""
    cfg, model, params = served
    requests = _requests(cfg, n=4, seed=11, sampling=True)
    want = single_reference(model, params, requests)

    fleet = make_fleet(model, params)
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    got = fleet.run_to_completion()
    for uid in want:
        np.testing.assert_array_equal(np.asarray(got[uid], np.int32),
                                      want[uid], err_msg=f"uid {uid}")


def test_single_token_request_finishes_at_prefill(served):
    """max_new_tokens=1 never ships: the prefill side is the terminal
    owner and the transport stays untouched."""
    cfg, model, params = served
    fleet = make_fleet(model, params)
    prompt = np.arange(20, dtype=np.int32) % cfg.vocab_size
    fleet.submit(0, prompt, max_new_tokens=1)
    out = fleet.run_to_completion()
    assert len(out[0]) == 1
    assert fleet.transport.handoffs == 0
    assert fleet.transport.transfers == 0


# ---------------------------------------------------------------------------
# router admission under saturation
# ---------------------------------------------------------------------------

def test_router_typed_outcomes_and_shedding(served):
    """Past-SLO requests queue up to the bound, then shed — typed outcomes,
    consistent accounting, and queued requests still run to completion
    (force-admitted once the backend idles)."""
    cfg, model, params = served
    fleet = make_fleet(model, params)
    router = SLORouter(fleet, slo_ttft_s=1e-9, queue_limit=2,
                       prefix_affinity=False)
    rng = np.random.default_rng(2)
    outcomes = [router.submit(uid,
                              rng.integers(0, cfg.vocab_size, 24)
                              .astype(np.int32), max_new_tokens=3)
                for uid in range(5)]
    # an impossible SLO queues everything; the queue bound sheds the rest
    assert [type(o) for o in outcomes] == [RequestQueued, RequestQueued,
                                           RequestRejected, RequestRejected,
                                           RequestRejected]
    assert outcomes[2].reason.startswith("predicted TTFT")
    assert router.report()["queue_depth"] == 2
    assert router.shed_rate == pytest.approx(3 / 5)

    out = router.run_to_completion()
    assert set(out) == {0, 1}  # shed requests never ran
    assert all(len(v) == 3 for v in out.values())
    rep = router.report()
    assert rep["admitted"] + rep["rejected"] == rep["submitted"]
    assert rep["queue_depth"] == 0


def test_router_admits_under_slo_and_rejects_unservable(served):
    cfg, model, params = served
    fleet = make_fleet(model, params)
    router = SLORouter(fleet, slo_ttft_s=60.0, prefix_affinity=False)
    a = router.submit(0, np.arange(16, dtype=np.int32) % cfg.vocab_size,
                      max_new_tokens=2)
    assert isinstance(a, RequestAdmitted)
    assert 0 < a.predicted_ttft_s <= 60.0
    # a prompt that cannot fit any replica's max_context sheds immediately
    # with a typed reason instead of a scheduler ValueError
    r = router.submit(1, np.zeros(200, np.int32), max_new_tokens=2)
    assert isinstance(r, RequestRejected) and "max_context" in r.reason
    assert len(router.run_to_completion()[0]) == 2


def test_router_prefix_affinity_pulls_to_warm_replica(served):
    """A prompt whose prefix is cached on one prefill replica routes there
    (the cached blocks shrink its predicted TTFT) and records the hit."""
    cfg, model, params = served
    eng_cfg = dict(ENG, prefix_caching=True)
    fleet = make_fleet(model, params, engine_config=eng_cfg)
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, cfg.vocab_size, 33).astype(np.int32)
    # seed replica 1's prefix cache: the export at handoff commits the
    # prefilled blocks before releasing them
    fleet.submit(0, prompt, max_new_tokens=3, replica=1)
    fleet.run_to_completion()
    assert fleet.prefill[1][1].peek_prefix(prompt) > 0

    router = SLORouter(fleet, slo_ttft_s=60.0)
    a = router.submit(1, prompt, max_new_tokens=3)
    assert isinstance(a, RequestAdmitted)
    assert a.replica == 1 and a.affinity_tokens > 0
    assert router.affinity_hits == 1
    router.run_to_completion()


# ---------------------------------------------------------------------------
# page conservation + cancellation
# ---------------------------------------------------------------------------

def _total_free(fleet):
    return {role: [s.engine.free_blocks for _, s in side]
            for role, side in (("prefill", fleet.prefill),
                               ("decode", fleet.decode))}


def test_fleet_drains_all_kv_pages(served):
    """After a full run every pool is back to its initial free-block count:
    export released the prefill side, finish flushed the decode side."""
    cfg, model, params = served
    fleet = make_fleet(model, params)
    before = _total_free(fleet)
    for uid, (prompt, kwargs) in _requests(cfg, n=4, seed=13).items():
        fleet.submit(uid, prompt, **kwargs)
    fleet.run_to_completion()
    assert _total_free(fleet) == before


def test_fleet_cancel_frees_pages_on_either_side(served):
    """Cancel mid-prefill and mid-decode: both free their KV pages and the
    remaining requests still finish bit-exactly."""
    cfg, model, params = served
    requests = _requests(cfg, n=3, seed=17, max_new=8)
    want = single_reference(model, params,
                            {2: requests[2]})  # the survivor
    fleet = make_fleet(model, params)
    before = _total_free(fleet)
    for uid, (prompt, kwargs) in requests.items():
        fleet.submit(uid, prompt, **kwargs)
    assert fleet.cancel(0)          # still queued/prefilling
    while fleet.transport.handoffs == 0 and fleet.has_work:
        fleet.step()
    handed = [uid for uid, r in fleet._route.items() if r[0] == "decode"]
    if 1 in handed:
        assert fleet.cancel(1)      # now lives on the decode side
    out = fleet.run_to_completion()
    np.testing.assert_array_equal(np.asarray(out[2], np.int32), want[2])
    assert _total_free(fleet) == before
    assert fleet.cancel(99) is False  # unknown uid


# ---------------------------------------------------------------------------
# load signals + telemetry
# ---------------------------------------------------------------------------

def test_load_report_and_public_accessors(served):
    cfg, model, params = served
    fleet = make_fleet(model, params)
    rep = fleet.load_report()
    assert [r["replica"] for r in rep["replicas"]] == \
        ["prefill0", "prefill1", "decode0"]
    assert all(r["active"] == 0 and r["kv_occupancy"] == 0.0
               for r in rep["replicas"])
    assert rep["transport"]["pages_shipped"] == 0
    prompt = np.arange(30, dtype=np.int32) % cfg.vocab_size
    replica = fleet.submit(0, prompt, max_new_tokens=4)
    sched = fleet.prefill[replica][1]
    assert sched.active_count() == 1
    stats = sched.kv_stats()
    assert {"occupancy", "free_blocks"} <= set(stats)
    fleet.run_to_completion()
    assert sched.active_count() == 0


def test_fleet_telemetry_stream(served):
    """Router admissions and handoffs land in summary()["fleet"]: typed
    event counts, queue/shed gauges, and handoff page/byte/latency totals
    with pages shipped == pages bound."""
    cfg, model, params = served
    telemetry.configure(enabled=True, sample_sync=False,
                        jax_annotations=False)
    fleet = make_fleet(model, params)
    router = SLORouter(fleet, slo_ttft_s=60.0, prefix_affinity=False)
    requests = _requests(cfg, n=3, seed=23)
    for uid, (prompt, kwargs) in requests.items():
        assert isinstance(router.submit(uid, prompt, **kwargs),
                          RequestAdmitted)
    router.run_to_completion()

    flt = telemetry.summary()["fleet"]
    assert flt["events"]["admitted"] == 3
    h = flt["handoff"]
    assert h["count"] == 3
    assert h["pages_shipped"] == h["pages_bound"] > 0
    assert h["bytes"] > 0 and h["total_s"] > 0
    hists = telemetry.summary()["serving"]["histograms"]
    assert hists["fleet/predicted_ttft_s"]["count"] == 3
    assert hists["fleet/handoff_s"]["count"] == 3
