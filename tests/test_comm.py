"""Comm shim tests (mirrors reference ``tests/unit/comm/test_dist.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.parallel.topology import MeshTopology


@pytest.fixture
def mesh(eight_devices):
    return MeshTopology(dp=8).mesh


def _smap(mesh, fn, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)


def test_all_reduce_sum(mesh):
    f = _smap(mesh, lambda x: dist.all_reduce(x, axis_name="dp"), P("dp"), P("dp"))
    x = jnp.arange(8.0)
    np.testing.assert_allclose(f(x), np.full(8, x.sum()))


def test_all_reduce_ops(mesh):
    for op, expect in [(dist.ReduceOp.MAX, 7.0), (dist.ReduceOp.MIN, 0.0), (dist.ReduceOp.AVG, 3.5)]:
        f = _smap(mesh, lambda x, op=op: dist.all_reduce(x, op=op, axis_name="dp"), P("dp"), P("dp"))
        np.testing.assert_allclose(f(jnp.arange(8.0)), np.full(8, expect))


def test_all_gather(mesh):
    f = _smap(mesh, lambda x: dist.all_gather(x, axis_name="dp"), P("dp"), P())
    x = jnp.arange(16.0)
    np.testing.assert_allclose(f(x), x)


def test_reduce_scatter(mesh):
    # every rank holds the full 16-vector; after reduce_scatter each holds its
    # 2-slice of the sum over ranks
    f = _smap(mesh, lambda x: dist.reduce_scatter(x, axis_name="dp"), P(), P("dp"))
    x = jnp.arange(16.0)
    np.testing.assert_allclose(f(x), x * 8)


def test_all_to_all_single(mesh):
    f = _smap(mesh,
              lambda x: dist.all_to_all_single(x, axis_name="dp", split_axis=1, concat_axis=0),
              P("dp", None), P(None, "dp"))
    x = jnp.arange(64.0).reshape(8, 8)
    out = f(x)
    np.testing.assert_allclose(out, x.T.reshape(8, 8).T)  # a2a is transpose of blocks
    assert out.shape == (8, 8)


def test_broadcast(mesh):
    def body(x):
        return dist.broadcast(x, src=3, axis_name="dp")
    f = _smap(mesh, body, P("dp"), P("dp"))
    x = jnp.arange(8.0)
    np.testing.assert_allclose(f(x), np.full(8, 3.0))


def test_send_next_ring(mesh):
    f = _smap(mesh, lambda x: dist.send_next(x, axis_name="dp"), P("dp"), P("dp"))
    x = jnp.arange(8.0)
    np.testing.assert_allclose(f(x), np.roll(x, 1))


def test_host_level_api():
    assert dist.get_rank() == 0
    assert dist.get_world_size() >= 1
    dist.barrier()  # no-op single-process
    dist.init_distributed()
    assert dist.is_initialized()


def test_comms_logger_records():
    dist.configure(enabled=True, verbose=False)
    log = dist.get_comms_logger()
    log.append("all_reduce", "all_reduce", 0.001, 1024)
    assert log.comms_dict["all_reduce"][1024][0] == 1
    tput, busbw = __import__("deepspeed_tpu.utils.comms_logging", fromlist=["calc_bw_log"]).calc_bw_log(
        "all_reduce", 1024, 0.001, n=8)
    assert busbw == pytest.approx(tput * 2 * 7 / 8)
    dist.configure(enabled=False)


def test_coalesced_and_scatter_gather_verbs():
    """gather/scatter/all_reduce_coalesced/all_gather_coalesced/isend parity
    (reference comm/comm.py:380,391,475,512,362)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    a = jnp.arange(8.0).reshape(4, 2)   # sharded -> per-device [1, 2]
    b = jnp.arange(4.0)                 # sharded -> per-device [1]

    def body(x, y):
        g = dist.gather(x, axis_name="dp")            # [4, 1, 2]
        summed = dist.all_reduce_coalesced([x, y], axis_name="dp")
        st = dist.scatter(jnp.ravel(g) * 0 + jnp.arange(8.0), axis_name="dp")
        ag = dist.all_gather_coalesced([x, y], axis_name="dp")
        h = dist.isend(x, dst=1, src=0, axis_name="dp")
        return g, summed[0], summed[1], st, ag[0], ag[1], h.wait()

    fn = jax.jit(jax.shard_map(body, mesh=mesh,
                               in_specs=(P("dp"), P("dp")),
                               out_specs=(P(), P("dp"), P("dp"), P("dp"),
                                          P(), P(), P("dp")),
                               check_vma=False))
    g, s0, s1, st, ag0, ag1, snt = fn(a, b)
    np.testing.assert_allclose(np.asarray(g).reshape(4, 2), np.asarray(a))
    # all_reduce_coalesced: every shard receives the sum over shards
    np.testing.assert_allclose(np.asarray(s0)[0], np.asarray(a).sum(axis=0))
    np.testing.assert_allclose(np.asarray(s1)[0], np.asarray(b).sum())
    # scatter: rank i takes slice i of the source tensor
    np.testing.assert_allclose(np.asarray(st), np.arange(8.0))
    np.testing.assert_allclose(np.asarray(ag0).reshape(4, 1, 2)[2],
                               np.asarray(a)[2:3])
    np.testing.assert_allclose(np.asarray(ag1).reshape(4, 1)[1],
                               np.asarray(b)[1:2])
    # isend (0 -> 1): rank 1 holds rank 0's value, others zero
    snt = np.asarray(snt)
    np.testing.assert_allclose(snt[1], np.asarray(a)[0])
    np.testing.assert_allclose(snt[0], 0.0)


def test_coalesced_mixed_dtypes_preserved():
    """Mixed-dtype buckets come back in their own dtypes (no silent
    promotion through the flat concat)."""
    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    a = jnp.ones((4, 2), jnp.bfloat16)
    b = jnp.ones((4, 3), jnp.float32)

    def body(x, y):
        r = dist.all_reduce_coalesced([x, y], axis_name="dp")
        g = dist.all_gather_coalesced([x, y], axis_name="dp")
        return r[0], r[1], g[0], g[1]

    r0, r1, g0, g1 = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("dp"), P("dp")),
        out_specs=(P("dp"), P("dp"), P(), P()), check_vma=False))(a, b)
    assert r0.dtype == jnp.bfloat16 and g0.dtype == jnp.bfloat16
    assert r1.dtype == jnp.float32 and g1.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(r1), 4.0)
